#!/usr/bin/env python3
"""CLI entry point.

Mirrors the reference CLI (/root/reference/main.py:17-22):
  python3 main.py --model configs/foo.json --run_mode {train,sample,query,web_api,debug}
``--tpu``/``--workers``/``--debug_grad`` are accepted for drop-in
compatibility (TPU connection is implicit through jax; no TF1 session).
"""
import argparse
import json
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", type=str, default="config.json",
                    help="path to the model config JSON")
    ap.add_argument("--tpu", type=str, default="",
                    help="accepted for compatibility; jax discovers devices")
    ap.add_argument("--workers", type=int, default=None,
                    help="REST worker count; overrides web_workers in the "
                         "config only when given explicitly")
    ap.add_argument("--run_mode", type=str, default="train",
                    choices=["train", "sample", "query", "web_api", "debug",
                             "debug_old", "analyze"])
    ap.add_argument("--debug_grad", action="store_true")
    args = ap.parse_args()

    # multi-host: explicit HBNLP_* flags (the CPU multiprocess rig /
    # run_manager --num-processes) or the standard env / TPU pod metadata
    # (the reference resolved a TPUClusterResolver here, src/main.py:107-117).
    # Single-process runs skip this entirely (docs/DISTRIBUTED.md).
    from homebrewnlp_tpu.distributed import bootstrap as dist_bootstrap
    dist_bootstrap.maybe_initialize()

    with open(args.model) as f:
        config = json.load(f)

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.run.modes import RUN_MODE_FNS
    from homebrewnlp_tpu.train import checkpoint as ckpt
    from homebrewnlp_tpu.utils import retry

    params = ModelParameter(config)
    # persistent XLA compile cache applies to EVERY run mode and must be
    # configured before the first jit compile: warm restarts (run_manager
    # relaunches, serving respawns) then skip the compile+warmup tax
    from homebrewnlp_tpu.utils.compile_cache import install_compile_cache
    cache_dir = install_compile_cache(params)
    if cache_dir:
        print(f"persistent compilation cache: {cache_dir}")
    # storage retry knobs apply to EVERY run mode (serving restores through
    # the same flaky bucket as training; train() re-installs identically)
    retry.set_default_policy(retry.RetryPolicy(
        max_attempts=params.storage_retry_attempts,
        base_delay=params.storage_retry_base_delay))
    params.debug_gradients = args.debug_grad
    # CLI --workers overrides the config (reference src/main.py:60) — but
    # only when actually passed, so web_workers in the JSON stays effective
    if args.workers is not None:
        params.web_workers = args.workers
    params.train = args.run_mode == "train"
    if not params.use_autoregressive_sampling and args.run_mode in ("sample",):
        print("use_autoregressive_sampling is off; enabling for sample mode")
        params.use_autoregressive_sampling = True
    params.current_step = ckpt.latest_step(params.model_path)

    # train_mode returns PREEMPTED_EXIT_CODE (143) after a SIGTERM-triggered
    # emergency checkpoint so supervisors relaunch instead of finishing
    try:
        rc = RUN_MODE_FNS[args.run_mode](params, args)
    finally:
        # clean disconnect from the coordinator — including on the
        # preemption path, so peers fail their next barrier with a named
        # error instead of a gRPC reset (no-op unless bootstrap initialized)
        dist_bootstrap.shutdown()
    return int(rc) if rc else 0


if __name__ == "__main__":
    sys.exit(main())
