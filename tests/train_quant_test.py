"""Training quantization quality guard (``train_quantized_matmuls``).

The repo's established quantization methodology, applied to the TRAINING
path (core/quant.py; docs/PERFORMANCE.md 'Round 11'):

* disabled default — the step is BIT-identical to a build that never heard
  of the knob (the PR 7 parity-test idiom: the plumbing costs nothing when
  off);
* enabled — the quantized forward's teacher-forcing argmax agrees with the
  full-precision forward on >= 99% of positions and the loss stays within
  noise, gradients flow to the full-precision masters (STE), and training
  still converges;
* the compiled quantized train step emits NO float promotion of an int8
  operand outside the fused dequant scope (graft-lint
  ``int8_promotion_audit``; a synthetic negative control proves the pass
  bites).
"""
import jax
import jax.numpy as jnp
import numpy as np

from backend import make_params
from homebrewnlp_tpu.analysis import hlo_lint
from homebrewnlp_tpu.core import quant
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer

#: eligible-scale config: features_per_head 128 x heads 2 puts the
#: bottleneck/attention matmul weights over MIN_QUANT_SIZE (same scale as
#: tests/quant_test.py's serving harness)
_CFG = dict(features_per_head=128, heads=2, depth=2, train_batch_size=2,
            sequence_length=16, vocab_size=64,
            memory_reduction_strategy="revnet",
            optimizer="sm3-learning_rate", learning_rate=0.01)


def _build(**kw):
    cfg = dict(_CFG)
    cfg.update(kw)
    params = make_params(**cfg)
    model = Model(params)
    trainer = Trainer(params, model)
    rng = np.random.default_rng(0)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    batch = {"token_x": jnp.asarray(x),
             "token_y": jnp.asarray((x + 1) % params.vocab_size)}
    return params, model, trainer, trainer.init_state(batch), batch


def disabled_default_bit_identical_test():
    """A config that never mentions the knob and one that sets it False
    produce bit-identical losses AND updated parameters over two steps —
    the quantization seam costs exactly nothing at the default."""
    results = []
    for kw in ({}, {"train_quantized_matmuls": False}):
        _, _, trainer, state, batch = _build(**kw)
        losses = []
        for i in range(2):
            state, metrics = trainer.step(state, batch,
                                          jax.random.PRNGKey(i))
            losses.append(np.asarray(metrics["loss"]))
        results.append((losses, state))
    (l0, s0), (l1, s1) = results
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(a, b)
    for name in s0.variables:
        np.testing.assert_array_equal(np.asarray(s0.variables[name]),
                                      np.asarray(s1.variables[name]),
                                      err_msg=name)


def enabled_argmax_agreement_test():
    """>= 99% teacher-forcing argmax agreement between the quantized and
    full-precision forward on the SAME weights, loss within noise — the
    grid the training step reads is the serving-measured one (99.3% on a
    trained checkpoint)."""
    params, model, _, state, batch = _build()
    full = model.apply(state.variables, batch)
    qvars = quant.quantize_for_training(state.variables, model.param_dims,
                                        model.param_fan_in,
                                        params.calculation_dtype)
    assert any(not np.shares_memory(np.asarray(qvars[k]),
                                    np.asarray(state.variables[k]))
               for k in qvars), "quantization was a no-op"
    quantized = model.apply(qvars, batch)
    a = np.argmax(np.asarray(full.token_out.data, np.float32), axis=-1)
    b = np.argmax(np.asarray(quantized.token_out.data, np.float32), axis=-1)
    agreement = float(np.mean(a == b))
    assert agreement >= 0.99, f"argmax agreement {agreement:.4f} < 0.99"
    lf = float(full.total_loss.data)
    lq = float(quantized.total_loss.data)
    assert abs(lf - lq) <= max(0.02, 0.01 * abs(lf)), (lf, lq)


def enabled_trains_and_updates_masters_test():
    """With the knob on, gradients reach the full-precision masters via
    the STE (eligible weights actually move) and the loss still trends
    down on the synthetic task — fake-quantization must not freeze or
    poison training."""
    params, model, trainer, state, batch = _build(
        train_quantized_matmuls=True)
    eligible = [k for k, v in state.variables.items()
                if quant.eligible(k, v, model.param_dims.get(k, ()))]
    assert eligible, "harness scale produced no eligible weights"
    before = {k: np.asarray(state.variables[k], np.float32)
              for k in eligible}
    first = None
    rng = np.random.default_rng(1)
    for i in range(20):
        x = rng.integers(0, params.vocab_size,
                         (params.train_batch_size,
                          params.sequence_length, 1))
        b = {"token_x": jnp.asarray(x),
             "token_y": jnp.asarray((x + 1) % params.vocab_size)}
        state, metrics = trainer.step(state, b, jax.random.PRNGKey(i))
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert np.isfinite(last)
    assert last < first, (first, last)
    for k in eligible:
        assert not np.array_equal(before[k],
                                  np.asarray(state.variables[k], np.float32)), \
            f"STE left master {k} frozen"


def quantized_step_hlo_int8_promotion_test():
    """The compiled quantized train step carries int8->float converts ONLY
    inside the named dequant scope (the property graft-lint's
    int8_promotion_audit enforces), and the step does carry int8 at all —
    a vacuously-clean module would prove nothing."""
    _, _, trainer, state, batch = _build(train_quantized_matmuls=True)
    hlo = trainer.lowered(state, batch).compile().as_text()
    assert "s8[" in hlo, "quantized step compiled without any int8 buffer"
    findings = hlo_lint.int8_promotion_audit("train_step", hlo)
    assert not findings, "\n".join(str(f) for f in findings)


def int8_promotion_audit_negative_control_test():
    """A synthetic dequant-scope-less int8 promotion IS flagged (the pass
    has teeth), while the same line under a dequant scope is not."""
    bad = ('  %evil = f32[4,256,128]{2,1,0} convert(s8[4,256,128]{2,1,0} '
           '%w), metadata={op_name="jit(step_fn)/gpt0/body0/somewhere/'
           'convert_element_type"}')
    good = bad.replace("body0/somewhere", "body0/dequant")
    assert hlo_lint.int8_promotion_audit("t", bad)
    assert not hlo_lint.int8_promotion_audit("t", good)
