"""VTT subtitle decoding + word-timestamp -> token alignment
(VERDICT r1 missing #2/#3; reference semantics from
/root/reference/scripts/video2tfrecord.py:186-361,684-707).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

from homebrewnlp_tpu.data.vtt import (decode_vtt, frames_token_groups,
                                      split_tokens_on_words)

WORD_LEVEL_VTT = """WEBVTT
Kind: captions
Language: en

00:00:00.500 --> 00:00:03.000
hello<00:00:01.000><c> brave</c><00:00:01.500><c> new</c><00:00:02.000><c> world</c>

00:00:03.000 --> 00:00:05.000
again<00:00:04.000><c> tokens</c>
"""

CUE_LEVEL_VTT = """WEBVTT

00:00:00.000 --> 00:00:02.000
hello brave

00:00:02.000 --> 00:00:04.000
new world here
"""


def word_level_decode_test():
    text, words, stamps = decode_vtt(WORD_LEVEL_VTT)
    assert [w.strip() for w in words] == \
        ["hello", "brave", "new", "world", "again", "tokens"]
    assert stamps == [0.5, 1.0, 1.5, 2.0, 3.0, 4.0]
    assert text == " hello brave new world again tokens"


def cue_level_decode_test():
    text, words, stamps = decode_vtt(CUE_LEVEL_VTT)
    assert [w.strip() for w in words] == ["hello", "brave", "new", "world", "here"]
    # cue spans divide evenly across their words
    np.testing.assert_allclose(stamps, [0.0, 1.0, 2.0, 2.0 + 2 / 3, 2.0 + 4 / 3])


def token_split_bytes_test():
    """Byte-level round trip: every byte lands on its word, none dropped."""
    text, words, stamps = decode_vtt(WORD_LEVEL_VTT)
    enc = lambda t: list(t.encode())
    dec = lambda ids: bytes(ids).decode()
    groups = split_tokens_on_words(enc, dec, words, text)
    assert len(groups) == len(words)
    assert sum(len(g) for g in groups) == len(text.encode())
    for word, g in zip(words, groups):
        assert bytes(g).decode().replace(" ", "") == word.replace(" ", "")


def frame_grouping_test():
    """Reference worker-loop semantics: words fall into the frame whose
    interval covers their stamp; groups of ltp-1 with overflow skip-frames;
    empty frames get an all-padding mask-0 group."""
    _, words, stamps = decode_vtt(WORD_LEVEL_VTT)
    bpe = [[10 + i] for i in range(len(words))]  # one token per word
    PAD = 99
    state = {}
    # 1s frames, ltp=3 -> capacity 2 real tokens per frame record
    g1 = frames_token_groups(bpe, stamps, 1.0, 3, PAD, state)   # hello@0.5
    assert g1 == [([10, PAD, PAD], 1, False)]
    g2 = frames_token_groups(bpe, stamps, 2.0, 3, PAD, state)   # brave, new
    assert g2 == [([11, 12, PAD], 2, False)]
    g3 = frames_token_groups(bpe, stamps, 5.0, 3, PAD, state)   # 3 words left
    assert g3 == [([13, 14, PAD], 2, False), ([15, PAD, PAD], 1, True)]
    g4 = frames_token_groups(bpe, stamps, 6.0, 3, PAD, state)   # nothing left
    assert g4 == [([PAD, PAD, PAD], 0, False)]


def video_roundtrip_vtt_test(tmp_path):
    """End-to-end: synthetic video + .vtt -> records with per-frame aligned
    tokens/mask/skip_frame."""
    cv2 = pytest.importorskip("cv2")
    from homebrewnlp_tpu.data.tfrecord import decode_example, read_records

    vid = str(tmp_path / "clip.mp4")
    w = cv2.VideoWriter(vid, cv2.VideoWriter_fourcc(*"mp4v"), 4.0, (64, 48))
    assert w.isOpened()
    rng = np.random.default_rng(0)
    for _ in range(24):  # 6 seconds at 4 fps
        w.write(rng.integers(0, 255, (48, 64, 3)).astype(np.uint8))
    w.release()
    (tmp_path / "clip.vtt").write_text(WORD_LEVEL_VTT)

    out = tmp_path / "records"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "video2records.py"),
         vid, "--output-dir", str(out), "--fps", "1", "--width", "64",
         "--height", "48", "--subtitles", "--language-tokens-per-frame", "8",
         "--padding-token", "0"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr

    records = []
    for f in sorted(os.listdir(out)):
        for raw in read_records(str(out / f)):
            records.append(decode_example(raw))
    assert records, "no records written"
    # every record carries tokens + mask; frame 1 (ends t=2s) holds
    # ' hello brave' -> mask > 0; a frame past 5s is all padding, mask 0
    masks = [int(r["mask"][0]) for r in records]
    assert all(len(r["tokens"]) == 8 for r in records)
    assert masks[0] > 0
    assert masks[-1] == 0
    assert records[0]["concat"][0] == 1 and all(r["concat"][0] == 0
                                                for r in records[1:])
    # total real tokens across frames == total subtitle bytes
    text, words, stamps = decode_vtt(WORD_LEVEL_VTT)
    assert sum(masks) == len(text.encode())
    # skip_frame records (overflow groups) are black padding frames
    for r in records:
        if r["skip_frame"][0]:
            img = cv2.imdecode(np.frombuffer(r["frame"], np.uint8),
                               cv2.IMREAD_COLOR)
            assert img.max() <= 2


def chunk_video_json_test(tmp_path):
    src = tmp_path / "vids.json"
    src.write_text(json.dumps({"id": [f"v{i}" for i in range(20)],
                               "duration": [30 + i for i in range(20)]}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chunk_video_json.py"),
         str(src), "100", "-prefix", str(tmp_path) + "/", "-seed", "0"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    out = json.load(open(tmp_path / "work_chunks.json"))
    flat = [v for c in out["id"] for v in c]
    assert sorted(flat) == sorted(f"v{i}" for i in range(20))
    # every chunk but possibly the last reaches the minimum duration
    sums = [sum(c) for c in out["duration"]]
    assert all(s >= 100 for s in sums[:-1])
