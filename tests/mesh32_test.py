"""Beyond-8-device assumptions: schedule tables and process-group derivation
at 32 ways, and one composed train step on a 32-device virtual mesh.

Everything else in the suite runs on the 8-device conftest mesh; these pin
the topology-dependent pieces (interleaved-1F1B ring wrap at V>2, mesh
auto-derivation, data-axis process groups) at sizes the driver never
exercises.
"""
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def schedule_32way_invariants_test():
    """build_schedule + _choose_slots at S=8, V=4, M=32: every unit fires
    exactly once, after its dataflow dependencies (including the ring-wrap
    hops only live at V>1), and the static stash verification finds a
    collision-free slot count for BOTH stashes."""
    from homebrewnlp_tpu.parallel.pipeline_1f1b import (FWD, BWD, IDLE,
                                                        build_schedule,
                                                        _choose_slots)
    M, S, V = 32, 8, 4
    kinds, mbs, chunks = build_schedule(M, S, V)
    fired = {}
    for t in range(kinds.shape[0]):
        for s in range(S):
            k = kinds[t, s]
            if k == IDLE:
                continue
            unit = ("F" if k == FWD else "B", int(mbs[t, s]),
                    int(chunks[t, s]), s)
            assert unit not in fired, f"double fire {unit}"
            fired[unit] = t
    assert len(fired) == 2 * M * V * S  # one F and one B per (m, chunk, stage)
    for (kind, m, c, s), t in fired.items():
        if kind == "F":
            if s > 0:
                assert fired[("F", m, c, s - 1)] < t, (m, c, s)
            elif c > 0:  # ring wrap S-1 -> 0 advances the chunk
                assert fired[("F", m, c - 1, S - 1)] < t, (m, c, s)
        else:
            assert fired[("F", m, c, s)] < t, (m, c, s)
            if s < S - 1:
                assert fired[("B", m, c, s + 1)] < t, (m, c, s)
            elif c < V - 1:  # backward wrap 0 -> S-1 retreats the chunk
                assert fired[("B", m, c + 1, 0)] < t, (m, c, s)
    p = _choose_slots(kinds, mbs, chunks, S, V)
    assert S + 1 <= p <= S * V + V + 2


def process_groups_32way_test():
    """process_data_slice at a 32-device mesh laid out 8 processes x 4
    devices: with data=8 outermost each process owns exactly one data
    coordinate block."""
    from homebrewnlp_tpu.core.sharding import process_data_slice
    import jax
    from jax.sharding import Mesh

    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs the 8-device conftest mesh")
    # synthesize coordinates: 8 virtual CPU devices as a data(8) axis is the
    # largest real check available in-process; the 32-way layout runs in the
    # subprocess leg below
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8, 1),
                ("data", "model"))
    idx, count = process_data_slice(mesh)
    assert (idx, count) == (0, 1)  # single process owns all coords


def composed_step_32dev_subprocess_test():
    """Two train steps on a 32-device virtual CPU mesh: the 1b_long_context
    layout (dp 4 x sp 4 x tp 2) and an interleaved-1F1B pipeline layout
    (dp 4 x pipe 4 x tp 2, V=2 — exercising the ring wrap at S=4) — both at
    tiny shapes, both finite.  pipe x sequence is not composed: ring
    attention opens its own shard_map, which cannot nest inside the
    pipe-manual one (parallel/pipeline.py 'Composition')."""
    code = """
import numpy as np
import __graft_entry__ as g
from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.core import sharding as shardlib
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer
import jax
devices = jax.devices()
assert len(devices) == 32, len(devices)

def leg(tag, **overrides):
    cfg = dict(train_batch_size=8, tpu_size=32, heads=2, features_per_head=16,
               sequence_length=64)
    cfg.update(overrides)
    params = ModelParameter(g._config(**cfg))
    mesh = shardlib.build_mesh(params, devices)
    trainer = Trainer(params, Model(params), mesh=mesh)
    batch = g._batch(params)
    state = trainer.init_state(batch)
    _, metrics = trainer.step(state, batch)
    loss = float(metrics["loss"])
    assert np.isfinite(loss), (tag, loss)
    print("32dev", tag, "loss", loss, "mesh", dict(mesh.shape))

leg("dp4 x sp4 x tp2", depth=2,
    block_config=[{"layer": ["norm-shift-scale-features-group",
                             "attention-dot_product-context-in:relu"]}],
    mesh_shape_override={"data": 4, "sequence": 4, "model": 2})
leg("dp4 x pipe4 x tp2 1f1b V=2", depth=8, train_batch_size=16,
    pipeline_schedule="1f1b", pipeline_interleave=2,
    pipeline_microbatches=4,
    mesh_shape_override={"data": 4, "pipe": 4, "model": 2})
print("32dev composed loss ok")
"""
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=32")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "32dev composed loss" in proc.stdout
