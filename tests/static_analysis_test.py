"""graft-lint tier-1 suite: the static-analysis layer audits every jitted
entry point AND every pass/rule is proven to bite on a seeded violation.

Two positive checks pin the repo at HEAD clean (the compiled-HLO audit of
all four entry points against analysis/budgets.json, and the AST rules
over homebrewnlp_tpu/ + scripts/); each HLO pass and each AST rule then
gets a negative control — synthetic HLO text or source carrying exactly
the violation the pass exists to catch, mirroring the decode checker's
negative control (tests/decode_inplace_test.py) so no future refactor can
reduce an audit to a vacuous assertion.  The donation audit additionally
gets REAL negative controls: the train step and the prefill entry compiled
with donation disabled (the same jit, ``donate=False``) must be flagged.
"""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

from homebrewnlp_tpu.analysis import ast_lint, entry_points, hlo_lint

pytestmark = pytest.mark.staticanalysis

REPO = os.path.join(os.path.dirname(__file__), "..")


# ---- shared lowering (one audit model for the whole module) ----------------

@pytest.fixture(scope="module")
def audit_model():
    return entry_points.build_audit_model()


# ---- positive: the repo at HEAD is clean -----------------------------------

def hlo_audit_all_entry_points_clean_test():
    """All four jitted entry points (train step, decode chunk step, prefill
    entry, eval fn) pass every HLO pass against analysis/budgets.json."""
    findings = entry_points.audit_all()
    assert findings == [], "\n".join(str(f) for f in findings)


def ast_rules_repo_clean_test():
    findings = ast_lint.lint_repo()
    assert findings == [], "\n".join(str(f) for f in findings)


def budgets_cover_every_entry_point_test():
    """EXACTLY the registered entry points — an orphan row (entry renamed
    or dropped) would silently audit nothing, so it fails here and in
    ``mesh_audit.budget_coverage_audit`` (tests/mesh_audit_test.py covers
    the meshes-section half)."""
    budgets = hlo_lint.load_budgets()
    assert set(entry_points.ENTRY_POINTS) == set(budgets["entry_points"])


# ---- donation audit: real negative controls --------------------------------

def donation_audit_flags_undonated_train_step_test(audit_model):
    """The SAME train step compiled without donation must be flagged
    against the donated-case expectation — proof the audit reads the real
    alias table, not a vacuous count."""
    import jax

    params, model, variables, token_x, batch = audit_model
    trainer, state = entry_points.make_trainer(params, model, batch)
    hlo, ctx = entry_points.lower_train_step(params, model, variables,
                                             batch, donate=False,
                                             trainer=trainer, state=state)
    expected = len(jax.tree_util.tree_leaves(ctx["state"]))
    findings = hlo_lint.donation_audit("train_step", hlo, expected)
    assert findings and "NOT aliased" in findings[0].message
    # and the donated compile satisfies the same expectation
    hlo, ctx = entry_points.lower_train_step(params, model, variables,
                                             batch, donate=True,
                                             trainer=trainer, state=state)
    assert hlo_lint.donation_audit("train_step", hlo,
                                   ctx["donated_leaves"]) == []


def donation_audit_flags_undonated_prefill_entry_test(audit_model):
    import jax.numpy as jnp

    _, model, variables, token_x, _ = audit_model
    hlo, ctx = entry_points.lower_prefill_entry(model, variables,
                                                jnp.asarray(token_x),
                                                donate=False)
    findings = hlo_lint.donation_audit("prefill_entry_step", hlo,
                                       ctx["donated_leaves"])
    assert findings and findings[0].rule == "donation"


# ---- per-pass synthetic negative controls ----------------------------------

PROTECTED = {"f32[2,4,16,2,16]"}
LIVE_COPY = ("%copy.9 = f32[2,4,16,2,16]{4,3,2,1,0} "
             "copy(f32[2,4,16,2,16]{4,3,2,1,0} %get-tuple-element.1)")


def big_copy_audit_negative_control_test():
    findings = hlo_lint.big_copy_audit("e", LIVE_COPY, PROTECTED)
    assert findings and findings[0].rule == "big-copy"
    assert "NOT aliased" in findings[0].message


def big_copy_audit_async_pair_test():
    """Async copies count exactly once: ``copy-start``'s tuple result is
    unmatchable, its ``copy-done`` twin is flagged — at production scale
    XLA emits the big copies as async pairs, so this is where the round-5
    regression would actually surface on TPU."""
    pair = "\n".join([
        "%copy-start.9 = (f32[2,4,16,2,16]{4,3,2,1,0}, "
        "f32[2,4,16,2,16]{4,3,2,1,0}, u32[]{:S(2)}) "
        "copy-start(f32[2,4,16,2,16]{4,3,2,1,0} %get-tuple-element.1)",
        "%copy-done.9 = f32[2,4,16,2,16]{4,3,2,1,0} "
        "copy-done((f32[2,4,16,2,16]{4,3,2,1,0}, "
        "f32[2,4,16,2,16]{4,3,2,1,0}, u32[]{:S(2)}) %copy-start.9)",
    ])
    findings = hlo_lint.big_copy_audit("e", pair, PROTECTED)
    assert findings and findings[0].rule == "big-copy"
    nbytes = hlo_lint.shape_bytes("f32[2,4,16,2,16]")
    assert f"{nbytes} bytes copied" in findings[0].message  # counted ONCE


def big_copy_audit_relayout_of_live_state_test():
    """A relayout copy of FULL protected LIVE state (get-tuple-element
    operand — the carry) is the unaliasable-layout failure the
    pre-refactor decode checker named — still flagged."""
    relayout = ("%copy.2 = f32[2,4,16,2,16]{4,3,2,1,0} "
                "copy(f32[2,4,16,2,16]{0,1,2,3,4} %get-tuple-element.7)")
    findings = hlo_lint.big_copy_audit("e", relayout, PROTECTED)
    assert findings and findings[0].rule == "big-copy"


def big_copy_audit_exemptions_test():
    """The three legitimate copy flavors pass: differently-shaped buffers,
    fresh-init (broadcast operand) materialization, and relayout copies of
    explicit data-movement results (the train optimizer's transposes) —
    and a byte budget tolerates small preserved leaves."""
    block = ("%copy.1 = f32[4,16,2,16]{3,2,1,0} "
             "copy(f32[4,16,2,16]{2,0,3,1} %transpose.1)")
    fresh = ("%copy.3 = f32[2,4,16,2,16]{4,3,2,1,0} "
             "copy(f32[2,4,16,2,16]{4,3,2,1,0} %broadcast.2)")
    relayout_intermediate = ("%copy.4 = f32[2,4,16,2,16]{4,3,2,1,0} "
                             "copy(f32[2,4,16,2,16]{0,1,2,3,4} "
                             "%transpose.9)")
    for ok in (block, fresh, relayout_intermediate):
        assert hlo_lint.big_copy_audit("e", ok, PROTECTED) == [], ok
    # a budget at least the copied bytes tolerates the copy...
    nbytes = hlo_lint.shape_bytes("f32[2,4,16,2,16]")
    assert hlo_lint.big_copy_audit("e", LIVE_COPY, PROTECTED,
                                   max_copied_bytes=nbytes) == []
    # ...one byte less does not
    assert hlo_lint.big_copy_audit("e", LIVE_COPY, PROTECTED,
                                   max_copied_bytes=nbytes - 1)


def dtype_promotion_audit_negative_control_test():
    bad = "%convert.5 = f32[32,64]{1,0} convert(bf16[32,64]{1,0} %p.7)"
    params = {"bf16[32,64]"}
    findings = hlo_lint.dtype_promotion_audit("e", bad, params)
    assert findings and findings[0].rule == "dtype-promotion"
    # allowlisted shape passes; a non-param shape was never in scope
    assert hlo_lint.dtype_promotion_audit("e", bad, params,
                                          allow={"bf16[32,64]"}) == []
    other = "%convert.5 = f32[8,8]{1,0} convert(bf16[8,8]{1,0} %p.7)"
    assert hlo_lint.dtype_promotion_audit("e", other, params) == []


def collective_census_and_budget_negative_control_test():
    hlo = "\n".join([
        "%all-reduce.1 = f32[4]{0} all-reduce(f32[4]{0} %x)",
        # async pair: -start counts, -done must not double-count
        "%ag = (f32[4]{0}, f32[8]{0}) all-gather-start(f32[4]{0} %y)",
        "%ag2 = f32[8]{0} all-gather-done((f32[4]{0}, f32[8]{0}) %ag)",
    ])
    census = hlo_lint.collective_census(hlo)
    assert census["all-reduce"] == 1 and census["all-gather"] == 1
    assert census["reduce-scatter"] == 0
    findings = hlo_lint.collective_budget_audit("e", census, {})
    assert {f.rule for f in findings} == {"collective-budget"}
    assert len(findings) == 2  # one per over-budget op kind
    assert hlo_lint.collective_budget_audit(
        "e", census, {"all-reduce": 1, "all-gather": 1}) == []


def host_sync_audit_negative_control_test():
    infeed = "%infeed.1 = (f32[4]{0}, token[]) infeed(token[] %tok)"
    cb = ('%custom-call.2 = f32[4]{0} custom-call(f32[4]{0} %x), '
          'custom_call_target="xla_python_cpu_callback"')
    for bad in (infeed, cb):
        findings = hlo_lint.host_sync_audit("e", bad)
        assert findings and findings[0].rule == "host-sync", bad
    clean = "%add.1 = f32[4]{0} add(f32[4]{0} %x, f32[4]{0} %y)"
    assert hlo_lint.host_sync_audit("e", clean) == []


# ---- AST rules: seeded-violation negative controls -------------------------

def wallclock_rule_negative_control_test():
    bad = "import time\nt0 = time.time()\n"
    findings = ast_lint.lint_source("x.py", bad)
    assert [f.rule for f in findings] == ["wallclock"]
    assert findings[0].entry == "x.py:2"
    ok = "import time\nt0 = time.monotonic()\n"
    assert ast_lint.lint_source("x.py", ok) == []


def wallclock_rule_alias_spellings_test():
    """Every spelling of the wall clock is caught — a from-import or module
    alias must not bypass the ban."""
    for bad in ("from time import time\nt0 = time()\n",
                "from time import time as now\nt0 = now()\n",
                "import time as t\nt0 = t.time()\n"):
        assert [f.rule for f in ast_lint.lint_source("x.py", bad)] \
            == ["wallclock"], bad
    # other names stay out of scope: monotonic from-imports, local time()
    for ok in ("from time import monotonic\nt0 = monotonic()\n",
               "def time():\n    return 0\nt0 = time()\n"):
        assert ast_lint.lint_source("x.py", ok) == [], ok


def wallclock_rule_suppression_test():
    marked = ("import time\n"
              "stamp = time.time()  # graft-lint: allow[wallclock]\n")
    assert ast_lint.lint_source("x.py", marked) == []
    line_above = ("import time\n"
                  "# graft-lint: allow[wallclock]\n"
                  "stamp = time.time()\n")
    assert ast_lint.lint_source("x.py", line_above) == []
    # the marker is rule-scoped: it does not blanket other rules
    wrong_rule = ("import time\n"
                  "t = time.time()  # graft-lint: allow[unseeded-rng]\n")
    assert [f.rule for f in ast_lint.lint_source("x.py", wrong_rule)] \
        == ["wallclock"]


def unseeded_rng_rule_negative_control_test():
    bad = "import numpy as np\nr = np.random.default_rng()\n"
    findings = ast_lint.lint_source("x.py", bad)
    assert [f.rule for f in findings] == ["unseeded-rng"]
    assert ast_lint.lint_source(
        "x.py", "import numpy as np\nr = np.random.default_rng(7)\n") == []
    marked = ("import numpy as np\n"
              "r = np.random.default_rng()  # graft-lint: allow[unseeded-rng]\n")
    assert ast_lint.lint_source("x.py", marked) == []


def donated_jit_rule_negative_control_test():
    bad = ("import jax\n"
           "def my_new_step():\n"
           "    return jax.jit(lambda x: x, donate_argnums=(0,))\n")
    findings = ast_lint.lint_source("some/new_module.py", bad)
    assert [f.rule for f in findings] == ["donated-jit"]
    assert "some/new_module.py::my_new_step" in findings[0].message
    # the registered real site passes under its registry key
    registered = ("import jax\n"
                  "def _build_step():\n"
                  "    return jax.jit(lambda x: x, donate_argnums=(0,))\n")
    assert ast_lint.lint_source(
        "homebrewnlp_tpu/train/__init__.py", registered) == []
    # a jit WITHOUT donation needs no registration
    plain = "import jax\nf = jax.jit(lambda x: x)\n"
    assert ast_lint.lint_source("some/new_module.py", plain) == []


def engine_registry_rule_negative_control_test():
    """A donated jit under infer/ outside the Engine's registered builder
    sites is a forked chunk-program carry escaping the composition
    registry: the engine-registry rule flags it (on top of donated-jit);
    the registered builder stays clean under its key, and the same site
    OUTSIDE infer/ trips only the donated-jit registration rule."""
    bad = ("import jax\n"
           "def my_forked_program():\n"
           "    return jax.jit(lambda c: c, donate_argnums=(0,))\n")
    findings = ast_lint.lint_source("homebrewnlp_tpu/infer/forked.py", bad)
    assert sorted(f.rule for f in findings) == ["donated-jit",
                                               "engine-registry"]
    msg = next(f.message for f in findings if f.rule == "engine-registry")
    assert "ENGINE_PROGRAMS" in msg and "_chunk_jit" in msg
    # the Engine's single builder passes under its registered key
    registered = ("import jax\n"
                  "def _chunk_jit():\n"
                  "    return jax.jit(lambda c: c, donate_argnums=(0,))\n")
    assert ast_lint.lint_source("homebrewnlp_tpu/infer/engine.py",
                                registered) == []
    # outside infer/ the composition registry does not apply
    assert [f.rule for f in ast_lint.lint_source(
        "homebrewnlp_tpu/train/other.py", bad)] == ["donated-jit"]
    # the suppression marker silences the fork complaint too
    marked = ("import jax\n"
              "def my_forked():  # graft-lint: allow[engine-registry]\n"
              "    return jax.jit(lambda c: c, donate_argnums=(0,))  "
              "# graft-lint: allow[donated-jit]\n")
    assert ast_lint.lint_source("homebrewnlp_tpu/infer/forked.py",
                                marked) == []


def registry_keys_point_at_real_sites_test():
    """Every DONATED_JIT_REGISTRY / ENGINE_REGISTRY_SITES key names an
    existing file — a stale key after a refactor would silently stop
    covering (or stop permitting) the moved site."""
    for key in (set(ast_lint.DONATED_JIT_REGISTRY)
                | set(ast_lint.ENGINE_REGISTRY_SITES)):
        rel = key.split("::")[0]
        assert os.path.exists(os.path.join(REPO, rel)), key
    # the Engine builder's registry row promises an audit per composition
    assert ("homebrewnlp_tpu/infer/engine.py::_chunk_jit"
            in ast_lint.ENGINE_REGISTRY_SITES)


def engine_programs_mirror_entry_points_test():
    """infer/engine.py ENGINE_PROGRAMS and analysis/entry_points.py
    ENTRY_POINTS are mirrored, not imported (entry_points must import
    without jax): the chunk-step tail of the audit registry must list
    exactly the Engine's compositions in registry order, every
    (spec, paged) pair must resolve to exactly one program, and the
    builder's DONATED_JIT_REGISTRY row must name each audit."""
    from homebrewnlp_tpu.analysis import entry_points
    from homebrewnlp_tpu.infer.engine import ENGINE_PROGRAMS, program_name
    progs = list(ENGINE_PROGRAMS)
    assert list(entry_points.ENTRY_POINTS[-len(progs):]) == progs
    assert sorted(program_name(**parts)
                  for parts in ENGINE_PROGRAMS.values()) == sorted(progs)
    row = ast_lint.DONATED_JIT_REGISTRY[
        "homebrewnlp_tpu/infer/engine.py::_chunk_jit"]
    for name in progs:
        assert name in row, (name, row)


def config_docs_rule_negative_control_test(tmp_path):
    cfg = tmp_path / "config.py"
    lines = ["class ModelParameter:",
             "    def __init__(self, config):"]
    lines += [f"        self.knob_{i} = {i}" for i in range(60)]
    lines += ["        self.forgotten_knob = 2",
              "        for k, v in config.items():",
              "            self.__dict__[k] = v"]
    cfg.write_text("\n".join(lines) + "\n")
    md = tmp_path / "CONFIG.md"
    md.write_text("| Key | Default |\n|---|---|\n"
                  + "".join(f"| `knob_{i}` | `{i}` |\n" for i in range(60)))
    findings = ast_lint.config_docs_findings(str(cfg), str(md))
    assert [f.rule for f in findings] == ["config-docs"]
    assert "forgotten_knob" in findings[0].message


# ---- the CLI ---------------------------------------------------------------

def graft_lint_cli_ast_clean_test():
    """`graft_lint.py --ast` exits 0 on the repo at HEAD (the full --all
    run rides the in-process audit_all test above; the subprocess here pins
    argument parsing + exit semantics without a second 15 s compile)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graft_lint.py"),
         "--ast"], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def graft_lint_cli_reports_findings_test(monkeypatch):
    """Findings drive a nonzero exit and a per-rule summary on stderr."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import graft_lint
    finally:
        sys.path.pop(0)
    fake = [hlo_lint.Finding("donation", "train_step", "seeded"),
            hlo_lint.Finding("donation", "eval_fn", "seeded"),
            hlo_lint.Finding("big-copy", "train_step", "seeded")]
    monkeypatch.setattr(graft_lint, "run_ast", lambda: list(fake))
    assert graft_lint.main(["--ast"]) == 1
    monkeypatch.setattr(graft_lint, "run_ast", lambda: [])
    assert graft_lint.main(["--ast"]) == 0
