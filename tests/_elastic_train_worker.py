"""Worker for tests/elastic_test.py: the REAL train loop under the elastic
controller, plus a restore-probe mode for the acceptance comparisons.

Two invocation shapes:

1. **Under the controller** (``scripts/run_manager.py --elastic`` sets the
   ``HBNLP_*`` env including ``HBNLP_GENERATION``)::

     python _elastic_train_worker.py <cfg.json> [--step-delay S]

   Writes ``<model_path>/pids/g<gen>_p<rank>.pid`` (so the test can SIGKILL
   a specific rank), prints restore/probe markers, runs ``train()``, and
   exits with the run mode's code (0 / 143 preempted / 144 membership).
   ``--step-delay`` stretches each step so the test has a deterministic
   window to kill into (the model itself is deliberately tiny).

2. **Probe fleet** (spawned via ``multihost_test._spawn_workers``)::

     python _elastic_train_worker.py <port> <pid> <nproc> <cfg.json> \
         --probe-only --step N

   Restores checkpoint step N and prints the same probe markers — the
   "fresh restore at this world size" reference the elastic run's resumed
   generations are compared against.

Probe markers (chief only; the probe batch is synthetic and fixed, so the
values are comparable across runs):

- ``ELASTIC_RESTORE g=<gen> world=<n> step=<s> fwd=<repr>`` — single-device
  forward loss of the restored parameters: NO collectives, bit-identical
  for the same checkpoint bytes no matter the world size.
- ``ELASTIC_STEP g=<gen> world=<n> step=<s> loss=<repr>`` — one sharded
  trainer step from the restored state on the live mesh: comparable within
  reduction-order tolerance at the same world size.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _probe_batch(params):
    import numpy as np
    rng = np.random.default_rng(123)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": np.asarray(x, np.int32),
            "token_y": np.asarray((x + 1) % params.vocab_size, np.int32)}


def _fwd_loss(params, variables_host) -> float:
    """Single-device forward on the fixed probe batch (no collectives)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from homebrewnlp_tpu.model import Model

    host_batch = _probe_batch(params)
    model = Model(params)
    template = model.init({k: np.asarray(v) for k, v in host_batch.items()})
    batch = {k: jnp.asarray(v) for k, v in host_batch.items()}
    fn = jax.jit(lambda v, b: model.apply(v, b).total_loss.data)
    host = {k: jnp.asarray(np.asarray(variables_host[k])) for k in template}
    return float(np.asarray(jax.device_get(fn(host, batch))))


def _probe_step_loss(params, restored) -> float:
    """One sharded trainer step from the restored state on the live mesh
    (every rank must call this — it is a collective)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer, TrainState

    mesh = shardlib.build_mesh(params)
    trainer = Trainer(params, Model(params), mesh=mesh)
    slice_index, slice_count = shardlib.process_data_slice(mesh)
    gb = params.train_batch_size
    local = gb // slice_count
    full = _probe_batch(params)
    rows = slice(slice_index * local, (slice_index + 1) * local)
    batch = {k: v[rows] for k, v in full.items()}
    state = trainer.init_state(batch)
    variables = {k: np.asarray(v).astype(state.variables[k].dtype)
                 for k, v in restored[0].items()}
    st = TrainState(shardlib.place_tree(state.variables, variables),
                    shardlib.place_tree(state.opt_state, restored[1]),
                    jnp.asarray(restored[2], jnp.int32))
    _, metrics = trainer.step(st, batch, rng=jax.random.PRNGKey(999))
    return float(np.asarray(jax.device_get(metrics["loss"])))


def _print_probes(params, restored, gen, tag=""):
    import jax
    step_loss = _probe_step_loss(params, restored)
    if jax.process_index() == 0:
        world = jax.process_count()
        print(f"ELASTIC_RESTORE{tag} g={gen} world={world} "
              f"step={restored[2]} fwd={_fwd_loss(params, restored[0])!r}",
              flush=True)
        print(f"ELASTIC_STEP{tag} g={gen} world={world} "
              f"step={restored[2]} loss={step_loss!r}", flush=True)


def main() -> int:
    args = list(sys.argv[1:])
    probe_only = "--probe-only" in args
    if probe_only:
        args.remove("--probe-only")
    step_delay = 0.0
    if "--step-delay" in args:
        i = args.index("--step-delay")
        step_delay = float(args[i + 1])
        del args[i:i + 2]
    # per-rank straggle (forensics_test straggler e2e): ONE rank's host
    # wedges for --straggle-delay seconds at step --straggle-step while
    # its lease agent keeps beating — the slow-but-alive shape (GC pause,
    # storage stall) the chief's straggler detector must flag.  In
    # synchronous training a merely-proportionally-slower rank equalizes
    # the whole fleet's step rate (collectives gate everyone), so the
    # detectable — and operationally real — shape is the one-shot wedge
    straggle_rank, straggle_delay, straggle_step = -1, 0.0, 3
    if "--straggle-rank" in args:
        i = args.index("--straggle-rank")
        straggle_rank = int(args[i + 1])
        del args[i:i + 2]
    if "--straggle-delay" in args:
        i = args.index("--straggle-delay")
        straggle_delay = float(args[i + 1])
        del args[i:i + 2]
    if "--straggle-step" in args:
        i = args.index("--straggle-step")
        straggle_step = int(args[i + 1])
        del args[i:i + 2]
    probe_step = None
    if "--step" in args:
        i = args.index("--step")
        probe_step = int(args[i + 1])
        del args[i:i + 2]

    if len(args) == 4:  # _spawn_workers convention: port pid nproc cfg
        port, pid, nproc, cfg_path = args
        os.environ["HBNLP_COORDINATOR"] = f"localhost:{port}"
        os.environ["HBNLP_NUM_PROCESSES"] = nproc
        os.environ["HBNLP_PROCESS_ID"] = pid
    else:  # controller convention: env already set by run_manager
        (cfg_path,) = args

    with open(cfg_path) as f:
        cfg = json.load(f)
    gen = int(os.environ.get("HBNLP_GENERATION", "0"))
    rank = int(os.environ.get("HBNLP_PROCESS_ID", "0"))

    if not probe_only:
        # pidfile so the test can SIGKILL THIS rank of THIS generation
        pid_dir = os.path.join(cfg["model_path"], "pids")
        os.makedirs(pid_dir, exist_ok=True)
        with open(os.path.join(pid_dir, f"g{gen}_p{rank}.pid"), "w") as f:
            f.write(str(os.getpid()))

    from homebrewnlp_tpu.distributed import bootstrap
    assert bootstrap.maybe_initialize()

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.train import Trainer, checkpoint as ckpt

    params = ModelParameter(dict(cfg))

    if probe_only:
        restored = ckpt.restore(cfg["model_path"], probe_step)
        assert restored is not None and restored[2] == probe_step
        _print_probes(params, restored, gen, tag="_FRESH")
        return 0

    # same probe pair at every elastic generation start: the test compares
    # a resumed generation against a fresh restore of the same checkpoint
    restored = ckpt.restore_latest_valid(cfg["model_path"], strict=False)
    if restored is not None:
        _print_probes(ModelParameter(dict(cfg)), restored, gen)

    if step_delay:
        # stretch each step so the test's SIGKILL lands mid-training on a
        # box where the tiny model would otherwise finish in under a second
        orig_step = Trainer.step

        def slow_step(self, *a, **k):
            time.sleep(step_delay)
            return orig_step(self, *a, **k)

        Trainer.step = slow_step

    if straggle_rank == rank and straggle_delay > 0:
        # the wedge lives in the DATA FETCH (a storage stall), blocking
        # BEFORE this rank enters its next step: the step-entry progress
        # the lease publishes then lags the fleet — the shape the chief's
        # straggler detector keys on.  (A sleep inside the step call would
        # land after the entry marker and be indistinguishable from peers
        # blocked on this rank's own collective.)
        from homebrewnlp_tpu.data.inputs import Prefetcher
        orig_next = Prefetcher.__next__
        fetches = [0]

        def wedge_next(self):
            fetches[0] += 1
            if fetches[0] == straggle_step:
                time.sleep(straggle_delay)
            return orig_next(self)

        Prefetcher.__next__ = wedge_next

    from homebrewnlp_tpu.run.train_loop import (MEMBERSHIP_EXIT_CODE,
                                                PREEMPTED_EXIT_CODE, train)
    params = ModelParameter(dict(cfg))
    result = train(params, log_every=4)
    import jax
    if jax.process_index() == 0:
        print(f"ELASTIC_DONE g={gen} world={jax.process_count()} "
              f"final_step={result['final_step']}", flush=True)
    if result.get("membership_change"):
        return MEMBERSHIP_EXIT_CODE
    if result.get("preempted"):
        return PREEMPTED_EXIT_CODE
    return 0


if __name__ == "__main__":
    sys.exit(main())
