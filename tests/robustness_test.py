"""Run-level fault tolerance: preemption-safe shutdown (SIGTERM mid-train →
emergency checkpoint → resume to target), the non-finite-loss guard, the
Prefetcher error seam, and the fleet manager's clean-preemption exit code.
Deterministic: signals are raised from inside the step cadence (no subprocess
polling), divergence is forced analytically, manager sleeps are patched out."""
import json
import os
import signal
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from backend import make_params
from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.data.inputs import Prefetcher
from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer, TrainState
from homebrewnlp_tpu.train import checkpoint as ckpt
from run_manager_test import _load_run_manager


# ---- Prefetcher error seam -------------------------------------------------

def prefetcher_error_propagation_test():
    """Satellite: a fill-thread exception must re-raise in the consumer, not
    masquerade as dataset exhaustion (train() would exit cleanly at the
    wrong step)."""

    def gen():
        yield 1
        yield 2
        raise IOError("decode failed mid-stream")

    out = []
    with pytest.raises(IOError, match="decode failed"):
        for item in Prefetcher(gen(), depth=2):
            out.append(item)
    assert out == [1, 2]


def prefetcher_sentinel_not_dropped_test():
    """The done sentinel survives a full queue: a slow consumer must still
    see the end of a finite dataset instead of blocking forever."""
    import queue
    import time

    p = Prefetcher(iter(range(4)), depth=4)
    deadline = time.time() + 10  # watchdog only; normally instant
    while not p.q.full() and time.time() < deadline:
        pass  # wait for the fill thread to park with the queue FULL
    assert p.q.full()
    out = []
    while True:  # manual drain: queue.Empty instead of a hang on regression
        item = p.q.get(timeout=10)
        if item is p._done:
            break
        out.append(item)
    assert out == list(range(4))


# ---- non-finite loss guard -------------------------------------------------

def nonfinite_skip_preserves_state_test():
    """The jitted step SELECTS the pre-step state on a non-finite loss (the
    input state is donated, so the skip must happen on-device): variables
    and the step counter come back unchanged."""
    params = make_params(nonfinite_loss_tolerance=3, depth=1,
                         optimizer="learning_rate", learning_rate=0.1,
                         weight_decay=0.0)
    m = Model(params)
    tr = Trainer(params, m)
    rng = np.random.default_rng(0)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    batch = {"token_x": jnp.asarray(x),
             "token_y": jnp.asarray((x + 1) % params.vocab_size)}
    state = tr.init_state(batch)

    # finite path first: the guard must not block normal training
    state, metrics = tr.step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1

    poisoned = {k: jnp.full(np.shape(v), jnp.inf, jnp.float32)
                for k, v in state.variables.items()}
    state = TrainState(poisoned, state.opt_state, state.step)
    new_state, metrics = tr.step(state, batch, jax.random.PRNGKey(1))
    assert not np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1  # counter not advanced
    for k, v in new_state.variables.items():
        # kept = the poisoned +inf inputs; an applied update would be nan
        assert np.isinf(np.asarray(v, np.float32)).all(), k


# ---- in-process smoke-train helpers ----------------------------------------

def _write_records(tmp_path, n_files=2, tokens_per_file=2048):
    data_dir = tmp_path / "data"
    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(n_files):
        tokens = rng.integers(0, 32, tokens_per_file).astype(np.uint8)
        with RecordWriter(str(data_dir / f"p_{i}.tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))
    return data_dir


def _train_cfg(tmp_path, data_dir, **overrides):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 16, "features_per_head": 8, "heads": 2,
        "depth": 1, "train_batch_size": 8, "vocab_size": 32, "tpu_size": 8,
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    "feed_forward-in:relu"]}],
        "memory_reduction_strategy": "none",
        "optimizer": "adam-learning_rate", "learning_rate": 1e-3,
        "weight_decay": 0.0, "train_steps": 8, "interleaved_datasets": 2,
        "use_checkpointing": True, "steps_per_checkpoint": 1000,
        "max_checkpoints_keep": 3, "data_seed": 1337,
        "storage_retry_base_delay": 0.0,
        "dataset_configs": [{"path": str(data_dir / "*"), "type": "text",
                             "weight": 1}],
        "model_path": str(tmp_path / "run"),
    }
    cfg.update(overrides)
    return cfg


def sigterm_mid_train_resume_test(tmp_path, monkeypatch):
    """Tentpole acceptance: SIGTERM mid-smoke-train finishes the in-flight
    step, writes the emergency checkpoint, reports preempted; a fresh
    train() resumes from it and reaches the target step with the run log
    rewritten to the consumed counts.  The signal is raised from the metric
    cadence — deterministic, no subprocess, no polling."""
    import homebrewnlp_tpu.train.metrics as metrics_mod
    from homebrewnlp_tpu.run import train_loop as tl

    cfg = _train_cfg(tmp_path, _write_records(tmp_path))
    orig_log = metrics_mod.MetricLogger.log

    def log_then_preempt(self, step, *a, **k):
        orig_log(self, step, *a, **k)
        if step >= 3:
            signal.raise_signal(signal.SIGTERM)

    monkeypatch.setattr(metrics_mod.MetricLogger, "log", log_then_preempt)
    result = tl.train(ModelParameter(cfg), log_every=1)
    assert result["preempted"] is True
    stopped = result["final_step"]
    assert 0 < stopped < cfg["train_steps"]
    # the emergency checkpoint is on disk at the stopped step
    assert ckpt.latest_step(cfg["model_path"]) == stopped
    # the run log was rewritten to the steps actually consumed
    log = [json.loads(line) for line in
           open(os.path.join(cfg["model_path"], "DataLog.log"))]
    assert log[-1]["steps"] == result["steps"]

    # resume: no preemption hook, fresh params — reaches the target
    monkeypatch.setattr(metrics_mod.MetricLogger, "log", orig_log)
    result2 = tl.train(ModelParameter(cfg), log_every=100)
    assert result2["preempted"] is False
    assert result2["final_step"] == cfg["train_steps"]
    assert result2["steps"] == cfg["train_steps"] - stopped
    log = [json.loads(line) for line in
           open(os.path.join(cfg["model_path"], "DataLog.log"))]
    assert len(log) == 2 and log[-1]["steps"] == result2["steps"]


def nonfinite_abort_after_tolerance_test(tmp_path):
    """A diverged run (lr so large the z-loss overflows fp32) skips the
    poisoned updates, then aborts with NonFiniteLossError after N
    consecutive non-finite losses — leaving the emergency checkpoint at the
    LAST GOOD step."""
    from homebrewnlp_tpu.run import train_loop as tl

    cfg = _train_cfg(tmp_path, _write_records(tmp_path),
                     optimizer="learning_rate", learning_rate=1e30,
                     weight_standardisation=False,
                     weight_centralisation=False,
                     nonfinite_loss_tolerance=2, train_steps=20)
    with pytest.raises(tl.NonFiniteLossError, match="consecutive"):
        tl.train(ModelParameter(cfg), log_every=100)
    # the update at the diverged steps was skipped: the checkpoint holds the
    # last good state (step 1 — the first update is what diverged)
    assert ckpt.latest_step(cfg["model_path"]) == 1
    restored = ckpt.restore_latest_valid(cfg["model_path"])
    assert restored is not None and restored[2] == 1
    for arr in restored[0].values():
        assert np.isfinite(np.asarray(arr, np.float32)).all()


def all_corrupt_checkpoints_refuse_resume_test(tmp_path):
    """When checkpoints exist but NONE restores cleanly, train() must fail
    loudly instead of silently training from random init over the corpse
    (replaying the data log and pruning the old checkpoints)."""
    from homebrewnlp_tpu.run import train_loop as tl

    cfg = _train_cfg(tmp_path, _write_records(tmp_path), train_steps=2)
    tl.train(ModelParameter(cfg), log_every=100)
    run = cfg["model_path"]
    for d in os.listdir(run):
        if not d.startswith("ckpt_"):
            continue
        for f in os.listdir(os.path.join(run, d)):
            if f.startswith("arr_"):
                p = os.path.join(run, d, f)
                blob = bytearray(open(p, "rb").read())
                blob[0] ^= 0xFF
                open(p, "wb").write(bytes(blob))
    with pytest.raises(ckpt.CheckpointError, match="none restored"):
        tl.train(ModelParameter(cfg), log_every=100)


def train_mode_preempted_exit_code_test(monkeypatch):
    """modes.train_mode maps the preempted result onto the distinct exit
    code (143) that scripts/run_manager.py recognises."""
    from homebrewnlp_tpu.run import modes

    monkeypatch.setattr(modes, "train_loop",
                        lambda p: {"preempted": True, "steps": 3})
    assert modes.train_mode(None, None) == modes.PREEMPTED_EXIT_CODE
    monkeypatch.setattr(modes, "train_loop",
                        lambda p: {"preempted": False, "steps": 3})
    assert modes.train_mode(None, None) == 0


# ---- fleet manager: clean preemption is a relaunch, not a finish -----------

def manager_relaunches_on_preempted_exit_code_test(tmp_path, monkeypatch):
    """Satellite: rc=143 (clean preemption after the emergency checkpoint)
    relaunches the run WITHOUT consuming the crash budget — max_restarts=1
    would abandon the run if preemptions counted — and a later rc=0 still
    finishes it."""
    rm = _load_run_manager()
    monkeypatch.setattr(rm.time, "sleep", lambda *_: None)
    monkeypatch.setattr(rm.random, "randint", lambda *_: 0)

    d = str(tmp_path)
    # two clean preemptions, then success: with max_restarts=1 the run only
    # completes if preempted relaunches bypass the restart counter
    run_cmd = (f"n=$(cat {d}/n 2>/dev/null || echo 0); "
               f"echo $((n+1)) > {d}/n; "
               f"if [ \"$n\" -ge 2 ]; then exit 0; "
               f"else exit {rm.PREEMPTED_RC}; fi")
    args = types.SimpleNamespace(
        run_command=run_cmd, model_path=d, create_cmd="", health_cmd="",
        delete_cmd="", poll_interval=0, poll_jitter=0, stall_timeout=0,
        max_restarts=1)
    rm.Manager(args).run()

    log = open(os.path.join(d, "run.log")).read()
    assert log.count("clean preemption") == 2, log
    assert "max restarts exceeded" not in log, log
    assert "restarting (#" not in log, log  # crash budget untouched
    assert "training exited rc=0; done" in log, log
    assert open(f"{d}/n").read().strip() == "3"
