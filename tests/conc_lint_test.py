"""graft-lint ``--conc`` tier-1 suite: the host-concurrency audit is
clean at HEAD AND every rule + explorer invariant is proven to bite.

Mirrors tests/static_analysis_test.py: positive checks pin the repo
clean (the static lock-discipline pass over homebrewnlp_tpu/ + scripts/,
and the scenario library under every default seed); each AST rule then
gets a negative control — synthetic source carrying exactly the
violation the rule exists to catch — and the explorer gets synthetic
deadlock and lost-update harnesses it MUST catch, plus the found-race
regression: the GlobalPrefixIndex sync-vs-invalidate resurrection the
explorer surfaced, replayed against the real class with the
owner-generation guard on and off.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from homebrewnlp_tpu.analysis import conc_lint, interleave
from homebrewnlp_tpu.analysis.conc_lint import lint_source

pytestmark = pytest.mark.conc

REPO = os.path.join(os.path.dirname(__file__), "..")


# ---- positive: the repo at HEAD is clean -----------------------------------

def conc_static_repo_clean_test():
    """Static half (lock-guard, lock-blocking, lock-order, thread-hygiene,
    conc-registry) over the whole repo: zero findings at HEAD."""
    findings = conc_lint.lint_repo_conc()
    assert findings == [], "\n".join(str(f) for f in findings)


def explorer_scenarios_all_seeds_clean_test():
    """Every scenario holds its invariant under every default schedule
    seed — the ``--conc`` CLI's exploration half at HEAD."""
    violations = interleave.run_scenarios()
    assert violations == [], "\n".join(
        f"{n}@seed{s}: {m}" for n, s, m in violations)


# ---- lock-guard: negative controls -----------------------------------------

_REG = {
    "x.py::Box": {"lock": "_lock", "guards": {"_items": "rw",
                                              "count": "w"}},
}

_GUARD_BAD_READ = """\
class Box:
    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._items = []
        self.count = 0

    def peek(self):
        return self._items[-1]
"""

_GUARD_OK = """\
class Box:
    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._items = []
        self.count = 0

    def peek(self):
        with self._lock:
            return self._items[-1]
"""


def lock_guard_negative_control_test():
    findings = lint_source("x.py", _GUARD_BAD_READ, _REG)
    assert [f.rule for f in findings] == ["lock-guard"]
    assert "self._items" in findings[0].message
    assert "Box.peek" in findings[0].entry
    assert lint_source("x.py", _GUARD_OK, _REG) == []


def lock_guard_init_exempt_test():
    """Attribute establishment in __init__ precedes sharing — exempt
    (both snippets above assign guarded attrs unlocked in __init__)."""
    only_init = ("class Box:\n"
                 "    def __init__(self):\n"
                 "        self._lock = None\n"
                 "        self._items = []\n"
                 "        self.count = 0\n")
    assert lint_source("x.py", only_init, _REG) == []


def lock_guard_write_only_mode_test():
    """Mode "w": unlocked WRITES are flagged, unlocked READS pass (the
    benignly-racy Replica.inflight load-balance hint)."""
    bad_write = ("class Box:\n"
                 "    def bump(self):\n"
                 "        self.count += 1\n")
    findings = lint_source("x.py", bad_write, _REG)
    assert [f.rule for f in findings] == ["lock-guard"]
    ok_read = ("class Box:\n"
               "    def hint(self):\n"
               "        return self.count\n")
    assert lint_source("x.py", ok_read, _REG) == []


def lock_guard_other_object_prefix_test():
    """The prefix-held semantics track the HOLDER object: ``with m._lock``
    legalizes ``m._items``, not ``other._items``."""
    ok = ("def drain(m):\n"
          "    with m._lock:\n"
          "        return list(m._items)\n")
    assert lint_source("x.py", ok, _REG) == []
    bad = ("def steal(m, other):\n"
           "    with m._lock:\n"
           "        return list(other._items)\n")
    findings = lint_source("x.py", bad, _REG)
    assert [f.rule for f in findings] == ["lock-guard"]
    assert "other._items" in findings[0].message


def lock_guard_nested_def_resets_held_test():
    """A nested def runs LATER: locks held at definition time are not
    held at call time."""
    bad = ("class Box:\n"
           "    def sched(self):\n"
           "        with self._lock:\n"
           "            def later():\n"
           "                return self._items[-1]\n"
           "            return later\n")
    findings = lint_source("x.py", bad, _REG)
    assert [f.rule for f in findings] == ["lock-guard"]


def lock_guard_suppression_test():
    marked = ("class Box:\n"
              "    def peek(self):\n"
              "        return self._items[-1]  # graft-lint: "
              "allow[lock-guard]\n")
    assert lint_source("x.py", marked, _REG) == []
    line_above = ("class Box:\n"
                  "    def peek(self):\n"
                  "        # graft-lint: allow[lock-guard]\n"
                  "        return self._items[-1]\n")
    assert lint_source("x.py", line_above, _REG) == []
    # rule-scoped: an allow for a DIFFERENT rule does not blanket this one
    wrong = ("class Box:\n"
             "    def peek(self):\n"
             "        return self._items[-1]  # graft-lint: "
             "allow[lock-blocking]\n")
    assert [f.rule for f in lint_source("x.py", wrong, _REG)] \
        == ["lock-guard"]


# ---- lock-blocking: negative controls --------------------------------------

def lock_blocking_negative_control_test():
    bad = ("import time\n"
           "def hold(lock):\n"
           "    with lock:\n"
           "        time.sleep(1.0)\n")
    findings = lint_source("x.py", bad)
    assert [f.rule for f in findings] == ["lock-blocking"]
    assert "time.sleep" in findings[0].message
    ok = ("import time\n"
          "def hold(lock):\n"
          "    with lock:\n"
          "        pass\n"
          "    time.sleep(1.0)\n")
    assert lint_source("x.py", ok) == []


def lock_blocking_io_variants_test():
    for call in ("open('f')", "fs.open_('f')", "subprocess.run(cmd)",
                 "urllib.request.urlopen(u)", "self._q.get()",
                 "sock.recv(1)"):
        bad = (f"def hold(self, lock, fs, subprocess, urllib, cmd, u, "
               f"sock):\n"
               f"    with lock:\n"
               f"        {call}\n")
        findings = lint_source("x.py", bad)
        assert [f.rule for f in findings] == ["lock-blocking"], call
    # pure path helpers on the fs seam never block
    ok = ("def hold(lock, fs):\n"
          "    with lock:\n"
          "        return fs.join('a', 'b')\n")
    assert lint_source("x.py", ok) == []


def lock_blocking_suppression_test():
    marked = ("import time\n"
              "def hold(lock):\n"
              "    with lock:\n"
              "        time.sleep(1.0)  # graft-lint: "
              "allow[lock-blocking]\n")
    assert lint_source("x.py", marked) == []


# ---- lock-order: negative controls -----------------------------------------

def lock_order_cycle_negative_control_test():
    """Two functions nesting the same two locks in opposite order — the
    classic AB/BA deadlock — produce exactly one cycle finding."""
    bad = ("class M:\n"
           "    def ab(self):\n"
           "        with self._a_lock:\n"
           "            with self._b_lock:\n"
           "                pass\n"
           "    def ba(self):\n"
           "        with self._b_lock:\n"
           "            with self._a_lock:\n"
           "                pass\n")
    findings = lint_source("x.py", bad)
    assert [f.rule for f in findings] == ["lock-order"]
    assert "M._a_lock" in findings[0].entry
    assert "M._b_lock" in findings[0].entry
    # consistent order across every site: no cycle
    ok = bad.replace("with self._b_lock:\n            "
                     "with self._a_lock:",
                     "with self._a_lock:\n            "
                     "with self._b_lock:")
    assert lint_source("x.py", ok) == []


def lock_order_merges_external_edges_test():
    """order_findings is the shared checker: static edges + explorer
    edges + runtime-trace edges all fold into one graph."""
    assert conc_lint.order_findings({("A", "B"), ("B", "C")}) == []
    cyc = conc_lint.order_findings({("A", "B"), ("B", "C"), ("C", "A")})
    assert [f.rule for f in cyc] == ["lock-order"]


def runtime_trace_edges_roundtrip_test(tmp_path):
    """utils/locks.py JSONL rows parse into edges; a torn tail line is
    skipped; a cyclic observed order is flagged."""
    rows = [{"t": 1.0, "lock": "B", "held": ["A"], "wait_s": 0.0},
            {"t": 2.0, "lock": "A", "held": ["B"], "wait_s": 0.0}]
    p = tmp_path / "lock_trace_1234.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows)
                 + '\n{"torn": tru')
    edges = conc_lint.load_trace_edges(str(tmp_path))
    assert edges == {("A", "B"), ("B", "A")}
    findings = conc_lint.trace_findings(str(tmp_path))
    assert [f.rule for f in findings] == ["lock-order"]


def traced_lock_records_and_meters_test(tmp_path, monkeypatch):
    """End-to-end through utils/locks.py: a traced named lock writes
    acquire/release rows and registers the hbnlp_lock_* series."""
    monkeypatch.setenv("HBNLP_LOCK_TRACE", str(tmp_path))
    monkeypatch.setattr("homebrewnlp_tpu.utils.locks._sink", None)
    from homebrewnlp_tpu.telemetry.registry import Registry, set_registry
    from homebrewnlp_tpu.utils import locks
    r = Registry()
    old = set_registry(r)
    try:
        outer = locks.named_lock("T.outer")
        inner = locks.named_lock("T.inner")
        assert isinstance(outer, locks.TracedLock)
        with outer:
            with inner:
                pass
        edges = conc_lint.load_trace_edges(str(tmp_path))
        assert ("T.outer", "T.inner") in edges
        names = {s for s in map(str, r.snapshot())}
        assert "hbnlp_lock_acquire_total" in names
        assert "hbnlp_lock_wait_seconds" in names
        assert "hbnlp_lock_hold_seconds" in names
    finally:
        set_registry(old)


def named_lock_untraced_is_plain_primitive_test(monkeypatch):
    """Without HBNLP_LOCK_TRACE the factories return the raw primitives —
    zero overhead, Condition-compatible."""
    import threading
    monkeypatch.delenv("HBNLP_LOCK_TRACE", raising=False)
    from homebrewnlp_tpu.utils import locks
    assert isinstance(locks.named_lock("x"), type(threading.Lock()))
    assert isinstance(locks.named_rlock("x"), type(threading.RLock()))


# ---- thread-hygiene: negative controls -------------------------------------

def thread_hygiene_negative_controls_test():
    no_name = ("import threading\n"
               "t = threading.Thread(target=f, daemon=True)\n")
    assert [f.rule for f in lint_source("x.py", no_name)] \
        == ["thread-hygiene"]
    no_daemon = ("import threading\n"
                 "t = threading.Thread(target=f, name='w')\n")
    assert [f.rule for f in lint_source("x.py", no_daemon)] \
        == ["thread-hygiene"]
    no_join = ("import threading\n"
               "t = threading.Thread(target=f, name='w', daemon=False)\n")
    findings = lint_source("x.py", no_join)
    assert [f.rule for f in findings] == ["thread-hygiene"]
    assert "join" in findings[0].message
    ok_daemon = ("import threading\n"
                 "t = threading.Thread(target=f, name='w', daemon=True)\n")
    assert lint_source("x.py", ok_daemon) == []
    ok_joined = ("import threading\n"
                 "t = threading.Thread(target=f, name='w', daemon=False)\n"
                 "t.start()\n"
                 "t.join()\n")
    assert lint_source("x.py", ok_joined) == []


# ---- conc-registry: stale-entry controls -----------------------------------

def conc_registry_stale_entries_test(tmp_path):
    (tmp_path / "mod.py").write_text(
        "class Real:\n"
        "    def __init__(self):\n"
        "        self._lock = None\n"
        "        self._state = {}\n")
    ok = {"mod.py::Real": {"lock": "_lock", "guards": {"_state": "rw"}}}
    assert conc_lint.registry_findings(str(tmp_path), ok) == []
    stale = {
        "gone.py::Real": {"lock": "_lock", "guards": {}},
        "mod.py::Ghost": {"lock": "_lock", "guards": {}},
        "mod.py::Real": {"lock": "_lock",
                         "guards": {"_renamed_attr": "rw"}},
    }
    findings = conc_lint.registry_findings(str(tmp_path), stale)
    assert [f.rule for f in findings] == ["conc-registry"] * 3
    messages = "\n".join(f.message for f in findings)
    assert "gone.py" in messages and "Ghost" in messages \
        and "_renamed_attr" in messages


# ---- explorer: determinism + it must catch seeded bugs ---------------------

def explorer_seed_reproducible_test():
    """Same seed + same task code => byte-identical schedule and effects;
    different seeds diverge somewhere across a batch."""
    def run(seed):
        ex = interleave.Explorer(seed)
        lock = ex.lock("L")
        out = []

        def worker(tag):
            def fn():
                for i in range(3):
                    with lock:
                        out.append(f"{tag}{i}")
            return fn

        ex.task(worker("a"), "a")
        ex.task(worker("b"), "b")
        ex.run()
        return tuple(ex.trace), tuple(out)

    assert run(3) == run(3)
    assert len({run(s) for s in range(8)}) > 1


def explorer_catches_seeded_deadlock_test():
    """The synthetic AB/BA deadlock: some schedule MUST reach the cross
    hold-and-wait and raise DeadlockError naming both waiters."""
    def attempt(seed):
        ex = interleave.Explorer(seed)
        a, b = ex.lock("A"), ex.lock("B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        ex.task(ab, "t-ab")
        ex.task(ba, "t-ba")
        try:
            ex.run()
        except interleave.DeadlockError as e:
            assert len(e.waiters) == 2
            return True, ex
        return False, ex

    hits = [seed for seed in range(10) if attempt(seed)[0]]
    assert hits, "no schedule in 10 seeds reached the AB/BA deadlock"
    # and the observed edges alone reveal the cycle statically
    edges = set()
    for seed in range(10):
        edges |= attempt(seed)[1].order_edges
    assert conc_lint.order_findings(edges), \
        "explorer edges did not expose the AB/BA cycle"


def explorer_catches_seeded_lost_update_test():
    """An unlocked read-modify-write (the bug class the lock-guard rule
    bans): the explorer MUST find a schedule that loses an update; the
    locked version never does."""
    def attempt(seed, locked):
        ex = interleave.Explorer(seed)
        lock = ex.lock("L")
        box = {"n": 0}

        def bump():
            for _ in range(3):
                if locked:
                    with lock:
                        v = box["n"]
                        ex.step("rmw")
                        box["n"] = v + 1
                else:
                    v = box["n"]
                    ex.step("rmw")  # preemption inside the RMW window
                    box["n"] = v + 1

        ex.task(bump, "w1")
        ex.task(bump, "w2")
        ex.run()
        return box["n"]

    unlocked = [attempt(s, locked=False) for s in range(10)]
    assert any(n < 6 for n in unlocked), \
        f"no schedule lost an update: {unlocked}"
    assert all(attempt(s, locked=True) == 6 for s in range(10))


def explored_lock_reentrancy_test():
    """rlock() re-enters; a plain explored lock self-deadlocks instead of
    silently recursing."""
    ex = interleave.Explorer(0)
    r = ex.rlock("R")

    def nest():
        with r:
            with r:
                pass

    ex.task(nest, "n")
    ex.run()  # completes: reentrant

    ex2 = interleave.Explorer(0)
    plain = ex2.lock("P")

    def self_deadlock():
        with plain:
            with plain:
                pass

    ex2.task(self_deadlock, "n")
    with pytest.raises(interleave.DeadlockError):
        ex2.run()


# ---- the found race: sync_global_index vs invalidate-on-owner-death --------

def _gindex_resurrection_attempt(seed, with_gen):
    """Replay the exact race ``--conc`` surfaced against the REAL
    GlobalPrefixIndex: a syncer fetches replica 1's digest BEFORE the
    owner dies, then absorbs it AFTER invalidate_owner ran.  Without the
    owner-generation guard the stale digest resurrects the dead owner's
    entries; with it the absorb is dropped.  ``with_gen=False`` models
    the pre-fix absorb (no generation snapshot)."""
    from homebrewnlp_tpu.infer.router import GlobalPrefixIndex

    ex = interleave.Explorer(seed)
    g = GlobalPrefixIndex(block_tokens=4)
    interleave.wrap_lock(ex, g, "_lock", "gindex")
    g.record([1, 2, 3, 4], 1)
    state = {"killed": False, "fetch_before_kill": False}

    def syncer():
        gen = g.owner_generation(1)
        digest = {"block_tokens": 4, "paths": [[1, 2, 3, 4]]}
        # the transport fetch happened strictly before the kill iff the
        # killer has not run yet (killer flips the flag FIRST, so a torn
        # observation can only under-count violations, never invent one)
        state["fetch_before_kill"] = not state["killed"]
        ex.step("fetched")
        g.absorb(1, digest, gen=gen if with_gen else None)

    def killer():
        state["killed"] = True
        g.invalidate_owner(1)

    ex.task(syncer, "syncer")
    ex.task(killer, "killer")
    ex.run()
    owner, _ = g.lookup([1, 2, 3, 4])
    return owner == 1 and state["fetch_before_kill"]


def gindex_stale_absorb_race_regression_test():
    """Pre-fix semantics (absorb without a generation snapshot) MUST show
    the resurrection under some deterministic schedule — proof the
    explorer finds the real race — and the shipped generation guard
    closes it under every one of those schedules."""
    pre = [s for s in range(20)
           if _gindex_resurrection_attempt(s, with_gen=False)]
    assert pre, "no schedule reproduced the stale-absorb resurrection"
    post = [s for s in range(20)
            if _gindex_resurrection_attempt(s, with_gen=True)]
    assert post == [], \
        f"generation guard failed to close the race under seeds {post}"


def gindex_generation_guard_unit_test():
    """The fix's synchronous contract, no explorer: a gen snapshotted
    before invalidate_owner voids both record() and absorb()."""
    from homebrewnlp_tpu.infer.router import GlobalPrefixIndex

    g = GlobalPrefixIndex(block_tokens=4)
    stale = g.owner_generation(2)
    g.invalidate_owner(2)
    g.record([5, 6, 7, 8], 2, gen=stale)
    assert g.lookup([5, 6, 7, 8]) == (None, 0)
    g.absorb(2, {"block_tokens": 4, "paths": [[5, 6, 7, 8]]}, gen=stale)
    assert g.lookup([5, 6, 7, 8]) == (None, 0)
    # a current-generation claim still lands
    g.record([5, 6, 7, 8], 2, gen=g.owner_generation(2))
    assert g.lookup([5, 6, 7, 8])[0] == 2


# ---- the CLI ---------------------------------------------------------------

def graft_lint_cli_conc_clean_test():
    """`graft_lint.py --conc` exits 0 on the repo at HEAD (static rules +
    registry check + explorer sweep in one subprocess)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graft_lint.py"),
         "--conc"], capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout
    assert "[conc]" in proc.stdout


def graft_lint_cli_conc_reports_findings_test(monkeypatch):
    """Seeded conc findings drive exit 1 + the per-rule summary, same
    semantics as every other graft-lint family."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import graft_lint
    finally:
        sys.path.pop(0)
    fake = [conc_lint.Finding("lock-guard", "x.py:Box.peek", "seeded"),
            conc_lint.Finding("interleave", "s@seed0", "seeded")]
    monkeypatch.setattr(graft_lint, "run_conc", lambda: list(fake))
    assert graft_lint.main(["--conc"]) == 1
    monkeypatch.setattr(graft_lint, "run_conc", lambda: [])
    assert graft_lint.main(["--conc"]) == 0
