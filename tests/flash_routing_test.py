"""Flash-attention routing in the model's dot-product path (spatial.py).

The flash route must match the dense softmax path numerically (same loss,
same updated params after a step) — including per-device under shard_map on
data x model meshes — and must not fire where the dense map is semantically
required (bias flags, decode, sequence-/pipe-sharded meshes).
"""
import numpy as np
import pytest

from backend import make_params  # noqa: F401
from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer


def _cfg(flash, flags="dot_product-context", **over):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 128, "features_per_head": 16, "heads": 4,
        "depth": 2, "train_batch_size": 4, "vocab_size": 64,
        "memory_reduction_strategy": "none",
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    f"attention-{flags}"]}],
        "optimizer": "sm3-learning_rate",
        "learning_rate": 0.01, "weight_decay": 0.0,
        "calculation_dtype": "float32", "storage_dtype": "float32",
        "slice_dtype": "float32", "use_flash_attention": flash,
        "model_path": "/tmp/flash_route_test",
    }
    cfg.update(over)
    return ModelParameter(cfg)


def _step(flash, flags="dot_product-context", **over):
    import jax
    params = _cfg(flash, flags, **over)
    model = Model(params)
    trainer = Trainer(params, model)
    rng = np.random.default_rng(0)
    import jax.numpy as jnp
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    batch = {"token_x": jnp.asarray(x),
             "token_y": jnp.asarray((x + 1) % params.vocab_size)}
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch, rng=jax.random.PRNGKey(3))
    return state, metrics


@pytest.mark.parametrize("flags", ["dot_product-context",
                                   "dot_product-positional-absolute",
                                   "dot_product-embedded-absolute-shared_key_value",
                                   "dot_product-context-input_as_value"])
def flash_route_matches_dense_test(flags):
    state_d, metrics_d = _step(False, flags)
    state_f, metrics_f = _step(True, flags)
    np.testing.assert_allclose(float(metrics_f["loss"]),
                               float(metrics_d["loss"]), rtol=1e-5)
    for name in state_d.variables:
        np.testing.assert_allclose(
            np.asarray(state_f.variables[name]),
            np.asarray(state_d.variables[name]), rtol=1e-4, atol=1e-6,
            err_msg=f"{flags}: {name}")


def flash_sharded_matches_unsharded_test():
    # data x model mesh: the shard_map flash route (batch on 'data', heads on
    # 'model') must match the unmeshed step exactly
    import jax
    from homebrewnlp_tpu.core import sharding as shardlib
    params = _cfg(True, "dot_product-context", heads=4,
                  mesh_shape_override={"data": 2, "model": 2}, tpu_size=4)
    model = Model(params)
    mesh = shardlib.build_mesh(params, jax.devices()[:4])
    trainer = Trainer(params, model, mesh=mesh)
    rng = np.random.default_rng(0)
    import jax.numpy as jnp
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    batch = {"token_x": jnp.asarray(x),
             "token_y": jnp.asarray((x + 1) % params.vocab_size)}
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch, rng=jax.random.PRNGKey(3))
    state_u, metrics_u = _step(True, "dot_product-context", heads=4)
    np.testing.assert_allclose(float(metrics["loss"]),
                               float(metrics_u["loss"]), rtol=1e-5)
    # updated params validate the shard_map backward, not just the forward
    for name in state_u.variables:
        np.testing.assert_allclose(
            np.asarray(state.variables[name]),
            np.asarray(state_u.variables[name]), rtol=2e-4, atol=1e-6,
            err_msg=name)


def flash_skips_biased_map_test():
    # bias-map attention needs the dense [s, s] map; both settings must agree
    # because the flash route declines these flags
    flags = "dot_product-context-biased_softmax-absolute"
    state_d, metrics_d = _step(False, flags)
    state_f, metrics_f = _step(True, flags)
    np.testing.assert_allclose(float(metrics_f["loss"]),
                               float(metrics_d["loss"]), rtol=1e-6)


def flash_indivisible_gate_precedes_qkv_test():
    """Shard-divisibility bail must happen BEFORE qkv extraction: bailing
    after it has consumed scoped parameter counters (and prefill kv-cache
    name counters), so the dense fallback would resolve names init never
    created (KeyError) and double-capture prefill caches.  heads=2 on
    model=4 forces the bail; the step must run and match the unmeshed
    dense result."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from homebrewnlp_tpu.core import sharding as shardlib
    params = _cfg(True, "dot_product-context", heads=2,
                  features_per_head=32,
                  mesh_shape_override={"data": 2, "model": 4}, tpu_size=8)
    model = Model(params)
    mesh = shardlib.build_mesh(params, jax.devices()[:8])
    trainer = Trainer(params, model, mesh=mesh)
    rng = np.random.default_rng(0)
    import jax.numpy as jnp
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    batch = {"token_x": jnp.asarray(x),
             "token_y": jnp.asarray((x + 1) % params.vocab_size)}
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch, rng=jax.random.PRNGKey(3))
    _, metrics_u = _step(False, "dot_product-context", heads=2,
                         features_per_head=32)
    np.testing.assert_allclose(float(metrics["loss"]),
                               float(metrics_u["loss"]), rtol=1e-5)
