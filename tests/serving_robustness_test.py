"""Serving-path fault tolerance (marker: serving; docs/RELIABILITY.md
'Serving').

Unit sweep: breaker state machine, HTTP-edge validation, truncation
surfacing, per-row batch isolation, deadline shedding with the
exactly-one-answer invariant, and a no-fault smoke test pinning the guarded
path byte-identical to a direct handler call.

Integration sweep (real spawn subprocess + Manager IPC, stub decode so no
device work): /health + /ready answering from the HTTP child while the
device loop is wedged in a decode, 429 under queue pressure, 504 on expiry,
the breaker open -> fast-fail -> probe -> reclose cycle, and survival of a
SIGKILLed HTTP child mid-traffic.  All device-free (tier-1 on CPU)."""
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from backend import make_params
from homebrewnlp_tpu.infer import rest_api, serving_guard
from homebrewnlp_tpu.infer.interface import Tokenizer
from homebrewnlp_tpu.utils.fault_injection import FaultyInterface

pytestmark = pytest.mark.serving


def _serve_params(**kw):
    cfg = dict(vocab_size=256, serve_batch_size=4, serve_queue_limit=8,
               serve_request_deadline_s=10.0, serve_breaker_threshold=3,
               serve_breaker_cooldown_s=0.5, serve_child_max_restarts=3,
               serve_child_restart_backoff_s=0.1)
    cfg.update(kw)
    return make_params(**cfg)


class _StubInterface:
    """Interface-shaped stub with a deterministic, device-free 'decode' so
    the serving stack's control flow is testable in milliseconds."""

    def __init__(self, params):
        self.params = params
        self.tokenizer = Tokenizer(params)
        self.decode_calls = 0

    @property
    def prompt_capacity(self):
        return self.params.sequence_length // self.params.token_patch_size - 1

    def _one(self, tokens, response_len):
        seq = self.params.sequence_length // self.params.token_patch_size
        toks = np.asarray(tokens, np.int32).reshape(-1)[:seq - 1]
        end = seq if response_len is None else min(seq,
                                                   len(toks) + int(response_len))
        out = np.zeros(end, np.int32)
        out[:len(toks)] = toks
        out[len(toks):] = np.arange(end - len(toks))
        return out

    def complete_tokens(self, tokens, temperature=0.0, response_len=None,
                        seed=0, top_k=None, top_p=None,
                        repetition_penalty=None):
        self.decode_calls += 1
        return self._one(tokens, response_len)

    def complete_tokens_batch(self, token_lists, temperatures=None,
                              response_lens=None, seed=0, top_ks=None,
                              top_ps=None, rep_penalties=None):
        self.decode_calls += 1
        rls = response_lens or [None] * len(token_lists)
        return [self._one(t, rl) for t, rl in zip(token_lists, rls)]

    def complete(self, query, temperature=0.0, response_len=None, seed=0,
                 top_k=None, top_p=None, repetition_penalty=None):
        toks = self.tokenizer.encode(query)
        out = self.complete_tokens(toks, temperature, response_len, seed)
        return self.tokenizer.decode(out[min(len(toks), self.prompt_capacity):])

    def decode_path(self, width=None):
        return {"loop": "stub"}


# ---------------------------------------------------------------- unit sweep

def breaker_state_machine_test():
    t = [0.0]
    brk = serving_guard.CircuitBreaker(threshold=3, cooldown_s=10.0,
                                       clock=lambda: t[0])
    assert brk.tick() == "closed"
    brk.record_failure()
    brk.record_failure()
    assert brk.state == "closed"      # below threshold
    brk.record_success()              # success resets the CONSECUTIVE count
    brk.record_failure()
    brk.record_failure()
    assert brk.state == "closed"
    brk.record_failure()
    assert brk.state == "open" and brk.opened == 1
    assert brk.retry_after() == 10.0
    brk.record_failure()              # straggler failures while open (e.g.
    assert brk.opened == 1            # per-row retries) don't re-trip or
    assert brk.open_until == 10.0     # extend the cooldown
    t[0] = 9.9
    assert brk.tick() == "open"
    t[0] = 10.0
    assert brk.tick() == "half_open"
    brk.record_failure()              # failed probe reopens, fresh cooldown
    assert brk.state == "open" and brk.open_until == 20.0 and brk.opened == 2
    t[0] = 20.0
    assert brk.tick() == "half_open"
    brk.record_success()              # successful probe recloses
    assert brk.state == "closed" and brk.failures == 0
    off = serving_guard.CircuitBreaker(0, 1.0, clock=lambda: t[0])
    for _ in range(10):
        off.record_failure()
    assert off.tick() == "closed"     # threshold 0 = breaker disabled


def edge_validation_test():
    cfg = serving_guard.serve_config(_serve_params())
    assert cfg["seq_tokens"] == 16 and cfg["max_response_tokens"] == 0

    def rejected(path, body):
        try:
            serving_guard.validate_request(path, body, cfg)
            return False
        except serving_guard.HTTPStatusError as e:
            assert e.status == 400 and e.payload["code"] == "bad_request"
            return True

    assert rejected("/completion", [])                      # non-object body
    assert rejected("/token_completion", {"tokens": "bogus"})
    assert rejected("/token_completion", {"tokens": list(range(17))})
    serving_guard.validate_request("/token_completion",
                                   {"tokens": list(range(16))}, cfg)
    assert rejected("/completion", {"prompt": "x" * 17})    # byte-level vocab
    assert rejected("/completion", {"prompt": 7})
    # default cap 0 = off: over-asks clamp later instead of rejecting
    serving_guard.validate_request("/completion",
                                   {"prompt": "a", "max_tokens": 99}, cfg)
    capped = serving_guard.serve_config(
        _serve_params(serve_max_response_tokens=8))
    try:
        serving_guard.validate_request(
            "/completion", {"prompt": "a", "max_tokens": 99}, capped)
        raise AssertionError("expected 400 above the configured cap")
    except serving_guard.HTTPStatusError as e:
        assert e.status == 400
    assert rejected("/completion", {"prompt": "a", "max_tokens": -1})
    assert rejected("/completion", {"prompt": "a", "max_tokens": "lots"})
    assert rejected("/completion", {"prompt": "a",
                                    "max_tokens": float("inf")})
    serving_guard.validate_request("/completion",
                                   {"prompt": "a", "max_tokens": 5}, cfg)
    assert rejected("/encode", {"prompt": "a", "timeout_s": 0})
    assert rejected("/encode", {"prompt": "a", "timeout_s": "soon"})
    serving_guard.validate_request("/encode",
                                   {"prompt": "a", "timeout_s": 2.5}, cfg)
    # client timeout_s is honored below the cap, capped above it
    assert serving_guard.request_deadline_s({"timeout_s": 3}, cfg) == 3.0
    assert serving_guard.request_deadline_s({"timeout_s": 1e9}, cfg) == 10.0
    assert serving_guard.request_deadline_s({}, cfg) == 10.0


def child_probe_payload_test():
    """Pure-function /health + /ready semantics: half_open reports READY
    (probe traffic must reach the breaker to reclose it), open does not;
    /health flips to 'stale' past the opt-in heartbeat-age threshold."""
    cfg = serving_guard.serve_config(_serve_params())
    state = {"hb": 100.0, "model_loaded": True, "breaker": "half_open"}
    ok, payload = serving_guard.child_ready(state, 0, cfg)
    assert ok and payload["ready"] is True
    state["breaker"] = "open"
    ok, payload = serving_guard.child_ready(state, 0, cfg)
    assert not ok and payload["reasons"] == ["circuit breaker open"]
    # heartbeat staleness: off by default, 503-shaped "stale" when enabled
    h = serving_guard.child_health(state, 0, cfg, clock=lambda: 1000.0)
    assert h["status"] == "ok" and h["heartbeat_age_s"] == 900.0
    cfg2 = serving_guard.serve_config(
        _serve_params(serve_heartbeat_stale_s=30.0))
    assert serving_guard.child_health(state, 0, cfg2,
                                      clock=lambda: 1000.0
                                      )["status"] == "stale"
    assert serving_guard.child_health(state, 0, cfg2,
                                      clock=lambda: 120.0
                                      )["status"] == "ok"


def poll_backoff_test():
    delays = []
    d = 0.0
    for _ in range(12):
        d = serving_guard.poll_delay(d)
        delays.append(d)
    assert delays[0] == pytest.approx(0.003)   # starts near 2 ms
    assert delays[-1] == 0.05                  # grows to the 50 ms ceiling
    assert all(b >= a for a, b in zip(delays, delays[1:]))


def truncated_prompt_flag_test():
    stub = _StubInterface(_serve_params())
    handlers = rest_api._handlers(stub)
    out = handlers["/token_completion"]({"tokens": list(range(16))})
    assert out["truncated"] is True and out["prompt_tokens_kept"] == 15
    out = handlers["/token_completion"]({"tokens": [1, 2, 3]})
    assert "truncated" not in out and "prompt_tokens_kept" not in out
    out = handlers["/completion"]({"prompt": "x" * 16})
    assert out["truncated"] is True and out["prompt_tokens_kept"] == 15
    assert "truncated" not in handlers["/completion"]({"prompt": "hi"})
    outs = rest_api._complete_batch(stub, [
        ("/token_completion", {"tokens": list(range(16))}),
        ("/token_completion", {"tokens": [5]})])
    assert outs[0]["truncated"] is True and outs[0]["prompt_tokens_kept"] == 15
    assert "truncated" not in outs[1]


def response_cap_bounds_default_decode_test():
    """serve_max_response_tokens bounds EVERY completion's decode length —
    including requests that omit max_tokens (or send 0), which previously
    meant 'decode the full sequence'."""
    stub = _StubInterface(_serve_params(serve_max_response_tokens=4))
    handlers = rest_api._handlers(stub)
    out = handlers["/token_completion"]({"tokens": [1, 2]})
    assert len(out["tokens"]) == 6          # 2 prompt + 4 capped generation
    out = handlers["/token_completion"]({"tokens": [1, 2], "max_tokens": 0})
    assert len(out["tokens"]) == 6
    out = handlers["/token_completion"]({"tokens": [1, 2], "max_tokens": 3})
    assert len(out["tokens"]) == 5          # explicit below the cap wins
    # cap off (default): full sequence, unchanged
    stub = _StubInterface(_serve_params())
    out = rest_api._handlers(stub)["/token_completion"]({"tokens": [1, 2]})
    assert len(out["tokens"]) == 16


def batch_parse_misalignment_test():
    """A row rejected mid-parse (bad filter AFTER its tokens were read) must
    not shift its neighbors onto the wrong prompts: each surviving row
    decodes its OWN prompt."""
    stub = _StubInterface(_serve_params())
    outs = rest_api._complete_batch(stub, [
        ("/token_completion", {"tokens": [9, 9], "repetition_penalty": 0}),
        ("/token_completion", {"tokens": [1, 2, 3]})])
    assert outs[0]["_status"] == 400 and "_error" in outs[0]
    assert outs[1]["tokens"][:3] == [1, 2, 3]


def batch_row_isolation_test():
    """A failed batch decode retries per row: the poisoned request fails
    alone (500), its co-batched neighbors still get real answers, and the
    breaker's failure counter records the events."""
    params = _serve_params()
    faulty = FaultyInterface(_StubInterface(params), fail_at={0, 2})
    guard = serving_guard.ServingGuard(params)
    items = [("/token_completion", {"tokens": [1, 2]}),
             ("/token_completion", {"tokens": [3]}),
             ("/token_completion", {"tokens": [4, 5, 6]})]
    # call 0 fails the whole batch; calls 1..3 are the per-row retries with
    # the middle row (call 2) poisoned
    outs = rest_api._complete_batch(faulty, items, guard=guard)
    assert outs[0]["tokens"][:2] == [1, 2]
    assert outs[1].get("_status") == 500 and "_error" in outs[1]
    assert outs[2]["tokens"][:3] == [4, 5, 6]
    assert guard.decode_failures == 2   # the batch event + the poisoned row
    assert guard.breaker.state == "closed"  # row successes reset the streak


def process_group_deadline_and_answer_test():
    """Expired requests are shed AND answered (504); every request in the
    group gets exactly one response."""
    stub = _StubInterface(_serve_params())
    handlers = rest_api._handlers(stub)
    guard = serving_guard.ServingGuard(stub.params)
    responses = {}
    now = time.monotonic()
    group = [("expired", "/token_completion", {"tokens": [1]}, now - 1),
             ("live", "/token_completion", {"tokens": [2]}, now + 60),
             ("enc", "/encode", {"prompt": "hi"}, now + 60)]
    rest_api._process_group(handlers, stub, guard, responses, group)
    assert set(responses) == {"expired", "live", "enc"}
    assert responses["expired"]["r"]["_status"] == 504
    assert responses["expired"]["r"]["_code"] == "timeout"
    assert responses["live"]["r"]["tokens"][0] == 2
    assert responses["enc"]["r"]["tokens"] == [104, 105]
    assert stub.decode_calls == 1       # the expired request cost no decode
    # malformed-but-valid-JSON element values (np parse TypeError) are
    # client errors: 400, and NEVER counted toward the breaker
    rest_api._process_group(handlers, stub, guard, responses,
                            [("bad", "/token_completion",
                              {"tokens": [None]}, now + 60)])
    assert responses["bad"]["r"]["_status"] == 400
    assert guard.decode_failures == 0
    assert guard.breaker.state == "closed"


def single_path_decode_error_classification_test():
    """Single-request path: malformed input answers 400 without touching
    the breaker, but a decode-side exception — even a ValueError — is a
    server fault (500) the breaker must see."""
    params = _serve_params(serve_breaker_threshold=1)
    stub = _StubInterface(params)

    def bad_decode(*a, **k):
        raise ValueError("device-side shape mismatch")

    stub.complete_tokens = bad_decode
    handlers = rest_api._handlers(stub)
    guard = serving_guard.ServingGuard(params)
    responses = {}
    now = time.monotonic()
    rest_api._process_group(handlers, stub, guard, responses,
                            [("ok-parse", "/token_completion",
                              {"tokens": [1]}, now + 60)])
    assert responses["ok-parse"]["r"]["_status"] == 500
    assert guard.decode_failures == 1 and guard.breaker.state == "open"


def breaker_shed_and_probe_test():
    """Driven entirely by a fake clock: the breaker opens at the threshold,
    open sheds with 503 + retry-after without touching decode, half-open
    admits exactly ONE probe, and a successful probe recloses."""
    params = _serve_params(serve_breaker_threshold=2,
                           serve_breaker_cooldown_s=5.0)
    t = [100.0]
    faulty = FaultyInterface(_StubInterface(params), fail_at={0, 1})
    handlers = rest_api._handlers(faulty)
    guard = serving_guard.ServingGuard(params, clock=lambda: t[0])
    responses = {}

    def send(rid):
        rest_api._process_group(
            handlers, faulty, guard, responses,
            [(rid, "/token_completion", {"tokens": [1]}, t[0] + 60)],
            clock=lambda: t[0])
        return responses[rid]["r"]

    assert send("a")["_status"] == 500
    assert send("b")["_status"] == 500
    assert guard.breaker.state == "open"
    out = send("c")
    assert out["_status"] == 503 and out["_retry_after"] == 5.0
    assert faulty.calls == 2            # the shed request never hit decode
    t[0] += 5.0
    group = [("probe", "/token_completion", {"tokens": [7]}, t[0] + 60),
             ("extra", "/token_completion", {"tokens": [8]}, t[0] + 60)]
    rest_api._process_group(handlers, faulty, guard, responses, group,
                            clock=lambda: t[0])
    assert responses["extra"]["r"]["_status"] == 503    # only ONE probe
    assert responses["probe"]["r"]["tokens"][0] == 7
    assert guard.breaker.state == "closed"
    assert send("d")["tokens"][0] == 1


def guarded_happy_path_smoke_test():
    """No faults: the guarded device-loop path returns byte-identical JSON
    to a direct handler call, and /completion matches the pre-guard
    ``InterfaceWrapper.complete`` output."""
    from rest_api_test import _interface
    interface = _interface()
    handlers = rest_api._handlers(interface)
    body = {"tokens": [1, 2, 3], "temperature": 0.0}
    direct = handlers["/token_completion"](dict(body))
    guard = serving_guard.ServingGuard(interface.params)
    responses = {}
    now = time.monotonic()
    rest_api._process_group(handlers, interface, guard, responses,
                            [("rid", "/token_completion", dict(body),
                              now + 600)])
    assert (json.dumps(responses["rid"]["r"], sort_keys=True)
            == json.dumps(direct, sort_keys=True))
    direct = handlers["/completion"]({"prompt": "ab", "temperature": 0.0})
    assert direct["completion"] == interface.complete("ab", 0.0)
    assert "truncated" not in direct


# -------------------------------------------------------- integration sweep

def _spawn_serve(interface, control=None):
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve,
                         args=(interface.params, interface),
                         kwargs={"port": port, "isolate": True, "stop": stop,
                                 "control": control},
                         daemon=True)
    t.start()
    return port, stop, t


def _post(port, path, payload, timeout=30, connect_retries=120):
    """POST returning (status, json_body, headers); retries only CONNECTION
    failures (server not up yet / child mid-restart) — an HTTP error status
    is a final answer and returns immediately."""
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=json.dumps(payload).encode(),
                                 headers={"Content-Type": "application/json"})
    for _ in range(connect_retries):
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read()), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read()), dict(e.headers)
        except (ConnectionError, urllib.error.URLError, OSError):
            time.sleep(0.1)
    raise TimeoutError(path)


def health_liveness_under_wedged_decode_test():
    """/health and /ready answer from the HTTP child without crossing the
    device loop: they stay responsive while the loop is wedged inside a
    decode; queued traffic hits the admission budget (429) and per-request
    deadlines (504); every accepted request gets exactly one answer."""
    # limit 3 = the wedged in-decode request (in-flight counts toward the
    # budget) + the two queued behind it
    params = _serve_params(serve_queue_limit=3, serve_request_deadline_s=8.0,
                           serve_breaker_threshold=0, serve_batch_size=1,
                           serve_max_response_tokens=16)
    release = threading.Event()
    faulty = FaultyInterface(_StubInterface(params), block_on=release,
                             block_timeout_s=30.0)
    port, stop, t = _spawn_serve(faulty)
    try:
        status, out, _ = _post(port, "/health", {})
        assert status == 200 and out["status"] == "ok"
        assert out["decode_path"] == {"loop": "stub"}
        status, out, _ = _post(port, "/ready", {})
        assert status == 200 and out["ready"] is True
        # k8s-style GET probes work too
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/health",
                                    timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/ready",
                                    timeout=10) as r:
            assert json.loads(r.read())["ready"] is True
        # HTTP-edge rejections cost no device call while the loop is healthy
        status, out, _ = _post(port, "/token_completion",
                               {"tokens": [1], "max_tokens": 1000})
        assert status == 400 and out["code"] == "bad_request"
        status, out, _ = _post(port, "/token_completion",
                               {"tokens": [1], "pad": "x" * (2 << 20)})
        assert status == 400 and out["code"] == "bad_request"  # body cap
        status, out, _ = _post(port, "/token_completion",
                               {"tokens": [1], "repetition_penalty": 0})
        assert status == 400 and out["code"] == "bad_request"  # device-side
        assert faulty.calls == 0

        results = {}

        def bg(name, payload):
            results[name] = _post(port, "/token_completion", payload,
                                  timeout=25)

        th1 = threading.Thread(target=bg, args=("wedged", {"tokens": [1]}),
                               daemon=True)
        th1.start()
        deadline = time.monotonic() + 10
        while faulty.calls < 1:      # the decode call is now in flight
            assert time.monotonic() < deadline
            time.sleep(0.01)
        t0 = time.monotonic()
        status, out, _ = _post(port, "/health", {})
        assert status == 200 and time.monotonic() - t0 < 2.0
        # fill the pending budget behind the wedged decode
        th2 = threading.Thread(target=bg, args=("queued", {"tokens": [2]}),
                               daemon=True)
        th2.start()
        th3 = threading.Thread(target=bg,
                               args=("expiring", {"tokens": [3],
                                                  "timeout_s": 1.0}),
                               daemon=True)
        th3.start()
        deadline = time.monotonic() + 10
        while _post(port, "/health", {})[1]["queue_depth"] < 3:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        status, out, headers = _post(port, "/token_completion",
                                     {"tokens": [9]})
        assert status == 429 and out["code"] == "overloaded"
        assert "Retry-After" in headers
        status, out, _ = _post(port, "/ready", {})
        assert status == 503 and out["ready"] is False
        th3.join(timeout=15)         # its 1 s deadline expires while queued
        assert results["expiring"][0] == 504
        assert results["expiring"][1]["code"] == "timeout"
        time.sleep(0.2)              # ensure the expiry predates the release
        release.set()
        th1.join(timeout=15)
        th2.join(timeout=15)
        assert results["wedged"][0] == 200
        assert results["queued"][0] == 200
        assert results["wedged"][1]["tokens"][0] == 1
    finally:
        release.set()
        stop.set()
        t.join(timeout=15)
    assert not t.is_alive()


def malformed_transport_rejected_test():
    """The fallback HTTP server answers chunked bodies and malformed
    Content-Length with a structured 400 instead of silently treating the
    body as empty (chunked) or crashing the handler (bad length)."""
    import socket
    stub = _StubInterface(_serve_params())
    port, stop, t = _spawn_serve(stub)

    def raw(request_bytes):
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.sendall(request_bytes)
        chunks = []
        try:
            while True:
                d = c.recv(4096)
                if not d:
                    break
                chunks.append(d)
        except socket.timeout:
            pass
        c.close()
        return b"".join(chunks)

    try:
        _post(port, "/health", {})      # wait for the server to come up
        out = raw(b"POST /completion HTTP/1.1\r\nHost: x\r\n"
                  b"Transfer-Encoding: chunked\r\n\r\n"
                  b"5\r\nhello\r\n0\r\n\r\n")
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:80]
        assert b"bad_request" in out
        out = raw(b"POST /completion HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Length: abc\r\n\r\n")
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:80]
        assert b"bad_request" in out
        # negative length would read(-N) to EOF: a held-open connection
        # would pin the handler thread and bypass the body-size cap
        out = raw(b"POST /completion HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Length: -1\r\n\r\nxxxx")
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:80]
    finally:
        stop.set()
        t.join(timeout=15)


def breaker_cycle_integration_test():
    """End to end over real IPC: consecutive decode failures open the
    breaker, 503s fast-fail well under the request deadline with a
    Retry-After, a single successful probe recloses it, and traffic
    resumes."""
    params = _serve_params(serve_breaker_threshold=2,
                           serve_breaker_cooldown_s=1.0, serve_batch_size=1)
    faulty = FaultyInterface(_StubInterface(params), fail_at={0, 1})
    port, stop, t = _spawn_serve(faulty)
    try:
        status, out, _ = _post(port, "/token_completion", {"tokens": [1]})
        assert status == 500 and out["code"] == "server_error"
        status, out, _ = _post(port, "/token_completion", {"tokens": [2]})
        assert status == 500
        t0 = time.monotonic()
        status, out, headers = _post(port, "/token_completion",
                                     {"tokens": [3]})
        elapsed = time.monotonic() - t0
        assert status == 503 and out["code"] == "unavailable"
        assert elapsed < 0.5, elapsed    # fast-fail target is < 100 ms
        assert "Retry-After" in headers
        _, health, _ = _post(port, "/health", {})
        assert health["breaker"] in ("open", "half_open")
        assert health["decode_failures"] == 2
        assert health["breaker_trips"] == 1
        status, ready, _ = _post(port, "/ready", {})
        assert status == 503 and ready["ready"] is False
        assert faulty.calls == 2         # shed requests never reached decode
        time.sleep(1.2)                  # cooldown elapses
        deadline = time.monotonic() + 10
        while True:                      # probe; tolerate a stale open state
            status, out, _ = _post(port, "/token_completion", {"tokens": [7]})
            if status == 200:
                break
            assert status == 503
            assert time.monotonic() < deadline
            time.sleep(0.2)
        assert out["tokens"][0] == 7
        deadline = time.monotonic() + 5
        while _post(port, "/health", {})[1]["breaker"] != "closed":
            assert time.monotonic() < deadline
            time.sleep(0.05)
        status, out, _ = _post(port, "/token_completion", {"tokens": [9]})
        assert status == 200             # reclosed: traffic flows again
    finally:
        stop.set()
        t.join(timeout=15)


def http_child_kill_relaunch_test():
    """A SIGKILLed HTTP subprocess is relaunched with bounded backoff: the
    device loop survives, the child pid changes, /health counts the
    restart, and completions flow end to end afterwards."""
    params = _serve_params(serve_child_max_restarts=3,
                           serve_child_restart_backoff_s=0.1,
                           serve_batch_size=1)
    stub = _StubInterface(params)
    control = {}
    port, stop, t = _spawn_serve(stub, control=control)
    try:
        status, out, _ = _post(port, "/encode", {"prompt": "hi"})
        assert status == 200 and out["tokens"] == [104, 105]
        pid1 = control["child_pid"]
        os.kill(pid1, signal.SIGKILL)
        status, out, _ = _post(port, "/encode", {"prompt": "hi"},
                               connect_retries=300)
        assert status == 200 and out["tokens"] == [104, 105]
        assert control["child_pid"] != pid1
        _, health, _ = _post(port, "/health", {})
        assert health["child_restarts"] == 1
        status, out, _ = _post(port, "/token_completion", {"tokens": [1, 2]})
        assert status == 200 and out["tokens"][:2] == [1, 2]
        assert t.is_alive()              # the device loop never died
    finally:
        stop.set()
        t.join(timeout=15)
    assert not t.is_alive()
