"""scan-over-layers (blocks.py rev_scan/momentum_scan/_plain_scan).

The scanned body must be numerically identical to the unrolled custom-vjp
sequences: same loss, same gradients, same updated parameters after an
optimizer step — for every memory-reduction strategy, with cross-layer
``shared`` weights in the mix (their gradients accumulate in the scan carry).
"""
import numpy as np
import pytest

from backend import make_params  # noqa: F401  (ensures test env is set up)
from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer

BLOCKS = [
    {"layer": ["norm-shift-scale-features-group",
               "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:shift-mid:scale-mid:features"]},
    {"layer": ["norm-shift-scale-features-group",
               "attention-biased_attention_map-absolute-input_as_value-shared",
               "norm-shift-scale-features-group", "activation-gelu",
               "attention-biased_attention_map-absolute-input_as_value-shared"]}]


def _cfg(strategy, scan, **over):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 16, "heads": 4,
        "depth": 3, "train_batch_size": 4, "vocab_size": 64,
        "memory_reduction_strategy": strategy, "block_config": BLOCKS,
        "group_linear_factor": 2,
        "intermediate_feed_forward_multiplier_multiplier": 0.5,
        "optimizer": "adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
        "learning_rate": 0.01, "weight_decay": 1e-4,
        "learning_rate_config": {"linear_warmup": {"final_step": 64}},
        "calculation_dtype": "float32", "storage_dtype": "float32",
        "slice_dtype": "float32", "scan_layers": scan,
        "model_path": "/tmp/scan_test",
    }
    cfg.update(over)
    return ModelParameter(cfg)


def _batch(params, seed=0):
    import jax.numpy as jnp
    rng = np.random.default_rng(seed)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": jnp.asarray(x),
            "token_y": jnp.asarray((x + 1) % params.vocab_size)}


def _run_steps(strategy, scan, n_steps=2, **over):
    params = _cfg(strategy, scan, **over)
    model = Model(params)
    trainer = Trainer(params, model)
    state = trainer.init_state(_batch(params))
    metrics = None
    import jax
    for s in range(n_steps):
        state, metrics = trainer.step(state, _batch(params, seed=s),
                                      rng=jax.random.PRNGKey(7 + s))
    return state, metrics


@pytest.mark.parametrize("strategy",
                         ["revnet", "momentum", "checkpoint", "none"])
def scan_matches_unrolled_test(strategy):
    state_u, metrics_u = _run_steps(strategy, scan=False)
    state_s, metrics_s = _run_steps(strategy, scan=True)
    np.testing.assert_allclose(float(metrics_s["loss"]),
                               float(metrics_u["loss"]), rtol=1e-5)
    for name in state_u.variables:
        np.testing.assert_allclose(
            np.asarray(state_s.variables[name]),
            np.asarray(state_u.variables[name]), rtol=2e-4, atol=2e-6,
            err_msg=f"{strategy}: {name}")


def scan_falls_back_on_depth_one_test():
    # depth 1 has nothing to scan; must run (via the unrolled path) and agree
    state_u, metrics_u = _run_steps("revnet", scan=False, depth=1)
    state_s, metrics_s = _run_steps("revnet", scan=True, depth=1)
    np.testing.assert_allclose(float(metrics_s["loss"]),
                               float(metrics_u["loss"]), rtol=1e-6)


def scan_falls_back_on_paramless_stack_test():
    # every per-depth parameter shared/absent -> nothing to stack; the scan
    # gate must fall back to the unrolled path instead of crashing lax.scan
    blocks = [{"layer": ["attention-biased_attention_map-absolute-input_as_value-shared"]}]
    state_u, metrics_u = _run_steps("revnet", scan=False, block_config=blocks)
    state_s, metrics_s = _run_steps("revnet", scan=True, block_config=blocks)
    np.testing.assert_allclose(float(metrics_s["loss"]),
                               float(metrics_u["loss"]), rtol=1e-6)


def decode_scan_engages_test(monkeypatch):
    """The KV sampler's while_loop body must take the scanned decode path
    (a silent fallback to the unrolled body is a 16x decode regression)."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.model import blocks
    from homebrewnlp_tpu.infer import sampler
    hits = {"scan": 0}
    orig = blocks._try_decode_scan

    def spy(*a, **k):
        out = orig(*a, **k)
        if out is not None:
            hits["scan"] += 1
        return out

    monkeypatch.setattr(blocks, "_try_decode_scan", spy)
    params = _cfg("revnet", scan=True, depth=3, train_batch_size=1)
    model = Model(params)
    variables = {k: jnp.asarray(v) for k, v in model.init(
        {"token_x": np.zeros((1, params.sequence_length, 1), np.int32),
         "token_y": np.zeros((1, params.sequence_length, 1), np.int32)}).items()}
    out = sampler.sample_text(model, variables,
                              np.asarray([[1, 2, 3]], np.int32),
                              temperature=0.0, seed=0)
    assert out.shape[1] == params.sequence_length
    assert hits["scan"] >= 1, "decode scan never engaged"


def scan_with_dropout_matches_test():
    # dropout draws from the per-depth folded rng; traced fold must replay
    # identically in the scanned backward recompute
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "feed_forward-in:relu-dropout:0.3"]},
              BLOCKS[1]]
    state_u, metrics_u = _run_steps("revnet", scan=False, block_config=blocks)
    state_s, metrics_s = _run_steps("revnet", scan=True, block_config=blocks)
    np.testing.assert_allclose(float(metrics_s["loss"]),
                               float(metrics_u["loss"]), rtol=1e-5)
    for name in state_u.variables:
        np.testing.assert_allclose(
            np.asarray(state_s.variables[name]),
            np.asarray(state_u.variables[name]), rtol=2e-4, atol=2e-6,
            err_msg=name)


def decode_carry_is_stacked_test():
    """init_decode_caches returns the depth-STACKED cache layout when the
    decode scan engages, so the while_loop carry feeds the scan as xs with
    no per-token flat<->stacked restack (docs/PERFORMANCE.md 'Decoding');
    and the stacked round-trip is lossless."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.model import blocks
    from homebrewnlp_tpu.infer import sampler

    params = _cfg("revnet", scan=True, depth=3, train_batch_size=1)
    model = Model(params)
    variables = {k: jnp.asarray(v) for k, v in model.init(
        {"token_x": np.zeros((1, params.sequence_length, 1), np.int32),
         "token_y": np.zeros((1, params.sequence_length, 1), np.int32)}).items()}
    tok = jnp.zeros((1, params.sequence_length, 1), jnp.int32)
    caches = sampler.init_decode_caches(model, variables, tok)
    stacked_keys = [k for k in caches
                    if k.startswith(blocks.STACKED_CACHE_PREFIX)]
    assert stacked_keys, "decode carry fell back to the flat layout"
    for k in stacked_keys:
        assert caches[k].shape[0] == params.depth, (k, caches[k].shape)

    # round-trip: unstack -> stack reproduces keys and shapes exactly
    flat = blocks.unstack_decode_caches(params, caches)
    restacked = blocks.stack_decode_caches(params, flat)
    assert set(restacked) == set(caches)
    for k in caches:
        assert restacked[k].shape == caches[k].shape

    # and the sampler still decodes greedily through the stacked carry
    out = sampler.sample_text(model, variables,
                              np.asarray([[1, 2, 3]], np.int32),
                              temperature=0.0, seed=0)
    assert out.shape[1] == params.sequence_length
