"""Model-level sequence parallelism: a dot-product-attention model on a
(model x sequence) mesh routes through ring attention and matches the
unsharded model numerically."""
import jax
import jax.numpy as jnp
import numpy as np

from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.core import sharding as shardlib
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer


def _params(**overrides):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 8, "heads": 2,
        "depth": 2, "train_batch_size": 4, "vocab_size": 32,
        "memory_reduction_strategy": "none",
        "block_config": [
            {"layer": ["norm-shift-scale-features-group",
                       "attention-dot_product-context"]}],
        "group_linear_factor": 2, "tpu_size": 8,
    }
    cfg.update(overrides)
    return ModelParameter(cfg)


def _batch(params, rng):
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": jnp.asarray(x),
            "token_y": jnp.asarray((x + 1) % params.vocab_size)}


def sp_matches_dense_test():
    rng = np.random.default_rng(0)
    params_a = _params()
    m_a = Model(params_a)
    batch = _batch(params_a, rng)
    variables = m_a.init(batch)
    loss_a = float(jax.jit(lambda v: m_a.apply(v, batch).total_loss.data)(variables))

    params_b = _params(sequence_parallel=4)
    assert params_b.mesh_shape.get("sequence") == 4
    m_b = Model(params_b)
    m_b.init(batch)  # same seed/config -> same params
    mesh = shardlib.build_mesh(params_b)
    assert mesh.shape["sequence"] == 4
    loss_b = float(jax.jit(
        lambda v: m_b.apply(v, batch, mesh=mesh).total_loss.data)(variables))
    np.testing.assert_allclose(loss_a, loss_b, rtol=2e-5)


def ring_backward_memory_test():
    """The 1b_long_context trainability proof (VERDICT round 2, weak #2):
    compile a ring-attention gradient at seq 16384 over 8 shards and assert
    the compiled temp memory is a small fraction of what the per-hop
    probability residuals of a naive autodiff-through-the-ring backward
    would require (8 hops x [b, h, sq, sq] f32 per device).  The custom_vjp
    saves only (q, k, v, out, lse) and recomputes probability blocks
    chunk-by-chunk in the backward."""
    from jax.sharding import Mesh
    from homebrewnlp_tpu.parallel.ring_attention import ring_attention

    mesh = Mesh(np.asarray(jax.devices()).reshape(1, 8), ("data", "sequence"))
    b, s, h, d = 1, 16384, 4, 64
    sq = s // 8

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

    sd = jax.ShapeDtypeStruct((b, s, h, d), jnp.float32)
    comp = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(sd, sd, sd).compile()
    temp = comp.memory_analysis().temp_size_in_bytes
    dense_residuals = 8 * b * h * sq * sq * 4  # what autodiff would stash
    # /3 (not /4): the zigzag layout's per-hop chunk selects/concats cost
    # ~45MB of copies at this shape (149MB vs 104MB contiguous, measured) in
    # exchange for halving the attention FLOPs; the property pinned here is
    # that residuals stay O(seq/P . d), far under the O(seq^2/P) stash
    assert temp < dense_residuals / 3, (temp, dense_residuals)


def sp_long_context_train_test():
    """An 8k-token sequence-parallel training run on the 8-device CPU mesh
    — a sequence length at which storing dense per-hop attention residuals
    would dwarf every other buffer — trains to finite, decreasing loss.
    (scripts/demo_long_context.py drives the full 32k x sp=8 shape; the
    16k memory bound is pinned by ring_backward_memory_test.)"""
    params = _params(sequence_length=8192, sequence_parallel=8,
                     train_batch_size=1, depth=1,
                     optimizer="momentum:0.9:1:1-learning_rate",
                     learning_rate=0.01, weight_decay=0.0,
                     memory_reduction_strategy="revnet")
    mesh = shardlib.build_mesh(params)
    assert mesh.shape["sequence"] == 8
    rng = np.random.default_rng(0)
    model = Model(params)
    batch = _batch(params, rng)
    tr = Trainer(params, model, mesh=mesh)
    state = tr.init_state(batch)
    losses = []
    for i in range(3):
        state, metrics = tr.step(state, batch, jax.random.PRNGKey(i))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def sp_train_step_test():
    """Full sharded train step with sequence parallelism: runs + loss finite +
    matches the meshless step."""
    rng = np.random.default_rng(0)
    params_a = _params(optimizer="momentum:0.9:1:1-learning_rate",
                       learning_rate=0.01, weight_decay=0.0)
    m_a = Model(params_a)
    batch = _batch(params_a, rng)
    tr_a = Trainer(params_a, m_a)
    state_a = tr_a.init_state(batch)
    state_a, metrics_a = tr_a.step(state_a, batch, jax.random.PRNGKey(0))

    params_b = _params(sequence_parallel=4,
                       optimizer="momentum:0.9:1:1-learning_rate",
                       learning_rate=0.01, weight_decay=0.0)
    m_b = Model(params_b)
    mesh = shardlib.build_mesh(params_b)
    tr_b = Trainer(params_b, m_b, mesh=mesh)
    state_b = tr_b.init_state(batch)
    state_b, metrics_b = tr_b.step(state_b, batch, jax.random.PRNGKey(0))

    np.testing.assert_allclose(float(metrics_a["loss"]), float(metrics_b["loss"]),
                               rtol=2e-5)
    for k in state_a.variables:
        np.testing.assert_allclose(np.asarray(state_a.variables[k], np.float32),
                                   np.asarray(state_b.variables[k], np.float32),
                                   rtol=5e-5, atol=1e-6, err_msg=k)
