"""Model-level sequence parallelism: a dot-product-attention model on a
(model x sequence) mesh routes through ring attention and matches the
unsharded model numerically."""
import jax
import jax.numpy as jnp
import numpy as np

from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.core import sharding as shardlib
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer


def _params(**overrides):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 8, "heads": 2,
        "depth": 2, "train_batch_size": 4, "vocab_size": 32,
        "memory_reduction_strategy": "none",
        "block_config": [
            {"layer": ["norm-shift-scale-features-group",
                       "attention-dot_product-context"]}],
        "group_linear_factor": 2, "tpu_size": 8,
    }
    cfg.update(overrides)
    return ModelParameter(cfg)


def _batch(params, rng):
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": jnp.asarray(x),
            "token_y": jnp.asarray((x + 1) % params.vocab_size)}


def sp_matches_dense_test():
    rng = np.random.default_rng(0)
    params_a = _params()
    m_a = Model(params_a)
    batch = _batch(params_a, rng)
    variables = m_a.init(batch)
    loss_a = float(jax.jit(lambda v: m_a.apply(v, batch).total_loss.data)(variables))

    params_b = _params(sequence_parallel=4)
    assert params_b.mesh_shape.get("sequence") == 4
    m_b = Model(params_b)
    m_b.init(batch)  # same seed/config -> same params
    mesh = shardlib.build_mesh(params_b)
    assert mesh.shape["sequence"] == 4
    loss_b = float(jax.jit(
        lambda v: m_b.apply(v, batch, mesh=mesh).total_loss.data)(variables))
    np.testing.assert_allclose(loss_a, loss_b, rtol=2e-5)


def sp_train_step_test():
    """Full sharded train step with sequence parallelism: runs + loss finite +
    matches the meshless step."""
    rng = np.random.default_rng(0)
    params_a = _params(optimizer="momentum:0.9:1:1-learning_rate",
                       learning_rate=0.01, weight_decay=0.0)
    m_a = Model(params_a)
    batch = _batch(params_a, rng)
    tr_a = Trainer(params_a, m_a)
    state_a = tr_a.init_state(batch)
    state_a, metrics_a = tr_a.step(state_a, batch, jax.random.PRNGKey(0))

    params_b = _params(sequence_parallel=4,
                       optimizer="momentum:0.9:1:1-learning_rate",
                       learning_rate=0.01, weight_decay=0.0)
    m_b = Model(params_b)
    mesh = shardlib.build_mesh(params_b)
    tr_b = Trainer(params_b, m_b, mesh=mesh)
    state_b = tr_b.init_state(batch)
    state_b, metrics_b = tr_b.step(state_b, batch, jax.random.PRNGKey(0))

    np.testing.assert_allclose(float(metrics_a["loss"]), float(metrics_b["loss"]),
                               rtol=2e-5)
    for k in state_a.variables:
        np.testing.assert_allclose(np.asarray(state_a.variables[k], np.float32),
                                   np.asarray(state_b.variables[k], np.float32),
                                   rtol=5e-5, atol=1e-6, err_msg=k)
