"""Single-pass prompt prefill parity vs the per-token decode walk.

``make_kv_sampler(prefill=True)`` replaces the sampler's O(prompt)
per-token prompt walk with ONE full forward that captures the decode caches
(model/decode.py ``PrefillState``), entering the while_loop at the last
prompt position.  Greedy outputs must be IDENTICAL to the plain KV sampler
(and hence the full-forward sampler) for every layer family with a
streaming form — attention (dense and kernel-routed), cumsum/cummean,
causal convolution — under every memory-reduction strategy, with float and
int8 cache dtypes, scanned and unrolled depth stacks, and per-row prompt
lengths (batched serving).
"""
import jax
import jax.numpy as jnp
import numpy as np

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer.sampler import decode_cache_shapes, make_kv_sampler
from homebrewnlp_tpu.model import Model


def _setup(cfg_overrides, seed=0):
    params = make_params(**cfg_overrides)
    model = Model(params)
    rng = np.random.default_rng(seed)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    token_x = rng.integers(0, params.vocab_size,
                           (params.train_batch_size, seq, tps)).astype(np.int32)
    batch = {"token_x": jnp.asarray(token_x), "token_y": jnp.asarray(token_x)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return params, model, variables, token_x


def _pair(cfg_overrides, initial_pos=5, end_iterations=None, seed=0,
          temperature=0.0):
    params, model, variables, token_x = _setup(cfg_overrides, seed)
    seq = params.sequence_dim.size
    end = seq if end_iterations is None else end_iterations
    args = (variables, jnp.asarray(token_x),
            jnp.asarray(initial_pos, jnp.int32),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(end, jnp.int32), jax.random.PRNGKey(seed), None)
    walk = np.asarray(jax.jit(make_kv_sampler(model))(*args))
    pre = np.asarray(jax.jit(make_kv_sampler(model, prefill=True))(*args))
    return walk, pre, token_x


def _assert_parity(cfg, **kw):
    walk, pre, token_x = _pair(cfg, **kw)
    np.testing.assert_array_equal(walk, pre)


def mixer_revnet_prefill_parity_test():
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "revnet"})


def mixer_momentum_prefill_parity_test():
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "momentum"})


def dot_product_prefill_parity_test():
    """Flash/kernel-routed attention captures in _plain_softmax_qkv; the
    CPU fallback runs the fused XLA reference — either way the capture
    order (key, then val) must match the decode build's cache names."""
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "attention-dot_product-embedded-absolute"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "none"})


def biased_softmax_prefill_parity_test():
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "attention-dot_product-context-biased_softmax-absolute"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "checkpoint"})


def shared_key_value_prefill_parity_test():
    """shared_key_value writes ONE kv cache (val = key skips the second
    spread site); prefill must mirror that count or every later cache name
    shifts."""
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "attention-dot_product-embedded-absolute-shared_key_value"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "none"})


def cumsum_prefill_parity_test():
    """The cumsum capture stores the full-forward prefix row n-1 where
    decode accumulates sequentially — associativity differs, so this also
    guards that the difference stays below argmax-flipping size."""
    blocks = [{"layer": ["norm-shift-scale-features-group", "cumsum"]},
              {"layer": ["norm-shift-scale-features-group", "cummean",
                         "feed_forward-in:relu"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "momentum"})


def convolution_prefill_parity_test():
    blocks = [{"layer": ["norm-shift-scale-features-group", "convolution",
                         "activation-gelu"]}]
    _assert_parity({"block_config": blocks, "convolution_size": 4,
                    "memory_reduction_strategy": "none"})


def conv_window_longer_than_prompt_prefill_test():
    """Prompt shorter than the conv kernel: the captured window's leading
    rows are the causal zero padding."""
    blocks = [{"layer": ["norm-shift-scale-features-group", "convolution",
                         "activation-gelu"]}]
    _assert_parity({"block_config": blocks, "convolution_size": 8,
                    "memory_reduction_strategy": "none"}, initial_pos=3)


def unrolled_stack_prefill_parity_test():
    """scan_layers off: the unrolled prefill writes flat per-block cache
    names (no __stacked__ grouping) — the layout matcher must pass them
    through to the unrolled decode body unchanged."""
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "revnet",
                    "scan_layers": False})


def int8_cache_prefill_test():
    """int8 caches: EXACT parity with the walk is impossible by design —
    the sequential walk computes each position's activations from the
    DEQUANTIZED (lossy, ~1/127) history, so deeper-layer k/v inputs carry
    compounded quantization error, while prefill captures from the exact
    full forward.  Prefill's caches are the more accurate of the two.
    Assert the quantized prompt rows agree within a few quantization steps
    and the generated stream is structurally valid."""
    from homebrewnlp_tpu.infer.sampler import _match_cache_layout
    cfg = {"block_config": MIXER_BLOCKS,
           "memory_reduction_strategy": "revnet",
           "decode_cache_dtype": "int8"}
    params, model, variables, token_x = _setup(cfg)
    seq = params.sequence_dim.size
    n0 = 4
    expected = decode_cache_shapes(model, variables, jnp.asarray(token_x))
    walk_caches = {k: jnp.zeros(v.shape, v.dtype) for k, v in expected.items()}
    for q in range(n0):
        _, walk_caches = model.apply_decode(
            variables, jnp.asarray(token_x[:, q:q + 1]), jnp.int32(q),
            walk_caches)
    pre = _match_cache_layout(
        model, dict(model.apply_prefill(variables, jnp.asarray(token_x),
                                        jnp.int32(n0))), expected)
    checked = 0
    for k, v in expected.items():
        if v.dtype != jnp.int8:
            continue
        a, b = np.asarray(walk_caches[k]), np.asarray(pre[k])
        # stacked layout [depth, batch, seq, ...]: sequence axis = 2
        d = np.abs(a[:, :, :n0].astype(int) - b[:, :, :n0].astype(int))
        assert d.max() <= 8, (k, d.max())
        assert np.mean(d > 1) < 0.05, (k, np.mean(d > 1))
        checked += 1
    assert checked, f"no int8 caches discovered: {sorted(expected)[:4]}"
    # generated stream: prompt preserved, tokens in vocab
    out = np.asarray(jax.jit(make_kv_sampler(model, prefill=True))(
        variables, jnp.asarray(token_x), jnp.asarray(5, jnp.int32),
        jnp.asarray(0.0, jnp.float32), jnp.asarray(seq, jnp.int32),
        jax.random.PRNGKey(0), None))
    np.testing.assert_array_equal(out[:, 1:5], token_x[:, 1:5])
    assert out.min() >= 0 and out.max() < params.vocab_size


def per_row_prompt_prefill_parity_test():
    """Batched serving: per-row prompt lengths; prefill covers only
    min(ipb)-1 steps and the loop's row guard handles the longer prompts."""
    params, model, variables, token_x = _setup(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "revnet"})
    seq = params.sequence_dim.size
    ipb = np.array([3, 7, 5, 9], np.int32)[:params.train_batch_size]
    args = (variables, jnp.asarray(token_x), jnp.asarray(ipb),
            jnp.asarray(0.0, jnp.float32), jnp.asarray(seq, jnp.int32),
            jax.random.PRNGKey(0), None)
    walk = np.asarray(jax.jit(make_kv_sampler(model))(*args))
    pre = np.asarray(jax.jit(make_kv_sampler(model, prefill=True))(*args))
    np.testing.assert_array_equal(walk, pre)
    # per-row prompt regions preserved
    for r, p in enumerate(ipb):
        np.testing.assert_array_equal(pre[r, 1:p], token_x[r, 1:p])


def initial_pos_zero_prefill_test():
    """n0 clamps to 0: nothing to capture, prefill degenerates to the plain
    walk (wasted forward, identical output)."""
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "none"}, initial_pos=0)


def prompt_fills_sequence_prefill_test():
    """Prompt occupying all but the last position: the loop runs exactly
    one step after prefill."""
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "none"}, initial_pos=15)


def prefill_cache_structure_matches_decode_test():
    """apply_prefill must produce the cache pytree apply_decode's discovery
    expects — keys, shapes, AND dtypes (the layout matcher re-stacks but
    hard-fails on shape/dtype drift)."""
    from homebrewnlp_tpu.infer.sampler import _match_cache_layout
    for cfg in ({"block_config": MIXER_BLOCKS,
                 "memory_reduction_strategy": "revnet"},
                {"block_config": MIXER_BLOCKS,
                 "memory_reduction_strategy": "revnet",
                 "decode_cache_dtype": "int8"},
                {"block_config": MIXER_BLOCKS,
                 "memory_reduction_strategy": "revnet",
                 "scan_layers": False}):
        params, model, variables, token_x = _setup(cfg)
        produced = jax.jit(
            lambda v, t: model.apply_prefill(v, t, jnp.int32(5)))(
                variables, jnp.asarray(token_x))
        expected = decode_cache_shapes(model, variables, jnp.asarray(token_x))
        matched = _match_cache_layout(model, dict(produced), expected)
        assert set(matched) == set(expected)


def output_block_cache_prefill_parity_test():
    """output_block_config layers can create caches too (a cumsum head
    block): apply_prefill runs the output blocks (but not the vocab
    projection) so those caches are captured rather than crashing the
    layout match."""
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "output_block_config": [{"layer": ["cumsum"]}],
                    "memory_reduction_strategy": "none"})


def prefill_sample_text_route_test():
    """sample_text picks the prefill sampler for real prompts and the plain
    walk for initial_pos <= 1; both produce identical greedy streams."""
    from homebrewnlp_tpu.infer.sampler import sample_text
    params, model, variables, token_x = _setup(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "revnet"})
    out_pre = sample_text(model, variables, token_x[:, :6, 0], initial_pos=6,
                          temperature=0.0)
    assert (model._sampler_jit_cache and
            any(k[1] == "kv_prefill" for k in model._sampler_jit_cache))
    walk = jax.jit(make_kv_sampler(model))(
        variables, jnp.asarray(np.concatenate(
            [token_x[:, :6], np.zeros_like(token_x[:, 6:])], 1)),
        jnp.asarray(6, jnp.int32), jnp.asarray(0.0, jnp.float32),
        jnp.asarray(params.sequence_dim.size, jnp.int32),
        jax.random.PRNGKey(0), None)
    np.testing.assert_array_equal(out_pre, np.asarray(walk))
