"""Elastic pod training tests (marker ``elastic``; docs/DISTRIBUTED.md
'Elasticity', ROADMAP item 5 / ISSUE 14).

Three tiers:

- **Agent state machine** (device-free, injected KV/clock): lease lapse
  detection, missing-peer startup grace, coordinator loss, the
  grace-then-force-exit path with the pre-exit hook, exit-code
  classification, and the controller's jax-free checkpoint probe.
- **Gradient all-reduce policy** (8 virtual devices): bucket-plan shape
  (reverse-topological, size-targeted, dtype-homogeneous), eligibility
  gates, loud fused fallback, and — marked slow — the fused-vs-bucketed
  loss tolerance on a real data-parallel step.
- **Controller e2e** (marked slow; real ``run_manager.py --elastic``
  subprocess fleets): SIGKILL one of 4 ranks mid-training → the survivors
  re-form at world size 3 from the freshest complete checkpoint with no
  human input and no fixed world size, grow back to 4 at a checkpoint
  boundary, and finish — with restore losses pinned against fresh
  restores and the DataLog chain proven multiset-exact across both
  membership changes.  A second e2e drives the proactive
  preemption-notice shrink (graceful 143 path).
"""
import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from multihost_test import _spawn_workers  # noqa: E402

pytestmark = pytest.mark.elastic

WORKER = os.path.join(HERE, "_elastic_train_worker.py")
RUN_MANAGER = os.path.join(HERE, "..", "scripts", "run_manager.py")


# ---- agent state machine (device-free) -------------------------------------

class _FakeKV:
    def __init__(self):
        self.store = {}
        self.fail_puts = False

    def put(self, key, value):
        if self.fail_puts:
            return False
        self.store[key] = value
        return True

    def dir_get(self, prefix):
        return [(k, v) for k, v in self.store.items()
                if k.startswith(prefix)]

    def beat(self, pid, seq, gen=0):
        self.store[f"hbnlp/elastic/g{gen}/p{pid}"] = json.dumps(
            {"seq": seq, "ospid": 1000 + pid})


def _agent(tmp_path, kv, clock, pid=0, n=3, **kw):
    from homebrewnlp_tpu.distributed.elastic import ElasticAgent
    a = ElasticAgent(str(tmp_path), pid, n, gen=0, interval_s=1.0,
                     timeout_s=5.0, exit_grace_s=1.0, kv_put=kv.put,
                     kv_dir_get=kv.dir_get, clock=lambda: clock[0],
                     exit_fn=lambda rc: None, **kw)
    a._started_at = clock[0]  # start() would stamp this; ticks are manual
    return a


def lease_lapse_detection_test(tmp_path):
    """A peer whose lease stops ADVANCING is declared lapsed after
    timeout_s on the local monotonic clock; the event names the rank, and
    the membership marker + chief lease mirror land on shared storage."""
    from homebrewnlp_tpu.distributed import elastic

    kv, clock = _FakeKV(), [0.0]
    agent = _agent(tmp_path, kv, clock)
    kv.beat(1, 1)
    kv.beat(2, 1)
    assert agent.tick() is None
    clock[0] = 3.0
    kv.beat(1, 2)  # p1 advances; p2 stalls (its age: 3s < 5s)
    assert agent.tick() is None
    # the chief mirror rides every tick
    mirror = json.load(open(elastic.lease_mirror_path(str(tmp_path))))
    assert mirror["generation"] == 0 and mirror["world_size"] == 3
    assert "1" in mirror["leases"] and "2" in mirror["leases"]
    clock[0] = 6.0
    kv.beat(1, 3)
    event = agent.tick()  # p2's last advance was t=0: age 6s > 5s
    assert event is not None and "p2" in event, event
    assert agent.lapsed == [2]
    marker = elastic.read_membership_marker(str(tmp_path), 0)
    assert marker is not None and marker["lapsed"] == [2], marker
    # sticky: later ticks cannot overwrite the first cause
    clock[0] = 9.0
    assert agent.tick() == event


def missing_peer_startup_grace_test(tmp_path):
    """A peer that NEVER published only counts as lapsed once the
    generation had timeout_s to come up — processes start their agents at
    different times (compile skew), so a missing key must not instantly
    shrink the pod."""
    kv, clock = _FakeKV(), [0.0]
    agent = _agent(tmp_path, kv, clock)
    kv.beat(1, 1)  # p2 never publishes
    assert agent.tick() is None
    clock[0] = 4.0
    kv.beat(1, 2)
    assert agent.tick() is None  # still inside the startup grace
    clock[0] = 6.0
    kv.beat(1, 3)
    event = agent.tick()
    assert event is not None and "p2" in event, event


def coordinator_loss_detection_test(tmp_path):
    """Repeated kv_put failure = the coordination service (process 0) is
    gone — a membership event blaming rank 0, not a silent retry loop."""
    kv, clock = _FakeKV(), [0.0]
    agent = _agent(tmp_path, kv, clock, pid=1)
    kv.beat(0, 1)
    kv.beat(2, 1)
    assert agent.tick() is None
    kv.fail_puts = True
    clock[0] = 2.0
    assert agent.tick() is None  # first failure only starts the window
    clock[0] = 8.0
    event = agent.tick()
    assert event is not None and "coordination service" in event, event
    assert agent.lapsed == [0]


def force_exit_grace_and_pre_exit_test(tmp_path):
    """The trigger path: grace for the main loop's own check first (a
    stop() inside the window cancels the exit), then pre_exit hook, then
    exit_fn — os._exit skips every finally, so the hook is the last
    chance for host-side accounting (the chief's DataLog flush)."""
    from homebrewnlp_tpu.distributed.elastic import (ElasticAgent,
                                                     MEMBERSHIP_EXIT_CODE)

    calls = []
    agent = ElasticAgent(str(tmp_path), 0, 2, gen=0, exit_grace_s=0.2,
                         kv_put=lambda k, v: True, kv_dir_get=lambda p: [],
                         exit_fn=lambda rc: calls.append(("exit", rc)),
                         pre_exit=lambda: calls.append(("pre", None)))
    agent.event = "test event"
    agent._trigger_exit()
    assert calls == [("pre", None), ("exit", MEMBERSHIP_EXIT_CODE)], calls

    calls.clear()
    agent2 = ElasticAgent(str(tmp_path), 0, 2, gen=0, exit_grace_s=5.0,
                          kv_put=lambda k, v: True, kv_dir_get=lambda p: [],
                          exit_fn=lambda rc: calls.append(("exit", rc)))
    agent2.event = "test event"
    agent2._stop.set()  # the main loop noticed and is exiting cleanly
    agent2._trigger_exit()
    assert calls == [], calls


def classify_exit_test():
    from homebrewnlp_tpu.distributed.elastic import classify_exit
    assert classify_exit(None) == "running"
    assert classify_exit(0) == "ok"
    assert classify_exit(143) == "preempted"
    assert classify_exit(144) == "membership"
    assert classify_exit(137) == "killed"
    assert classify_exit(-9) == "killed"
    assert classify_exit(-6) == "collateral"   # SIGABRT 'another task died'
    assert classify_exit(134) == "collateral"
    assert classify_exit(-11) == "collateral"
    assert classify_exit(-15) == "collateral"  # drain-TERMed wedged rank
    assert classify_exit(1) == "crash"


def latest_complete_step_test(tmp_path):
    """The controller's grow-boundary probe: committed ``ckpt_<step>``
    directories only — a torn ``.tmp`` save stays invisible."""
    from homebrewnlp_tpu.distributed.elastic import latest_complete_step
    assert latest_complete_step(str(tmp_path / "missing")) == -1
    assert latest_complete_step(str(tmp_path)) == -1
    for name in ("ckpt_5", "ckpt_12", "ckpt_40.tmp", "elastic", "pids"):
        os.makedirs(tmp_path / name)
    assert latest_complete_step(str(tmp_path)) == 12


# ---- gradient all-reduce policy --------------------------------------------

def _ga_cfg(model_path, **over):
    cfg = {"model_mode": "gpt", "use_video": False, "use_language": True,
           "sequence_length": 32, "features_per_head": 16, "heads": 8,
           "depth": 1, "train_batch_size": 8, "vocab_size": 32,
           "tpu_size": 8,
           "block_config": [{"layer": ["norm-shift-scale-features-group",
                                       "feed_forward-in:relu"]}],
           "memory_reduction_strategy": "none",
           "optimizer": "adam-learning_rate", "learning_rate": 1e-3,
           "weight_decay": 0.0, "mesh_shape_override": {"data": 8},
           "model_path": str(model_path)}
    cfg.update(over)
    return cfg


def _ga_trainer(model_path, **over):
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer
    params = ModelParameter(_ga_cfg(model_path, **over))
    mesh = shardlib.build_mesh(params)
    return params, Trainer(params, Model(params), mesh=mesh)


def _ga_batch(params):
    rng = np.random.default_rng(42)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": np.asarray(x, np.int32),
            "token_y": np.asarray((x + 1) % params.vocab_size, np.int32)}


def bucket_plan_test(tmp_path):
    """Buckets cover every grad leaf exactly once in REVERSE creation
    order (output-side leaves first — the ones whose backward
    contributions complete first), stay under the size target unless a
    single leaf exceeds it, and never mix dtypes in one flat buffer."""
    params, trainer = _ga_trainer(tmp_path / "r", grad_allreduce="bucketed",
                                  grad_bucket_mb=0.015625)  # 16 KiB
    variables = trainer.model.init(_ga_batch(params))
    buckets = trainer._bucket_plan(variables)
    flat = [k for b in buckets for k in b]
    assert flat == list(reversed(list(variables))), (flat[:4], buckets[:2])
    target = 16 * 1024
    for b in buckets:
        dtypes = {np.dtype(np.asarray(variables[k]).dtype) for k in b}
        assert len(dtypes) == 1, b
        size = sum(np.asarray(variables[k]).nbytes for k in b)
        assert len(b) == 1 or size <= target, (b, size)
    # a larger target coalesces harder
    params2, trainer2 = _ga_trainer(tmp_path / "r2",
                                    grad_allreduce="bucketed",
                                    grad_bucket_mb=64.0)
    assert len(trainer2._bucket_plan(variables)) < len(buckets)


def grad_allreduce_eligibility_test(tmp_path):
    """The policy refuses loudly instead of silently changing the
    program: every gate names its reason, the eligible config returns
    None, and the resolved fallback warns once."""
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    _, ok = _ga_trainer(tmp_path / "a", grad_allreduce="bucketed")
    assert ok.grad_allreduce_fallback() is None

    _, fused = _ga_trainer(tmp_path / "b")
    assert fused.grad_allreduce_fallback() is None  # fused: nothing to gate

    _, ga = _ga_trainer(tmp_path / "c", grad_allreduce="bucketed",
                        grad_accumulation=2)
    assert "accumulation" in ga.grad_allreduce_fallback()

    _, ml = _ga_trainer(tmp_path / "d", grad_allreduce="bucketed",
                        multi_loss_strategy="pcgrad")
    assert "pcgrad" in ml.grad_allreduce_fallback()

    from homebrewnlp_tpu.config import ModelParameter
    params = ModelParameter(_ga_cfg(tmp_path / "e",
                                    grad_allreduce="bucketed"))
    single = Trainer(params, Model(params), mesh=None)
    assert "single-device" in single.grad_allreduce_fallback()

    # the resolved fallback is LOUD (warns) and lands on fused
    import types

    import jax.numpy as jnp
    _, warned = _ga_trainer(tmp_path / "f", grad_allreduce="bucketed",
                            grad_accumulation=2)
    fake_info = types.SimpleNamespace(
        total_loss=types.SimpleNamespace(data=jnp.float32(0)),
        token_loss=None, video_loss=None, accuracy=None)
    warned._grads = lambda v, b, r: ({}, fake_info)  # no compile needed
    with pytest.warns(UserWarning, match="falling back"):
        warned._grads_with_policy({}, {}, None)
    assert warned._grad_allreduce_resolved == "fused"

    # config validation rejects typos outright
    with pytest.raises(ValueError, match="grad_allreduce"):
        ModelParameter(_ga_cfg(tmp_path / "g", grad_allreduce="buckted"))


@pytest.mark.slow
def bucketed_matches_fused_within_tolerance_test(tmp_path):
    """The acceptance pin: at the ``fused`` default the policy layer is
    bit-identical to the historical path (same ``_grads`` seam, asserted
    bit-for-bit against an explicit ``fused``); ``bucketed`` matches
    within float reduction-order tolerance (mean-of-shard-means vs global
    mean; measured ~7e-8 relative) while every bucket reduces once."""
    import jax

    losses = {}
    for name, over in (("default", {}), ("fused", {"grad_allreduce": "fused"}),
                       ("bucketed", {"grad_allreduce": "bucketed"})):
        params, trainer = _ga_trainer(tmp_path / name, **over)
        batch = _ga_batch(params)
        state = trainer.init_state(batch)
        seq = []
        for i in range(3):
            state, metrics = trainer.step(state, batch,
                                          rng=jax.random.PRNGKey(100 + i))
            seq.append(float(np.asarray(jax.device_get(metrics["loss"]))))
        losses[name] = seq
        assert trainer._grad_allreduce_resolved == (
            "bucketed" if name == "bucketed" else "fused")
    assert losses["default"] == losses["fused"], losses  # bit-identical
    np.testing.assert_allclose(losses["bucketed"], losses["fused"],
                               rtol=1e-5)


# ---- controller e2e --------------------------------------------------------

def _write_records(data_dir, n_files, tokens_per_file, seed=3):
    from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example
    os.makedirs(data_dir)
    rng = np.random.default_rng(seed)
    for i in range(n_files):
        tokens = rng.integers(0, 32, tokens_per_file).astype(np.uint8)
        with RecordWriter(str(data_dir / f"p_{i}_{tokens_per_file}"
                               ".tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))


def _elastic_cfg(tmp_path, data_dir, **over):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 8, "heads": 2,
        "depth": 1, "train_batch_size": 12, "vocab_size": 32,
        "tpu_size": 4, "calc_accuracy": False,
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    "feed_forward-in:relu"]}],
        "memory_reduction_strategy": "none",
        "optimizer": "adam-learning_rate", "learning_rate": 1e-3,
        "weight_decay": 0.0,
        "learning_rate_config": {"linear_warmup": {"final_step": 8}},
        "mesh_shape_override": {"data": 4},
        "train_steps": 60, "use_checkpointing": True,
        "steps_per_checkpoint": 8, "checkpoint_async": True,
        "max_checkpoints_keep": 50, "interleaved_datasets": 2,
        "data_seed": 7, "storage_retry_base_delay": 0.0,
        "distributed_barrier_timeout_s": 30.0,
        "elastic_training": True, "elastic_lease_interval_s": 0.5,
        "elastic_lease_timeout_s": 8.0, "elastic_exit_grace_s": 2.0,
        "dataset_configs": [{"path": str(data_dir / "*"), "type": "text",
                             "weight": 1}],
        "model_path": str(tmp_path / "run"),
    }
    cfg.update(over)
    return cfg


def _controller_cmd(cfg_path, model_path, target, step_delay, extra=()):
    return [sys.executable, RUN_MANAGER,
            f"{sys.executable} {WORKER} {cfg_path} --step-delay "
            f"{step_delay}",
            "--model-path", str(model_path),
            "--num-processes", str(target), "--devices-per-process", "1",
            "--poll-interval", "2", "--poll-jitter", "0",
            "--stall-timeout", "0", "--term-grace", "120",
            "--max-restarts", "3", "--restart-delay", "1",
            "--elastic", *extra]


def _window_rows(ds, n_batches=None):
    """Token-x rows of the first n batches (full drain when None)."""
    out = []
    it = iter(ds)
    while n_batches is None or n_batches > 0:
        try:
            b = next(it)
        except StopIteration:
            assert n_batches is None, "stream ended early"
            break
        out.extend(bytes(row.tobytes()) for row in np.asarray(b["token_x"]))
        if n_batches is not None:
            n_batches -= 1
    return out


def _assert_datalog_multiset_exact(cfg, model_path):
    """PR 10's multiset property carried THROUGH the elastic membership
    changes: replaying every generation's DataLog entry (its own slice
    geometry, resumed through the preceding entries) and then draining
    the rest of the epoch reproduces the uninterrupted epoch exactly —
    nothing lost, nothing duplicated."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.data.inputs import TextDataset

    entries = [json.loads(line)
               for line in open(os.path.join(model_path, "DataLog.log"))
               if line.strip()]
    assert len(entries) >= 2, entries
    consumed = []
    for i, e in enumerate(entries):
        local = cfg["train_batch_size"] // e["slice_count"]
        for s in range(e["slice_count"]):
            ds = TextDataset(ModelParameter(dict(cfg)), local,
                             slice_index=s, slice_count=e["slice_count"],
                             runs_log=entries[:i] or None, repeat=True)
            consumed += _window_rows(ds, e["steps"])
    remainder = _window_rows(TextDataset(
        ModelParameter(dict(cfg)), cfg["train_batch_size"], slice_index=0,
        slice_count=1, runs_log=entries, repeat=False))
    reference = _window_rows(TextDataset(
        ModelParameter(dict(cfg)), cfg["train_batch_size"], slice_index=0,
        slice_count=1, repeat=False))
    assert sorted(consumed + remainder) == sorted(reference), (
        len(consumed), len(remainder), len(reference))
    return entries


@pytest.mark.slow
def elastic_shrink_grow_e2e_test(tmp_path):
    """The headline acceptance: SIGKILL one of 4 ranks mid-training.  The
    elastic controller — no human input, no fixed world size — re-forms
    the 3 survivors at a new generation resuming from the freshest
    COMPLETE checkpoint, grows back to 4 at a checkpoint boundary once
    the shrunken generation proves itself, and trains to completion.
    Pins: the resumed generation's restore forward-loss is BIT-IDENTICAL
    to a fresh 3-process restore of the same checkpoint; the re-grown
    4-process step matches a fresh 4-process restore within
    reduction-order tolerance; the DataLog chain stays multiset-exact."""
    from homebrewnlp_tpu.distributed.elastic import latest_complete_step

    data_dir = tmp_path / "data"
    _write_records(data_dir, 12, 4096)
    model_path = str(tmp_path / "run")
    cfg = _elastic_cfg(tmp_path, data_dir)
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    proc = subprocess.Popen(
        _controller_cmd(cfg_path, model_path, 4, 0.2,
                        extra=("--grow-delay", "3", "--elastic-drain",
                               "45")),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    killed = False
    pidfile = os.path.join(model_path, "pids", "g0_p1.pid")
    deadline = time.monotonic() + 700
    try:
        while proc.poll() is None and time.monotonic() < deadline:
            if not killed and latest_complete_step(model_path) >= 8 \
                    and os.path.exists(pidfile):
                victim = int(open(pidfile).read())
                os.kill(victim, signal.SIGKILL)
                killed = True
            time.sleep(0.5)
        assert proc.poll() is not None, "controller did not finish in time"
    finally:
        if proc.poll() is None:
            proc.kill()
    out, _ = proc.communicate(timeout=30)
    log = open(os.path.join(model_path, "run.log")).read()
    assert killed, log[-3000:]
    assert proc.returncode == 0, out[-2000:] + log[-4000:]

    # the controller's story: shrink to 3 survivors, grow back to 4, done
    assert "elastic: membership change generation 0" in log, log[-4000:]
    m = re.search(r"resuming 3 survivor\(s\) from checkpoint step (\d+)",
                  log)
    assert m, log[-4000:]
    shrink_step = int(m.group(1))
    assert "graceful grow 3 -> 4" in log, log[-4000:]
    assert "fleet finished cleanly" in log, log[-4000:]
    # the agents named the killed rank on shared storage (a survivor the
    # gloo runtime SIGABRTed on the dead rank's sockets may ride along in
    # the marker — the controller's exit census filters it back out, which
    # is exactly what the world-size-3 pins above prove)
    marker = json.load(open(os.path.join(model_path, "elastic",
                                         "membership_g0.json")))
    assert 1 in marker["lapsed"], marker

    # worker markers (pumped into run.log with [pN] prefixes)
    restores = re.findall(r"ELASTIC_RESTORE g=(\d+) world=(\d+) "
                          r"step=(\d+) fwd=(\S+)", log)
    steps_m = re.findall(r"ELASTIC_STEP g=(\d+) world=(\d+) "
                         r"step=(\d+) loss=(\S+)", log)
    shrunk = [r for r in restores if r[1] == "3"]
    assert shrunk, (restores, log[-3000:])
    g3, _, s3, fwd3 = shrunk[0]
    assert int(s3) == shrink_step, (s3, shrink_step)
    grown = [r for r in restores if r[1] == "4" and int(r[0]) > int(g3)]
    assert grown, restores
    g4, _, s4, fwd4 = grown[-1]
    loss4 = [sm[3] for sm in steps_m if sm[0] == g4 and sm[1] == "4"]
    assert loss4, steps_m
    done = re.findall(r"ELASTIC_DONE g=(\d+) world=(\d+) final_step=(\d+)",
                      log)
    assert done and done[-1][1] == "4" and done[-1][2] == "60", done

    # fresh 3-process restore of the SAME checkpoint: bit-identical
    # forward loss (single-device probe — no reduction-order excuse)
    outs3 = _spawn_workers(WORKER, [str(cfg_path), "--probe-only",
                                    "--step", s3],
                           env_devcount=1, n_procs=3, timeout=420)
    assert all(p.returncode == 0 for p, _ in outs3), \
        "\n".join(o[-2000:] for _, o in outs3)
    fresh3 = re.findall(r"ELASTIC_RESTORE_FRESH g=\d+ world=3 "
                        r"step=\d+ fwd=(\S+)",
                        "\n".join(o for _, o in outs3))
    assert fresh3 and fresh3[0] == fwd3, (fresh3, fwd3)

    # fresh 4-process restore: the re-grown step within reduction-order
    # tolerance (and the restored bytes themselves still bit-identical)
    outs4 = _spawn_workers(WORKER, [str(cfg_path), "--probe-only",
                                    "--step", s4],
                           env_devcount=1, n_procs=4, timeout=420)
    assert all(p.returncode == 0 for p, _ in outs4), \
        "\n".join(o[-2000:] for _, o in outs4)
    joined = "\n".join(o for _, o in outs4)
    fresh4_fwd = re.findall(r"ELASTIC_RESTORE_FRESH g=\d+ world=4 "
                            r"step=\d+ fwd=(\S+)", joined)
    fresh4_loss = re.findall(r"ELASTIC_STEP_FRESH g=\d+ world=4 "
                             r"step=\d+ loss=(\S+)", joined)
    assert fresh4_fwd and fresh4_fwd[0] == fwd4, (fresh4_fwd, fwd4)
    assert fresh4_loss, joined[-2000:]
    np.testing.assert_allclose(float(loss4[0]), float(fresh4_loss[0]),
                               rtol=1e-5)

    # data-stream accounting across BOTH membership changes
    entries = _assert_datalog_multiset_exact(cfg, model_path)
    counts = [e["slice_count"] for e in entries]
    assert counts[0] == 4 and 3 in counts and counts[-1] == 4, counts


@pytest.mark.slow
def preempt_notice_graceful_shrink_test(tmp_path):
    """The PROACTIVE path: cloud tooling announces an upcoming capacity
    loss by writing ``elastic/preempt.json``; the controller shrinks
    through the graceful 143 rotation (pod-wide SIGTERM → emergency
    checkpoint → relaunch smaller) — no steps lost, notice cleared, and
    the ``hbnlp_elastic_*`` gauges visible in the run's telemetry."""
    data_dir = tmp_path / "data"
    _write_records(data_dir, 8, 4096, seed=5)
    model_path = str(tmp_path / "run")
    cfg = _elastic_cfg(
        tmp_path, data_dir, train_batch_size=8, tpu_size=2,
        mesh_shape_override={"data": 2}, train_steps=40,
        steps_per_checkpoint=6, telemetry_enabled=True,
        telemetry_jsonl_interval_s=0.05)
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    proc = subprocess.Popen(
        _controller_cmd(cfg_path, model_path, 2, 0.25,
                        extra=("--grow-delay", "100000",)),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    noticed = False
    deadline = time.monotonic() + 500
    try:
        while proc.poll() is None and time.monotonic() < deadline:
            if not noticed and os.path.exists(
                    os.path.join(model_path, "metrics.jsonl")):
                os.makedirs(os.path.join(model_path, "elastic"),
                            exist_ok=True)
                with open(os.path.join(model_path, "elastic",
                                       "preempt.json"), "w") as f:
                    json.dump({"count": 1}, f)
                noticed = True
            time.sleep(0.5)
        assert proc.poll() is not None, "controller did not finish in time"
    finally:
        if proc.poll() is None:
            proc.kill()
    out, _ = proc.communicate(timeout=30)
    log = open(os.path.join(model_path, "run.log")).read()
    assert noticed and proc.returncode == 0, out[-2000:] + log[-4000:]
    assert "elastic: preemption notice" in log, log[-4000:]
    assert "graceful shrink 2 -> 1" in log, log[-4000:]
    assert "fleet finished cleanly" in log, log[-4000:]
    # the notice was consumed, not left to re-trigger forever
    assert not os.path.exists(os.path.join(model_path, "elastic",
                                           "preempt.json"))
    # graceful = the 143 path: gen 0 wrote its emergency checkpoint and
    # gen 1 finished the full run single-process
    done = re.findall(r"ELASTIC_DONE g=(\d+) world=(\d+) final_step=(\d+)",
                      log)
    assert done and done[-1][1] == "1" and done[-1][2] == "40", done
    # elastic observability rode the normal telemetry pipeline (world 2)
    tele = open(os.path.join(model_path, "telemetry.jsonl")).read()
    assert "hbnlp_elastic_generation" in tele
    assert "hbnlp_elastic_world_size" in tele
    entries = _assert_datalog_multiset_exact(cfg, model_path)
    assert [e["slice_count"] for e in entries] == [2, 1], entries
