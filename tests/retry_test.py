"""Retry policy (utils/retry.py): deterministic backoff schedule with an
injected clock/rng, transient-vs-permanent classification, attempt budget —
and the policy wired through the fs seam (GCSFS primitives retry a flaky
fake client; the mem:// path retries injected transients at the checkpoint
call sites in fault_injection_test.py)."""
import random

import pytest

from backend import make_params  # noqa: F401  (CPU env bootstrap)
from homebrewnlp_tpu.utils import retry


class _FixedRng:
    """rng.random() -> constant: jitter becomes exactly base * (1 + j * c)."""

    def __init__(self, value=0.0):
        self.value = value

    def random(self):
        return self.value


def _policy(sleeps, **kw):
    kw.setdefault("rng", _FixedRng(0.0))
    return retry.RetryPolicy(sleep=sleeps.append, **kw)


def backoff_schedule_test():
    """Exponential, capped, jittered — deterministic under injected rng."""
    sleeps = []
    pol = _policy(sleeps, max_attempts=6, base_delay=1.0, max_delay=8.0,
                  multiplier=2.0, jitter=0.25, rng=_FixedRng(1.0))
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        raise retry.TransientError("always down")

    with pytest.raises(retry.TransientError):
        pol.call(flaky)
    assert calls["n"] == 6  # the full attempt budget, then re-raise
    # delays: min(8, 1*2^n) * (1 + 0.25*1.0) for n = 0..4
    assert sleeps == [1.25, 2.5, 5.0, 10.0, 10.0]


def transient_recovers_test():
    sleeps = []
    pol = _policy(sleeps, max_attempts=5, base_delay=0.5)
    calls = {"n": 0}

    def twice_down():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise ConnectionResetError("peer reset")
        return "ok"

    assert pol.call(twice_down) == "ok"
    assert calls["n"] == 3 and len(sleeps) == 2


def permanent_not_retried_test():
    """FileNotFoundError & friends surface immediately — retrying a missing
    checkpoint shard only delays the real diagnostic."""
    sleeps = []
    pol = _policy(sleeps)
    calls = {"n": 0}

    def missing():
        calls["n"] += 1
        raise FileNotFoundError("gs://bucket/absent")

    with pytest.raises(FileNotFoundError):
        pol.call(missing)
    assert calls["n"] == 1 and sleeps == []


@pytest.mark.parametrize("exc,transient", [
    (ConnectionResetError("reset"), True),
    (TimeoutError("deadline"), True),
    (BrokenPipeError("pipe"), True),
    (retry.TransientError("explicit"), True),
    (type("ServiceUnavailable", (Exception,), {})("503"), True),   # GCS name
    (type("TooManyRequests", (Exception,), {})("429"), True),
    (type("ApiError", (Exception,), {"code": 503})("503"), True),  # http attr
    (type("ApiError", (Exception,), {"code": 404})("404"), False),
    (FileNotFoundError("absent"), False),
    (PermissionError("denied"), False),
    (IsADirectoryError("dir"), False),
    (ValueError("corrupt"), False),
    (type("NotFound", (Exception,), {})("404"), False),            # GCS 404
])
def classification_test(exc, transient):
    assert retry.is_transient(exc) is transient


def default_policy_swap_test():
    """set_default_policy swaps take effect at existing call sites at once
    (consumers look the policy up at call time, never cache it)."""
    old = retry.default_policy()
    try:
        marker = retry.RetryPolicy(max_attempts=1)
        retry.set_default_policy(marker)
        assert retry.default_policy() is marker
        retry.set_default_policy(None)
        assert retry.default_policy() is not marker
    finally:
        retry.set_default_policy(old)


def gcsfs_primitives_retry_test(monkeypatch):
    """Every GCSFS primitive retries transient client failures: a fake
    google-cloud client that 503s the first N calls of each method succeeds
    under the policy, and the blobs land intact."""
    import sys
    import types

    from homebrewnlp_tpu.utils import fs

    class ServiceUnavailable(Exception):  # matched by NAME, like the real one
        pass

    failures = {"n": 0}

    def maybe_fail():
        if failures["n"] > 0:
            failures["n"] -= 1
            raise ServiceUnavailable("503 backend error")

    store = {}

    class Blob:
        def __init__(self, name):
            self.name = name

        def upload_from_string(self, data):
            maybe_fail()
            store[self.name] = bytes(data)

        def download_as_bytes(self):
            maybe_fail()
            return store[self.name]

        def delete(self):
            maybe_fail()
            store.pop(self.name, None)

    class Bucket:
        name = "bucket"

        def blob(self, name):
            return Blob(name)

        def list_blobs(self, prefix=""):
            maybe_fail()
            return [Blob(n) for n in sorted(store) if n.startswith(prefix)]

    class Client:
        def bucket(self, name):
            return Bucket()

    storage_mod = types.ModuleType("google.cloud.storage")
    storage_mod.Client = Client
    cloud_mod = types.ModuleType("google.cloud")
    cloud_mod.storage = storage_mod
    monkeypatch.setitem(sys.modules, "google.cloud.storage", storage_mod)
    monkeypatch.setitem(sys.modules, "google.cloud", cloud_mod)

    sleeps = []
    old = retry.default_policy()
    retry.set_default_policy(retry.RetryPolicy(
        max_attempts=4, base_delay=0.1, sleep=sleeps.append,
        rng=random.Random(0)))
    try:
        gcsfs = fs.GCSFS()
        fs.register("gs", gcsfs)
        for op in (lambda: gcsfs._write("gs://bucket/a", b"payload"),
                   lambda: gcsfs._read("gs://bucket/a"),
                   lambda: gcsfs._keys("gs://bucket/"),
                   lambda: gcsfs._delete("gs://bucket/a")):
            failures["n"] = 2  # two 503s, then success — inside the budget
            op()
        assert "gs://bucket/a"[len("gs://bucket/"):] not in store
        assert len(sleeps) == 8  # 2 retries x 4 primitives
        # budget exhaustion: 4 failures > 4 attempts - 1 retries
        failures["n"] = 99
        with pytest.raises(ServiceUnavailable):
            gcsfs._read("gs://bucket/b")
    finally:
        retry.set_default_policy(old)
        fs.register("gs", fs.GCSFS)
