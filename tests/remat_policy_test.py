"""Measured remat policy (``remat_policy``, model/remat.py).

Every policy executes the SAME primal recurrence: losses match exactly and
updated parameters agree to reconstruction ulps (the tolerance class the
stash tests established).  ``auto`` resolution is pinned: explicit values
pass through, the legacy ``stash_attention_outputs`` boolean maps onto
stash/recompute, the long-context stash rule still fires, and short-context
default resolves to recompute (the round-11 A/B measured the save modes
SLOWER on the memory-bound rig — auto must not silently adopt them).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from backend import make_params
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.model.remat import remat_report, resolve_remat
from homebrewnlp_tpu.train import Trainer

_CFG = dict(sequence_length=32, features_per_head=16, heads=2, depth=2,
            train_batch_size=4, vocab_size=64,
            optimizer="momentum:0.9:1:1-learning_rate", learning_rate=0.01)


def _step(policy, strategy, scan):
    params = make_params(memory_reduction_strategy=strategy,
                         scan_layers=scan, remat_policy=policy, **_CFG)
    model = Model(params)
    trainer = Trainer(params, model)
    rng = np.random.default_rng(0)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    batch = {"token_x": jnp.asarray(x),
             "token_y": jnp.asarray((x + 1) % params.vocab_size)}
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch, jax.random.PRNGKey(0))
    return state, metrics


@pytest.mark.parametrize("strategy", ["revnet", "momentum"])
@pytest.mark.parametrize("scan", [True, False])
@pytest.mark.parametrize("policy", ["save", "save_dots"])
def save_policy_parity_test(strategy, scan, policy):
    """save/save_dots vs the recompute default: identical loss (same
    primal), same updated params to reconstruction ulps — scanned and
    unrolled, both invertible strategies."""
    s0, m0 = _step("recompute", strategy, scan)
    s1, m1 = _step(policy, strategy, scan)
    np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                               rtol=1e-6)
    for n in s0.variables:
        np.testing.assert_allclose(np.asarray(s0.variables[n], np.float32),
                                   np.asarray(s1.variables[n], np.float32),
                                   rtol=2e-4, atol=1e-5, err_msg=n)


def resolve_remat_mapping_test():
    def p(**kw):
        return make_params(**{**_CFG, **kw})

    # explicit values pass straight through
    for v in ("recompute", "stash", "save", "save_dots"):
        assert resolve_remat(p(remat_policy=v)) == v
    # legacy boolean maps onto the policy when remat_policy stays auto
    assert resolve_remat(p(stash_attention_outputs=True)) == "stash"
    assert resolve_remat(p(stash_attention_outputs=False)) == "recompute"
    # explicit policy WINS over the legacy boolean
    assert resolve_remat(p(remat_policy="save",
                           stash_attention_outputs=False)) == "save"
    # the long-context auto-stash rule survives the policy layer (the
    # measured 16k recipe), short context resolves to recompute
    assert resolve_remat(p(sequence_length=16384)) == "stash"
    assert resolve_remat(p(sequence_length=512)) == "recompute"
    assert resolve_remat(p(sequence_length=16384 + 64)) == "recompute"
    # a stash too big for 15% of HBM falls back (32k x batch 64 at the
    # 16k-recipe width: ~70GB of stash vs a 16GB planning figure —
    # stash_test pins the same boundary through resolve_stash)
    assert resolve_remat(p(sequence_length=32768, train_batch_size=64,
                           features_per_head=128, heads=8,
                           depth=16)) == "recompute"


def remat_report_fields_test():
    rep = remat_report(make_params(**_CFG))
    for key in ("stash_bytes_per_device", "save_residual_bytes_per_device",
                "hbm_bytes", "recompute_block_s", "save_block_s"):
        assert rep[key] > 0, key


def auto_is_recompute_at_flagship_shapes_test():
    """The flagship (CPU-shrunk) bench shapes resolve to recompute — the
    round-11 A/B measured recompute 204 / save 280 / save_dots 249 ms/step
    there, and auto must track the measurement, not a hunch."""
    params = make_params(sequence_length=64, features_per_head=64, heads=8,
                         depth=4, train_batch_size=8,
                         memory_reduction_strategy="revnet")
    assert resolve_remat(params) == "recompute"
