"""Cost-attribution layer (marker: attribution; docs/OBSERVABILITY.md
'Cost attribution').

Cheap half: scope folding, the HLO instruction->scope join on synthetic
text, trace loading/filtering on the checked-in miniature fixture
(tests/data/mini_trace), the ledger regression check's negative controls
(an inflated ledger MUST fail the lint), and the serving TTFT/ITL/cache-
bandwidth recording driven through the real hook plumbing.

Expensive half (one audit-model build per module): the committed
``analysis/cost_ledger.json`` matches a fresh build, and
``scripts/attribute_step.py`` on a real CPU ``jax.profiler`` capture of
the audit train step attributes >= 5 distinct model scopes with < 15% of
device time unattributed — the PR's acceptance criterion.
"""
import copy
import glob
import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

import analyze_trace  # noqa: E402
import attribute_step  # noqa: E402
from backend import make_params  # noqa: E402
from homebrewnlp_tpu import telemetry  # noqa: E402
from homebrewnlp_tpu.analysis import cost_ledger  # noqa: E402

pytestmark = pytest.mark.attribution

MINI_TRACE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "data", "mini_trace")


@pytest.fixture
def fresh_registry():
    reg = telemetry.Registry()
    prev = telemetry.set_registry(reg)
    import homebrewnlp_tpu.infer.rest_api as ra
    saved = ra._SERVE_METRICS
    ra._SERVE_METRICS = None
    try:
        yield reg
    finally:
        ra._SERVE_METRICS = saved
        telemetry.set_registry(prev)


# ------------------------------------------------------------- scope folding

def scope_key_test():
    sk = cost_ledger.scope_key
    assert sk("jit(step_fn)/jit(main)/jvp(gpt0)/body0/while/body/"
              "block0_1_0/attention_1/abc,dcae->dbae/dot_general") \
        == "body/attention"
    # backward ops fold into the SAME per-block scope as forward
    assert sk("transpose(jvp(gpt0))/body0/while/body/block0_0_0/"
              "bottleneck_group_linear_0/dot_general") \
        == "body/bottleneck_group_linear"
    assert sk("jvp(gpt0)/input0/gather0/embed0/convert") == "input/embed"
    assert sk("jvp(gpt0)/input0/abcd,de->abce/dot_general") == "input"
    assert sk("gpt0/output0/embed0/orthogonal_var0/convert") \
        == "output/unembed"
    assert sk("gpt0/loss0/reduce_sum") == "loss"
    assert sk("jit(step_fn)/jit(main)/optimizer/mul") == "optimizer"
    assert sk("gpt0/body0/block0_1_0/attention_0/cache_write/"
              "dynamic_update_slice") == "decode/cache_write"
    assert sk("sampling/argmax") == "decode/sampling"
    assert sk("jit(step_fn)/jit(main)/mul") == "unscoped"


# ------------------------------------------- instruction table + event join

_SYNTH_HLO = """\
HloModule jit_step_fn, entry_computation_layout={()->f32[4]}

%fused_computation.1 (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  %mul.3 = f32[4]{0} multiply(f32[4]{0} %p0, f32[4]{0} %p0), metadata={op_name="jit(step_fn)/jit(main)/gpt0/body0/block0_0_0/norm_0/mul"}
  ROOT %bitcast.9 = f32[4]{0} bitcast(f32[4]{0} %mul.3)
}

ENTRY %main.10 () -> f32[4] {
  %dot.5 = f32[4]{0} dot(f32[4]{0} %x, f32[4]{0} %y), lhs_contracting_dims={0}, rhs_contracting_dims={0}, metadata={op_name="jit(step_fn)/jit(main)/gpt0/body0/block0_1_0/attention_0/dot_general"}
  %convert_add_fusion.clone = f32[4]{0} fusion(f32[4]{0} %dot.5), kind=kLoop, calls=%fused_computation.1
  %copy_bitcast_fusion.2 = f32[4]{0} fusion(f32[4]{0} %dot.5), kind=kLoop, calls=%fused_computation.1
  %while.1 = (s32[], f32[4]{0}) while((s32[], f32[4]{0}) %tup), condition=%cond, body=%bodyc
  ROOT %broadcast.9 = f32[4]{0} broadcast(f32[] %c), dimensions={}
}
"""


def instruction_table_test():
    table = cost_ledger.instruction_table(_SYNTH_HLO)
    assert table["dot.5"]["kind"] == "dot"
    assert table["dot.5"]["op_name"].endswith("attention_0/dot_general")
    # fusion without own metadata inherits through calls= (root is a
    # metadata-less bitcast -> majority vote of the computation's members)
    assert table["convert_add_fusion.clone"]["op_name"].endswith("norm_0/mul")
    assert table["copy_bitcast_fusion.2"]["op_name"].endswith("norm_0/mul")
    assert table["while.1"]["kind"] == "while"


_CHAINED_HLO = """\
HloModule jit_chain

%inner (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  ROOT %mul.1 = f32[4]{0} multiply(f32[4]{0} %p0, f32[4]{0} %p0), metadata={op_name="jit(f)/gpt0/body0/block0_0_0/norm_0/mul"}
}

%wrapper (p1: f32[4]) -> f32[4] {
  %p1 = f32[4]{0} parameter(0)
  ROOT %fusion.2 = f32[4]{0} fusion(f32[4]{0} %p1), kind=kLoop, calls=%inner
}

ENTRY %main () -> f32[4] {
  %call.3 = f32[4]{0} call(f32[4]{0} %x), to_apply=%wrapper
  ROOT %tuple.4 = (f32[4]{0}) tuple(f32[4]{0} %call.3)
}
"""


def instruction_table_chained_inheritance_test():
    """A metadata-less call into a computation whose ONLY member is a
    metadata-less fusion must hop through that fusion's computation: the
    'call -> computation whose root is a fusion' chain resolves instead of
    inflating the unattributed share."""
    table = cost_ledger.instruction_table(_CHAINED_HLO)
    assert table["fusion.2"]["op_name"].endswith("norm_0/mul")
    assert table["call.3"]["op_name"].endswith("norm_0/mul")


def attribute_events_test():
    table = cost_ledger.instruction_table(_SYNTH_HLO)
    events = [("dot.5", 300.0),
              ("convert_add_fusion", 200.0),   # .clone fallback lookup
              ("copy_bitcast_fusion.2", 100.0),
              ("while.1", 650.0),              # container: excluded
              ("broadcast.9", 50.0)]           # no metadata: unattributed
    per_scope, unattr, total = cost_ledger.attribute_events(events, table)
    assert total == 650.0                      # while excluded from total
    assert per_scope["body/attention"] == 300.0
    assert per_scope["body/norm"] == 300.0
    assert per_scope["unattributed"] == 50.0 and unattr == {"broadcast.9": 50.0}


def attribute_fn_with_ledger_test():
    ledger_entry = {"scopes": {
        "body/attention": {"flops_share": 0.9, "bytes_share": 0.5,
                           "bound": "compute"},
        "body/norm": {"flops_share": 0.0, "bytes_share": 0.1,
                      "bound": "hbm"}}}
    table_events = [("dot.5", 100.0), ("convert_add_fusion", 400.0)]
    rows, unattributed, total = attribute_step.attribute(
        table_events, _SYNTH_HLO, ledger_entry)
    by_scope = {r["scope"]: r for r in rows}
    # norm burns 80% of time with ~0 flops and 10% of bytes: pure overhead
    assert by_scope["body/norm"]["overhead"] is True
    assert by_scope["body/attention"]["overhead"] is False
    assert unattributed == 0.0 and total == 500.0


# ---------------------------------------------------- trace loading fixture

def mini_trace_load_test():
    evs = analyze_trace.load_events(MINI_TRACE)
    # 0-duration and non-X events dropped
    assert len(evs) == 9
    dev = analyze_trace.device_events(evs)
    assert len(dev) == 6
    assert all(e["args"]["hlo_op"] for e in dev)
    mods = {e["args"]["hlo_module"] for e in dev}
    assert mods == {"jit_step_fn", "jit_other"}


def mini_trace_categorize_test():
    assert analyze_trace.categorize("dynamic-update-slice.3") \
        == "scan-stack (DUS)"
    assert analyze_trace.categorize("convert_bitcast_fusion.9") \
        == "convert/copy/transpose"
    assert analyze_trace.categorize("copy_bitcast_fusion.2") \
        == "convert/copy/transpose"
    assert analyze_trace.categorize("reduce.17") == "reduce"
    assert analyze_trace.categorize("fusion.3") == "fusion (dot-rooted)"
    # loop/input fusions are elementwise bodies, NOT dot-rooted
    assert analyze_trace.categorize("loop_fusion.42") \
        == "fusion (loop/elementwise)"
    assert analyze_trace.categorize("input_fusion.7") \
        == "fusion (loop/elementwise)"


def empty_trace_fails_loudly_test(tmp_path):
    import gzip
    import subprocess
    d = tmp_path / "plugins" / "profile" / "0"
    d.mkdir(parents=True)
    p = d / "host.trace.json.gz"
    with gzip.open(p, "wt") as f:
        json.dump({"traceEvents": [{"ph": "M", "name": "meta"}]}, f)
    assert analyze_trace.load_events(str(tmp_path)) == []
    # the CLI: zero timed events exits nonzero NAMING the file, instead of
    # printing an empty table
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable,
                        os.path.join(repo, "scripts", "analyze_trace.py"),
                        str(tmp_path)], capture_output=True, text=True)
    assert r.returncode != 0
    assert "host.trace.json.gz" in (r.stderr + r.stdout)
    # attribute_step fails loudly too
    with pytest.raises(SystemExit, match="zero device-side"):
        attribute_step.main([str(tmp_path), "--hlo", os.devnull])


def missing_trace_dir_fails_test(tmp_path):
    with pytest.raises(SystemExit, match="no .*trace.json.gz"):
        analyze_trace.resolve_trace_file(str(tmp_path))


# ------------------------------------------------- ledger negative controls

def ledger_missing_file_is_finding_test(tmp_path):
    f = cost_ledger.ledger_audit(path=str(tmp_path / "absent.json"),
                                 current={"entry_points": {}})
    assert len(f) == 1 and "missing" in f[0].message


def ledger_inflated_negative_control_test():
    """Acceptance: a synthetically inflated ledger entry MUST fail the
    regression check (and an identical one must pass)."""
    stored = cost_ledger.load_ledger()
    assert stored is not None, "analysis/cost_ledger.json must be committed"
    assert set(stored["entry_points"]) == {"train_step", "decode_chunk_step",
                                           "prefill_entry_step", "eval_fn",
                                           "engine_chunk_step",
                                           "spec_chunk_step",
                                           "paged_chunk_step"}
    clean = cost_ledger.ledger_audit(current=copy.deepcopy(stored))
    assert clean == []
    bad = copy.deepcopy(stored)
    bad["entry_points"]["train_step"]["scopes"]["body/attention"]["flops"] \
        *= 2
    findings = cost_ledger.ledger_audit(current=bad)
    assert findings and findings[0].rule == "cost-ledger"
    assert "body/attention" in findings[0].message
    # a vanished scope is a finding too
    gone = copy.deepcopy(stored)
    gone["entry_points"]["train_step"]["scopes"].pop("body/attention")
    findings = cost_ledger.ledger_audit(current=gone)
    assert any("vanished" in f.message or "not in the committed" in f.message
               for f in findings)
    # ... and so is a whole entry point dropping out of the fresh build
    dropped = copy.deepcopy(stored)
    dropped["entry_points"].pop("eval_fn")
    findings = cost_ledger.ledger_audit(current=dropped)
    assert any(f.entry == "eval_fn" and "vanished" in f.message
               for f in findings)


def ledger_schema_test():
    """Every entry carries per-scope flops/bytes/shares/bound and a total;
    >= 5 distinct model scopes per entry (the attribution floor)."""
    stored = cost_ledger.load_ledger()
    for entry, tab in stored["entry_points"].items():
        assert {"flops", "bytes", "intensity", "bound"} <= set(tab["total"])
        assert len(tab["scopes"]) >= 5, (entry, list(tab["scopes"]))
        for scope, s in tab["scopes"].items():
            assert {"flops", "bytes", "flops_share", "bytes_share",
                    "intensity", "bound"} <= set(s), (entry, scope)
            assert s["bound"] in ("compute", "hbm")
    decode_scopes = stored["entry_points"]["decode_chunk_step"]["scopes"]
    assert "decode/sampling" in decode_scopes
    assert "decode/cache_write" in decode_scopes


# --------------------------------------- serving hook -> TTFT/ITL recording

def decode_progress_recording_test(fresh_registry):
    """rest_api._decode_progress turns sampler hook events into TTFT (one
    per co-batched request, from its own admission timestamp), ITL (per
    chunk) and the cache-bandwidth gauges."""
    import homebrewnlp_tpu.infer.rest_api as ra
    import homebrewnlp_tpu.infer.sampler as sampler_mod
    t0 = time.monotonic()
    with ra._decode_progress([t0 - 2.0, t0 - 1.0, None]):
        hook = sampler_mod.decode_progress_hook()
        assert hook is not None
        hook("chunk", dt=0.2, steps=4, cache_bytes=1 << 30)
        hook("first_token")
        hook("chunk", dt=0.1, steps=2, cache_bytes=1 << 30)
    assert sampler_mod.decode_progress_hook() is None  # restored
    snap = fresh_registry.snapshot()
    ttft = snap["hbnlp_serve_ttft_seconds"]["series"][()]
    assert sum(ttft["counts"]) == 3
    assert ttft["sum"] >= 3.0          # 2s + 1s + ~0s
    itl = snap["hbnlp_serve_itl_seconds"]["series"][()]
    assert sum(itl["counts"]) == 2
    assert abs(itl["sum"] - 0.1) < 0.02  # 0.2/4 + 0.1/2
    bps = snap["hbnlp_decode_cache_read_bytes_per_second"]["series"][()]
    assert abs(bps - (1 << 30) * 2 / 0.1) / bps < 0.01  # last chunk wins
    frac = snap["hbnlp_decode_cache_bw_fraction_of_peak"]["series"][()]
    assert frac > 0


def per_row_ttft_heterogeneous_prompts_test(fresh_registry):
    """Co-batched requests close TTFT individually: a row whose prompt is
    still being walked when the batch's first token fires must NOT record
    its TTFT yet (the short prompt's event closes only its own row), and a
    row never closes twice."""
    import homebrewnlp_tpu.infer.rest_api as ra
    t0 = time.monotonic()
    with ra._decode_progress([t0 - 1.0, t0 - 1.0]):
        import homebrewnlp_tpu.infer.sampler as sampler_mod
        hook = sampler_mod.decode_progress_hook()
        hook("first_token", rows=[0])
        snap = fresh_registry.snapshot()
        assert sum(snap["hbnlp_serve_ttft_seconds"]["series"][()]
                   ["counts"]) == 1
        hook("first_token", rows=[0, 1])    # row 0 already closed
    snap = fresh_registry.snapshot()
    ttft = snap["hbnlp_serve_ttft_seconds"]["series"][()]
    assert sum(ttft["counts"]) == 2


def retry_does_not_double_count_ttft_test(fresh_registry):
    """A failed batch attempt that already fired a row's first token must
    not contribute a SECOND TTFT sample from that row's per-item retry —
    the caller-shared ``closed`` flags carry the state across attempts,
    while a row the batch never reached still records from its retry."""
    import homebrewnlp_tpu.infer.rest_api as ra
    import homebrewnlp_tpu.infer.sampler as sampler_mod
    t0 = time.monotonic()
    flags = [False, False]
    with ra._decode_progress([t0 - 1.0, t0 - 1.0], closed=flags):
        sampler_mod.decode_progress_hook()("first_token", rows=[0])
    assert flags == [True, False]
    # batch decode failed after row 0's first token: per-row retries
    with ra._decode_progress([t0 - 1.0], closed=flags[0:1]):
        sampler_mod.decode_progress_hook()("first_token")
    with ra._decode_progress([t0 - 1.0], closed=flags[1:2]):
        sampler_mod.decode_progress_hook()("first_token")
    snap = fresh_registry.snapshot()
    assert sum(snap["hbnlp_serve_ttft_seconds"]["series"][()]
               ["counts"]) == 2


def stepped_per_row_first_token_test(fresh_registry):
    """The REAL stepped loop fires first_token per row at that row's own
    initial position: with prompts of length 4 and 20 (chunk 4), row 0's
    event lands chunks before row 1's."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.infer import sampler

    params = make_params(vocab_size=64, sequence_length=32, depth=2,
                         heads=2, features_per_head=8, train_batch_size=2,
                         decode_loop="stepped", decode_chunk_tokens=4)
    model = Model(params)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 64, (2, 32, 1)).astype(np.int32)
    variables = {k: jnp.asarray(v) for k, v in model.init(
        {"token_x": jnp.asarray(tok), "token_y": jnp.asarray(tok)}).items()}
    events = []
    prev = sampler.set_decode_progress_hook(
        lambda ev, **kw: events.append((ev, dict(kw))))
    try:
        sampler.sample_text(model, variables, tok[:, :20, 0],
                            initial_pos=np.asarray([4, 20]),
                            temperature=0.0, end_iterations=28, seed=0)
    finally:
        sampler.set_decode_progress_hook(prev)
    firsts = [(i, kw["rows"]) for i, (ev, kw) in enumerate(events)
              if ev == "first_token"]
    assert [rows for _, rows in firsts] == [[0], [1]]
    assert firsts[0][0] < firsts[1][0], "row 1 must fire in a LATER chunk"


def stepped_zero_chunk_decode_still_fires_first_token_test():
    """A stepped decode that ends before ANY chunk runs (end_iterations
    at/below the prefill position) still closes one first_token per row at
    completion — otherwise the serving TTFT histogram silently drops
    exactly the cheapest requests and its quantiles bias upward."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.infer import sampler

    params = make_params(vocab_size=64, sequence_length=32, depth=2,
                         heads=2, features_per_head=8, train_batch_size=2,
                         decode_loop="stepped", decode_chunk_tokens=4)
    model = Model(params)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 64, (2, 32, 1)).astype(np.int32)
    variables = {k: jnp.asarray(v) for k, v in model.init(
        {"token_x": jnp.asarray(tok), "token_y": jnp.asarray(tok)}).items()}
    events = []
    prev = sampler.set_decode_progress_hook(
        lambda ev, **kw: events.append((ev, dict(kw))))
    try:
        sampler.sample_text(model, variables, tok[:, :20, 0],
                            initial_pos=np.asarray([4, 20]),
                            temperature=0.0, end_iterations=4, seed=0)
    finally:
        sampler.set_decode_progress_hook(prev)
    rows = [kw["rows"] for ev, kw in events if ev == "first_token"]
    assert sorted(r for rs in rows for r in rs) == [0, 1], events


def decode_progress_hook_thread_isolated_test():
    """The hook is per-thread: concurrent in-process requests install and
    restore without swapping each other's hooks mid-decode (both serving
    modes run the decode on the installing thread)."""
    import threading
    import homebrewnlp_tpu.infer.sampler as sampler_mod

    installed = threading.Event()
    checked = threading.Event()
    other: list = []

    def worker():
        mine = lambda ev, **kw: None  # noqa: E731
        assert sampler_mod.set_decode_progress_hook(mine) is None
        installed.set()
        checked.wait(timeout=10)
        other.append(sampler_mod.decode_progress_hook() is mine)
        sampler_mod.set_decode_progress_hook(None)

    t = threading.Thread(target=worker)
    t.start()
    installed.wait(timeout=10)
    # the worker's hook is invisible here, and installing here is
    # invisible to the worker
    assert sampler_mod.decode_progress_hook() is None
    prev = sampler_mod.set_decode_progress_hook(lambda ev, **kw: 1)
    assert prev is None
    checked.set()
    t.join(timeout=10)
    sampler_mod.set_decode_progress_hook(None)
    assert other == [True]


def stepped_decode_fires_hook_test(fresh_registry):
    """The REAL stepped loop fires chunk + first_token events, and the
    instrumented decode is bit-identical to the uninstrumented one."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.infer import sampler

    params = make_params(vocab_size=64, sequence_length=32, depth=2,
                         heads=2, features_per_head=8, train_batch_size=2,
                         decode_loop="stepped", decode_chunk_tokens=4)
    model = Model(params)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 64, (2, 32, 1)).astype(np.int32)
    variables = {k: jnp.asarray(v) for k, v in model.init(
        {"token_x": jnp.asarray(tok), "token_y": jnp.asarray(tok)}).items()}
    events = []
    prev = sampler.set_decode_progress_hook(
        lambda ev, **kw: events.append((ev, kw)))
    try:
        out = sampler.sample_text(model, variables, tok[:, :8, 0],
                                  initial_pos=8, temperature=0.0,
                                  end_iterations=20, seed=0)
    finally:
        sampler.set_decode_progress_hook(prev)
    kinds = [e[0] for e in events]
    assert "first_token" in kinds and kinds.count("chunk") >= 2
    chunks = [kw for ev, kw in events if ev == "chunk"]
    assert all(kw["cache_bytes"] > 0 and kw["dt"] > 0 for kw in chunks)
    assert sum(kw["steps"] for kw in chunks) == 19 - 7  # q walks 7 -> 19
    out2 = sampler.sample_text(model, variables, tok[:, :8, 0],
                               initial_pos=8, temperature=0.0,
                               end_iterations=20, seed=0)
    assert np.array_equal(out, out2), "hook changed decode output"


@pytest.mark.serving
def serving_metrics_carry_ttft_and_build_info_test():
    """Through the REAL isolated serving stack (spawn child + Manager IPC):
    a decode that reports progress lands TTFT/ITL histograms on the scraped
    /metrics, alongside the build-info gauge — the device loop installs the
    hook around the batch decode, publishes its registry over the
    heartbeat, and the HTTP child merges it at scrape time."""
    import urllib.request
    from serving_robustness_test import (_StubInterface, _post,
                                         _serve_params, _spawn_serve)
    from telemetry_test import _parse_exposition
    import homebrewnlp_tpu.infer.sampler as sampler_mod

    class _ProgressStub(_StubInterface):
        def _fire(self):
            hook = sampler_mod.decode_progress_hook()
            assert hook is not None, \
                "device loop must install the decode-progress hook"
            hook("chunk", dt=0.05, steps=5, cache_bytes=1 << 20)
            hook("first_token")

        def complete_tokens(self, *a, **k):
            self._fire()
            return super().complete_tokens(*a, **k)

        def complete_tokens_batch(self, *a, **k):
            self._fire()
            return super().complete_tokens_batch(*a, **k)

    params = _serve_params(serve_batch_size=4)
    port, stop, t = _spawn_serve(_ProgressStub(params))

    def scrape():
        req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.read().decode()

    try:
        _post(port, "/health", {})
        status, out, _ = _post(port, "/token_completion", {"tokens": [1, 2]})
        assert status == 200
        deadline = time.monotonic() + 10
        while True:
            types, series = _parse_exposition(scrape())
            if series.get(("hbnlp_serve_ttft_seconds_count", "")):
                break
            assert time.monotonic() < deadline, \
                "TTFT histogram never reached /metrics"
            time.sleep(0.1)
        assert types["hbnlp_serve_ttft_seconds"] == "histogram"
        assert series[("hbnlp_serve_itl_seconds_count", "")] >= 1
        assert types["hbnlp_build_info"] == "gauge"
        build = [k for k in series
                 if k[0] == "hbnlp_build_info" and 'git_rev="' in k[1]]
        assert build and series[build[0]] == 1
    finally:
        stop.set()
        t.join(timeout=15)
    assert not t.is_alive()


# --------------------------------------------- expensive: real audit model

@pytest.fixture(scope="module")
def audit_rig():
    from homebrewnlp_tpu.analysis import entry_points
    params, model, variables, token_x, batch = \
        entry_points.build_audit_model()
    trainer, state = entry_points.make_trainer(params, model, batch)
    hlo, ctx = entry_points.lower_train_step(params, model, variables,
                                             batch, trainer=trainer,
                                             state=state)
    return {"params": params, "model": model, "variables": variables,
            "batch": batch, "trainer": trainer, "state": state,
            "train_hlo": hlo, "train_ctx": ctx}


def committed_ledger_matches_fresh_build_test(audit_rig):
    """The regression check graft_lint --hlo runs: a fresh analytical build
    of the train-step entry agrees with analysis/cost_ledger.json within
    tolerance (full four-entry agreement is checked by the lint itself)."""
    stored = cost_ledger.load_ledger()
    fresh = cost_ledger.scope_table(audit_rig["train_ctx"]["trace"]())
    old = stored["entry_points"]["train_step"]
    tol = stored["tolerance"]
    assert set(fresh["scopes"]) == set(old["scopes"])
    for scope, s in fresh["scopes"].items():
        for metric in ("flops", "bytes"):
            a, b = old["scopes"][scope][metric], s[metric]
            assert abs(b - a) <= tol * max(abs(a), 1), (scope, metric, a, b)


def attribute_step_end_to_end_test(audit_rig, tmp_path, capsys):
    """PR acceptance: attribute_step on a CPU profile_steps-style capture
    of the audit model prints a per-scope table with >= 5 distinct model
    scopes attributed and < 15% of device time unattributed."""
    import jax
    trainer, state, batch = (audit_rig["trainer"], audit_rig["state"],
                             audit_rig["batch"])
    state, m = trainer.step(state, batch)    # compile outside the capture
    jax.block_until_ready(m["loss"])
    jax.profiler.start_trace(str(tmp_path))
    for _ in range(3):
        state, m = trainer.step(state, batch)
    jax.block_until_ready(m["loss"])
    jax.profiler.stop_trace()
    assert glob.glob(str(tmp_path / "**" / "*.trace.json.gz"),
                     recursive=True)

    hlo_file = tmp_path / "train_step_compiled.txt"
    hlo_file.write_text(audit_rig["train_hlo"])
    rc = attribute_step.main([str(tmp_path), "--steps", "3",
                              "--hlo", str(hlo_file)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "scope attribution" in out and "ms/step" in out
    model_scopes = [ln.split()[0] for ln in out.splitlines()
                    if ln.strip() and ln.split()[0].startswith(
                        ("body", "input", "output", "loss", "optimizer",
                         "decode"))]
    assert len(set(model_scopes)) >= 5, out
    unattr = [ln for ln in out.splitlines()
              if ln.startswith("unattributed device time:")]
    assert unattr, out
    share = float(unattr[0].split(":")[1].split("%")[0])
    assert share < 15.0, out
