"""Worker for the 2-process multi-host input-assembly test.

Run as: python _multihost_worker.py <coordinator_port> <process_id> <num_procs>
with JAX_PLATFORMS=cpu and --xla_force_host_platform_device_count=4 so the
two processes form one 8-device multi-controller CPU "pod".

Each process feeds a DISTINCT per-process batch slice (rows filled with its
process id); shard_batch must assemble them into one global batch
(core/sharding.py shard_batch via jax.make_array_from_process_local_data —
the named equivalent of the reference's per-host infeed placement,
/root/reference/src/run/dataloader_placement.py:153-227).  The check reads
back per-row sums of the global array: the first half must come from
process 0, the second from process 1 — a plain device_put of the local slice
(the pre-fix behavior) would make every host see its own slice as the whole
batch instead.
"""
import sys

import numpy as np


def main() -> int:
    port, pid, nproc = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    import os
    os.environ["HBNLP_COORDINATOR"] = f"localhost:{port}"
    os.environ["HBNLP_NUM_PROCESSES"] = str(nproc)
    os.environ["HBNLP_PROCESS_ID"] = str(pid)
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    # the real bootstrap: explicit-flag discovery + gloo CPU collectives
    # (XLA's default CPU client refuses multi-process computations)
    from homebrewnlp_tpu.distributed import bootstrap
    assert bootstrap.maybe_initialize()
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib

    assert len(jax.devices()) == 4 * nproc, \
        f"expected {4 * nproc} global devices, got {len(jax.devices())}"

    global_batch = 4 * nproc   # divisible by the data axis at any fan-out
    cfg = {"model_mode": "gpt", "use_video": False, "use_language": True,
           "sequence_length": 16, "features_per_head": 8, "heads": 2,
           "depth": 1, "train_batch_size": global_batch, "vocab_size": 256,
           "tpu_size": 4 * nproc,
           "mesh_shape_override": {"data": 4 * nproc},
           "model_path": "/tmp/multihost_worker_run"}
    params = ModelParameter(cfg)
    mesh = shardlib.build_mesh(params)

    local = global_batch // nproc
    batch = {"token_x": np.full((local, 16, 1), pid, np.int32),
             "token_y": np.full((local, 16, 1), pid, np.int32)}
    sharded = shardlib.shard_batch(params, batch, mesh)

    g = sharded["token_x"]
    assert g.shape == (global_batch, 16, 1), g.shape

    # fully-replicated per-row sums: forces the cross-process gather so every
    # process can check the other's rows actually landed in the global batch
    rep = NamedSharding(mesh, PartitionSpec())
    row_sums = jax.jit(lambda x: jnp.sum(x, axis=(1, 2)),
                       out_shardings=rep)(g)
    got = np.asarray(row_sums)
    want = np.repeat(np.arange(nproc) * 16, local)
    assert np.array_equal(got, want), (got, want)

    # macro-batching path: leading axis is the macro index, batch axis is 1
    params.macro_batching = 2
    mb = {"token_x": np.full((2, local, 16, 1), pid, np.int32)}
    g2 = shardlib.shard_batch(params, mb, mesh)["token_x"]
    assert g2.shape == (2, global_batch, 16, 1), g2.shape
    got2 = np.asarray(jax.jit(lambda x: jnp.sum(x, axis=(2, 3)),
                              out_shardings=rep)(g2))
    assert np.array_equal(got2, np.stack([want, want])), (got2, want)

    print(f"worker {pid}: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
