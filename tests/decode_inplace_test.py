"""In-place KV-cache decode carry (ISSUE 2 tentpole).

Three properties of the stepped (donated chunked) decode loop:

  1. exact greedy/filtered parity with the fused while_loop sampler and the
     full-forward reference sampler — the loop restructure must not change
     one sampled token;
  2. the COMPILED per-token step contains no full-KV-cache-shaped copy and
     aliases every donated cache leaf input->output (infer/hlo_check.py) —
     the property whose loss made 32k decode cost 7.5x its read bound
     (BASELINE.md round 5); this asserts the fix at the artifact level, not
     the source level;
  3. the sequence-scaling probe is ~linear in cache bytes (slow-marked:
     timing-based).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer import hlo_check
from homebrewnlp_tpu.infer.sampler import (_sample_kv_stepped,
                                           decode_cache_bytes,
                                           init_decode_caches,
                                           make_kv_sampler, make_sampler)
from homebrewnlp_tpu.model import Model


def _build(cfg_overrides, seed=0):
    params = make_params(**cfg_overrides)
    model = Model(params)
    rng = np.random.default_rng(seed)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    token_x = rng.integers(0, params.vocab_size,
                           (params.train_batch_size, seq, tps)
                           ).astype(np.int32)
    batch = {"token_x": jnp.asarray(token_x), "token_y": jnp.asarray(token_x)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return params, model, variables, token_x


def stepped_decode_parity_test():
    """Greedy outputs of full-forward, fused-while_loop, and stepped
    samplers are identical — at 3x the harness default sequence and depth
    (a cache deep/long enough to exercise the restructured stacked carry)
    with a chunk size that forces many donated dispatches and a
    non-chunk-aligned final chunk."""
    params, model, variables, token_x = _build(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "revnet",
         "sequence_length": 48, "depth": 3, "decode_chunk_tokens": 5})
    seq = params.sequence_dim.size
    full = jax.jit(make_sampler(model))(
        variables, jnp.asarray(token_x), jnp.asarray(token_x),
        jnp.int32(4), jnp.float32(0.0), jnp.int32(seq), jax.random.PRNGKey(0))
    caches = init_decode_caches(model, variables, jnp.asarray(token_x))
    fused = jax.jit(make_kv_sampler(model))(
        variables, jnp.asarray(token_x), jnp.int32(4), jnp.float32(0.0),
        jnp.int32(seq), jax.random.PRNGKey(0), caches)
    stepped = _sample_kv_stepped(model, variables, jnp.asarray(token_x),
                                 4, 0.0, seq, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(full), np.asarray(fused))
    np.testing.assert_array_equal(np.asarray(full), np.asarray(stepped))


def stepped_prefill_parity_test():
    """The stepped loop entered after a one-shot prefill produces the same
    greedy stream as walking from position 0."""
    params, model, variables, token_x = _build(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "none",
         "decode_chunk_tokens": 3})
    seq = params.sequence_dim.size
    walk = _sample_kv_stepped(model, variables, jnp.asarray(token_x),
                              6, 0.0, seq, jax.random.PRNGKey(0),
                              prefill=False)
    pf = _sample_kv_stepped(model, variables, jnp.asarray(token_x),
                            6, 0.0, seq, jax.random.PRNGKey(0), prefill=True)
    np.testing.assert_array_equal(np.asarray(walk), np.asarray(pf))


def stepped_filter_parity_test():
    """Sampled (temperature + top-k/top-p/repetition) streams match the
    fused sampler bit-for-bit: both loops consume the identical per-step
    gumbel draw through the identical body."""
    params, model, variables, token_x = _build(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "none",
         "decode_chunk_tokens": 4})
    seq = params.sequence_dim.size
    batch = token_x.shape[0]
    fargs = (jnp.full((batch,), 5, jnp.int32),
             jnp.full((batch,), 0.9, jnp.float32),
             jnp.full((batch,), 1.3, jnp.float32))
    caches = init_decode_caches(model, variables, jnp.asarray(token_x))
    fused = jax.jit(make_kv_sampler(model, logits_filter=True))(
        variables, jnp.asarray(token_x), jnp.int32(4), jnp.float32(0.7),
        jnp.int32(seq), jax.random.PRNGKey(3), caches, *fargs)
    stepped = _sample_kv_stepped(model, variables, jnp.asarray(token_x),
                                 4, 0.7, seq, jax.random.PRNGKey(3),
                                 fargs=fargs)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(stepped))


def sample_text_stepped_routing_test():
    """decode_loop="stepped" routes sample_text through the donated chunk
    step (observable via the per-model jit cache; the prompt region must
    come back intact), and flipping the same model's knobs exercises the
    "auto" threshold routing against the measured cache size.  Output
    parity between the loops is pinned by the parity tests above —
    re-deriving it here would pay a second fused compile for no new
    information."""
    from homebrewnlp_tpu.infer.sampler import (_use_stepped_loop,
                                               sample_text)
    _, model, variables, token_x = _build(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "none",
         "decode_chunk_tokens": 4, "decode_loop": "stepped"})
    out = sample_text(model, variables, token_x[:, :4, 0],
                      initial_pos=4, temperature=0.0)
    assert any(k[1].startswith("kv_step")
               for k in model._sampler_jit_cache)
    np.testing.assert_array_equal(out[:, 1:4, 0], token_x[:, 1:4, 0])
    # "auto" picks the loop by measured cache size vs the threshold knob
    nbytes = decode_cache_bytes(model, variables, token_x)
    assert nbytes > 0
    model.params.decode_loop = "auto"
    model.params.decode_stepped_min_cache_gb = (nbytes + 1) / 1024 ** 3
    assert not _use_stepped_loop(model, variables, token_x)
    model.params.decode_stepped_min_cache_gb = (nbytes - 1) / 1024 ** 3
    assert _use_stepped_loop(model, variables, token_x)


def decode_step_inplace_hlo_test():
    """The compiled donated step: no full-cache-shaped copy, every cache
    leaf aliased input->output.  Revnet is the flagship strategy (the
    depth-scan layout); the "none" strategy rides the filter variant below
    and int8 its own test — together the three scan layouts and cache
    dtypes are covered at one compile each."""
    _, model, variables, token_x = _build(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "revnet"})
    hlo_check.assert_decode_step_inplace(model, variables,
                                         jnp.asarray(token_x))


def decode_step_int8_inplace_hlo_test():
    """int8 caches add the sibling f32 scale buffers to the donated carry;
    both must alias (a copied scale cache would silently re-grow with
    context length like the round-5 bug)."""
    _, model, variables, token_x = _build(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "revnet",
         "decode_cache_dtype": "int8"})
    hlo_check.assert_decode_step_inplace(model, variables,
                                         jnp.asarray(token_x))


def decode_step_filter_inplace_hlo_test():
    """The logits-filter variant (extra ``seen`` carry leaf) keeps the
    cache aliasing property."""
    _, model, variables, token_x = _build(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "none"})
    hlo_check.assert_decode_step_inplace(model, variables,
                                         jnp.asarray(token_x),
                                         logits_filter=True)


def hlo_checker_detects_full_cache_copy_test():
    """Negative control: the checker FLAGS a module that copies a
    full-cache-shaped buffer, and passes the same module once the copy is
    block-shaped — so a future aliasing regression cannot slip through a
    vacuous assertion."""
    shapes = {"cache/x/kv0": jax.ShapeDtypeStruct((2, 4, 16, 2, 16),
                                                  jnp.float32)}
    bad = ("%copy.9 = f32[2,4,16,2,16]{4,3,2,1,0} "
           "copy(f32[2,4,16,2,16]{4,3,2,1,0} %get-tuple-element.1)")
    ok = ("%copy.9 = f32[4,16,2,16]{3,2,1,0} "
          "copy(f32[4,16,2,16]{2,0,3,1} %transpose.1)")
    with pytest.raises(AssertionError, match="NOT aliased"):
        hlo_check.assert_no_full_cache_copy(bad, shapes)
    hlo_check.assert_no_full_cache_copy(ok, shapes)
    assert hlo_check.input_output_alias_count(
        "input_output_alias={ {0}: (31, {}, may-alias), "
        "{1}: (32, {}, may-alias) }") == 2


def spread_records_row_updates_test():
    """The KV scatter site records the row it wrote (and the int8 scale
    row) so the depth scan can copy back a ROW instead of the block —
    model/blocks.py relies on the recording to keep per-token writes
    row-sized."""
    from homebrewnlp_tpu.core import scope as scope_mod
    from homebrewnlp_tpu.core.dims import Dim
    from homebrewnlp_tpu.core.tensor import nt as nt_
    from homebrewnlp_tpu.model.decode import DecodeState, spread
    rng = np.random.default_rng(0)
    b, h, f, s = 2, 3, 8, 8
    x = jnp.asarray(rng.standard_normal((b, 1, h, f)), jnp.float32)
    dims = [Dim("batch", b), Dim("sequence", 1), Dim("heads", h),
            Dim("features_per_head", f)]
    for dtype, n_updates in ((None, 1), (jnp.int8, 2)):
        state = DecodeState(jnp.int32(2), s, "sequence", {},
                            cache_dtype=dtype)
        ctx = scope_mod.Context("apply", params={})
        ctx.decode = state
        with scope_mod.context(ctx):
            spread(nt_(x, dims), dims[1])
        assert len(state.row_updates) == n_updates, state.row_updates
        for name, (row, axis) in state.row_updates.items():
            assert axis == 1, (name, axis)
            assert row.shape[axis] == 1, (name, row.shape)
            assert row.shape[0] == b


def rest_health_decode_path_test():
    """/health reports which decode loop serves the deployment (the ops
    surface for the in-place carry property)."""
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.infer.rest_api import _handlers
    params, model, variables, _ = _build(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "none",
         "decode_loop": "stepped"})
    iface = InterfaceWrapper(params, model, variables)
    res = _handlers(iface)["/health"]({})
    assert res["status"] == "ok"
    assert res["decode_path"]["loop"] == "stepped"
    assert res["decode_path"]["cache_gb"] >= 0
    assert res["decode_path"]["chunk_tokens"] == params.decode_chunk_tokens


@pytest.mark.slow
def sequence_scaling_ratio_test():
    """The probe's per-token cost is ~linear in cache bytes: the large/small
    ms-per-token ratio stays within 1.5x the byte ratio (the fused-loop
    regression measured 6x for a 4x cache).  Timing-based: slow-marked and
    bounded generously for CI noise."""
    import sys
    import os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import bench_decode
    # best-of-2 with a wide timed window: the small-seq denominator is
    # tens of sub-millisecond CPU steps, so a single run's ratio can blow
    # past the bound on one scheduler/GC spike (observed ~1-in-5); min()
    # is the standard noise-robust latency estimator
    best = {}
    for _ in range(2):
        res = bench_decode.run(seqs=(256, 1024), cache_dtypes=("bfloat16",),
                               gen=64)
        for r in res["rows"]:
            if "ms_per_token" in r:
                best[r["seq"]] = min(best.get(r["seq"], float("inf")),
                                     r["ms_per_token"])
    assert set(best) == {256, 1024}, res["rows"]
    ratio = best[1024] / best[256]
    byte_ratio = 4.0
    assert ratio <= 1.5 * byte_ratio, (
        f"per-token cost scaled {ratio:.2f}x for a {byte_ratio:.1f}x cache "
        "— superlinear in cache bytes: the in-place carry regressed")
