"""Paged KV cache + radix prefix sharing (marker: pagedkv; docs/SERVING.md).

Device-free sweep: the BlockPool/RadixIndex lifecycle state machines —
refcounts with hard-error double-free negative controls, reservation
accounting, LRU eviction of refcount-0 leaves only, partial-prefix
matching — and the scheduler's fits-gate (block exhaustion QUEUES at the
FIFO head, never errors or skips).

Device sweep: greedy bit-parity of the paged engine against the plain
stepped loop — cold admissions, admissions into RECLAIMED (dirty) blocks
on an undersized pool, and prefix-HIT admissions whose prefill is skipped
over the shared span — plus copy-on-write leaving the shared parent block
bit-unchanged on device, exact free-accounting at release, the paged
chunk step's HLO audit (every pool leaf aliased, no full-pool copy), the
``kv_paging`` knob resolution matrix, and the REST path with the
``hbnlp_kv_*`` gauges.

Standalone-runnable (tier-1 truncates at 870s on this box;
``scripts/run_late_markers.sh`` runs this suite in the late-marker set):
``python -m pytest tests/paged_kv_test.py -q``
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer.paged import BlockPool, RadixIndex
from homebrewnlp_tpu.infer.scheduler import (EngineController, EngineRequest,
                                             SlotScheduler)

pytestmark = pytest.mark.pagedkv


# ---------------------------------------------------------- pool lifecycle

def block_pool_lifecycle_test():
    """alloc/addref/deref/reclaim accounting, and the double-free negative
    controls: deref of a freed or zero-ref block raises, reclaim of a free
    or still-referenced block raises."""
    pool = BlockPool(4)
    assert pool.free_count == 4 and pool.live_count == 0
    a = pool.alloc()
    b = pool.alloc()
    assert pool.free_count == 2 and pool.live_count == 2
    pool.addref(a)
    assert pool.deref(a) == 1          # shared ref gone, owner remains
    assert pool.deref(a) == 0
    pool.reclaim(a)
    assert pool.free_count == 3
    # negative controls: every double-free shape must raise
    with pytest.raises(ValueError):
        pool.deref(a)                   # deref of a freed block
    with pytest.raises(ValueError):
        pool.reclaim(a)                 # reclaim twice
    with pytest.raises(ValueError):
        pool.reclaim(b)                 # reclaim of a live block
    assert pool.deref(b) == 0
    with pytest.raises(ValueError):
        pool.deref(b)                   # deref below zero
    with pytest.raises(ValueError):
        pool.addref(a)                  # addref of a freed block
    # reservations subtract from availability
    pool.reserve(2)
    assert pool.available() == pool.free_count - 2
    assert pool.available(evictable=1) == pool.free_count - 1
    pool.unreserve(5)                   # floors at zero
    assert pool.available() == pool.free_count


def radix_lookup_insert_partial_test():
    """Full-block path matching, partial (divergence-point) matching, and
    the existing-node-wins insert rule."""
    tree = RadixIndex(block_tokens=4)
    pool = BlockPool(8)
    b0, b1 = pool.alloc(), pool.alloc()
    n0 = tree.insert(None, (1, 2, 3, 4), b0)
    n1 = tree.insert(n0, (5, 6, 7, 8), b1)
    assert tree.holds(b0) and tree.holds(b1) and len(tree) == 2
    full, partial, d = tree.lookup([1, 2, 3, 4, 5, 6, 7, 8, 9])
    assert [n.block for n in full] == [b0, b1] and d == 0
    # divergence inside the second block: partial match at depth 2
    full, partial, d = tree.lookup([1, 2, 3, 4, 5, 6, 99, 98])
    assert [n.block for n in full] == [b0]
    assert partial is n1 and d == 2
    # no match at all
    full, partial, d = tree.lookup([9, 9, 9, 9])
    assert full == [] and partial is None and d == 0
    # an identical insert returns the EXISTING node; the caller's block
    # stays private (not tree-held)
    b2 = pool.alloc()
    again = tree.insert(None, (1, 2, 3, 4), b2)
    assert again is n0 and not tree.holds(b2)


def radix_lru_eviction_test():
    """evict_lru removes only refcount-0 LEAVES, oldest-touched first; a
    referenced or internal node survives."""
    tree = RadixIndex(block_tokens=2)
    pool = BlockPool(8)
    blocks = [pool.alloc() for _ in range(3)]
    n0 = tree.insert(None, (1, 2), blocks[0])
    tree.insert(n0, (3, 4), blocks[1])       # leaf under n0
    tree.insert(None, (9, 9), blocks[2])     # independent leaf
    for b in blocks:
        assert pool.deref(b) == 0            # all cache-resident
    assert tree.evictable_count(pool) == 3
    # touch the independent leaf so the n0-subtree leaf is LRU
    tree.lookup([9, 9])
    assert tree.evict_lru(pool)
    assert not tree.holds(blocks[1])         # leaf evicted, not internal n0
    assert pool.free_count == 6
    # a referenced leaf is not evictable
    pool.addref(blocks[2])
    tree.lookup([1, 2])                      # make (9,9) LRU again
    assert tree.evict_lru(pool)
    assert not tree.holds(blocks[0]) and tree.holds(blocks[2])
    assert not tree.evict_lru(pool)          # only the referenced one left


def scheduler_fits_gate_queues_at_head_test():
    """The fits-gate (block exhaustion) stops admission AT the FIFO head:
    nothing errors, nothing skips ahead, and admission resumes when
    capacity returns."""
    t = [0.0]
    sched = SlotScheduler(4, clock=lambda: t[0])
    capacity = [1]                           # admissions the "pool" can hold

    def fits(req):
        return len(sched.resident) < capacity[0]

    for i in range(3):
        sched.submit(EngineRequest(rid=f"r{i}", path="/token_completion",
                                   toks=np.asarray([1, 2])))
    admitted = sched.admit(fits=fits)
    assert [r.rid for _, r, _ in admitted] == ["r0"]
    assert sched.admit(fits=fits) == []      # r1 queued, r2 behind it
    assert [r.rid for r in sched.pending] == ["r1", "r2"]
    capacity[0] = 3
    admitted = sched.admit(fits=fits)
    assert [r.rid for _, r, _ in admitted] == ["r1", "r2"]


# ----------------------------------------------------------- device parity

def _interface(**kw):
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp
    cfg = dict(block_config=MIXER_BLOCKS, memory_reduction_strategy="none",
               sequence_length=32, train_batch_size=1,
               decode_loop="stepped", decode_chunk_tokens=5)
    cfg.update(kw)
    params = make_params(**cfg)
    params.train = False
    model = Model(params)
    seq = params.sequence_dim.size
    batch = {"token_x": np.zeros((1, seq, 1), np.int32),
             "token_y": np.zeros((1, seq, 1), np.int32)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return InterfaceWrapper(params, model, variables)


def _paged_controller(iface, slots=4, block_tokens=4, pool_blocks=None,
                      decode_chunk=5, prefill_chunk=8):
    from homebrewnlp_tpu.infer.paged import PagedEngineExecutor
    ex = PagedEngineExecutor(iface, slots=slots, block_tokens=block_tokens,
                            pool_blocks=pool_blocks)
    answers = {}
    sched = SlotScheduler(ex.slots, clock=time.monotonic)
    ctl = EngineController(
        ex, sched, clock=time.monotonic, decode_chunk=decode_chunk,
        prefill_chunk=prefill_chunk,
        answer=lambda req, oc: answers.__setitem__(req.rid, oc))
    return ex, ctl, sched, answers


def _serve(ctl, answers, reqs, rounds=80):
    ctl.round(reqs)
    for _ in range(rounds):
        if all(r.rid in answers for r in reqs):
            return
        ctl.round()
    raise AssertionError(f"unanswered: "
                         f"{[r.rid for r in reqs if r.rid not in answers]}")


def _req(rid, toks, rl):
    return EngineRequest(rid=rid, path="/token_completion",
                         toks=np.asarray(toks, np.int32), response_len=rl)


def paged_greedy_bit_parity_reclaimed_blocks_test():
    """Paged-vs-plain greedy bit-parity token-for-token: co-resident
    strangers at mixed positions, then THREE more admission waves on an
    UNDERSIZED pool (blocks cycle through the free list and the radix
    cache gets LRU-evicted), so late requests decode in reclaimed dirty
    blocks — parity must hold through all of it."""
    iface = _interface()
    # pool of 16 blocks = half the slot-engine equivalent (4 slots x 8)
    ex, ctl, sched, answers = _paged_controller(iface, pool_blocks=16)
    assert ex.sharing
    waves = [
        [([1, 2, 3], 6), ([7, 8], 12), ([4, 5, 6, 7, 9], 3), ([10], None)],
        [([3, 1, 4], 8), ([2, 7, 1, 8], 10)],
        [([11, 12, 13, 14, 15], 7), ([9], 20)],
    ]
    n = 0
    for wave in waves:
        reqs = [_req(f"r{n + i}", toks, rl)
                for i, (toks, rl) in enumerate(wave)]
        n += len(wave)
        _serve(ctl, answers, reqs)
    n = 0
    for wave in waves:
        for toks, rl in wave:
            want = np.asarray(iface.complete_tokens(
                np.asarray(toks, np.int32), 0.0, rl))
            kind, got = answers[f"r{n}"]
            assert kind == "ok", (n, kind)
            np.testing.assert_array_equal(np.asarray(got), want, str(n))
            n += 1
    stats = ex.pool_stats()
    assert stats["blocks_total"] == 16
    assert stats["blocks_in_use"] == 0       # everything released


def paged_int8_kv_parity_test():
    """int8 KV pools page too: the sibling per-row scale caches carry the
    same sequence axis, ride the same block tables, and greedy output
    stays bit-identical to the plain stepped loop — including through a
    prefix-hit admission (shared blocks hold identical int8 rows AND
    identical scales, by quantization determinism)."""
    iface = _interface(decode_cache_dtype="int8")
    ex, ctl, sched, answers = _paged_controller(iface)
    # both the int8 rows and their f32 scale siblings must be paged
    paged = [n for n, (_, sax) in ex.leaf_info.items() if sax is not None]
    assert any(n.endswith("_scale") for n in paged), ex.leaf_info
    sysp = list(range(1, 14))
    a, b = sysp + [40], sysp + [41, 42]
    _serve(ctl, answers, [_req("a", a, 8)])
    _serve(ctl, answers, [_req("b", b, 8)])
    assert ex.pool_stats()["prefix_hit_tokens"] > 0
    for rid, toks, rl in (("a", a, 8), ("b", b, 8)):
        np.testing.assert_array_equal(
            np.asarray(answers[rid][1]),
            np.asarray(iface.complete_tokens(np.asarray(toks, np.int32),
                                             0.0, rl)), rid)


def paged_prefix_hit_skips_prefill_at_parity_test():
    """Two requests sharing a long system prompt: the second references
    the first's radix-cached blocks (prefix_hit_tokens grows, its q starts
    past the shared span — prefill skipped) and its output is BIT-IDENTICAL
    to a cold decode of the same prompt."""
    iface = _interface()
    ex, ctl, sched, answers = _paged_controller(iface)
    sysp = list(range(1, 17))                # 16 shared tokens, 4 blocks
    a, b = sysp + [21, 22], sysp + [23]
    _serve(ctl, answers, [_req("a", a, 6)])
    st0 = dict(ex.pool_stats())
    assert st0["prefix_hit_tokens"] == 0
    _serve(ctl, answers, [_req("b", b, 6)])
    st1 = ex.pool_stats()
    assert st1["prefix_hits"] == st0["prefix_hits"] + 1
    assert st1["prefix_hit_tokens"] - st0["prefix_hit_tokens"] == 16
    np.testing.assert_array_equal(
        np.asarray(answers["b"][1]),
        np.asarray(iface.complete_tokens(np.asarray(b, np.int32), 0.0, 6)))
    np.testing.assert_array_equal(
        np.asarray(answers["a"][1]),
        np.asarray(iface.complete_tokens(np.asarray(a, np.int32), 0.0, 6)))


def paged_cow_parent_blocks_bit_unchanged_test():
    """Copy-on-write at the divergence point: a child diverging INSIDE a
    shared block writes its own copy; the parent's physical block in the
    device pool stays bit-identical, and the child's output matches a cold
    decode."""
    iface = _interface()
    ex, ctl, sched, answers = _paged_controller(iface)
    parent = [5, 6, 7, 8, 9, 10]             # blocks: [5,6,7,8] + partial
    _serve(ctl, answers, [_req("parent", parent, 4)])
    st = ex.pool_stats()
    assert st["blocks_cached"] >= 1          # block (5,6,7,8) promoted
    # find the promoted block's physical id and snapshot its pool content
    full, _, _ = ex.tree.lookup(parent[:4])
    assert len(full) == 1
    phys = full[0].block

    def block_content():
        out = {}
        for name, leaf in ex._carry[2].items():
            baxis, sax = ex.leaf_info[name]
            if sax is None:
                continue
            out[name] = np.take(np.asarray(leaf), phys, axis=baxis).copy()
        return out

    before = block_content()
    assert before, "no paged leaves found"
    # child shares tokens 5,6 then diverges inside the first block
    child = [5, 6, 99, 98, 97]
    cow0 = ex.pool_stats()["cow_copies"]
    _serve(ctl, answers, [_req("child", child, 5)])
    assert ex.pool_stats()["cow_copies"] == cow0 + 1
    after = block_content()
    for name in before:
        np.testing.assert_array_equal(before[name], after[name], name)
    np.testing.assert_array_equal(
        np.asarray(answers["child"][1]),
        np.asarray(iface.complete_tokens(np.asarray(child, np.int32),
                                         0.0, 5)))


def paged_release_returns_exact_blocks_test():
    """Finishing a request returns exactly its non-shared blocks: private
    generation blocks land on the free list, fully-walked prompt blocks
    stay radix-cached (refcount 0, reclaimable), and shared parent blocks
    only lose the child's reference."""
    iface = _interface()
    ex, ctl, sched, answers = _paged_controller(iface)
    parent = list(range(1, 13))              # 12 prompt tokens = 3 blocks
    _serve(ctl, answers, [_req("p", parent, 8)])
    base = ex.pool_stats()
    assert base["blocks_in_use"] == 0
    # prompt blocks (1..8) cached; child references the first two
    full, _, _ = ex.tree.lookup(parent[:11])
    shared_ids = [n.block for n in full]
    assert len(shared_ids) == 2
    child = parent[:8] + [50, 51]            # shares 2 full blocks
    ex2_free_before = ex.pool.free_count
    _serve(ctl, answers, [_req("c", child, 6)])
    st = ex.pool_stats()
    # shared parents still cached with refcount back to 0, not freed
    for b in shared_ids:
        assert ex.tree.holds(b) and ex.pool.refcount(b) == 0
    assert st["blocks_in_use"] == 0
    # free + cached partition the pool exactly (nothing leaked)
    assert st["blocks_free"] + st["blocks_cached"] == st["blocks_total"]
    # the child's private non-prompt blocks came BACK to the free list:
    # free count only moved by what its own prompt left in the cache
    assert ex.pool.free_count >= ex2_free_before - 3


def paged_pool_exhaustion_queues_test():
    """An admission whose worst-case extent cannot be reserved QUEUES (the
    429/500-free invariant) and admits once the resident finishes."""
    iface = _interface()
    # pool = exactly one full-length request (8 blocks of 4)
    ex, ctl, sched, answers = _paged_controller(iface, pool_blocks=8)
    long_a = _req("a", [1, 2], None)         # end = seq: needs all 8
    long_b = _req("b", [3, 4], None)
    ctl.round([long_a, long_b])
    assert "a" not in answers and "b" not in answers
    assert len(sched.resident) == 1          # b queued on blocks, not slots
    assert sched.free_slots > 0
    for _ in range(120):
        if "a" in answers and "b" in answers:
            break
        ctl.round()
    assert answers["a"][0] == "ok" and answers["b"][0] == "ok"
    np.testing.assert_array_equal(
        np.asarray(answers["b"][1]),
        np.asarray(iface.complete_tokens(np.asarray([3, 4], np.int32),
                                         0.0, None)))


# --------------------------------------------------- resolution + HLO audit

def kv_paging_knob_resolution_test():
    """kv_paging=off resolves the plain slot engine (byte-identical
    serving), "on" the paged executor; the contradictions
    (batch engine + on, spec draft + paging) refuse loudly; "auto" falls
    back to the plain engine when the geometry cannot page."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.engine import EngineExecutor
    from homebrewnlp_tpu.infer.paged import PagedEngineExecutor
    from homebrewnlp_tpu.infer.rest_api import _resolve_engine

    iface = _interface()

    def resolve(**kw):
        params = ModelParameter(iface.params, serve_slots=2, **kw)
        params.train = False
        return _resolve_engine(params, iface)

    off = resolve(kv_paging="off")
    assert type(off) is EngineExecutor
    on = resolve(kv_paging="on", kv_block_tokens=4)
    assert type(on) is PagedEngineExecutor
    with pytest.raises(RuntimeError):
        resolve(kv_paging="on", serve_engine="batch")
    with pytest.raises(RuntimeError):
        resolve(kv_paging="on", spec_decode="draft")
    # geometry the pool cannot carry: "auto" falls back, "on" refuses
    auto = resolve(kv_paging="auto", kv_block_tokens=7)  # 32 % 7 != 0
    assert type(auto) is EngineExecutor
    with pytest.raises(RuntimeError):
        resolve(kv_paging="on", kv_block_tokens=7)


def paged_hlo_audit_test():
    """The paged chunk step's compiled module: every block-pool leaf
    donated+aliased, no full-pool-shaped copy — the gather/scatter
    round-trip must not cost a resident duplicate of the pool."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.analysis import entry_points, hlo_lint
    params, model, variables, token_x, _ = entry_points.build_audit_model()
    hlo, ctx = entry_points.lower_paged_step(model, variables,
                                             jnp.asarray(token_x))
    assert hlo_lint.input_output_alias_count(hlo) >= ctx["donated_leaves"]
    findings = hlo_lint.audit("paged_chunk_step", hlo,
                              expected_aliases=ctx["donated_leaves"],
                              protected_shapes=ctx["protected"],
                              bf16_param_shapes=ctx["bf16_params"],
                              budget={})
    assert findings == [], [str(f) for f in findings]


def paged_rest_roundtrip_test():
    """End to end over real IPC with kv_paging=on: completions answer
    bit-identically to the direct interface call, /health reports the
    paging geometry, and /metrics exports the hbnlp_kv_* block series."""
    import socket
    from homebrewnlp_tpu.infer import rest_api
    iface = _interface(serve_engine="continuous", serve_slots=4,
                       serve_batch_size=4, kv_paging="on",
                       kv_block_tokens=4)
    ref = np.asarray(iface.complete_tokens(np.asarray([1, 2, 3], np.int32),
                                           0.0, 6))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve,
                         args=(iface.params, iface),
                         kwargs={"port": port, "isolate": True, "stop": stop},
                         daemon=True)
    t.start()

    def post(path, payload, timeout=120):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        for _ in range(240):
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())
            except (ConnectionError, urllib.error.URLError, OSError):
                time.sleep(0.25)
        raise TimeoutError(path)

    try:
        status, health = post("/health", {})
        assert status == 200
        engine = health["engine"]
        assert engine["mode"] == "continuous"
        paging = engine["paging"]
        assert paging["block_tokens"] == 4 and paging["sharing"]
        assert paging["blocks_total"] == 4 * (32 // 4)
        status, out = post("/token_completion",
                           {"tokens": [1, 2, 3], "max_tokens": 6,
                            "temperature": 0.0})
        assert status == 200 and out["tokens"] == [int(x) for x in ref]
        # a second identical prompt hits the prefix cache; same answer
        status, out2 = post("/token_completion",
                            {"tokens": [1, 2, 3], "max_tokens": 6,
                             "temperature": 0.0})
        assert status == 200 and out2["tokens"] == out["tokens"]
        req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
        deadline = time.monotonic() + 30
        while True:
            with urllib.request.urlopen(req, timeout=30) as resp:
                text = resp.read().decode()
            if "hbnlp_kv_blocks_total" in text:
                break
            assert time.monotonic() < deadline, text[:2000]
            time.sleep(0.5)
        assert "hbnlp_kv_blocks_total 32" in text
        assert "hbnlp_kv_blocks_in_use" in text
        assert "hbnlp_kv_prefix_hit_tokens_total" in text
    finally:
        stop.set()
        t.join(timeout=15)
    assert not t.is_alive()
