"""Multi-host runtime tests (docs/DISTRIBUTED.md; marker ``distributed``).

Every multi-process scenario runs REAL jax processes (multi-controller CPU,
gloo collectives, discovered through the explicit-flag bootstrap) as
timeout-guarded subprocesses — the pod_lowering_test idiom: a hung
coordinator can kill a worker fleet, never the pytest collection or run.

Covered here, per ROADMAP item 3 / ISSUE 10:

- 2-process smoke with BIT-EXACT loss vs the same mesh single-process
- save at 2 processes, restore at 1 AND 4 with identical post-restore loss
- async-save overlap: checkpoint-cadence steps cost plain-step wall time
  (and the synchronous save measurably does not — the discriminating
  control)
- fault injection: a worker crashing between shard write and manifest
  commit surfaces on every process, the torn save stays invisible, restore
  falls back
- bit-exact data-stream resume across a host-count change (2 slices -> 1)
- run_manager fleet semantics: exit-143 relaunch without consuming the
  crash budget
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from multihost_test import _spawn_workers  # noqa: E402

WORKER = os.path.join(HERE, "_distributed_worker.py")

pytestmark = pytest.mark.distributed


def _mesh_cfg(model_path, mesh, **over):
    import _distributed_worker as dw
    return dw._model_cfg(str(model_path), mesh, **over)


def _run_fleet(mode, args, n_procs=2, env_devcount=4, timeout=420,
               retries=1):
    """Spawn a _distributed_worker fleet and assert it succeeded.  The
    contention retry (and its visible reason line) lives in ONE place —
    ``multihost_test._spawn_workers`` — not here: two drifting copies of
    the single-core heartbeat-starvation policy is how the tier-1 flake
    stayed half-fixed."""
    results = _spawn_workers(WORKER, [mode, json.dumps(args)],
                             env_devcount=env_devcount, n_procs=n_procs,
                             timeout=timeout, retries=retries)
    if all(p.returncode == 0 for p, _ in results):
        return [out for _, out in results]
    # a dead rank surfaces on every peer (gloo resets, coordination
    # heartbeats) — dump ALL workers so the FIRST failure is visible
    raise AssertionError("fleet failed:\n" + "\n".join(
        f"--- worker {pid} rc={p.returncode} ---\n{out[-3000:]}"
        for pid, (p, out) in enumerate(results)))


def _marker(outs, prefix):
    for out in outs:
        for line in out.splitlines():
            if line.startswith(prefix):
                return line[len(prefix):].strip()
    raise AssertionError(f"no '{prefix}' line in worker output:\n"
                         + "\n".join(o[-1500:] for o in outs))


def two_process_lockstep_bitexact_test(tmp_path):
    """The 2-process fleet computes the same loss sequence as a single
    process over the identical 8-device mesh and global batch — the Mesh-TF
    transparency claim at the smallest real scale.  Two assertion tiers:
    the fleet is bit-exactly DETERMINISTIC (re-run reproduces every loss
    bit-for-bit), and it matches the single-process run to float32
    reduction-order tolerance — the all-reduce crosses processes through
    gloo, whose summation order differs from XLA's in-process collective
    in the last bits (measured ~7e-8 relative), exactly as on a real pod
    whose topology changes."""
    import _distributed_worker as dw

    cfg = _mesh_cfg(tmp_path / "run", {"data": 8})
    steps = 4
    outs = _run_fleet("lockstep", {"cfg": cfg, "steps": steps})
    fleet = [float(v) for v in json.loads(_marker(outs, "LOCKSTEP "))]
    outs2 = _run_fleet("lockstep", {"cfg": cfg, "steps": steps})
    fleet2 = [float(v) for v in json.loads(_marker(outs2, "LOCKSTEP "))]
    single = dw.run_lockstep(cfg, steps)
    assert len(fleet) == steps and all(np.isfinite(fleet))
    assert fleet == fleet2, (fleet, fleet2)  # bit-exact determinism
    np.testing.assert_allclose(fleet, single, rtol=1e-5, atol=0)


def save_at_2_restore_at_1_and_4_test(tmp_path):
    """Async distributed save from 2 processes (model axis spanning both);
    restore at 1 and at 4 processes — reshard-on-restore across a
    process-count change.  The single-device forward loss of the restored
    parameters is IDENTICAL (bit-for-bit, string-compared) across all
    three topologies: the checkpoint reassembly is byte-exact.  The live
    resharded step loss matches the save-time continuation to
    reduction-order tolerance (collective summation order differs between
    topologies in the last float32 bits)."""
    cfg = _mesh_cfg(tmp_path / "run", {"data": 1, "model": 8})
    outs = _run_fleet("save", {"cfg": cfg})
    ref = _marker(outs, "SAVE_REF_LOSS ")
    live_ref = float(_marker(outs, "SAVE_LIVE_LOSS "))

    # restore at 4 processes (2 virtual devices each — same 8-device mesh)
    outs4 = _run_fleet("restore", {"cfg": cfg}, n_procs=4, env_devcount=2)
    # restore at 1 process (subprocess so the restore path runs the same
    # code; 8 in-process devices)
    outs1 = _run_fleet("restore", {"cfg": cfg}, n_procs=1, env_devcount=8)
    assert _marker(outs1, "RESTORE_LOSS ") == ref
    assert _marker(outs4, "RESTORE_LOSS ") == ref
    np.testing.assert_allclose(
        [float(_marker(outs1, "RESTORE_LIVE_LOSS ")),
         float(_marker(outs4, "RESTORE_LIVE_LOSS "))],
        live_ref, rtol=1e-5)


def async_save_overlap_test(tmp_path):
    """On a slow object store (20 ms/write), the async saver takes the
    save stall out of the checkpoint-cadence step: sync cadence steps pay
    the full multi-second save on the step thread (the control proving the
    measurement discriminates), async cadence steps pay at most 10% of
    that stall — the host staging copy.  On a multi-core host that residue
    is also within 10% of a plain step (the acceptance's form); this CI
    box has ONE core, so the background writer's cycles leak into every
    step and the stall-removal form is the noise-robust statement of the
    same property."""
    base = dict(sequence_length=128, features_per_head=32, depth=2,
                train_batch_size=16,
                distributed_barrier_timeout_s=60.0)
    common = dict(write_delay=0.02, steps=18, cadence=6)

    cfg_a = _mesh_cfg("dstore://run_async", {"data": 1, "model": 8}, **base)
    outs = _run_fleet("overlap", {"cfg": cfg_a, "use_async": True,
                                  "store": str(tmp_path / "store_a"),
                                  **common}, timeout=600)
    a = json.loads(_marker(outs, "OVERLAP "))

    cfg_s = _mesh_cfg("dstore://run_sync", {"data": 1, "model": 8}, **base)
    outs = _run_fleet("overlap", {"cfg": cfg_s, "use_async": False,
                                  "store": str(tmp_path / "store_s"),
                                  **common}, timeout=600)
    s = json.loads(_marker(outs, "OVERLAP "))

    # control: the sync save visibly stalls its cadence step (≥0.5s of
    # ~40 writes x 20ms) — if this fails the store is not slow enough to
    # measure anything
    sync_stall = s["cadence_median"] - s["plain_median"]
    assert sync_stall > 0.5, s
    # acceptance: the async cadence step carries at most 10% of that
    # stall (staging only; the write/commit runs behind the step loop)
    async_overhead = a["cadence_median"] - a["plain_median"]
    assert async_overhead <= 0.10 * sync_stall, (a, s, sync_stall)


def faultsave_crash_between_shard_and_manifest_test(tmp_path):
    """Process 1's storage dies between its shard writes and its shard
    manifest: both processes must fail the save loudly (injected fault on
    p1, commit-barrier timeout on p0), the torn save must stay invisible,
    and restore must fall back to the good checkpoint."""
    cfg = _mesh_cfg("dstore://run_fault", {"data": 1, "model": 8},
                    distributed_barrier_timeout_s=8.0)
    outs = _run_fleet("faultsave", {"cfg": cfg,
                                    "store": str(tmp_path / "store")},
                      timeout=600)
    assert any("FAULTSAVE OK" in o for o in outs)
    assert all("failed as injected" in o for o in outs), \
        "\n".join(o[-1000:] for o in outs)


def data_resume_across_host_count_change_test(tmp_path):
    """The windowed token stream resumes across a slice-count change
    (2 hosts -> 1) with no window lost or duplicated: run-log replay
    (split_files/simulate_data_pipeline) handles the geometry change, so a
    pod can shrink/grow between runs without silently skewing its data
    order.  Equal-size files: the resume is exact, not just multiset."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.data.inputs import TextDataset
    from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example

    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    rng = np.random.default_rng(3)
    for i in range(4):
        tokens = rng.integers(0, 32, 2048).astype(np.uint8)
        with RecordWriter(str(data_dir / f"p_{i}_2048.tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))

    def params():
        return ModelParameter({
            "model_mode": "gpt", "use_video": False, "use_language": True,
            "sequence_length": 64, "features_per_head": 8, "heads": 2,
            "depth": 1, "train_batch_size": 4, "vocab_size": 32,
            "tpu_size": 8, "interleaved_datasets": 2, "data_seed": 0,
            "token_patch_size": 1,
            "dataset_configs": [{"path": str(data_dir / "*"),
                                 "type": "text", "weight": 1}],
            "model_path": str(tmp_path / "run")})

    def windows(ds, n_batches=None):
        """Rows of the first n batches (or the FULL epoch when None)."""
        out = []
        it = iter(ds)
        while n_batches is None or n_batches > 0:
            try:
                b = next(it)
            except StopIteration:
                assert n_batches is None, "stream ended early"
                break
            out.extend(bytes(row.tobytes())
                       for row in np.asarray(b["token_x"]))
            if n_batches is not None:
                n_batches -= 1
        return out

    # run 1: TWO slices consume 3 batches each (batch 2 rows per slice)
    p = params()
    consumed = []
    for s in (0, 1):
        ds = TextDataset(p, 2, slice_index=s, slice_count=2, repeat=True)
        consumed += windows(ds, 3)
    run_log = [{"steps": 3, "grad_accumulation": 1, "batch_size": 4,
                "slice_count": 2, "ctx": 64, "token_patch_size": 1,
                "interleave_size": 2}]

    # run 2: ONE slice resumes through the log replay and drains the REST
    # of the epoch; the uninterrupted reference drains the whole epoch.
    # Multiset equality over (consumed before the geometry change) +
    # (resumed remainder) == (uninterrupted epoch): nothing lost, nothing
    # duplicated across the host-count change
    resumed = windows(TextDataset(params(), 4, slice_index=0, slice_count=1,
                                  runs_log=run_log, repeat=False))
    reference = windows(TextDataset(params(), 4, slice_index=0,
                                    slice_count=1, repeat=False))
    assert sorted(consumed + resumed) == sorted(reference), (
        len(consumed), len(resumed), len(reference))


def telemetry_process_label_merge_test():
    """Constant process labels ride every exported series, and
    merge_snapshots unions labeled per-process series instead of summing
    different hosts into anonymity (device-free unit half of the
    cross-host telemetry contract)."""
    from homebrewnlp_tpu import telemetry

    snaps = []
    for pid in range(2):
        reg = telemetry.Registry()
        reg.counter("hbnlp_test_items_total", "items").inc(3 + pid)
        reg.gauge("hbnlp_test_depth", "depth").set(10 * pid)
        snaps.append(telemetry.with_labels(reg.snapshot(),
                                           {"process": str(pid)}))
    merged = telemetry.merge_snapshots(*snaps)
    series = merged["hbnlp_test_items_total"]["series"]
    assert series == {("0",): 3, ("1",): 4}, series
    assert merged["hbnlp_test_items_total"]["labels"] == ("process",)
    text = telemetry.prometheus_text(merged)
    assert 'hbnlp_test_items_total{process="0"} 3' in text, text
    assert 'hbnlp_test_depth{process="1"} 10' in text, text

    # module-level snapshot() applies installed constant labels
    prev_reg = telemetry.set_registry(None)
    prev_labels = telemetry.set_constant_labels({"process": "7"})
    try:
        telemetry.registry().counter("hbnlp_test_x_total", "x").inc()
        snap = telemetry.snapshot()
        assert snap["hbnlp_test_x_total"]["series"] == {("7",): 1}, snap
    finally:
        telemetry.set_constant_labels(prev_labels)
        telemetry.set_registry(prev_reg)


def two_process_telemetry_jsonl_merge_test(tmp_path):
    """The full train loop over 2 processes with telemetry on: the
    non-chief publishes its process-labeled snapshot over the coordination
    KV store and the chief's telemetry.jsonl carries BOTH hosts' series —
    while the (global) MFU gauge and token counter stay chief-only."""
    from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example

    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    rng = np.random.default_rng(0)
    for i in range(4):
        tokens = rng.integers(0, 32, 4096).astype(np.uint8)
        with RecordWriter(str(data_dir / f"p_{i}_4096.tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))
    cfg = _mesh_cfg(tmp_path / "run", {"data": 8},
                    train_steps=12, interleaved_datasets=2, data_seed=7,
                    use_checkpointing=True, steps_per_checkpoint=8,
                    checkpoint_async=True, calc_accuracy=False,
                    telemetry_enabled=True,
                    telemetry_jsonl_interval_s=0.01,
                    dataset_configs=[{"path": str(data_dir / "*"),
                                      "type": "text", "weight": 1}])
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    results = _spawn_workers(
        os.path.join(HERE, "_multihost_train_worker.py"), [cfg_path])
    for pid, (p, out) in enumerate(results):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
    lines = [json.loads(line) for line in
             open(tmp_path / "run" / "telemetry.jsonl")]
    assert "build_info" in lines[0]
    metric_lines = [ln["metrics"] for ln in lines if "metrics" in ln]
    assert metric_lines
    flat = json.dumps(metric_lines[-1])
    assert "process=0" in flat, flat[:2000]
    assert "process=1" in flat, flat[:2000]
    # global series stay chief-only: no process=1 samples of the token
    # counter or MFU gauge anywhere in the file
    for ml in metric_lines:
        for name in ("hbnlp_train_tokens_total", "hbnlp_train_mfu"):
            for key in ml.get(name, {}).get("series", {}):
                assert "process=1" not in key, (name, key)


def kv_barrier_edge_cases_test(tmp_path):
    """bootstrap.py KV/barrier edge cases the elastic membership layer
    leans on, exercised directly (they were previously only implicit in
    fleet behavior): empty-prefix ``kv_dir_get`` returns [], ``kv_put``
    overwrites (a lease is a rewritten key), and a barrier a peer never
    joins raises a ``TimeoutError`` naming the barrier instead of
    hanging — with the client still usable afterwards."""
    cfg = _mesh_cfg(tmp_path / "run", {"data": 8})
    outs = _run_fleet("kvedge", {"cfg": cfg}, timeout=300)
    assert all("KVEDGE OK" in o for o in outs), \
        "\n".join(o[-1500:] for o in outs)
    assert any("barrier timeout surfaced" in o for o in outs), \
        "\n".join(o[-1500:] for o in outs)


def fleet_preemption_relaunch_test(tmp_path):
    """run_manager --num-processes: a fleet whose workers exit 143 (clean
    preemption) is relaunched WITHOUT consuming the crash budget; the
    relaunched generation finishing 0 ends the manager cleanly.  No jax —
    the run command is a script that preempts once, then succeeds."""
    script = tmp_path / "job.sh"
    stamp = tmp_path / "ran_once"
    script.write_text(
        "#!/bin/sh\n"
        f"if [ -f {stamp} ]; then echo second-run-ok; exit 0; fi\n"
        f"touch {stamp}.$HBNLP_PROCESS_ID\n"
        f"[ -f {stamp}.0 ] && [ -f {stamp}.1 ] && touch {stamp}\n"
        "echo preempting; exit 143\n")
    script.chmod(0o755)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(HERE, "..", "scripts", "run_manager.py"),
         f"sh {script}", "--model-path", str(tmp_path / "run"),
         "--num-processes", "2", "--poll-interval", "1",
         "--poll-jitter", "0", "--stall-timeout", "0",
         "--max-restarts", "1", "--restart-delay", "0"],
        capture_output=True, text=True, timeout=120)
    log = (tmp_path / "run" / "run.log").read_text()
    assert proc.returncode == 0, proc.stdout + proc.stderr + log
    assert "fleet preempted" in log, log
    assert "fleet finished cleanly" in log, log
    # the preemption relaunch must NOT have consumed the restart budget
    assert "restarting (#" not in log, log
    assert "[p0]" in log and "[p1]" in log, log
