"""The five BASELINE.json target configs ship in-repo (VERDICT r1 missing #4)
and must load at FULL parse fidelity — no key shrinking, no reliance on the
read-only reference mount — then train at a reduced size.

Targets (BASELINE.json "configs"): 32ctx_mixer, 32big_mixer, 32mixer_group,
video multimodal, 1B long-context.
"""
import glob
import json
import os

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
CONFIG_DIR = os.path.join(os.path.dirname(HERE), "configs")
TARGETS = ["32ctx_mixer.json", "32big_mixer.json", "32mixer_group.json",
           "video_jannet.json", "1b_long_context.json"]


def five_targets_present_test():
    have = {os.path.basename(p) for p in glob.glob(os.path.join(CONFIG_DIR, "*.json"))}
    missing = [t for t in TARGETS if t not in have]
    assert not missing, f"missing BASELINE target configs: {missing}"


@pytest.mark.parametrize("name", TARGETS)
def full_fidelity_parse_test(name):
    """Every key understood, block DSL parsed, mesh derivable — at the real
    (unshrunken) sizes."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.model.frontend import LAYER_FUNCTIONS

    with open(os.path.join(CONFIG_DIR, name)) as f:
        raw = json.load(f)
    params = ModelParameter(dict(raw))
    assert not params.unknown_config_keys, \
        f"unrecognised keys in {name}: {params.unknown_config_keys}"
    assert params.optimizer == raw["optimizer"]
    for block in params.block_config:
        for layer_str in block.layer:
            head = layer_str.split("-")[0]
            assert head in LAYER_FUNCTIONS, f"unknown layer {head!r} in {name}"
    import jax
    mesh = shardlib.build_mesh(params, jax.devices())
    assert np.prod(list(mesh.shape.values())) <= len(jax.devices())


@pytest.mark.parametrize("name", TARGETS)
def shrunk_train_step_test(name):
    """One real train step per target config with every semantic knob taken
    from the file; only the size knobs shrink."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    with open(os.path.join(CONFIG_DIR, name)) as f:
        cfg = json.load(f)
    cfg.update(depth=2, train_batch_size=2, use_checkpointing=False,
               model_path=f"/tmp/in_repo_config_test/{name}")
    if cfg.get("use_video"):
        cfg.update(sequence_length=4, features_per_head=16, heads=2,
                   frame_height=16, frame_width=16, patch_size=4,
                   language_token_per_frame=4, vocab_size=64)
    else:
        cfg.update(sequence_length=32, features_per_head=16, heads=2,
                   vocab_size=64, sequence_parallel=1)
    params = ModelParameter(cfg)
    model = Model(params)
    trainer = Trainer(params, model)
    rng = np.random.default_rng(0)
    if params.use_video:
        tps = params.time_patch_size
        fshape = (2, tps + 1, params.frame_height_patch,
                  params.frame_width_patch, params.channel_color_size) \
            if params.three_axes else \
            (2, tps + 1, params.frame_height_patch * params.frame_width_patch,
             params.channel_color_size)
        batch = {
            "frame": rng.integers(0, 255, fshape).astype(np.int32),
            "token_x": rng.integers(0, params.vocab_size,
                                    (2, tps, params.language_token_patch,
                                     params.token_patch_size)).astype(np.int32),
            "token_y": rng.integers(0, params.vocab_size,
                                    (2, tps, params.language_token_patch,
                                     params.token_patch_size)).astype(np.int32),
            "mask_x": np.ones((2, tps, params.language_token_patch,
                               params.token_patch_size), np.int32),
            "mask_y": np.ones((2, tps, params.language_token_patch,
                               params.token_patch_size), np.int32),
        }
    else:
        x = rng.integers(0, params.vocab_size, (2, params.sequence_length, 1))
        batch = {"token_x": x.astype(np.int32),
                 "token_y": ((x + 1) % params.vocab_size).astype(np.int32)}
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
