"""Continuous-batching decode engine (marker: contbatch; docs/SERVING.md).

Device-free sweep: the slot scheduler state machine under a fake clock and
a fake executor — admit-order fairness, slot exhaustion queues (never
errors), deadline eviction with the exactly-one-answer invariant,
finished-slot recycling, and the breaker interplay (open sheds the queue,
half-open admits a single probe, a failed dispatch fails every resident
with ONE breaker event).

Device sweep: greedy bit-parity — a request decoded continuously (co-
resident with strangers, admitted into a recycled slot mid-stream) matches
the plain stepped loop token-for-token — plus the engine's HLO audit
(every slot-pool cache leaf donated+aliased, no full-pool copy) and the
end-to-end REST path on the continuous engine.

Also here: the persistent-compilation-cache satellite — a second
in-process build of the same program hits the disk cache.

Standalone-runnable (tier-1 truncates at 870s on this box):
``python -m pytest tests/continuous_batching_test.py -q``
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer.scheduler import (EngineController, EngineRequest,
                                             SlotScheduler)

pytestmark = pytest.mark.contbatch


# ------------------------------------------------------------ fake executor

class _FakeExecutor:
    """Numpy stand-in for EngineExecutor: each dispatch advances every live
    slot by up to ``steps``; tokens are the prompt followed by a counting
    stream.  ``fail_at`` (dispatch indices) raises — the wedged/poisoned
    device."""

    def __init__(self, slots=4, seq=16, fail_at=()):
        self.slots, self.seq = slots, seq
        self.q = np.zeros(slots, np.int64)
        self.ipb = np.zeros(slots, np.int64)
        self.end = np.zeros(slots, np.int64)
        self.rows = np.zeros((slots, seq), np.int64)
        self.fail_at = set(fail_at)
        self.dispatches = 0
        self.resets = 0
        self.cache_bytes = 1 << 20

    def admit(self, slot, req):
        toks = np.asarray(req.toks).reshape(-1)[:self.seq - 1]
        self.rows[slot] = 0
        self.rows[slot, :len(toks)] = toks
        self.ipb[slot] = len(toks)
        self.end[slot] = req.end_pos(self.seq)
        self.q[slot] = 0

    def release(self, slot):
        self.end[slot] = 0

    def dispatch(self, steps):
        i = self.dispatches
        self.dispatches += 1
        if i in self.fail_at:
            raise RuntimeError(f"injected dispatch failure {i}")
        for s in range(self.slots):
            take = min(int(steps), max(0, int(self.end[s]) - 1 - int(self.q[s])))
            for _ in range(take):
                q = int(self.q[s])
                if q + 1 >= self.ipb[s]:
                    self.rows[s, q + 1] = 100 + q + 1  # deterministic stream
                self.q[s] += 1
        return self.q.copy()

    def tokens(self, slot):
        return self.rows[slot, :int(self.end[slot])]

    def reset(self):
        self.resets += 1
        self.q[:] = 0
        self.end[:] = 0


class _Guard:
    """Real breaker on a fake clock (the serving_guard one, unmodified)."""

    def __init__(self, threshold=2, cooldown=10.0, t=None):
        from homebrewnlp_tpu.infer.serving_guard import ServingGuard
        self.t = t if t is not None else [0.0]
        self.inner = ServingGuard(threshold=threshold, cooldown_s=cooldown,
                                  clock=lambda: self.t[0])

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _controller(ex, t, guard=None, answers=None, events=None, **kw):
    sched = SlotScheduler(ex.slots, clock=lambda: t[0])
    answers = answers if answers is not None else {}
    ctl = EngineController(
        ex, sched, guard=guard, clock=lambda: t[0],
        answer=lambda req, oc: answers.__setitem__(req.rid, oc),
        hooks=(lambda event, **k: events.append((event, k)))
        if events is not None else None, **kw)
    return ctl, sched, answers


def _req(rid, toks=(1, 2), rl=4, deadline=None):
    return EngineRequest(rid=rid, path="/token_completion",
                         toks=np.asarray(toks, np.int64),
                         response_len=rl, deadline=deadline)


# ------------------------------------------------------------- state machine

def admit_order_fairness_test():
    """Strict FIFO: with 2 slots and 5 requests, admission follows submit
    order, and every request is answered in that order as slots recycle."""
    t = [0.0]
    ex = _FakeExecutor(slots=2)
    ctl, sched, answers = _controller(ex, t, decode_chunk=32)
    order = []
    ctl.answer = lambda req, oc: order.append((req.rid, oc[0]))
    reqs = [_req(f"r{i}", rl=2 + i) for i in range(5)]
    ctl.round(reqs)
    assert len(sched.resident) == 2 and sched.free_slots == 0
    assert [r.rid for r, _ in sorted(sched.resident.values(),
                                     key=lambda x: x[1])] or True
    for _ in range(10):
        if len(order) == 5:
            break
        t[0] += 1.0
        ctl.round()
    assert [rid for rid, _ in order] == [f"r{i}" for i in range(5)]
    assert all(kind == "ok" for _, kind in order)


def slot_exhaustion_queues_test():
    """More requests than slots queue — no error outcome, and the pending
    backlog counts toward depth() (the admission-budget fix)."""
    t = [0.0]
    ex = _FakeExecutor(slots=2)
    ctl, sched, answers = _controller(ex, t)
    ctl.round([_req(f"r{i}") for i in range(6)])
    assert len(sched.resident) == 2 and len(sched.pending) == 4
    assert sched.depth() == 6          # resident + queued hold budget
    assert not answers                 # nothing failed, nothing answered yet
    for _ in range(12):
        ctl.round()
    assert sorted(answers) == [f"r{i}" for i in range(6)]
    assert all(oc[0] == "ok" for oc in answers.values())
    assert sched.depth() == 0


def deadline_eviction_answers_exactly_once_test():
    """A deadline-expired RESIDENT is evicted at the next chunk boundary
    and answered 504 exactly once; an expired QUEUED request never takes a
    slot; the freed slot recycles immediately."""
    t = [0.0]
    ex = _FakeExecutor(slots=1)
    counts = {}
    ctl, sched, _ = _controller(ex, t, decode_chunk=1)
    ctl.answer = lambda req, oc: counts.setdefault(req.rid, []).append(oc)
    # long decode (rl=10) with a deadline at t=5; one queued behind it with
    # an already-hopeless deadline, one healthy
    ctl.round([_req("res", rl=10, deadline=5.0),
               _req("doomed", deadline=2.0),
               _req("healthy", rl=2)])
    assert "res" not in counts
    t[0] = 3.0
    ctl.round()                        # doomed expires in the queue
    assert counts["doomed"] == [("timeout", "queue")]
    t[0] = 6.0
    ctl.round()                        # res evicted at this chunk boundary
    assert counts["res"] == [("timeout", "slot")]
    assert len(sched.resident) == 1    # healthy admitted into the freed slot
    for _ in range(6):
        ctl.round()
    assert counts["healthy"][0][0] == "ok"
    assert all(len(v) == 1 for v in counts.values()), counts


def finished_slot_recycling_test():
    """Recycling is immediate: a short request's slot hosts the next queued
    request in the SAME controller lifetime, and the hooks see
    admit/recycle events with residency/queue-age values."""
    t = [0.0]
    ex = _FakeExecutor(slots=1)
    events = []
    ctl, sched, answers = _controller(ex, t, events=events, decode_chunk=32)
    ctl.round([_req("a", rl=1), _req("b", rl=1)])
    for _ in range(8):
        if len(answers) == 2:
            break
        t[0] += 1.0
        ctl.round()
    assert answers["a"][0] == "ok" and answers["b"][0] == "ok"
    kinds = [e for e, _ in events]
    assert kinds.count("admitted") == 2 and kinds.count("recycled") == 2
    ages = [k["queue_age"] for e, k in events if e == "admitted"]
    assert ages[0] == 0.0 and ages[1] > 0.0   # b waited for a's slot
    assert all(k["residency"] >= 0 for e, k in events if e == "recycled")


def breaker_interplay_test():
    """Failed dispatches answer every resident with ONE breaker event each;
    at the threshold the breaker opens and the pending queue is shed with
    retry-after; after the cooldown exactly one probe admits, and its
    success recloses the breaker."""
    t = [0.0]
    ex = _FakeExecutor(slots=2, fail_at={0, 1})
    guard = _Guard(threshold=2, cooldown=10.0, t=t)
    ctl, sched, answers = _controller(ex, t, guard=guard)
    ctl.round([_req("a"), _req("b")])
    assert answers["a"][0] == "error" and answers["b"][0] == "error"
    assert guard.inner.decode_failures == 1    # ONE event per failed dispatch
    assert ex.resets == 1                      # pool re-initialises
    ctl.round([_req("c")])                     # second failure -> breaker opens
    assert answers["c"][0] == "error"
    assert guard.inner.breaker.state == "open"
    ctl.round([_req("shed")])
    assert answers["shed"][0] == "unavailable"
    assert answers["shed"][1] == pytest.approx(10.0)
    assert ex.dispatches == 2                  # shed request cost no dispatch
    t[0] = 10.0
    ctl.round([_req("probe", rl=1), _req("wait", rl=1)])
    # the half-open round admitted exactly ONE probe ("wait" stays queued,
    # not shed); its successful dispatch recloses the breaker in-round
    assert "wait" not in answers and len(sched.resident) <= 1
    assert guard.inner.breaker.state == "closed"
    for _ in range(6):
        ctl.round()
    assert answers["probe"][0] == "ok"
    assert answers["wait"][0] == "ok"          # queued, not shed, then served


def prefill_chunk_budget_test():
    """While an admitted request still walks its prompt, the dispatch
    budget is serve_prefill_chunk_tokens; steady-state decode uses
    decode_chunk_tokens."""
    t = [0.0]
    ex = _FakeExecutor(slots=1, seq=64)
    steps_seen = []
    real_dispatch = ex.dispatch
    ex.dispatch = lambda s: steps_seen.append(int(s)) or real_dispatch(s)
    ctl, sched, answers = _controller(ex, t, decode_chunk=4, prefill_chunk=9)
    ctl.round([_req("p", toks=list(range(1, 31)), rl=20)])   # 30-token prompt
    assert steps_seen[-1] == 9          # prompt walk: prefill budget
    while "p" not in answers:
        ctl.round()
    assert 4 in steps_seen              # steady decode chunks after the walk
    assert answers["p"][0] == "ok"


# ----------------------------------------------------------- device parity

def _interface(**kw):
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp
    cfg = dict(block_config=MIXER_BLOCKS, memory_reduction_strategy="none",
               sequence_length=32, train_batch_size=1,
               decode_loop="stepped", decode_chunk_tokens=5)
    cfg.update(kw)
    params = make_params(**cfg)
    params.train = False
    model = Model(params)
    seq = params.sequence_dim.size
    batch = {"token_x": np.zeros((1, seq, 1), np.int32),
             "token_y": np.zeros((1, seq, 1), np.int32)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return InterfaceWrapper(params, model, variables)


def engine_greedy_bit_parity_test():
    """A request decoded continuously — co-resident with strangers at
    other positions, including one admitted into a RECYCLED slot mid-
    stream — matches the plain stepped loop token-for-token."""
    from homebrewnlp_tpu.infer.engine import EngineExecutor
    iface = _interface()
    prompts = [[1, 2, 3], [7, 8], [4, 5, 6, 7, 9], [10]]
    rls = [6, 20, 3, None]
    ref = [np.asarray(iface.complete_tokens(np.asarray(p, np.int32), 0.0, rl))
           for p, rl in zip(prompts, rls)]
    ex = EngineExecutor(iface, slots=4)
    ctl, sched, answers = _controller(ex, [0.0], decode_chunk=5,
                                      prefill_chunk=8)
    ctl.clock = time.monotonic
    sched.clock = time.monotonic
    ctl.round([EngineRequest(rid=f"r{i}", path="/token_completion",
                             toks=np.asarray(p, np.int32), response_len=rl)
               for i, (p, rl) in enumerate(zip(prompts, rls))])
    for _ in range(40):
        if len(answers) == len(prompts):
            break
        ctl.round()
    for i, want in enumerate(ref):
        kind, got = answers[f"r{i}"]
        assert kind == "ok"
        np.testing.assert_array_equal(np.asarray(got), want), i
    # late admission into a recycled slot (the admit variant: cache-row
    # reset + co-residency with surviving streams) stays bit-identical
    late = EngineRequest(rid="late", path="/token_completion",
                         toks=np.asarray([3, 1, 4], np.int32), response_len=4)
    ctl.round([late])
    for _ in range(40):
        if "late" in answers:
            break
        ctl.round()
    np.testing.assert_array_equal(
        np.asarray(answers["late"][1]),
        np.asarray(iface.complete_tokens(np.asarray([3, 1, 4], np.int32),
                                         0.0, 4)))


def engine_hlo_audit_test():
    """The engine chunk step's compiled module: every slot-pool cache leaf
    donated+aliased, no full-pool-shaped copy (the ISSUE 7 acceptance
    property, also enforced repo-wide by graft-lint --hlo)."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.analysis import entry_points, hlo_lint
    params, model, variables, token_x, _ = entry_points.build_audit_model()
    hlo, ctx = entry_points.lower_engine_step(model, variables,
                                              jnp.asarray(token_x))
    assert hlo_lint.input_output_alias_count(hlo) >= ctx["donated_leaves"]
    findings = hlo_lint.audit("engine_chunk_step", hlo,
                              expected_aliases=ctx["donated_leaves"],
                              protected_shapes=ctx["protected"],
                              bf16_param_shapes=ctx["bf16_params"],
                              budget={})
    assert findings == [], [str(f) for f in findings]


def engine_rest_roundtrip_test():
    """End to end over real IPC with serve_engine=continuous: mixed-length
    completions answer correctly (bit-identical to the direct batch-path
    interface call), /health reports the engine, and /metrics exports the
    slot series."""
    import socket
    from homebrewnlp_tpu.infer import rest_api
    iface = _interface(serve_engine="continuous", serve_slots=4,
                       serve_batch_size=4)
    ref = np.asarray(iface.complete_tokens(np.asarray([1, 2, 3], np.int32),
                                           0.0, 6))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve,
                         args=(iface.params, iface),
                         kwargs={"port": port, "isolate": True, "stop": stop},
                         daemon=True)
    t.start()

    def post(path, payload, timeout=120):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        for _ in range(240):
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())
            except (ConnectionError, urllib.error.URLError, OSError):
                time.sleep(0.25)
        raise TimeoutError(path)

    try:
        status, health = post("/health", {})
        assert status == 200
        eng = health["engine"]
        assert eng["mode"] == "continuous" and eng["slots"] == 4
        assert eng["program"] == "engine_chunk_step"
        assert eng["replica_class"] == "" and eng["kv_transfer"] is False
        results = {}

        def bg(name, payload):
            results[name] = post("/token_completion", payload)

        threads = [threading.Thread(
            target=bg, args=(i, {"tokens": [1, 2, 3], "max_tokens": 6,
                                 "temperature": 0.0}
                             if i == 0 else
                             {"tokens": [5 + i], "max_tokens": 2 + i,
                              "temperature": 0.0}), daemon=True)
            for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        status, out = results[0]
        assert status == 200
        assert out["tokens"] == [int(x) for x in ref]
        assert all(st == 200 for st, _ in results.values())
        # parse errors still answer 400 without touching the engine
        status, out = post("/token_completion", {"tokens": [None]})
        assert status == 400 and out["code"] == "bad_request"
        # the slot series ride the device loop's published snapshot
        req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
        deadline = time.monotonic() + 30
        while True:
            with urllib.request.urlopen(req, timeout=30) as resp:
                text = resp.read().decode()
            if "hbnlp_serve_engine_recycled_total" in text:
                break
            assert time.monotonic() < deadline, text[:2000]
            time.sleep(0.5)
        assert "hbnlp_serve_slots_total 4" in text
        assert "hbnlp_serve_queue_age_seconds" in text
        assert "hbnlp_serve_slot_residency_seconds" in text
        assert "hbnlp_serve_ttft_seconds" in text
    finally:
        stop.set()
        t.join(timeout=15)
    assert not t.is_alive()


# ------------------------------------------------- compile-cache persistence

def compile_cache_second_build_hits_test(tmp_path):
    """compile_cache_dir wires jax's persistent compilation cache: the
    first build writes entries, and a second in-process build of the same
    program (after clearing jax's in-memory caches) adds NO new entries —
    it was served from disk."""
    import glob
    import os
    import jax
    import jax.numpy as jnp
    from homebrewnlp_tpu.utils.compile_cache import (install_compile_cache,
                                                     uninstall_compile_cache)

    class _P:
        compile_cache_dir = str(tmp_path / "xla-cache")

    try:
        path = install_compile_cache(_P())
        assert path == str(tmp_path / "xla-cache") and os.path.isdir(path)

        def entries():
            # only the named program under test: trivial helper jits
            # (constant converts) ride the in-memory cache across the test
            # boundary and would add unrelated keys after clear_caches()
            return sorted(p for p in glob.glob(os.path.join(path, "**"),
                                               recursive=True)
                          if os.path.isfile(p)
                          and "contbatch_cached_fn" in os.path.basename(p))

        def build():
            def contbatch_cached_fn(x):
                return (x @ x.T).sum() * 3
            return jax.jit(contbatch_cached_fn)

        build()(jnp.ones((32, 32))).block_until_ready()
        first = entries()
        assert first, "first compile wrote no cache entries"
        jax.clear_caches()
        build()(jnp.ones((32, 32))).block_until_ready()
        assert entries() == first, "second build missed the disk cache"
    finally:
        uninstall_compile_cache()
    # off by default: blank knob is a no-op
    class _Off:
        compile_cache_dir = ""
    assert install_compile_cache(_Off()) is None


def compile_cache_reload_broken_refusal_test(tmp_path):
    """A reload-broken probe verdict (the jax-0.4.37 CPU warm-cache
    segfault, classified by ``bench.py --compile-probe``) makes
    install_compile_cache REFUSE the persistent cache for that backend +
    jax version with a loud structured warning — graceful degradation to
    cold compiles, not a warm-relaunch crash.  A different jax version or
    a healthy re-probe re-enables it."""
    import warnings as warnings_mod
    from homebrewnlp_tpu.utils import compile_cache as cc

    cache = str(tmp_path / "xla-cache")

    class _P:
        compile_cache_dir = cache

    try:
        # no verdict: installs normally
        assert cc.install_compile_cache(_P()) == cache
        cc.uninstall_compile_cache()
        # a broken verdict for THIS env refuses, loudly
        path = cc.record_reload_verdict(cache, True,
                                        evidence="injected by test")
        assert path.endswith(cc.VERDICT_FILE)
        with warnings_mod.catch_warnings(record=True) as caught:
            warnings_mod.simplefilter("always")
            assert cc.install_compile_cache(_P()) is None
        assert any("reload-broken" in str(w.message) for w in caught)
        # verdicts are env-scoped: a different jax version installs fine
        # (an upgrade invalidates the classification — re-probe)
        import json as json_mod
        with open(path) as f:
            verdict = json_mod.load(f)
        verdict["jax_version"] = "999.0.0"
        with open(path, "w") as f:
            json_mod.dump(verdict, f)
        assert cc.install_compile_cache(_P()) == cache
        cc.uninstall_compile_cache()
        # a healthy re-probe clears the refusal
        cc.record_reload_verdict(cache, True, evidence="stale")
        cc.record_reload_verdict(cache, False, evidence="healthy re-probe")
        assert cc.install_compile_cache(_P()) == cache
        # unreadable verdict = no evidence, never "broken"
        with open(path, "w") as f:
            f.write("{not json")
        assert cc.install_compile_cache(_P()) == cache
    finally:
        cc.uninstall_compile_cache()
