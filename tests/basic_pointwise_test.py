"""Pointwise-layer statistical tests.

Port of /root/reference/tests/basic_pointwise_test.py: ReZero outputs exactly
zero (:14-20), dropout zero-fraction ≈ rate (:23-28), identity/activation
output std over a dtype grid (:31-63).
"""
import numpy as np
import pytest

from backend import RELU_STD, make_params, tolerance, OpHarness
from homebrewnlp_tpu.config import BlockArgs
from homebrewnlp_tpu.core import scope
from homebrewnlp_tpu.model.activation import activate
from homebrewnlp_tpu.model.basic import dropout, rezero

DTYPES = ["bfloat16", "float32"]


@pytest.mark.parametrize("calculation_dtype", DTYPES)
@pytest.mark.parametrize("features_per_head", [16, 256])
def rezero_test(calculation_dtype, features_per_head):
    params = make_params(calculation_dtype=calculation_dtype,
                         features_per_head=features_per_head)
    h = OpHarness(params)
    out = h.run_layer(rezero)
    assert np.all(out == 0)


@pytest.mark.parametrize("rate", [0.25, 0.5, 0.75])
def dropout_test(rate):
    import jax
    params = make_params(features_per_head=64, train_batch_size=16,
                         sequence_length=64)
    h = OpHarness(params, extras=[f"dropout_rate{rate}"])
    inp = h.input_tensor()
    args = BlockArgs(params, inp, [f"dropout_rate{rate}"])
    ctx = scope.Context("init", seed=0, rng_key=jax.random.PRNGKey(0))
    with scope.context(ctx):
        out = dropout(args)
    frac = float(np.mean(np.asarray(out.data, np.float32) == 0))
    assert abs(frac - rate) < 0.02, (frac, rate)


# std of relu(N(0,1)) = sqrt(1/2 - 1/(2*pi)); the reference's 1/1.42 constant
# (tests/backend.py:13) is a rounded normaliser, not the exact moment
RELU_TRUE_STD = float(np.sqrt(0.5 - 1 / (2 * np.pi)))


@pytest.mark.parametrize("calculation_dtype", DTYPES)
@pytest.mark.parametrize("fn,target_std", [("relu", RELU_TRUE_STD), ("identity", 1.0)])
def activation_std_test(calculation_dtype, fn, target_std):
    params = make_params(calculation_dtype=calculation_dtype,
                         features_per_head=64, train_batch_size=8,
                         sequence_length=64)
    h = OpHarness(params, extras=[fn])
    out = h.run_layer(activate)
    tol = max(tolerance(params), 0.02)
    assert abs(np.std(out) - target_std) < tol * 3, (np.std(out), target_std)


@pytest.mark.parametrize("fn", ["gelu", "silu", "mish", "softsign", "lecun_tanh",
                                "sigmoid", "tanh"])
def activation_finite_test(fn):
    params = make_params(features_per_head=64)
    h = OpHarness(params, extras=[fn])
    out = h.run_layer(activate)
    assert np.all(np.isfinite(out))


def activation_matches_closed_form_test():
    """Spot-check the hand-written kernels against their formulas
    (reference activation.py custom fwd/bwd ops)."""
    x = np.linspace(-4, 4, 101, dtype=np.float32)
    params = make_params()
    from homebrewnlp_tpu.core.tensor import nt
    from homebrewnlp_tpu.core.dims import Dim
    t = nt(x, [Dim("sequence", 101)])
    for fn, ref in [
        ("lecun_tanh", np.tanh(x) + 0.1 * x),
        ("softsign", x / (1 + np.abs(x))),
        ("silu", x / (1 + np.exp(-x))),
        ("mish", x * np.tanh(np.log1p(np.exp(x)))),
    ]:
        args = BlockArgs(params, t, [fn])
        ctx = scope.Context("init", seed=0)
        with scope.context(ctx):
            out = activate(args)
        # XLA's CPU tanh/exp lowerings differ from numpy by ~2e-4 relative
        # (observed on the jax 0.9 CPU backend); 5e-4 still rejects wrong
        # formulas while tolerating transcendental approximation error
        np.testing.assert_allclose(np.asarray(out.data), ref, rtol=5e-4, atol=5e-4)
