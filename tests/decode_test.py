"""KV-cached incremental decoding parity vs the full-forward sampler.

The full-forward sampler (infer/sampler.py:make_sampler) reproduces the
reference's semantics exactly (/root/reference/src/run/inference.py); the
KV-cached sampler (make_kv_sampler + Model.apply_decode) must produce
IDENTICAL greedy outputs for every layer family with a streaming form:
attention (all flag combinations), cumsum/cummean, causal convolution, under
every memory-reduction strategy.
"""
import jax
import jax.numpy as jnp
import numpy as np

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer.sampler import (init_decode_caches, make_kv_sampler,
                                           make_sampler)
from homebrewnlp_tpu.model import Model


def _greedy_pair(cfg_overrides, initial_pos=4, end_iterations=None, seed=0,
                 temperature=0.0):
    params = make_params(**cfg_overrides)
    model = Model(params)
    rng = np.random.default_rng(seed)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    token_x = rng.integers(0, params.vocab_size,
                           (params.train_batch_size, seq, tps)).astype(np.int32)
    batch = {"token_x": jnp.asarray(token_x), "token_y": jnp.asarray(token_x)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    end = seq if end_iterations is None else end_iterations

    full = jax.jit(make_sampler(model))(
        variables, jnp.asarray(token_x), jnp.asarray(token_x),
        jnp.asarray(initial_pos, jnp.int32),
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(end, jnp.int32), jax.random.PRNGKey(seed))

    caches = init_decode_caches(model, variables, jnp.asarray(token_x))
    cached = jax.jit(make_kv_sampler(model))(
        variables, jnp.asarray(token_x), jnp.asarray(initial_pos, jnp.int32),
        jnp.asarray(temperature, jnp.float32), jnp.asarray(end, jnp.int32),
        jax.random.PRNGKey(seed), caches)
    return np.asarray(full), np.asarray(cached), token_x, initial_pos, end


def _assert_parity(cfg, **kw):
    full, cached, token_x, pos, end = _greedy_pair(cfg, **kw)
    # prompt region untouched
    np.testing.assert_array_equal(cached[:, :pos], token_x[:, :pos])
    np.testing.assert_array_equal(full[:, :end], cached[:, :end])


def flagship_mixer_decode_parity_test():
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "revnet"})


def dot_product_attention_decode_parity_test():
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "attention-dot_product-embedded-absolute"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "none"})


def biased_softmax_attention_decode_parity_test():
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "attention-dot_product-context-biased_softmax-absolute"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "checkpoint"})


def shared_key_value_decode_parity_test():
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "attention-dot_product-embedded-absolute-shared_key_value"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "none"})


def scale_map_positional_decode_parity_test():
    blocks = [{"layer": ["attention-dot_product-positional-scale_attention_map-absolute"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "none"})


def cumsum_momentum_decode_parity_test():
    blocks = [{"layer": ["norm-shift-scale-features-group", "cumsum"]},
              {"layer": ["norm-shift-scale-features-group", "cummean",
                         "feed_forward-in:relu"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "momentum"})


def convolution_decode_parity_test():
    blocks = [{"layer": ["norm-shift-scale-features-group", "convolution",
                         "activation-gelu"]}]
    _assert_parity({"block_config": blocks, "convolution_size": 4,
                    "memory_reduction_strategy": "none"})


def axial_embedding_decode_parity_test():
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "none",
                    "position_embedding": "axial",
                    "use_initial_position_embedding": True})


def relative_embedding_decode_parity_test():
    blocks = [{"layer": ["attention-dot_product-positional-relative-learned"]}]
    _assert_parity({"block_config": blocks,
                    "memory_reduction_strategy": "none"})


def initial_pos_zero_decode_parity_test():
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "none"}, initial_pos=0)


def partial_end_iterations_decode_parity_test():
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "none"}, end_iterations=10)


def overlong_end_iterations_decode_parity_test():
    """end_iterations > seq: the full sampler's extra iterations are no-ops
    (one-hot write misses); the cached sampler clamps to match."""
    _assert_parity({"block_config": MIXER_BLOCKS,
                    "memory_reduction_strategy": "none"},
                   end_iterations=16 + 5)


def temperature_sampling_decode_smoke_test():
    """temperature>0 draws a different gumbel stream than the full sampler
    (documented in make_kv_sampler) — assert validity, not equality."""
    full, cached, token_x, pos, end = _greedy_pair(
        {"block_config": MIXER_BLOCKS, "memory_reduction_strategy": "none"},
        temperature=0.7)
    assert cached.min() >= 0 and cached.max() < 32
    np.testing.assert_array_equal(cached[:, :pos], token_x[:, :pos])


def sample_text_fallback_test():
    """A layer without a streaming form falls back to the full sampler."""
    from homebrewnlp_tpu.infer.sampler import sample_text
    params = make_params(
        sequence_length=16, features_per_head=16,
        block_config=[{"layer": ["transpose_sequence_features"]},
                      {"layer": ["norm-shift-scale-features-group",
                                 "feed_forward-in:relu"]}],
        memory_reduction_strategy="none")
    model = Model(params)
    rng = np.random.default_rng(0)
    token_x = rng.integers(0, params.vocab_size,
                           (params.train_batch_size, 16, 1)).astype(np.int32)
    batch = {"token_x": jnp.asarray(token_x), "token_y": jnp.asarray(token_x)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    out = sample_text(model, variables, token_x[:, :4, 0], initial_pos=4,
                      temperature=0.0)
    assert out.shape == token_x.shape


def decode_cache_dtype_override_test():
    """decode_cache_dtype stores the KV buffers in the requested dtype (the
    cache dominates decode HBM at wide batch) while compute stays in the
    calculation dtype; greedy decode still matches the full-forward sampler
    on an f32 model with bf16 caches at these small shapes."""
    cfg = {"block_config": MIXER_BLOCKS,
           "memory_reduction_strategy": "revnet",
           "decode_cache_dtype": "bfloat16"}
    params = make_params(**cfg)
    model = Model(params)
    rng = np.random.default_rng(1)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    token_x = rng.integers(0, params.vocab_size,
                           (params.train_batch_size, seq, tps)).astype(np.int32)
    batch = {"token_x": jnp.asarray(token_x), "token_y": jnp.asarray(token_x)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    caches = init_decode_caches(model, variables, jnp.asarray(token_x))
    kv = {k: v for k, v in caches.items() if "/kv" in k}
    assert kv, f"no KV caches discovered: {list(caches)[:5]}"
    assert all(v.dtype == jnp.bfloat16 for v in kv.values()), \
        {k: str(v.dtype) for k, v in kv.items()}
    # bf16 cache reads can flip near-tied argmaxes vs the f32 full-forward
    # sampler, so assert structure rather than exact parity: prompt region
    # preserved, generated tokens in-vocab
    out = jax.jit(make_kv_sampler(model))(
        variables, jnp.asarray(token_x), jnp.asarray(4, jnp.int32),
        jnp.asarray(0.0, jnp.float32), jnp.asarray(seq, jnp.int32),
        jax.random.PRNGKey(0), caches)
    out = np.asarray(out)
    np.testing.assert_array_equal(out[:, 1:4], token_x[:, 1:4])
    assert out.min() >= 0 and out.max() < params.vocab_size


def decode_cache_int8_test():
    """int8 KV caches: per-row symmetric quantization with a sibling f32
    scale cache (wide-batch decode is cache-read-bandwidth-bound; int8
    halves the bytes vs bf16).  Checks the quantized roundtrip error bound
    and that greedy decode runs with in-vocab outputs."""
    cfg = {"block_config": MIXER_BLOCKS,
           "memory_reduction_strategy": "revnet",
           "decode_cache_dtype": "int8"}
    params = make_params(**cfg)
    model = Model(params)
    rng = np.random.default_rng(2)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    token_x = rng.integers(0, params.vocab_size,
                           (params.train_batch_size, seq, tps)).astype(np.int32)
    batch = {"token_x": jnp.asarray(token_x), "token_y": jnp.asarray(token_x)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    caches = init_decode_caches(model, variables, jnp.asarray(token_x))
    kv = {k: v for k, v in caches.items()
          if "/kv" in k and not k.endswith("_scale")}
    scales = {k: v for k, v in caches.items() if k.endswith("_scale")}
    assert kv and scales, list(caches)[:6]
    assert all(v.dtype == jnp.int8 for v in kv.values())
    assert all(v.dtype == jnp.float32 and v.shape[-1] == 1
               for v in scales.values())

    out = jax.jit(make_kv_sampler(model))(
        variables, jnp.asarray(token_x), jnp.asarray(4, jnp.int32),
        jnp.asarray(0.0, jnp.float32), jnp.asarray(seq, jnp.int32),
        jax.random.PRNGKey(0), caches)
    out = np.asarray(out)
    np.testing.assert_array_equal(out[:, 1:4], token_x[:, 1:4])
    assert out.min() >= 0 and out.max() < params.vocab_size


def int8_spread_roundtrip_error_test():
    """The quantize->dequantize path in decode.spread keeps per-element
    relative error within the symmetric-int8 bound (~1/127 of the row max)."""
    from homebrewnlp_tpu.core.dims import Dim
    from homebrewnlp_tpu.core import scope as scope_mod
    from homebrewnlp_tpu.model.decode import DecodeState, spread
    from homebrewnlp_tpu.core.tensor import nt as nt_
    rng = np.random.default_rng(0)
    b, h, f, s = 2, 3, 64, 8
    x = jnp.asarray(rng.standard_normal((b, 1, h, f)) * 3, jnp.float32)
    dims = [Dim("batch", b), Dim("sequence", 1), Dim("heads", h),
            Dim("features_per_head", f)]
    state = DecodeState(jnp.int32(2), s, "sequence", {},
                        cache_dtype=jnp.int8)
    ctx = scope_mod.Context("apply", params={})
    ctx.decode = state
    with scope_mod.context(ctx):
        out = spread(nt_(x, dims), dims[1])
    got = np.asarray(out.data)[:, 2]                 # the written position
    want = np.asarray(x)[:, 0]
    bound = np.abs(want).max(-1, keepdims=True) / 127.0 + 1e-6
    assert np.all(np.abs(got - want) <= bound * 1.01)
    # untouched positions stay zero
    assert np.all(np.asarray(out.data)[:, 0] == 0)
