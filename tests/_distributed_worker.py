"""Worker for tests/distributed_test.py: one mode per scenario, run as

  python _distributed_worker.py <port> <pid> <nproc> <mode> <json-args>

with JAX_PLATFORMS=cpu and 4 virtual devices per process (the 2-process
runs form an 8-device multi-controller CPU pod).  Discovery goes through
the REAL bootstrap (homebrewnlp_tpu/distributed/bootstrap.py explicit-flag
env path + gloo CPU collectives), so every mode is also a bootstrap test.

Modes (each prints greppable marker lines the parent asserts on):

- ``lockstep``  — N deterministic trainer steps over a synthetic global
  batch; chief prints the full-precision loss sequence.  The parent runs
  the SAME function single-process (8 in-process devices, identical mesh)
  and compares bit-exact.
- ``save``      — deterministic state, one step, async distributed save at
  step 7, then one more step whose loss is the restore reference.
- ``restore``   — restore the mode-``save`` checkpoint at THIS process
  count, lay it onto the live mesh, run the same step, print its loss.
- ``overlap``   — per-iteration wall times with checkpoint submits riding
  a deliberately SLOW object store: proves the async saver keeps
  checkpoint-cadence steps at plain-step cost (and that the synchronous
  save measurably does not).
- ``faultsave`` — a good save, then a save where process 1's storage
  crashes BETWEEN shard write and manifest commit (FaultInjectionFS over
  the shared disk store): both processes must surface the failure, the
  torn save must stay invisible, and restore must fall back to the good
  checkpoint.
"""
from __future__ import annotations

import json
import os
import sys
import time
import typing

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from homebrewnlp_tpu.utils import fs as fslib  # noqa: E402


class DiskStoreFS(fslib._ObjectStoreFS):
    """Object store over a SHARED local directory: the cross-process
    stand-in for gs:// in multi-process tests (MemFS is per-process).
    Optional per-write delay turns it into a slow remote bucket for the
    async-overlap measurement; ``FaultInjectionFS`` wraps it for the
    crash schedules."""

    def __init__(self, base: str, write_delay: float = 0.0):
        self.base = base
        self.write_delay = write_delay
        self._tmp = base.rstrip("/") + ".inflight"
        os.makedirs(base, exist_ok=True)
        os.makedirs(self._tmp, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.base, key.split("://", 1)[1])

    def _keys(self, prefix):
        out = []
        for dirpath, _, files in os.walk(self.base):
            for f in files:
                rel = os.path.relpath(os.path.join(dirpath, f), self.base)
                out.append("dstore://" + rel.replace(os.sep, "/"))
        return sorted(k for k in out
                      if k == prefix
                      or k.startswith(prefix.rstrip("/") + "/")
                      or (prefix.endswith("/") and k.startswith(prefix)))

    def _read(self, key):
        p = self._p(key)
        if not os.path.isfile(p):
            raise FileNotFoundError(key)
        with open(p, "rb") as f:
            return f.read()

    def _write(self, key, data):
        if self.write_delay:
            time.sleep(self.write_delay)
        p = self._p(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        # atomic publish via a staging dir OUTSIDE the walked tree, so
        # readers never glimpse half-written objects as keys
        tmp = os.path.join(self._tmp, f"{os.getpid()}_{abs(hash(key))}")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)

    def _delete(self, key):
        try:
            os.remove(self._p(key))
        except FileNotFoundError:
            pass


def _model_cfg(model_path: str, mesh: dict, **overrides) -> dict:
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 16, "heads": 8,
        "depth": 1, "train_batch_size": 8, "vocab_size": 32, "tpu_size": 8,
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    "feed_forward-in:relu"]}],
        "memory_reduction_strategy": "none",
        "optimizer": "adam-learning_rate", "learning_rate": 1e-3,
        "weight_decay": 0.0, "storage_retry_base_delay": 0.0,
        "mesh_shape_override": mesh, "model_path": model_path,
    }
    cfg.update(overrides)
    return cfg


def _setup(cfg: dict):
    import jax
    import numpy as np

    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer

    params = ModelParameter(dict(cfg))
    mesh = shardlib.build_mesh(params)
    trainer = Trainer(params, Model(params), mesh=mesh)
    if jax.process_count() > 1:
        slice_index, slice_count = shardlib.process_data_slice(mesh)
    else:
        slice_index, slice_count = 0, 1
    gb = params.train_batch_size
    rng = np.random.default_rng(42)  # GLOBAL batch, identical in every mode
    x = rng.integers(0, params.vocab_size, (gb, params.sequence_length, 1))
    local = gb // slice_count
    rows = slice(slice_index * local, (slice_index + 1) * local)
    batch = {"token_x": np.asarray(x[rows], np.int32),
             "token_y": np.asarray((x[rows] + 1) % params.vocab_size,
                                   np.int32)}
    return params, trainer, batch


def run_lockstep(cfg: dict, steps: int) -> typing.List[float]:
    """Deterministic step sequence; also called IN-PROCESS by the parent
    test for the single-process reference (same mesh, same global batch,
    same per-step keys)."""
    import jax
    import numpy as np

    params, trainer, batch = _setup(cfg)
    state = trainer.init_state(batch)
    losses = []
    for i in range(steps):
        state, metrics = trainer.step(state, batch,
                                      rng=jax.random.PRNGKey(100 + i))
        losses.append(float(np.asarray(jax.device_get(metrics["loss"]))))
    return losses


def _mode_lockstep(args: dict) -> None:
    import jax
    losses = run_lockstep(args["cfg"], args["steps"])
    if jax.process_index() == 0:
        print("LOCKSTEP " + json.dumps([repr(v) for v in losses]),
              flush=True)


def _single_device_loss(params, variables_host: dict) -> float:
    """Forward loss of the restored parameters on ONE device with the full
    global batch — no mesh, no collectives, so the value is bit-identical
    no matter how many processes (or devices) the restore ran under.  This
    is the cross-topology 'identical post-restore loss' probe: sharded
    step losses differ in the last float32 bits between topologies because
    collective implementations order the reduction differently."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from homebrewnlp_tpu.model import Model

    rng = np.random.default_rng(42)
    gb = params.train_batch_size
    x = rng.integers(0, params.vocab_size, (gb, params.sequence_length, 1))
    batch = {"token_x": jnp.asarray(x, jnp.int32),
             "token_y": jnp.asarray((x + 1) % params.vocab_size, jnp.int32)}
    model = Model(params)
    template = model.init({k: np.asarray(v) for k, v in batch.items()})
    fn = jax.jit(lambda v, b: model.apply(v, b).total_loss.data)
    host_vars = {k: jnp.asarray(np.asarray(variables_host[k]))
                 for k in template}
    return float(np.asarray(jax.device_get(fn(host_vars, batch))))


def _mode_save(args: dict) -> None:
    import jax
    import numpy as np

    from homebrewnlp_tpu.distributed.async_checkpoint import AsyncCheckpointer
    from homebrewnlp_tpu.train import checkpoint as ckpt

    params, trainer, batch = _setup(args["cfg"])
    state = trainer.init_state(batch)
    state, _ = trainer.step(state, batch, rng=jax.random.PRNGKey(100))
    if jax.process_count() > 1:
        spanning = [k for k, v in state.variables.items()
                    if not v.is_fully_addressable]
        assert spanning, "expected model-sharded params to span processes"
    saver = AsyncCheckpointer(params.distributed_barrier_timeout_s)
    saver.submit(params.model_path, 7, state.variables, state.opt_state,
                 max_keep=2)
    saver.close()
    # live-continuation reference: one more sharded step from the saved
    # state (restores compare against it within reduction-order tolerance)
    _, metrics = trainer.step(state, batch, rng=jax.random.PRNGKey(200))
    live = float(np.asarray(jax.device_get(metrics["loss"])))
    if jax.process_index() == 0:
        restored = ckpt.restore(params.model_path, 7)
        ref = _single_device_loss(params, restored[0])
        print(f"SAVE_REF_LOSS {ref!r}", flush=True)
        print(f"SAVE_LIVE_LOSS {live!r}", flush=True)


def _mode_restore(args: dict) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.train import TrainState, checkpoint as ckpt

    params, trainer, batch = _setup(args["cfg"])
    state = trainer.init_state(batch)  # sharding template on THIS mesh
    restored = ckpt.restore_latest_valid(params.model_path, strict=True)
    assert restored is not None and restored[2] == 7, restored and restored[2]
    variables = {k: np.asarray(v).astype(state.variables[k].dtype)
                 for k, v in restored[0].items()}
    st = TrainState(shardlib.place_tree(state.variables, variables),
                    shardlib.place_tree(state.opt_state, restored[1]),
                    jnp.asarray(restored[2], jnp.int32))
    _, metrics = trainer.step(st, batch, rng=jax.random.PRNGKey(200))
    live = float(np.asarray(jax.device_get(metrics["loss"])))
    if jax.process_index() == 0:
        print(f"RESTORE_LOSS {_single_device_loss(params, restored[0])!r}",
              flush=True)
        print(f"RESTORE_LIVE_LOSS {live!r}", flush=True)


def _mode_overlap(args: dict) -> None:
    import jax
    import numpy as np

    from homebrewnlp_tpu.distributed.async_checkpoint import AsyncCheckpointer
    from homebrewnlp_tpu.train import checkpoint as ckpt

    fslib.register("dstore", DiskStoreFS(args["store"],
                                         write_delay=args["write_delay"]))
    params, trainer, batch = _setup(args["cfg"])
    state = trainer.init_state(batch)
    state, m = trainer.step(state, batch, rng=jax.random.PRNGKey(0))
    jax.block_until_ready(m["loss"])  # compile outside the timed region
    saver = AsyncCheckpointer(params.distributed_barrier_timeout_s) \
        if args["use_async"] else None
    steps = args["steps"]
    cadence = args["cadence"]
    times = []
    step_no = 7
    for i in range(steps):
        t0 = time.monotonic()
        state, metrics = trainer.step(state, batch,
                                      rng=jax.random.PRNGKey(1 + i))
        jax.block_until_ready(metrics["loss"])
        if (i + 1) % cadence == 0:
            step_no += 1
            if saver is not None:
                saver.submit(params.model_path, step_no, state.variables,
                             state.opt_state, max_keep=1)
            else:
                ckpt.save(params.model_path, step_no, state.variables,
                          state.opt_state, 1)
        times.append(time.monotonic() - t0)
    if saver is not None:
        saver.close()
    plain = [t for i, t in enumerate(times) if (i + 1) % cadence]
    cad = [t for i, t in enumerate(times) if not (i + 1) % cadence]
    if jax.process_index() == 0:
        print("OVERLAP " + json.dumps({
            "plain_median": float(np.median(plain)),
            "cadence_median": float(np.median(cad)),
            "plain": plain, "cadence": cad}), flush=True)
    # the checkpoint must actually have committed
    from homebrewnlp_tpu.train.checkpoint import list_checkpoints
    assert list_checkpoints(params.model_path), "no checkpoint committed"


def _mode_faultsave(args: dict) -> None:
    import jax
    import numpy as np

    from homebrewnlp_tpu.distributed import bootstrap
    from homebrewnlp_tpu.distributed.async_checkpoint import (
        AsyncCheckpointer, AsyncSaveError)
    from homebrewnlp_tpu.train import checkpoint as ckpt
    from homebrewnlp_tpu.utils.fault_injection import FaultInjectionFS

    pid = jax.process_index()
    store = DiskStoreFS(args["store"])
    recorder = FaultInjectionFS(inner=store)  # no faults: records op schedule
    fslib.register("dstore", recorder)
    params, trainer, batch = _setup(args["cfg"])
    state = trainer.init_state(batch)

    saver = AsyncCheckpointer(params.distributed_barrier_timeout_s)
    saver.submit(params.model_path, 5, state.variables, state.opt_state,
                 max_keep=3)
    saver.flush()
    good_ops = list(recorder.ops)
    # this process's manifest write: crashing exactly THERE is "between
    # shard write and manifest commit" — every shard file of save #2 is
    # on disk, its shards_<pid>.json (and therefore the chief's rename)
    # never happens
    manifest_idx = [i for i, (op, key) in enumerate(good_ops)
                    if op == "write" and key.endswith(f"shards_{pid}.json")]
    assert manifest_idx, good_ops

    if pid == 1:
        fslib.register("dstore", FaultInjectionFS(
            inner=store, crash_at=manifest_idx[0]))
    else:
        fslib.register("dstore", store)
    state, _ = trainer.step(state, batch, rng=jax.random.PRNGKey(100))
    failed = False
    try:
        saver.submit(params.model_path, 9, state.variables, state.opt_state,
                     max_keep=3)
        saver.flush()
    except (AsyncSaveError, TimeoutError) as e:
        # pid 1: the injected crash; pid 0: commit-barrier timeout because
        # its peer died mid-protocol — BOTH must fail loudly
        failed = True
        print(f"worker {pid}: save 9 failed as injected: "
              f"{type(e).__name__}", flush=True)
    assert failed, "torn save did not surface"

    fslib.register("dstore", store)  # storage 'recovers'
    bootstrap.barrier("post_fault_sync", 60.0)
    steps = ckpt.list_checkpoints(params.model_path)
    assert steps == [5], f"torn save must stay invisible, saw {steps}"
    restored = ckpt.restore_latest_valid(params.model_path, strict=True)
    assert restored is not None and restored[2] == 5
    # the fallback state is usable: one live step from it
    import jax.numpy as jnp
    from homebrewnlp_tpu.core import sharding as shardlib
    from homebrewnlp_tpu.train import TrainState
    st = TrainState(
        shardlib.place_tree(state.variables, {
            k: np.asarray(v).astype(state.variables[k].dtype)
            for k, v in restored[0].items()}),
        shardlib.place_tree(state.opt_state, restored[1]),
        jnp.asarray(restored[2], jnp.int32))
    _, metrics = trainer.step(st, batch, rng=jax.random.PRNGKey(300))
    assert np.isfinite(float(np.asarray(jax.device_get(metrics["loss"]))))
    print(f"FAULTSAVE OK p{pid}", flush=True)


def _mode_kvedge(args: dict) -> None:
    """Coordination-service KV/barrier edge cases the elastic membership
    layer leans on (docs/DISTRIBUTED.md 'Elasticity'), exercised directly
    instead of implicitly through fleet behavior:

    - ``kv_dir_get`` on a prefix nobody wrote: ``[]``, not an error
    - ``kv_put`` overwrite: last write wins (the heartbeat lease IS a
      rewritten key)
    - ``barrier`` timeout: raises a ``TimeoutError`` NAMING the barrier
      (a peer dead mid-protocol must surface as which-protocol-step, not
      a hang or an anonymous gRPC status)
    """
    import jax

    from homebrewnlp_tpu.distributed import bootstrap

    pid = jax.process_index()
    assert bootstrap.kv_dir_get("hbnlp/kvedge_nothing/") == []
    if pid == 0:
        assert bootstrap.kv_put("hbnlp/kvedge/shared", "first")
        assert bootstrap.kv_put("hbnlp/kvedge/shared", "second")
    assert bootstrap.kv_put(f"hbnlp/kvedge/p{pid}", f"worker{pid}")
    bootstrap.barrier("kvedge_published", 60.0)
    table = dict(bootstrap.kv_dir_get("hbnlp/kvedge/"))
    suffix = {k.rsplit("/", 1)[-1]: v for k, v in table.items()}
    assert suffix.get("shared") == "second", table  # overwrite won
    assert suffix.get("p0") == "worker0" and suffix.get("p1") == "worker1", \
        table
    if pid == 1:
        # process 0 never joins this barrier: the wait must END, raising
        # the barrier's own name — not hang until the fleet timeout
        t0 = time.monotonic()
        try:
            bootstrap.barrier("kvedge_never_joined", 3.0)
            raise AssertionError("barrier did not time out")
        except TimeoutError as e:
            assert "kvedge_never_joined" in str(e), e
            assert time.monotonic() - t0 < 30, "timed out far too late"
            print(f"worker {pid}: barrier timeout surfaced: {e}",
                  flush=True)
    # the client must survive a timed-out barrier (the faultsave recovery
    # path already depends on this): one more successful rendezvous
    bootstrap.barrier("kvedge_done", 60.0)
    print(f"KVEDGE OK p{pid}", flush=True)


MODES = {"lockstep": _mode_lockstep, "save": _mode_save,
         "restore": _mode_restore, "overlap": _mode_overlap,
         "faultsave": _mode_faultsave, "kvedge": _mode_kvedge}


def main() -> int:
    port, pid, nproc = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    mode, args = sys.argv[4], json.loads(sys.argv[5])
    if nproc > 1:
        os.environ["HBNLP_COORDINATOR"] = f"localhost:{port}"
        os.environ["HBNLP_NUM_PROCESSES"] = str(nproc)
        os.environ["HBNLP_PROCESS_ID"] = str(pid)
        from homebrewnlp_tpu.distributed import bootstrap
        assert bootstrap.maybe_initialize()
    MODES[mode](args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
