"""Data pipeline tests: TFRecord round-trip (incl. native fast path),
windowing semantics, file sharding, deterministic resume simulation."""
import os

import numpy as np
import pytest

from homebrewnlp_tpu.data import native_recordio
from homebrewnlp_tpu.data.inputs import (TextDataset, _file_windows,
                                         simulate_data_pipeline, split_files)
from homebrewnlp_tpu.data.tfrecord import (RecordWriter, decode_example,
                                           encode_example, read_records)
from backend import make_params


def _write_byte_file(path, payloads):
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(encode_example({"text": p}))


def example_roundtrip_test(tmp_path):
    path = str(tmp_path / "x_100.tfrecord")
    _write_byte_file(path, [b"hello world", b"second record"])
    got = [decode_example(p) for p in read_records(str(path), verify_crc=True)]
    assert got[0]["text"] == b"hello world"
    assert got[1]["text"] == b"second record"


def int64_roundtrip_test(tmp_path):
    path = str(tmp_path / "int64_0_6.tfrecord")
    with RecordWriter(path) as w:
        w.write(encode_example({"text": [1, 500, 65535, 2, 3, 4]}))
    (ex,) = [decode_example(p) for p in read_records(path)]
    np.testing.assert_array_equal(ex["text"], [1, 500, 65535, 2, 3, 4])


def native_fast_path_test(tmp_path):
    if not native_recordio.available():
        pytest.skip("g++ build unavailable")
    path = str(tmp_path / "n_10.tfrecord")
    _write_byte_file(path, [b"0123456789", b"abcdef"])
    payloads = list(native_recordio.read_records(path))
    assert len(payloads) == 2
    toks = native_recordio.feature_tokens(payloads[0])
    np.testing.assert_array_equal(toks, np.frombuffer(b"0123456789", np.uint8))
    # int64 fast path
    path2 = str(tmp_path / "int64_1_3.tfrecord")
    with RecordWriter(path2) as w:
        w.write(encode_example({"text": [7, 300, 9]}))
    (p,) = list(native_recordio.read_records(path2))
    np.testing.assert_array_equal(native_recordio.feature_tokens(p), [7, 300, 9])


def native_crc_and_writer_parity_test(tmp_path):
    if not native_recordio.available():
        pytest.skip("g++ build unavailable")
    from homebrewnlp_tpu.data import tfrecord as tfr
    rng = np.random.default_rng(0)
    for n in (0, 1, 7, 8, 9, 64, 1000):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        pure = tfr.crc32c(data)
        masked = ((((pure >> 15) | (pure << 17)) + 0xA282EAD8) & 0xFFFFFFFF)
        assert native_recordio.masked_crc(data) == masked, n
    # bulk writer vs python-framed reader with crc verification
    payloads = [rng.integers(0, 256, rng.integers(1, 500), dtype=np.uint8)
                .tobytes() for _ in range(20)]
    path = str(tmp_path / "bulk_0_20.tfrecord")
    assert native_recordio.write_records(path, payloads[:12])
    assert native_recordio.write_records(path, payloads[12:], append=True)
    got = list(read_records(path, verify_crc=True))
    assert got == payloads
    # payload corruption must be caught by verify_crc
    with open(path, "r+b") as f:
        f.seek(12 + 2)  # inside the first payload
        byte = f.read(1)
        f.seek(12 + 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(IOError):
        list(read_records(path, verify_crc=True))


def truncated_file_detection_test(tmp_path):
    payloads = [b"x" * 100, b"y" * 100]
    path = str(tmp_path / "trunc_0_2.tfrecord")
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    full = os.path.getsize(path)
    # cut inside the second payload: verify raises, non-verify yields 1 record
    with open(path, "r+b") as f:
        f.truncate(full - 54)
    assert len(list(read_records(path))) == 1
    with pytest.raises(IOError):
        list(read_records(path, verify_crc=True))
    # cut inside a header
    with open(path, "r+b") as f:
        f.truncate(116 + 5)
    with pytest.raises(IOError):
        list(read_records(path, verify_crc=True))


def window_semantics_test(tmp_path):
    """window(size=ctx+patch, shift=ctx, drop_remainder) per record
    (reference inputs.py:247-249)."""
    path = str(tmp_path / "w_32.tfrecord")
    _write_byte_file(path, [bytes(range(26))])
    windows = list(_file_windows(path, ctx=8, patch=1, skip_tokens=0,
                                 int_tokens=False))
    assert [w.tolist() for w in windows] == [
        list(range(0, 9)), list(range(8, 17)), list(range(16, 25))]
    # token skip consumes from the start
    windows = list(_file_windows(path, ctx=8, patch=1, skip_tokens=8,
                                 int_tokens=False))
    assert windows[0].tolist() == list(range(8, 17))


def split_files_test():
    files = [f"f_{i}_100.tfrecord" for i in range(10)]
    a, _, _, _ = split_files(files, 0, 2, seed=0)
    b, _, _, _ = split_files(files, 1, 2, seed=0)
    assert sorted(a + b) == sorted(files)
    assert not (set(a) & set(b))
    s1, _, _, _ = split_files(files, 0, 2, seed=123)
    s2, _, _, _ = split_files(files, 0, 2, seed=123)
    assert s1 == s2  # deterministic shuffle


def simulate_resume_test():
    """After a run consuming N windows, the computed skips resume exactly at
    window N (reference inputs.py:33-128)."""
    ctx, patch = 8, 1
    files = [f"f_{i:02d}_{64}.tfrecord" for i in range(4)]
    run = {"steps": 3, "grad_accumulation": 1, "batch_size": 1,
           "slice_count": 1, "ctx": ctx, "interleave_size": 2,
           "token_patch_size": patch}
    skip_flags, skips, resume = simulate_data_pipeline([run], files)
    # 3 windows consumed round-robin from files 0,1: order f0,f1,f0 ->
    # f0 skipped 16 tokens, f1 skipped 8; next draw is f1 (phase 1)
    assert skips[0] == 16 and skips[1] == 8
    assert not any(skip_flags)
    assert resume["phases"] == [1]


def text_dataset_batches_test(tmp_path):
    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    rng = np.random.default_rng(0)
    for i in range(3):
        payload = bytes(rng.integers(0, 256, 200).astype(np.uint8).tolist())
        _write_byte_file(str(data_dir / f"p_{i}_200.tfrecord"), [payload])
    params = make_params(sequence_length=16, train_batch_size=4,
                         interleaved_datasets=2,
                         dataset_configs=[{"path": str(data_dir / "*"),
                                           "type": "text", "weight": 1}])
    ds = TextDataset(params, sub_batch_size=4, repeat=False)
    batch = next(iter(ds))
    assert batch["token_x"].shape == (4, 16, 1)
    assert batch["token_y"].shape == (4, 16, 1)
    # y is x shifted by one within the shared window
    np.testing.assert_array_equal(batch["token_x"][:, 1:, 0],
                                  batch["token_y"][:, :-1, 0])


def dataset_determinism_test(tmp_path):
    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    for i in range(2):
        _write_byte_file(str(data_dir / f"p_{i}_300.tfrecord"),
                         [bytes(range(256)) + bytes(44)])
    params = make_params(sequence_length=16, train_batch_size=2,
                         dataset_configs=[{"path": str(data_dir / "*"),
                                           "type": "text", "weight": 1}])
    def take(n):
        out = []
        for i, b in enumerate(TextDataset(params, 2, repeat=False)):
            out.append(b["token_x"])
            if i + 1 == n:
                break
        return np.stack(out)
    np.testing.assert_array_equal(take(3), take(3))


def _make_record_dir(tmp_path, name, sizes, seed=3):
    rng = np.random.default_rng(seed)
    d = tmp_path / name
    os.makedirs(d)
    for i, sz in enumerate(sizes):
        payload = bytes(rng.integers(0, 256, sz).astype(np.uint8).tolist())
        _write_byte_file(str(d / f"p_{i}_{sz}.tfrecord"), [payload])
    return d


def _take(it, n):
    out = []
    for i, b in enumerate(it):
        out.append(b["token_x"])
        if i + 1 >= n:
            break
    return out


def _log_entry(ctx, interleave, batch, k, tps=1, slice_count=1):
    return {"steps": k, "ctx": ctx, "slice_count": slice_count,
            "interleave_size": interleave, "batch_size": batch,
            "grad_accumulation": 1, "token_patch_size": tps}


def _check_exact_resume(data_dir, ctx, interleave, batch, ks, tps=1,
                        repeat=False, horizon=3):
    """Assert: resuming after k batches continues BIT-EXACTLY with the
    batches an uninterrupted stream yields after its first k."""
    params = make_params(sequence_length=ctx, train_batch_size=batch,
                         interleaved_datasets=interleave,
                         token_patch_size=tps,
                         dataset_configs=[{"path": str(data_dir / "*"),
                                           "type": "text", "weight": 1}])
    for k in ks:
        full = _take(iter(TextDataset(params, batch, repeat=repeat)),
                     k + horizon)
        log = [_log_entry(ctx, interleave, batch, k, tps)]
        resumed = _take(iter(TextDataset(params, batch, runs_log=log,
                                         repeat=repeat)), horizon)
        tag = f"dir={data_dir.name} ctx={ctx} il={interleave} b={batch} " \
              f"k={k} tps={tps} repeat={repeat}"
        want = full[k:]
        assert len(resumed) == len(want), \
            f"{tag}: resumed {len(resumed)} batches, want {len(want)}"
        for j, (w, got) in enumerate(zip(want, resumed)):
            np.testing.assert_array_equal(got, w, err_msg=f"{tag} step={j}")


def resume_continuation_property_test(tmp_path):
    """THE load-bearing resume invariant (simulate_data_pipeline docstring):
    for slice_count==1 the resumed stream continues bit-exactly for ANY cut
    point — mid-interleave-group cuts included — because the executed stream
    uses static interleave groups and the resume state carries the
    round-robin phase."""
    import itertools
    equal = _make_record_dir(tmp_path, "equal", [2048] * 4)
    for ctx, interleave, batch in itertools.product((8, 16), (1, 2), (1, 2)):
        _check_exact_resume(equal, ctx, interleave, batch, ks=(1, 2, 3))


def resume_ragged_files_test(tmp_path):
    """Unequal file sizes: files exhaust mid-group, so the round robin runs
    with dead members — resume must still be bit-exact (this is where the
    reference's replay arithmetic and tf.data's dynamic interleave diverge;
    our static-group stream matches the replay exactly)."""
    ragged = _make_record_dir(tmp_path, "ragged", [330, 97, 512, 200, 64])
    for interleave in (2, 3):
        _check_exact_resume(ragged, 8, interleave, 1, ks=range(1, 8))
        _check_exact_resume(ragged, 8, interleave, 2, ks=range(1, 5))


def resume_token_patch_test(tmp_path):
    """token_patch_size > 1 changes the window arithmetic (window =
    ctx + tps, shift ctx); resume stays exact."""
    d = _make_record_dir(tmp_path, "tps", [400, 250, 333])
    _check_exact_resume(d, 16, 2, 1, ks=(1, 2, 3, 4), tps=2)


def resume_wrap_test(tmp_path):
    """Cuts after the stream wrapped past the end of the dataset
    (repeat=True): the replay fast-forwards whole passes and resumes inside
    the current pass."""
    d = _make_record_dir(tmp_path, "wrap", [40, 40])
    # 4 windows per file per pass (ctx 8, window 9) -> 8 windows per pass
    _check_exact_resume(d, 8, 2, 1, ks=(7, 8, 9, 10, 17, 23), repeat=True)
    _check_exact_resume(d, 8, 1, 1, ks=(8, 13), repeat=True)


def resume_repeat_restores_dropped_groups_test(tmp_path):
    """A cut that fully consumed an interleave GROUP must not drop that
    group from later epochs: pass 2+ reopens the full file list.  Long
    horizons drive the resumed stream across the wrap boundary."""
    d = _make_record_dir(tmp_path, "wrapgroups", [40, 40, 40, 40])
    # groups [f0,f1],[f2,f3]; 16 windows per pass
    _check_exact_resume(d, 8, 2, 1, ks=(9, 12, 16, 21), repeat=True,
                        horizon=20)
    ragged = _make_record_dir(tmp_path, "wrapragged", [330, 97, 512, 200, 64])
    _check_exact_resume(ragged, 8, 2, 1, ks=(30, 55, 80, 130), repeat=True,
                        horizon=40)


def resume_after_exact_exhaustion_test(tmp_path):
    """A logged run that STARTS after an earlier run exactly exhausted the
    dataset replays against the wrapped (full) list — its consumption must
    not be discarded."""
    d = _make_record_dir(tmp_path, "exact", [40, 40])  # 8 windows per pass
    params = make_params(sequence_length=8, train_batch_size=1,
                         interleaved_datasets=2,
                         dataset_configs=[{"path": str(d / "*"),
                                           "type": "text", "weight": 1}])
    for k1, k2 in ((8, 2), (8, 8), (16, 3), (8, 11)):
        full = _take(iter(TextDataset(params, 1, repeat=True)), k1 + k2 + 4)
        log = [_log_entry(8, 2, 1, k1), _log_entry(8, 2, 1, k2)]
        resumed = _take(iter(TextDataset(params, 1, runs_log=log,
                                         repeat=True)), 4)
        for j, (w, got) in enumerate(zip(full[k1 + k2:], resumed)):
            np.testing.assert_array_equal(
                got, w, err_msg=f"k1={k1} k2={k2} step={j}")


def resume_multi_run_test(tmp_path):
    """Two successive resumes (two log entries): the replay carries the
    round-robin phase across runs."""
    d = _make_record_dir(tmp_path, "multi", [330, 97, 512, 200, 64])
    params = make_params(sequence_length=8, train_batch_size=1,
                         interleaved_datasets=2,
                         dataset_configs=[{"path": str(d / "*"),
                                           "type": "text", "weight": 1}])
    for k1, k2 in ((1, 1), (1, 2), (3, 2), (2, 5)):
        full = _take(iter(TextDataset(params, 1, repeat=False)), k1 + k2 + 3)
        log = [_log_entry(8, 2, 1, k1), _log_entry(8, 2, 1, k2)]
        resumed = _take(iter(TextDataset(params, 1, runs_log=log,
                                         repeat=False)), 3)
        want = full[k1 + k2:]
        assert len(resumed) == len(want), f"k1={k1} k2={k2}"
        for j, (w, got) in enumerate(zip(want, resumed)):
            np.testing.assert_array_equal(got, w,
                                          err_msg=f"k1={k1} k2={k2} step={j}")


def resume_sliced_test(tmp_path):
    """slice_count=2 with equal file sizes: per-slice resume is bit-exact
    (group consumption is symmetric across slices; the per-slice phase is
    carried)."""
    d = _make_record_dir(tmp_path, "sliced", [257] * 8)
    params = make_params(sequence_length=8, train_batch_size=4,
                         interleaved_datasets=2,
                         dataset_configs=[{"path": str(d / "*"),
                                           "type": "text", "weight": 1}])
    for k in (1, 2, 3, 5):
        for s in (0, 1):
            full = _take(iter(TextDataset(params, 2, slice_index=s,
                                          slice_count=2, repeat=False)), k + 3)
            log = [_log_entry(8, 2, 4, k, slice_count=2)]
            resumed = _take(iter(TextDataset(params, 2, slice_index=s,
                                             slice_count=2, runs_log=log,
                                             repeat=False)), 3)
            want = full[k:]
            assert len(resumed) == len(want), f"k={k} slice={s}"
            for j, (w, got) in enumerate(zip(want, resumed)):
                np.testing.assert_array_equal(
                    got, w, err_msg=f"k={k} slice={s} step={j}")


def eval_holdout_split_test(tmp_path):
    """eval_holdout_files reserves the sorted file tail: the train side never
    reads those files, the eval side reads ONLY them, and holding out every
    file is a loud error (run/train_loop.py make_eval_batches feeds the
    'eval' side; make_dataset the 'train' side)."""
    import numpy as np
    from homebrewnlp_tpu.data.inputs import TextDataset
    from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example

    data_dir = tmp_path / "holdout"
    os.makedirs(data_dir)
    # distinct constant token per file makes provenance checkable
    for i in range(4):
        tokens = np.full(512, i + 1, np.uint8)
        with RecordWriter(str(data_dir / f"f_{i}.tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))
    params = make_params(sequence_length=16, train_batch_size=2,
                         interleaved_datasets=1,
                         dataset_configs=[{"path": str(data_dir / "*"),
                                           "type": "text", "weight": 1}])

    def seen_tokens(holdout, n_batches=8):
        ds = TextDataset(params, 2, holdout=holdout, repeat=True)
        out = set()
        it = iter(ds)
        for _ in range(n_batches):
            out.update(np.unique(next(it)["token_x"]).tolist())
        return out

    train_seen = seen_tokens(("train", 1))
    eval_seen = seen_tokens(("eval", 1))
    assert 4 not in train_seen, train_seen   # f_3 held out of training
    assert eval_seen <= {0, 4}, eval_seen    # eval reads ONLY f_3
    assert 4 in eval_seen
    try:
        TextDataset(params, 2, holdout=("train", 4))
        raise AssertionError("expected ValueError for total holdout")
    except ValueError:
        pass
