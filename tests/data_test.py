"""Data pipeline tests: TFRecord round-trip (incl. native fast path),
windowing semantics, file sharding, deterministic resume simulation."""
import os

import numpy as np
import pytest

from homebrewnlp_tpu.data import native_recordio
from homebrewnlp_tpu.data.inputs import (TextDataset, _file_windows,
                                         simulate_data_pipeline, split_files)
from homebrewnlp_tpu.data.tfrecord import (RecordWriter, decode_example,
                                           encode_example, read_records)
from backend import make_params


def _write_byte_file(path, payloads):
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(encode_example({"text": p}))


def example_roundtrip_test(tmp_path):
    path = str(tmp_path / "x_100.tfrecord")
    _write_byte_file(path, [b"hello world", b"second record"])
    got = [decode_example(p) for p in read_records(str(path), verify_crc=True)]
    assert got[0]["text"] == b"hello world"
    assert got[1]["text"] == b"second record"


def int64_roundtrip_test(tmp_path):
    path = str(tmp_path / "int64_0_6.tfrecord")
    with RecordWriter(path) as w:
        w.write(encode_example({"text": [1, 500, 65535, 2, 3, 4]}))
    (ex,) = [decode_example(p) for p in read_records(path)]
    np.testing.assert_array_equal(ex["text"], [1, 500, 65535, 2, 3, 4])


def native_fast_path_test(tmp_path):
    if not native_recordio.available():
        pytest.skip("g++ build unavailable")
    path = str(tmp_path / "n_10.tfrecord")
    _write_byte_file(path, [b"0123456789", b"abcdef"])
    payloads = list(native_recordio.read_records(path))
    assert len(payloads) == 2
    toks = native_recordio.feature_tokens(payloads[0])
    np.testing.assert_array_equal(toks, np.frombuffer(b"0123456789", np.uint8))
    # int64 fast path
    path2 = str(tmp_path / "int64_1_3.tfrecord")
    with RecordWriter(path2) as w:
        w.write(encode_example({"text": [7, 300, 9]}))
    (p,) = list(native_recordio.read_records(path2))
    np.testing.assert_array_equal(native_recordio.feature_tokens(p), [7, 300, 9])


def native_crc_and_writer_parity_test(tmp_path):
    if not native_recordio.available():
        pytest.skip("g++ build unavailable")
    from homebrewnlp_tpu.data import tfrecord as tfr
    rng = np.random.default_rng(0)
    for n in (0, 1, 7, 8, 9, 64, 1000):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        pure = tfr.crc32c(data)
        masked = ((((pure >> 15) | (pure << 17)) + 0xA282EAD8) & 0xFFFFFFFF)
        assert native_recordio.masked_crc(data) == masked, n
    # bulk writer vs python-framed reader with crc verification
    payloads = [rng.integers(0, 256, rng.integers(1, 500), dtype=np.uint8)
                .tobytes() for _ in range(20)]
    path = str(tmp_path / "bulk_0_20.tfrecord")
    assert native_recordio.write_records(path, payloads[:12])
    assert native_recordio.write_records(path, payloads[12:], append=True)
    got = list(read_records(path, verify_crc=True))
    assert got == payloads
    # payload corruption must be caught by verify_crc
    with open(path, "r+b") as f:
        f.seek(12 + 2)  # inside the first payload
        byte = f.read(1)
        f.seek(12 + 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(IOError):
        list(read_records(path, verify_crc=True))


def truncated_file_detection_test(tmp_path):
    payloads = [b"x" * 100, b"y" * 100]
    path = str(tmp_path / "trunc_0_2.tfrecord")
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    full = os.path.getsize(path)
    # cut inside the second payload: verify raises, non-verify yields 1 record
    with open(path, "r+b") as f:
        f.truncate(full - 54)
    assert len(list(read_records(path))) == 1
    with pytest.raises(IOError):
        list(read_records(path, verify_crc=True))
    # cut inside a header
    with open(path, "r+b") as f:
        f.truncate(116 + 5)
    with pytest.raises(IOError):
        list(read_records(path, verify_crc=True))


def window_semantics_test(tmp_path):
    """window(size=ctx+patch, shift=ctx, drop_remainder) per record
    (reference inputs.py:247-249)."""
    path = str(tmp_path / "w_32.tfrecord")
    _write_byte_file(path, [bytes(range(26))])
    windows = list(_file_windows(path, ctx=8, patch=1, skip_tokens=0,
                                 int_tokens=False))
    assert [w.tolist() for w in windows] == [
        list(range(0, 9)), list(range(8, 17)), list(range(16, 25))]
    # token skip consumes from the start
    windows = list(_file_windows(path, ctx=8, patch=1, skip_tokens=8,
                                 int_tokens=False))
    assert windows[0].tolist() == list(range(8, 17))


def split_files_test():
    files = [f"f_{i}_100.tfrecord" for i in range(10)]
    a, _ = split_files(files, 0, 2, seed=0)
    b, _ = split_files(files, 1, 2, seed=0)
    assert sorted(a + b) == sorted(files)
    assert not (set(a) & set(b))
    s1, _ = split_files(files, 0, 2, seed=123)
    s2, _ = split_files(files, 0, 2, seed=123)
    assert s1 == s2  # deterministic shuffle


def simulate_resume_test():
    """After a run consuming N windows, the computed skips resume exactly at
    window N (reference inputs.py:33-128)."""
    ctx, patch = 8, 1
    files = [f"f_{i:02d}_{64}.tfrecord" for i in range(4)]
    run = {"steps": 3, "grad_accumulation": 1, "batch_size": 1,
           "slice_count": 1, "ctx": ctx, "interleave_size": 2,
           "token_patch_size": patch}
    skip_flags, skips = simulate_data_pipeline([run], files)
    # 3 windows consumed round-robin from files 0,1: two from f0? order:
    # f0,f1,f0 -> f0 skipped 16 tokens, f1 skipped 8
    assert skips[0] == 16 and skips[1] == 8
    assert not any(skip_flags)


def text_dataset_batches_test(tmp_path):
    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    rng = np.random.default_rng(0)
    for i in range(3):
        payload = bytes(rng.integers(0, 256, 200).astype(np.uint8).tolist())
        _write_byte_file(str(data_dir / f"p_{i}_200.tfrecord"), [payload])
    params = make_params(sequence_length=16, train_batch_size=4,
                         interleaved_datasets=2,
                         dataset_configs=[{"path": str(data_dir / "*"),
                                           "type": "text", "weight": 1}])
    ds = TextDataset(params, sub_batch_size=4, repeat=False)
    batch = next(iter(ds))
    assert batch["token_x"].shape == (4, 16, 1)
    assert batch["token_y"].shape == (4, 16, 1)
    # y is x shifted by one within the shared window
    np.testing.assert_array_equal(batch["token_x"][:, 1:, 0],
                                  batch["token_y"][:, :-1, 0])


def dataset_determinism_test(tmp_path):
    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    for i in range(2):
        _write_byte_file(str(data_dir / f"p_{i}_300.tfrecord"),
                         [bytes(range(256)) + bytes(44)])
    params = make_params(sequence_length=16, train_batch_size=2,
                         dataset_configs=[{"path": str(data_dir / "*"),
                                           "type": "text", "weight": 1}])
    def take(n):
        out = []
        for i, b in enumerate(TextDataset(params, 2, repeat=False)):
            out.append(b["token_x"])
            if i + 1 == n:
                break
        return np.stack(out)
    np.testing.assert_array_equal(take(3), take(3))


def resume_continuation_property_test(tmp_path):
    """The load-bearing resume invariants (reference inputs.py:33-128):

    * when the consumed count lands on an interleave-cycle boundary (or
      interleave is 1) the resumed stream continues with EXACTLY the batches
      an uninterrupted stream yields after its first k;
    * otherwise the per-file skips are still exact — no window is repeated
      or lost — but the round-robin phase restarts, so the continuation is
      a rotation: compare as window multisets over the overlap horizon
      (matching the reference's own semantics)."""
    import itertools

    rng = np.random.default_rng(3)
    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    n_files = 4
    for i in range(n_files):
        payload = bytes(rng.integers(0, 256, 2048).astype(np.uint8).tolist())
        _write_byte_file(str(data_dir / f"p_{i}_2048.tfrecord"), [payload])

    def windows(batches):
        return [bytes(row.tobytes()) for b in batches for row in b]

    for ctx, interleave, batch, k in itertools.product(
            (8, 16), (1, 2), (1, 2), (1, 2, 3)):
        params = make_params(
            sequence_length=ctx, train_batch_size=batch,
            interleaved_datasets=interleave,
            dataset_configs=[{"path": str(data_dir / "*"), "type": "text",
                              "weight": 1}])
        horizon = 3
        full = []
        for i, b in enumerate(TextDataset(params, batch, repeat=False)):
            full.append(b["token_x"])
            if i + 1 >= k + horizon:
                break
        log_entry = {"steps": k, "ctx": ctx, "slice_count": 1,
                     "interleave_size": interleave, "batch_size": batch,
                     "grad_accumulation": 1, "token_patch_size": 1}
        resumed = []
        for i, b in enumerate(TextDataset(params, batch, runs_log=[log_entry],
                                          repeat=False)):
            resumed.append(b["token_x"])
            if i + 1 >= horizon:
                break
        tag = f"ctx={ctx} il={interleave} b={batch} k={k}"
        if interleave == 1 or (k * batch) % interleave == 0:
            for j, (want, got) in enumerate(zip(full[k:], resumed)):
                np.testing.assert_array_equal(got, want,
                                              err_msg=f"{tag} step={j}")
        else:
            want = sorted(windows(full[k:]))
            got = sorted(windows(resumed))
            assert got == want, f"{tag}: window multiset diverged on resume"
