"""Storage fault-injection harness (utils/fault_injection.py) against the
checkpoint save/replace/prune sequence: the acceptance sweep crashes
``checkpoint.save`` at EVERY fs-primitive index and proves
``restore_latest_valid`` always returns a complete checkpoint — never an
exception, never mixed state.  Plus: transient errors absorbed by the retry
seam, torn writes caught by length/crc verification, CheckpointError
wrapping, stale-tmp cleanup, and fallback walking.  All deterministic:
injected schedules, injected clock/rng, zero wall-clock sleeps."""
import random

import jax.numpy as jnp
import numpy as np
import pytest

from backend import make_params  # noqa: F401  (CPU env bootstrap)
from homebrewnlp_tpu.train import checkpoint as ckpt
from homebrewnlp_tpu.train.checkpoint import CheckpointError
from homebrewnlp_tpu.utils import fs, retry
from homebrewnlp_tpu.utils.fault_injection import (FaultInjectionFS,
                                                   InjectedFault,
                                                   InjectedTransient)

BASE = "fault://bucket/run"


@pytest.fixture(autouse=True)
def no_sleep_retry():
    """Deterministic no-wall-clock retry policy for every test here; the
    recorded sleeps prove the backoff schedule actually ran."""
    old = retry.default_policy()
    sleeps = []
    retry.set_default_policy(retry.RetryPolicy(
        max_attempts=4, base_delay=0.01, sleep=sleeps.append,
        rng=random.Random(0)))
    yield sleeps
    retry.set_default_policy(old)


def _install(**faults) -> FaultInjectionFS:
    fi = FaultInjectionFS(**faults)
    fs.register("fault", fi)
    return fi


def _state(step: int):
    """Step-derived values so cross-checkpoint mixing is detectable."""
    variables = {"w/a": jnp.full((4, 3), float(step), jnp.float32),
                 "w/b": jnp.arange(7, dtype=jnp.float32) * step}
    opt_state = {"w/a": {"m": jnp.full((4, 3), step * 10.0, jnp.float32)}}
    return variables, opt_state


def _assert_restored(restored, allowed_steps):
    assert restored is not None
    got_v, got_o, step, _ = restored
    assert step in allowed_steps, step
    np.testing.assert_array_equal(np.asarray(got_v["w/a"], np.float32),
                                  np.full((4, 3), float(step), np.float32))
    np.testing.assert_array_equal(np.asarray(got_v["w/b"], np.float32),
                                  np.arange(7, dtype=np.float32) * step)
    np.testing.assert_array_equal(np.asarray(got_o["w/a"]["m"], np.float32),
                                  np.full((4, 3), step * 10.0, np.float32))
    return step


@pytest.mark.faultinjection
def crash_at_every_op_sweep_test():
    """THE acceptance sweep: with a complete step-1 checkpoint on disk, crash
    the step-2 save (write, replace-copy, prune-delete — every primitive) at
    every index K; restore_latest_valid must return step 1 or step 2,
    complete and unmixed, at every crash point.  max_keep=1 so the sweep
    also crashes mid-prune of the old checkpoint."""
    v1, o1 = _state(1)
    v2, o2 = _state(2)
    # dry run: measure the op-index window of the second save
    fi = _install()
    ckpt.save(BASE, 1, v1, o1, max_keep=1)
    start = fi.op_index
    ckpt.save(BASE, 2, v2, o2, max_keep=1)
    n_ops = fi.op_index - start
    assert n_ops > 10, f"sweep window suspiciously small: {n_ops} ops"

    fell_back = 0
    for k in range(n_ops):
        fi = _install()
        ckpt.save(BASE, 1, v1, o1, max_keep=1)
        fi.crash_at = fi.op_index + k
        with pytest.raises(InjectedFault):
            ckpt.save(BASE, 2, v2, o2, max_keep=1)
        fi.crash_at = None  # "restart": the next reader is a fresh process
        step = _assert_restored(ckpt.restore_latest_valid(BASE), (1, 2))
        fell_back += step == 1
    # the sweep must cover both regimes: crashes before the checkpoint
    # became complete (fall back to 1) and after (step 2 survives)
    assert 0 < fell_back < n_ops, fell_back


@pytest.mark.faultinjection
def transient_errors_absorbed_test(no_sleep_retry):
    """GCS-style 503 bursts (transient, M < budget) on every array/manifest
    write and on the stale-tmp probe: the retry seam at the checkpoint fs
    call sites absorbs all of them and the checkpoint lands bit-perfect.
    (The non-idempotent directory replace is deliberately NOT retried at
    this layer — see checkpoint.save — so the schedule targets the
    retry-covered call sites.)"""
    v1, o1 = _state(1)
    fi = _install()
    ckpt.save(BASE, 1, v1, o1, max_keep=2)  # dry run: learn the op window
    n0 = fi.op_index
    v2, o2 = _state(2)
    ckpt.save(BASE, 2, v2, o2, max_keep=2)
    targets = [n0] + [i for i, (op, key) in enumerate(fi.ops)
                      if i >= n0 and op == "write" and ".tmp/" in key]
    assert len(targets) >= 5  # exists-probe + 3 arrays + manifest

    fi = _install(transient={i: 2 for i in targets})
    ckpt.save(BASE, 1, v1, o1, max_keep=2)
    ckpt.save(BASE, 2, v2, o2, max_keep=2)  # same op schedule, now flaky
    _assert_restored(ckpt.restore(BASE), (2,))
    assert len(no_sleep_retry) >= 2 * len(targets)  # the backoffs ran


@pytest.mark.faultinjection
def transient_budget_exhaustion_test():
    """More consecutive transients than the attempt budget: the error
    finally surfaces (as the transient, not something masked)."""
    v1, o1 = _state(1)
    _install(transient={0: 99})
    with pytest.raises(InjectedTransient):
        ckpt.save(BASE, 1, v1, o1, max_keep=2)


@pytest.mark.faultinjection
def torn_write_detected_test():
    """Truncate the tmp-dir write of each array file in turn: the recorded
    byte length catches it at restore, and restore_latest_valid falls back
    to the previous complete checkpoint."""
    v1, o1 = _state(1)
    v2, o2 = _state(2)
    fi = _install()
    ckpt.save(BASE, 1, v1, o1, max_keep=2)
    base_ops = fi.op_index
    ckpt.save(BASE, 2, v2, o2, max_keep=2)
    arr_writes = [i for i, (op, key) in enumerate(fi.ops)
                  if i >= base_ops and op == "write"
                  and "ckpt_2.tmp/arr_" in key]
    assert len(arr_writes) == 3  # w/a, w/b, opt m

    for target in arr_writes:
        _install(truncate={target: 3})
        ckpt.save(BASE, 1, v1, o1, max_keep=2)
        ckpt.save(BASE, 2, v2, o2, max_keep=2)  # same schedule, torn write
        with pytest.raises(CheckpointError, match="ckpt_2"):
            ckpt.restore(BASE, 2)
        _assert_restored(ckpt.restore_latest_valid(BASE), (1,))


def same_length_corruption_caught_by_crc_test():
    """A bit flip that preserves the byte length is invisible to the length
    check — the recorded crc must catch it (reusing the native slice-by-8
    crc32c when available, zlib crc32 otherwise)."""
    v1, o1 = _state(1)
    v2, o2 = _state(2)
    fi = _install()
    ckpt.save(BASE, 1, v1, o1, max_keep=2)
    ckpt.save(BASE, 2, v2, o2, max_keep=2)
    mem = fi.inner
    key = next(k for k in sorted(mem.objects) if "ckpt_2/arr_000000" in k)
    blob = bytearray(mem.objects[key])
    blob[0] ^= 0xFF
    mem.objects[key] = bytes(blob)
    with pytest.raises(CheckpointError, match="verification"):
        ckpt.restore(BASE, 2)
    _assert_restored(ckpt.restore_latest_valid(BASE), (1,))


def truncated_index_json_is_checkpoint_error_test():
    """Satellite: a torn index.json surfaces as CheckpointError naming the
    checkpoint directory, not a raw JSONDecodeError."""
    v1, o1 = _state(1)
    fi = _install()
    ckpt.save(BASE, 1, v1, o1, max_keep=2)
    key = next(k for k in sorted(fi.inner.objects)
               if k.endswith("ckpt_1/index.json"))
    fi.inner.objects[key] = fi.inner.objects[key][:10]
    with pytest.raises(CheckpointError) as ei:
        ckpt.restore(BASE, 1)
    assert "ckpt_1" in str(ei.value)
    assert ei.value.ckpt_dir.endswith("ckpt_1")


def missing_shard_file_is_checkpoint_error_test():
    """Satellite: a missing array file surfaces as CheckpointError naming
    the checkpoint directory, not a raw FileNotFoundError."""
    v1, o1 = _state(1)
    fi = _install()
    ckpt.save(BASE, 1, v1, o1, max_keep=2)
    key = next(k for k in sorted(fi.inner.objects) if "ckpt_1/arr_" in k)
    del fi.inner.objects[key]
    with pytest.raises(CheckpointError) as ei:
        ckpt.restore(BASE, 1)
    assert "ckpt_1" in str(ei.value)
    # with nothing valid left, the fallback reports no checkpoint at all
    assert ckpt.restore_latest_valid(BASE) is None


def stale_tmp_cleared_before_single_process_save_test():
    """Satellite: leftovers of a crashed earlier save in ckpt_<step>.tmp
    (including another run's shard manifests) must not leak into the final
    checkpoint directory (the distributed path has always cleared them)."""
    fi = _install()
    fi.inner._write(f"{BASE}/ckpt_5.tmp/arr_junk.bin", b"junk")
    fi.inner._write(f"{BASE}/ckpt_5.tmp/shards_7.json", b"{}")
    v5, o5 = _state(5)
    ckpt.save(BASE, 5, v5, o5, max_keep=2)
    stray = [k for k in fi.inner.objects
             if "arr_junk" in k or "shards_7" in k]
    assert not stray, stray
    _assert_restored(ckpt.restore(BASE), (5,))


def restore_latest_valid_walks_multiple_corrupt_test():
    """The fallback walks past SEVERAL broken checkpoints (torn marker,
    missing file) to the newest complete one."""
    fi = _install()
    for step in (1, 2, 3):
        v, o = _state(step)
        ckpt.save(BASE, step, v, o, max_keep=5)
    objs = fi.inner.objects
    # break 3: truncate its marker; break 2: delete an array file
    k3 = next(k for k in sorted(objs) if k.endswith("ckpt_3/index.json"))
    objs[k3] = objs[k3][:7]
    k2 = next(k for k in sorted(objs) if "ckpt_2/arr_" in k)
    del objs[k2]
    _assert_restored(ckpt.restore_latest_valid(BASE), (1,))


def restore_latest_valid_empty_test(tmp_path):
    assert ckpt.restore_latest_valid(str(tmp_path / "nowhere")) is None


def pre_integrity_manifest_still_restores_test():
    """Manifests written before integrity recording (no bytes/crc keys)
    restore without verification — forward compatibility of old runs."""
    import json
    v1, o1 = _state(1)
    fi = _install()
    ckpt.save(BASE, 1, v1, o1, max_keep=2)
    key = next(k for k in sorted(fi.inner.objects)
               if k.endswith("ckpt_1/index.json"))
    manifest = json.loads(fi.inner.objects[key].decode())
    for meta in manifest["arrays"].values():
        for field in ("bytes", "crc", "crc_algo"):
            meta.pop(field, None)
    fi.inner.objects[key] = json.dumps(manifest).encode()
    _assert_restored(ckpt.restore(BASE), (1,))


def prune_never_trusts_corrupt_future_steps_test():
    """After a corruption fallback rewound the run, pruning keeps the
    newest max_keep checkpoints AT OR BELOW the step just written and
    deletes the stale corrupt future directory — the naive newest-by-step
    prune deleted the fresh save and kept the corrupt one, making the run
    unrecoverable on the next restart."""
    fi = _install()
    v9, o9 = _state(9)
    ckpt.save(BASE, 9, v9, o9, max_keep=2)
    v12, o12 = _state(12)
    ckpt.save(BASE, 12, v12, o12, max_keep=2)
    key = next(k for k in sorted(fi.inner.objects) if "ckpt_12/arr_000000" in k)
    blob = bytearray(fi.inner.objects[key])
    blob[0] ^= 0xFF
    fi.inner.objects[key] = bytes(blob)
    _assert_restored(ckpt.restore_latest_valid(BASE), (9,))  # rewound
    # the resumed run's next periodic save lands BELOW the corrupt step
    v10, o10 = _state(10)
    ckpt.save(BASE, 10, v10, o10, max_keep=1)
    assert ckpt.list_checkpoints(BASE) == [10]
    _assert_restored(ckpt.restore_latest_valid(BASE), (10,))


def abandoned_writer_never_replays_test():
    """A writer whose commit failed must NOT replay its stale buffer from
    the destructor (io.IOBase.__del__ calls close()): the zombie write
    would land at GC time, possibly over a newer successful write."""
    import gc

    fi = _install(transient={0: 1})
    f = fs.open_(f"{BASE}/obj", "wb")
    f.write(b"stale")
    with pytest.raises(InjectedTransient):
        f.close()  # bare handle, no retry wrapper: the commit just fails
    with fs.open_(f"{BASE}/obj", "wb") as g:  # newer write succeeds
        g.write(b"fresh")
    del f
    gc.collect()
    with fs.open_(f"{BASE}/obj", "rb") as r:
        assert r.read() == b"fresh"


def checksum_algo_roundtrip_test():
    """The recorded algo verifies its own output; both algos available in
    this image must agree with a recompute."""
    from homebrewnlp_tpu.train.checkpoint import _checksum, _verify_bytes
    data = b"\x00\x01\x02checkpoint-bytes" * 37
    algo, value = _checksum(data)
    assert algo in ("crc32c-masked", "crc32")
    meta = {"bytes": len(data), "crc": value, "crc_algo": algo}
    _verify_bytes(data, meta, "arr", "ckpt_x")  # no raise
    with pytest.raises(CheckpointError):
        _verify_bytes(data[:-1], meta, "arr", "ckpt_x")
    with pytest.raises(CheckpointError):
        _verify_bytes(data[:-1] + b"\xff", meta, "arr", "ckpt_x")
