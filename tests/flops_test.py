"""Jaxpr matmul-FLOP counter (homebrewnlp_tpu/utils/flops.py) — feeds the
MFU number bench.py reports."""
import jax
import jax.numpy as jnp

from backend import make_params  # noqa: F401  (sets up the CPU mesh env)


def flops_counter_test():
    """The jaxpr matmul-FLOP counter handles dots, scans (x length), and
    batched dot_general."""
    from homebrewnlp_tpu.utils.flops import forward_flops

    a = jnp.zeros((8, 16))
    b = jnp.zeros((16, 4))
    assert forward_flops(lambda x, y: x @ y, a, b) == 2 * 8 * 16 * 4

    bm = jnp.zeros((3, 8, 16))
    wm = jnp.zeros((3, 16, 4))
    assert forward_flops(lambda x, y: jnp.einsum("bij,bjk->bik", x, y),
                         bm, wm) == 3 * 2 * 8 * 16 * 4

    def scanned(x, y):
        def body(c, _):
            return c @ y, None
        out, _ = jax.lax.scan(body, x, jnp.arange(5))
        return out
    sq = jnp.zeros((16, 16))
    assert forward_flops(scanned, sq, sq) == 5 * 2 * 16 ** 3


def flops_split_causal_flash_test():
    """count_matmul_flops_split: full keeps the stable full-square
    convention; executed subtracts the causally-dead pallas cells.  For a
    causal grid of n x n blocks, live pairs = n(n+1)/2, so executed/full of
    the kernel's own FLOPs is (n+1)/(2n)."""
    from homebrewnlp_tpu.parallel.flash_attention import flash_attention
    from homebrewnlp_tpu.utils.flops import forward_flops_split

    b, s, h, d, blk = 1, 64, 1, 16, 16  # 4 x 4 block grid
    q = jnp.zeros((b, s, h, d))

    def fwd(causal):
        return lambda x: flash_attention(x, x, x, 1.0, causal, blk, blk, True)

    full_c, exec_c = forward_flops_split(fwd(True), q)
    full_nc, exec_nc = forward_flops_split(fwd(False), q)
    # non-causal: nothing skipped
    assert full_nc == exec_nc
    # same full-square count either way (stable convention)
    assert full_c == full_nc
    # causal executed: 10 of 16 cells live -> kernel FLOPs scale by 10/16
    n = s // blk
    live_frac = (n + 1) / (2 * n)
    assert exec_c == int(full_c * live_frac)
