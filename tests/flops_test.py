"""Jaxpr matmul-FLOP counter (homebrewnlp_tpu/utils/flops.py) — feeds the
MFU number bench.py reports."""
import jax
import jax.numpy as jnp

from backend import make_params  # noqa: F401  (sets up the CPU mesh env)


def flops_counter_test():
    """The jaxpr matmul-FLOP counter handles dots, scans (x length), and
    batched dot_general."""
    from homebrewnlp_tpu.utils.flops import forward_flops

    a = jnp.zeros((8, 16))
    b = jnp.zeros((16, 4))
    assert forward_flops(lambda x, y: x @ y, a, b) == 2 * 8 * 16 * 4

    bm = jnp.zeros((3, 8, 16))
    wm = jnp.zeros((3, 16, 4))
    assert forward_flops(lambda x, y: jnp.einsum("bij,bjk->bik", x, y),
                         bm, wm) == 3 * 2 * 8 * 16 * 4

    def scanned(x, y):
        def body(c, _):
            return c @ y, None
        out, _ = jax.lax.scan(body, x, jnp.arange(5))
        return out
    sq = jnp.zeros((16, 16))
    assert forward_flops(scanned, sq, sq) == 5 * 2 * 16 ** 3
