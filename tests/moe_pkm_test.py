"""Soft mixture-of-experts + product-key-memory layer tests, incl. an
expert-parallel layout (layout_override {'experts': 'model'})."""
import jax
import jax.numpy as jnp
import numpy as np

from backend import make_params
from homebrewnlp_tpu.core import sharding as shardlib
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer


def _batch(params, rng):
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": jnp.asarray(x),
            "token_y": jnp.asarray((x + 1) % params.vocab_size)}


def moe_forward_backward_test():
    params = make_params(
        experts=4,
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 "feed_forward-in:relu-in:mixture_of_experts"]}])
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _batch(params, rng)
    variables = m.init(batch)
    expert_vars = [k for k, v in variables.items()
                   if any(d.name == "experts" for d in m.param_dims[k])]
    assert expert_vars, "MoE layer must create an experts-dim weight"
    loss, grads = jax.jit(jax.value_and_grad(
        lambda v: m.apply(v, batch).total_loss.data))(variables)
    assert np.isfinite(float(loss))
    assert all(np.all(np.isfinite(np.asarray(g, np.float32)))
               for g in grads.values())


def moe_expert_parallel_test():
    """experts dim sharded over 'model' via layout_override; sharded step
    matches the unsharded one."""
    cfg = dict(
        experts=4, heads=2, tpu_size=8, train_batch_size=8,
        optimizer="learning_rate", learning_rate=0.01, weight_decay=0.0,
        depth=1,
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 "feed_forward-in:relu-in:mixture_of_experts"]}])
    rng = np.random.default_rng(0)
    params_a = make_params(**cfg)
    m_a = Model(params_a)
    batch = _batch(params_a, rng)
    tr_a = Trainer(params_a, m_a)
    state_a = tr_a.init_state(batch)
    state_a, metrics_a = tr_a.step(state_a, batch, jax.random.PRNGKey(0))

    params_b = make_params(layout_override={"experts": "model", "heads": None},
                           **cfg)
    m_b = Model(params_b)
    mesh = shardlib.build_mesh(params_b)
    tr_b = Trainer(params_b, m_b, mesh=mesh)
    state_b = tr_b.init_state(batch)
    state_b, metrics_b = tr_b.step(state_b, batch, jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(metrics_a["loss"]), float(metrics_b["loss"]),
                               rtol=2e-5)
    for k in state_a.variables:
        np.testing.assert_allclose(np.asarray(state_a.variables[k], np.float32),
                                   np.asarray(state_b.variables[k], np.float32),
                                   rtol=5e-5, atol=1e-6, err_msg=k)


def pkm_forward_backward_test():
    params = make_params(
        features_per_head=16, heads=2, pkm_axes=2,
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 "feed_forward_product_key_memory-in:relu-absolute"]}])
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _batch(params, rng)
    variables = m.init(batch)
    pkm_vars = [k for k, v in variables.items()
                if any(d.name == "product_key_value_dim" for d in m.param_dims[k])]
    assert pkm_vars, "PKM must create the value table"
    assert variables[pkm_vars[0]].shape[0] == params.features_per_head ** 2
    loss, grads = jax.jit(jax.value_and_grad(
        lambda v: m.apply(v, batch).total_loss.data))(variables)
    assert np.isfinite(float(loss))
    # the PKM value table must receive sparse gradient through the gather
    g = np.asarray(grads[pkm_vars[0]], np.float32)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


ROUTED_LAYER = "feed_forward-in:relu-in:mixture_of_experts-in:routed"


def routed_moe_matches_dense_test():
    """Routed MoE with k = E and unbounded capacity reproduces the dense
    soft-MoE exactly: same gate/weight shapes and scope order, same softmax
    mass on every expert, no capacity drops."""
    common = dict(experts=4, heads=2, depth=1, train_batch_size=2,
                  sequence_length=16)
    rng = np.random.default_rng(0)
    params_d = make_params(
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 "feed_forward-in:relu-in:mixture_of_experts"]}],
        **common)
    m_d = Model(params_d)
    batch = _batch(params_d, rng)
    vars_d = m_d.init(batch)

    params_r = make_params(
        moe_top_k=4, moe_capacity_factor=100.0,
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 ROUTED_LAYER]}],
        **common)
    m_r = Model(params_r)
    vars_r = m_r.init(batch)
    assert set(vars_d) == set(vars_r), \
        "routed MoE must create the same parameters as the dense soft-MoE"
    for k in vars_d:
        np.testing.assert_array_equal(vars_d[k], vars_r[k])

    out_d = float(m_d.apply(vars_d, batch).total_loss.data)
    out_r = float(m_r.apply(vars_r, batch).total_loss.data)
    np.testing.assert_allclose(out_r, out_d, rtol=2e-5)


def routed_moe_top1_trains_test():
    """Top-1 routing with a tight capacity: finite loss + grads, and a real
    train step updates the expert weights."""
    params = make_params(
        experts=4, heads=2, depth=1, moe_top_k=1, moe_capacity_factor=1.0,
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 ROUTED_LAYER]}])
    m = Model(params)
    rng = np.random.default_rng(1)
    batch = _batch(params, rng)
    variables = m.init(batch)
    loss, grads = jax.jit(jax.value_and_grad(
        lambda v: m.apply(v, batch).total_loss.data))(variables)
    assert np.isfinite(float(loss))
    expert_grads = [k for k in grads
                    if any(d.name == "experts" for d in m.param_dims[k])]
    assert expert_grads
    assert any(float(np.abs(np.asarray(grads[k], np.float32)).max()) > 0
               for k in expert_grads), "expert weights got no gradient"


def routed_moe_flag_overrides_test():
    """Layer flags top_k<k>/capacity_factor<f> override the config knobs."""
    params = make_params(
        experts=4, heads=2, depth=1, moe_top_k=1,
        block_config=[{"layer": [
            "norm-shift-scale-features-group",
            ROUTED_LAYER + "-in:top_k2-in:capacity_factor2.0"]}])
    m = Model(params)
    rng = np.random.default_rng(2)
    batch = _batch(params, rng)
    variables = m.init(batch)
    assert np.isfinite(float(m.apply(variables, batch).total_loss.data))


def router_aux_inject_gradient_test():
    """_router_aux_inject is identity forward; its backward adds exactly
    jax.grad of the explicit aux losses to the incoming cotangent."""
    from homebrewnlp_tpu.model.basic import _router_aux, _router_aux_inject
    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.standard_normal((2, 16, 4)), jnp.float32)
    u = jnp.asarray(rng.standard_normal(logits.shape), jnp.float32)
    wb, wz, k = 0.3, 0.01, 2

    np.testing.assert_array_equal(
        np.asarray(_router_aux_inject(wb, wz, k, logits)), np.asarray(logits))
    g_inj = jax.grad(lambda l: jnp.sum(_router_aux_inject(wb, wz, k, l) * u)
                     )(logits)
    g_exp = u + jax.grad(lambda l: _router_aux(wb, wz, k, l))(logits)
    np.testing.assert_allclose(np.asarray(g_inj), np.asarray(g_exp),
                               rtol=1e-5, atol=1e-6)
    # balanced-router fixed point: equal logits give balance loss 1.0
    flat = jnp.zeros((1, 8, 4), jnp.float32)
    np.testing.assert_allclose(float(_router_aux(1.0, 0.0, 1, flat)), 1.0,
                               rtol=1e-6)


def routed_moe_stats_probe_test():
    """Trainer.moe_stats reports per-layer utilization / dropped fraction /
    aux-loss values — through the scanned revnet stack (depth 2), where a
    naive side-channel could never escape the lax.scan trace."""
    params = make_params(
        experts=4, heads=2, depth=2, moe_top_k=1, moe_capacity_factor=1.0,
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 ROUTED_LAYER]}])
    m = Model(params)
    rng = np.random.default_rng(5)
    batch = _batch(params, rng)
    tr = Trainer(params, m)
    state = tr.init_state(batch)
    stats = tr.moe_stats(state, batch)
    assert len(stats) == 2, f"one stats entry per depth, got {list(stats)}"
    for path, s in stats.items():
        assert "block" in path, path
        util = np.asarray(s["utilization"], np.float32)
        assert util.shape == (4,)
        np.testing.assert_allclose(util.sum(), 4.0, rtol=1e-5)
        assert 0.0 <= float(s["dropped_fraction"]) <= 1.0
        assert np.isfinite(float(s["balance_loss"]))
        assert float(s["balance_loss"]) >= 1.0 - 1e-5  # E*sum(f*P)/k >= 1
        assert np.isfinite(float(s["router_z_loss"]))
        assert float(s["utilization_min"]) <= 1.0 <= float(s["utilization_max"]) + 1e-5


def routed_moe_balance_loss_balances_router_test():
    """Training WITH the balance loss drives the routers measurably closer
    to the balanced fixed point (balance loss value 1.0) than the same run
    without it, and reduces capacity drops (same seed, same data)."""
    def run(balance):
        params = make_params(
            experts=4, heads=2, depth=2, moe_top_k=1, moe_capacity_factor=1.5,
            moe_balance_loss=balance,
            optimizer="learning_rate", learning_rate=0.05, weight_decay=0.0,
            block_config=[{"layer": ["norm-shift-scale-features-group",
                                     ROUTED_LAYER]}])
        m = Model(params)
        rng = np.random.default_rng(11)
        tr = Trainer(params, m)
        batch = _batch(params, rng)
        state = tr.init_state(batch)
        for i in range(80):
            state, metrics = tr.step(state, _batch(params, rng),
                                     jax.random.PRNGKey(i))
        assert np.isfinite(float(metrics["loss"]))
        stats = tr.moe_stats(state, batch, jax.random.PRNGKey(99))
        bal = [float(s["balance_loss"]) for s in stats.values()]
        dropped = [float(s["dropped_fraction"]) for s in stats.values()]
        assert all(0.0 <= d <= 1.0 for d in dropped)
        return sum(bal) / len(bal), max(dropped)

    bal_off, dropped_off = run(0.0)
    bal_on, dropped_on = run(1.0)
    # balanced router == balance loss 1.0 (E * sum(f*P) with f=P=1/E)
    assert bal_on < bal_off - 0.1, \
        f"balance loss did not balance the router: {bal_on} vs {bal_off}"
    assert bal_on < 1.3, f"router far from balance: {bal_on}"
    assert dropped_on <= dropped_off + 0.05, (dropped_on, dropped_off)


def routed_moe_expert_parallel_test():
    """Routed MoE with experts sharded over 'model' (the EP dryrun layout):
    the sharded step matches the unsharded step."""
    cfg = dict(
        experts=4, heads=2, tpu_size=8, train_batch_size=8, depth=1,
        moe_top_k=2, moe_capacity_factor=2.0,
        optimizer="learning_rate", learning_rate=0.01, weight_decay=0.0,
        block_config=[{"layer": ["norm-shift-scale-features-group",
                                 ROUTED_LAYER]}])
    rng = np.random.default_rng(3)
    params_a = make_params(**cfg)
    m_a = Model(params_a)
    batch = _batch(params_a, rng)
    tr_a = Trainer(params_a, m_a)
    state_a = tr_a.init_state(batch)
    state_a, metrics_a = tr_a.step(state_a, batch, jax.random.PRNGKey(0))

    params_b = make_params(layout_override={"experts": "model", "heads": None},
                           **cfg)
    m_b = Model(params_b)
    mesh = shardlib.build_mesh(params_b)
    tr_b = Trainer(params_b, m_b, mesh=mesh)
    state_b = tr_b.init_state(batch)
    state_b, metrics_b = tr_b.step(state_b, batch, jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(metrics_b["loss"]),
                               float(metrics_a["loss"]), rtol=1e-5)
