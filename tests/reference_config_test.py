"""Launch-parity check: the reference's shipped configs load unchanged.

BASELINE.md requires "existing configs/*.json launch unchanged".  When the
reference checkout is mounted (read-only at /root/reference) we parse each of
its shipped configs (configs/32big_mixer.json etc.) with our ModelParameter,
assert no key is silently dropped, and build + run the model forward at a
shrunken size (full 32-depth d4096 would be slow on the CPU test mesh but the
architecture string DSL, optimizer chain, LR schedule, and dtype policy are
taken verbatim from the file).
"""
import glob
import json
import os

import numpy as np
import pytest

REF_CONFIG_GLOB = "/root/reference/configs/*.json"
_ref_configs = sorted(glob.glob(REF_CONFIG_GLOB))

pytestmark = pytest.mark.skipif(
    not _ref_configs, reason="reference checkout not mounted")


def _load(path):
    from homebrewnlp_tpu.config import ModelParameter
    with open(path) as f:
        cfg = json.load(f)
    # shrink compute, keep every semantic knob from the file
    cfg.update(sequence_length=32, features_per_head=16, depth=2,
               train_batch_size=2, model_path="/tmp/ref_config_test",
               macro_batching=1)
    return ModelParameter(cfg), cfg


@pytest.mark.parametrize("path", _ref_configs,
                         ids=[os.path.basename(p) for p in _ref_configs])
def reference_config_loads_test(path):
    params, raw = _load(path)
    # every key in the file must be understood (reference warns on unknown
    # keys, dataclass.py:184-187); the two legacy clip knobs are unknown to
    # the reference's own dataclass as well
    legacy = {"adaptive_gradient_clipping", "gradient_clip"}
    assert set(params.unknown_config_keys) <= legacy, \
        f"unrecognised config keys: {set(params.unknown_config_keys) - legacy}"
    assert params.optimizer == raw["optimizer"]
    assert [b.layer for b in params.block_config] == \
        [b["layer"] for b in raw["block_config"]]


@pytest.mark.parametrize("path", _ref_configs,
                         ids=[os.path.basename(p) for p in _ref_configs])
def reference_config_trains_test(path):
    from homebrewnlp_tpu.model import Model
    from homebrewnlp_tpu.train import Trainer
    params, _ = _load(path)
    model = Model(params)
    trainer = Trainer(params, model)
    rng = np.random.default_rng(0)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    batch = {"token_x": x, "token_y": (x + 1) % params.vocab_size}
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
