"""Mesh-aware graft-lint suite (analysis/mesh_audit.py).

The repo at HEAD lowers every available parallel strategy on the 8-device
CPU mesh and passes all three pass families against the committed
``budgets.json`` ``meshes`` section; each pass is then proven to BITE:

* collective budgets — a synthetic surplus all-gather must be flagged
  WITH the mesh axis it reshards over named in the finding;
* sharding specs — a synthetic replicated entry parameter AND a REAL
  lowering with the layout rule broken (``layout_override`` un-mapping
  'heads') must both be flagged as silent replication;
* HBM liveness — a synthetic over-budget walk must be flagged.

Plus: replica-group -> mesh-axis attribution (explicit, iota, transposed
iota, permute pairs), the liveness walk on a hand-checked module, the
budgets-keys exactness contract (stale/orphan rows fail), and the
``mesh-axis-literal`` AST rule.
"""
from __future__ import annotations

import copy
import dataclasses
import json

import pytest

from homebrewnlp_tpu.analysis import ast_lint, hlo_lint, mesh_audit

pytestmark = pytest.mark.staticanalysis


# ---- replica-group / census parsing (pure) ---------------------------------

def replica_group_axes_test():
    mesh = {"data": 4, "model": 2}
    # explicit groups: members differ along 'data' (id = data*2 + model)
    assert hlo_lint.group_axes([[0, 2, 4, 6], [1, 3, 5, 7]],
                               mesh) == ("data",)
    # iota: [4,2]<=[8] -> {0,1},{2,3},... = 'model'
    assert hlo_lint.group_axes(
        hlo_lint._parse_replica_groups("[4,2]<=[8]"), mesh) == ("model",)
    # transposed iota: [2,4]<=[4,2]T(1,0) -> {0,2,4,6},{1,3,5,7} = 'data'
    assert hlo_lint.group_axes(
        hlo_lint._parse_replica_groups("[2,4]<=[4,2]T(1,0)"),
        mesh) == ("data",)
    # a global group spans both
    assert hlo_lint.group_axes([[0, 1, 2, 3, 4, 5, 6, 7]],
                               mesh) == ("data", "model")


def collective_inventory_axes_and_bytes_test():
    """The shared census: counts match collective_census conventions
    (async pairs once), bytes follow the result-shape rules, axes come
    from replica groups / permute pairs."""
    hlo = "\n".join([
        "%ar = f32[4,16]{1,0} all-reduce(f32[4,16]{1,0} %x), "
        "replica_groups={{0,2,4,6},{1,3,5,7}}",
        "%ag = (f32[4]{0}, f32[8]{0}) all-gather-start(f32[4]{0} %y), "
        "replica_groups=[4,2]<=[8]",
        "%agd = f32[8]{0} all-gather-done((f32[4]{0}, f32[8]{0}) %ag)",
        "%cp = f32[4]{0} collective-permute(f32[4]{0} %z), "
        "source_target_pairs={{0,2},{2,4},{4,6},{6,0}}",
    ])
    inv = hlo_lint.collective_inventory(hlo, {"data": 4, "model": 2})
    assert inv["all-reduce"] == {"count": 1, "bytes": 256,
                                 "axes": {"data": 1}}
    # async pair counted ONCE; -start bytes = the LARGEST tuple member
    assert inv["all-gather"] == {"count": 1, "bytes": 32,
                                 "axes": {"model": 1}}
    assert inv["collective-permute"]["axes"] == {"data": 1}
    # counting conventions agree with the plain census by construction
    census = hlo_lint.collective_census(hlo)
    assert {k: v["count"] for k, v in inv.items()} \
        == {k: v for k, v in census.items() if v}


# ---- pass 1 negative control: surplus collective names its axis ------------

def mesh_collective_surplus_names_axis_test():
    budget = {"all-gather": {"count": 1, "bytes": 32,
                             "axes": {"data": 1}}}
    fresh = {"all-gather": {"count": 3, "bytes": 96,
                            "axes": {"data": 1, "model": 2}}}
    findings = mesh_audit.mesh_collective_budget_audit("e", fresh, budget)
    assert findings and findings[0].rule == "mesh-collective"
    assert "mesh axis 'model' (+2)" in findings[0].message
    # within tolerance passes
    assert mesh_audit.mesh_collective_budget_audit("e", budget, budget) == []
    # a NEW collective kind (budget 0) always fails
    novel = {"all-to-all": {"count": 2, "bytes": 64,
                            "axes": {"model": 2}}}
    findings = mesh_audit.mesh_collective_budget_audit("e", novel, {})
    assert findings and "all-to-all" in findings[0].message
    # a large DROP is also a finding (the comms pattern changed)
    gone = {"all-gather": {"count": 0, "bytes": 0}}
    assert mesh_audit.mesh_collective_budget_audit("e", gone, budget)


# ---- pass 2 negative control: mis-sharded protected leaf -------------------

_ENTRY_HLO = "\n".join([
    "HloModule jit_step",
    "",
    "ENTRY %main.1_spmd (p0: f32[4,2,16], p1: s32[1,16,1]) -> f32[] {",
    "  %param.0 = f32[4,2,16]{2,1,0} parameter(0), sharding={replicated}, "
    "metadata={op_name=\"state.variables['blk/w']\"}",
    "  %param.1 = s32[1,16,1]{2,1,0} parameter(1), "
    "sharding={devices=[4,1,1,2]<=[8] last_tile_dim_replicate}, "
    "metadata={op_name=\"batch['token_x']\"}",
    "  ROOT %c = f32[] constant(0)",
    "}",
])

_PROTECTED = {
    "blk/w": {"kind": "exact", "full": "f32[4,2,16]",
              "shard": "f32[4,1,16]", "axes": ["model"]},
    "token_x": {"kind": "exact", "full": "s32[4,16,1]",
                "shard": "s32[1,16,1]", "axes": ["data"]},
}


def sharding_spec_replicated_leaf_test():
    """'blk/w' rides the entry at FULL shape -> silent replication is
    flagged (and names the contract axis); the correctly-sharded batch
    leaf passes."""
    findings = mesh_audit.sharding_spec_audit("e", _ENTRY_HLO, _PROTECTED)
    assert [f.rule for f in findings] == ["mesh-sharding"]
    msg = findings[0].message
    assert "SILENTLY REPLICATED" in msg and "blk/w" in msg \
        and "model" in msg
    # the same module against a contract it satisfies is clean
    ok = {"token_x": _PROTECTED["token_x"]}
    assert mesh_audit.sharding_spec_audit("e", _ENTRY_HLO, ok) == []


def sharding_spec_full_gather_test():
    """A compiler-inserted all-gather materialising a sharded leaf at
    full shape is flagged — unless it is in the committed baseline
    (``gather_ok_shapes``)."""
    hlo = _ENTRY_HLO.replace(
        "  ROOT %c = f32[] constant(0)",
        "  %ag = f32[4,2,16]{2,1,0} all-gather(f32[4,1,16]{2,1,0} %x), "
        "replica_groups=[4,2]<=[8]\n"
        "  ROOT %c = f32[] constant(0)")
    # make the leaf itself correctly sharded so ONLY the gather fires
    hlo = hlo.replace("f32[4,2,16]{2,1,0} parameter(0)",
                      "f32[4,1,16]{2,1,0} parameter(0)")
    protected = {"blk/w": _PROTECTED["blk/w"]}
    findings = mesh_audit.sharding_spec_audit("e", hlo, protected)
    assert findings and "all-gather" in findings[0].message \
        and "blk/w" in findings[0].message
    assert mesh_audit.sharding_spec_audit(
        "e", hlo, protected, gather_allow=("f32[4,2,16]",)) == []


def sharding_spec_missing_leaf_test():
    """A protected leaf that vanished from the entry parameters is a loud
    finding, not a silent skip."""
    findings = mesh_audit.sharding_spec_audit(
        "e", _ENTRY_HLO, {"gone/leaf": {"kind": "exact",
                                        "full": "f32[8,8]",
                                        "shard": "f32[8,4]",
                                        "axes": ["model"]}})
    assert findings and "not found" in findings[0].message


def sharding_spec_real_broken_layout_test():
    """REAL negative control: dp_tp lowered with the layout rule broken
    (``layout_override`` un-maps 'heads') compiles params replicated; the
    strategy contract still demands heads-over-'model', so the audit must
    flag silent replication on real compiled HLO, not only on synthetic
    text."""
    base = mesh_audit.MESH_STRATEGIES["dp_tp"]
    broken = dataclasses.replace(
        base, name="dp_tp_broken", entries=("train_step",),
        overrides={**base.overrides, "layout_override": {"heads": None}})
    hlo, ctx = mesh_audit.lower_train_under_mesh(broken)
    findings = mesh_audit.sharding_spec_audit("dp_tp_broken/train_step",
                                              hlo, ctx["protected"])
    assert any("SILENTLY REPLICATED" in f.message for f in findings), \
        [str(f) for f in findings]


# ---- pass 3 negative control: HBM-budget overflow --------------------------

_WALK_HLO = "\n".join([
    "HloModule m",
    "",
    "%helper (hp: f32[2]) -> f32[2] {",
    "  %hp = f32[2]{0} parameter(0)",
    "  %big = f32[100]{0} broadcast(f32[2]{0} %hp)",
    "  ROOT %r = f32[2]{0} slice(f32[100]{0} %big)",
    "}",
    "",
    "ENTRY %main (p0: f32[4]) -> f32[4] {",
    "  %p0 = f32[4]{0} parameter(0)",
    "  %t1 = f32[8]{0} broadcast(f32[4]{0} %p0)",
    "  %t2 = f32[8]{0} negate(f32[8]{0} %t1)",
    "  ROOT %out = f32[4]{0} slice(f32[8]{0} %t2)",
    "}",
])


def liveness_walk_hand_checked_test():
    """args=16B; t1 (32B) allocs, t2 (32B) allocs then t1 frees (last use
    was t2's line), out (16B) allocs while t2 live -> temp peak
    16 + 64 = 80 total at the t2 line; out line: t2 (32) + out (16) + args
    = 64.  Peak = args + max concurrent temps = 16 + 64 = 80."""
    est = mesh_audit.liveness_estimate(_WALK_HLO)
    assert est["args_bytes"] == 16
    assert est["peak_bytes"] == 80, est
    assert est["temp_peak_bytes"] == 64


def liveness_callee_peak_test():
    """A called computation's internal temporaries stack on the caller's
    live set at the call site."""
    hlo = _WALK_HLO.replace(
        "  %t2 = f32[8]{0} negate(f32[8]{0} %t1)",
        "  %t2 = f32[8]{0} call(f32[8]{0} %t1), to_apply=%helper")
    est = mesh_audit.liveness_estimate(hlo)
    # helper's internal big broadcast = 400B + its root slice 8B
    assert est["peak_bytes"] > 80 + 400 - 8, est


def hbm_liveness_over_budget_test():
    est = {"peak_bytes": 2000, "args_bytes": 1000, "temp_peak_bytes": 1000}
    committed = {"peak_bytes": 1000}
    findings = mesh_audit.hbm_liveness_audit("e", est, committed,
                                             hbm_bytes=10 ** 9)
    assert findings and findings[0].rule == "mesh-liveness"
    assert "OOM" in findings[0].message
    # within tolerance passes
    assert mesh_audit.hbm_liveness_audit(
        "e", est, {"peak_bytes": 1950}, hbm_bytes=10 ** 9) == []
    # absolute per-chip HBM overflow fails even with a matching budget
    findings = mesh_audit.hbm_liveness_audit(
        "e", est, {"peak_bytes": 2000}, hbm_bytes=1500)
    assert findings and "per-chip HBM" in findings[0].message


# ---- budgets-keys exactness (stale/orphan rows fail) -----------------------

def budgets_keys_exact_at_head_test():
    assert mesh_audit.budget_coverage_audit() == []


def budgets_stale_rows_fail_test():
    budgets = copy.deepcopy(hlo_lint.load_budgets())
    budgets["entry_points"]["renamed_step"] = {"all-reduce": 0}
    budgets["meshes"]["dropped_strategy"] = {"mesh": {}, "entries": {}}
    del budgets["meshes"]["ring_sp"]
    findings = mesh_audit.budget_coverage_audit(budgets)
    msgs = "\n".join(str(f) for f in findings)
    assert "renamed_step" in msgs          # orphan entry row
    assert "dropped_strategy" in msgs      # orphan mesh row
    assert "ring_sp" in msgs               # missing registered strategy
    assert all(f.rule == "mesh-budget-keys" for f in findings)


def budgets_stale_entry_within_strategy_fails_test():
    budgets = copy.deepcopy(hlo_lint.load_budgets())
    row = budgets["meshes"]["dp_tp"]["entries"]
    row["prefill_entry_step"] = dict(row["train_step"])  # orphan entry
    del row["decode_chunk_step"]                          # missing entry
    findings = mesh_audit.budget_coverage_audit(budgets)
    msgs = "\n".join(str(f) for f in findings)
    assert "prefill_entry_step" in msgs and "decode_chunk_step" in msgs


def committed_strategy_that_stops_lowering_fails_test():
    """A strategy with committed NON-pending budgets that env-gap-skips is
    a finding (the lint must not stay green while its budgets audit
    nothing); a row whose ``pending`` marker agrees with the skip stays a
    legitimate, loudly-printed skip."""
    findings = mesh_audit.audit_lowered_meshes(
        {}, {"ring_sp": "PartitionId instruction is not supported"})
    assert any(f.rule == "mesh-lowering" and "ring_sp" in f.entry
               for f in findings), [str(f) for f in findings]
    # the pp_* rows carry pending markers, so their skips stay clean
    findings = mesh_audit.audit_lowered_meshes(
        {}, {"pp_gpipe": "PartitionId instruction is not supported"})
    assert not any(f.rule == "mesh-lowering" for f in findings)


def analytic_floor_refuses_degenerate_write_test():
    """--write must refuse a train-step budget whose census shows the
    strategy is not actually parallel (no grad all-reduce)."""
    strategy = mesh_audit.MESH_STRATEGIES["dp_tp"]
    ctx = {"mesh_shape": {"data": 4, "model": 2}, "param_bytes": 10000,
           "protected": {}}
    row = {"collectives": {}}
    with pytest.raises(ValueError, match="not actually parallel"):
        mesh_audit._write_gate(strategy, "train_step", ctx, row)
    # collectives over a foreign axis are refused as resharding
    row = {"collectives": {
        "all-reduce": {"count": 5, "bytes": 10000,
                       "axes": {"data": 4, "sequence": 1}}}}
    with pytest.raises(ValueError, match="resharding"):
        mesh_audit._write_gate(strategy, "train_step", ctx, row)


# ---- the mesh-axis-literal AST rule ----------------------------------------

def mesh_axis_literal_rule_test():
    bad = ("from jax.sharding import PartitionSpec\n"
           "spec = PartitionSpec('model', None)\n")
    findings = ast_lint.lint_source("homebrewnlp_tpu/model/new.py", bad)
    assert [f.rule for f in findings] == ["mesh-axis-literal"]
    assert '"model"' in findings[0].message
    # mesh.shape subscripts / .get keys and axis_names membership count
    for snippet in ("n = mesh.shape['pipe']\n",
                    "n = mesh.shape.get('data', 1)\n",
                    "ok = 'sequence' in mesh.axis_names\n"):
        assert [f.rule for f in
                ast_lint.lint_source("homebrewnlp_tpu/x.py", snippet)] \
            == ["mesh-axis-literal"], snippet


def mesh_axis_literal_scope_test():
    """Only axis-consuming positions are flagged: dim names, dict
    literals, and unrelated strings stay out of scope; the axis-defining
    layers are exempt; the suppression marker works."""
    for ok in ("d = Dim('sequence', 8)\n",
               "cfg = {'data': 4, 'model': 2}\n",
               "mode = 'model'\n",
               "x = other.shape['data']\n"):  # not a mesh expression
        assert ast_lint.lint_source("homebrewnlp_tpu/x.py", ok) == [], ok
    exempt = "spec = PartitionSpec('model')\n"
    assert ast_lint.lint_source(
        "homebrewnlp_tpu/parallel/ring_attention.py", exempt) == []
    assert ast_lint.lint_source("homebrewnlp_tpu/core/sharding.py",
                                exempt) == []
    assert ast_lint.lint_source("homebrewnlp_tpu/config.py", exempt) == []
    marked = ("spec = PartitionSpec('model')  "
              "# graft-lint: allow[mesh-axis-literal]\n")
    assert ast_lint.lint_source("homebrewnlp_tpu/x.py", marked) == []


def mesh_axis_names_pinned_to_shardlib_test():
    """The rule's mirrored axis set stays in sync with the canonical
    constants (mirrored, not imported: ast_lint must import without
    jax)."""
    from homebrewnlp_tpu.core import sharding as shardlib
    assert ast_lint.MESH_AXIS_NAMES == frozenset(shardlib.MESH_AXES)


# ---- the repo at HEAD is clean ---------------------------------------------

@pytest.fixture(scope="module")
def lowered_strategies():
    """ONE lowering of every available strategy shared by the module — the
    head-clean audit and the budgets-reproduce check read the same
    compiles, like graft_lint --mesh does."""
    return mesh_audit.lower_strategies()


def mesh_audit_head_clean_test(lowered_strategies):
    """Every strategy the environment can lower passes all three pass
    families against the committed budgets; skips are ONLY the known
    jax-0.4.37 gaps, never silent."""
    lowered, skipped = lowered_strategies
    findings = mesh_audit.audit_lowered_meshes(lowered, skipped)
    assert findings == [], "\n".join(str(f) for f in findings)
    lowerable = set(mesh_audit.MESH_STRATEGIES) - set(skipped)
    # dp_tp, ring_sp, moe_ep lower on every rig this repo supports; the
    # pipeline strategies depend on partial-manual axis_index support
    assert {"dp_tp", "ring_sp", "moe_ep"} <= lowerable, skipped
    for reason in skipped.values():
        assert any(m in reason for m in mesh_audit._ENV_GAP_MARKERS)


def committed_budgets_match_fresh_lowering_test(lowered_strategies):
    """The committed meshes section reproduces from a fresh lowering (the
    same bit-for-bit census the --write protocol would emit), so a stale
    commit cannot hide behind tolerance."""
    lowered, skipped = lowered_strategies
    fresh = mesh_audit.build_mesh_budgets(lowered, skipped)
    stored = hlo_lint.load_budgets()["meshes"]
    for name in lowered:
        a = json.dumps(fresh[name]["entries"], sort_keys=True)
        b = json.dumps(stored[name]["entries"], sort_keys=True)
        assert a == b, f"{name} budgets drifted from HEAD"
