"""Weight-only int8 serving quantization (infer/quant.py).

Batch-1 decode is weight-read bound; int8 weights halve the bytes.  The
contract tested here: eligible weights round-trip within per-tensor int8
error (teacher-forcing loss moves by a small fraction), and the KV-cached
decode and full-forward sampler agree EXACTLY under the same quantized
weights — quantization must not break the cache machinery's internal
consistency even where it shifts the sampled tokens vs full precision.
"""
import jax.numpy as jnp
import numpy as np

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer.interface import InterfaceWrapper
from homebrewnlp_tpu.infer.quant import quantize_variables
from homebrewnlp_tpu.infer.sampler import sample_text
from homebrewnlp_tpu.model import Model


def _built(**kw):
    cfg = dict(features_per_head=128, heads=2, depth=2, train_batch_size=2,
               sequence_length=16, vocab_size=64,
               use_autoregressive_sampling=True,
               initial_autoregressive_position=4)
    cfg.update(kw)
    params = make_params(**cfg)
    params.train = False
    model = Model(params)
    rng = np.random.default_rng(0)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, 16, 1)).astype(np.int32)
    batch = {"token_x": x, "token_y": x.copy()}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return params, model, variables, batch


def quantize_variables_selects_matmul_weights_test():
    params, model, variables, _ = _built()
    qvars, scales = quantize_variables(variables, model.param_dims,
                                       model.param_fan_in)
    assert set(qvars) == set(variables)
    quantized = [k for k, v in qvars.items() if v.dtype == jnp.int8]
    assert quantized, "no weight was quantized"
    assert set(quantized) == set(scales)
    multi_channel = 0
    for k in quantized:
        assert "embed" not in k
        assert np.size(variables[k]) >= 1 << 16
        w = np.asarray(variables[k], np.float32)
        s = np.asarray(scales[k], np.float32)
        # per-channel scales: each axis is either fully covered (a
        # non-contracted axis the consuming einsum keeps) or reduced to 1
        # (a contracted axis — a channel scale there could not commute out
        # of the sum); scales must stay a small fraction of the weight
        assert s.ndim == w.ndim
        assert all(a in (1, b) for a, b in zip(s.shape, w.shape)), \
            (s.shape, w.shape)
        assert s.size * 4 <= w.size  # f32 scales <= 1/4 of the int8 bytes
        multi_channel += sum(a > 1 for a in s.shape) > 1
        # round-trip error bounded by half a quantization step per channel
        back = np.asarray(qvars[k], np.float32) * s
        assert np.all(np.abs(w - back) <= s * 0.5 + 1e-7)
    # the fan-in record makes at least some weights carry scales over more
    # than one non-contracted axis (e.g. new = (heads, features_per_head))
    assert multi_channel, "fan-in-aware scales never went beyond last-axis"
    small = [k for k, v in qvars.items() if v.dtype != jnp.int8]
    assert small, "everything was quantized (norm/small vars should stay)"


def quantized_forward_loss_close_test():
    """Teacher-forcing loss under int8 weights stays within a small
    fraction of the full-precision loss (the quantization is usable, not
    just mechanically wired)."""
    params, model, variables, batch = _built()
    full = float(model.apply(variables, batch).total_loss.data)
    qvars, scales = quantize_variables(variables, model.param_dims,
                                       model.param_fan_in)
    model.quant_scales = scales
    try:
        quant = float(model.apply(qvars, batch).total_loss.data)
    finally:
        model.quant_scales = None
    assert abs(quant - full) / abs(full) < 0.02, (full, quant)


def quantized_scale_reaches_replayed_blocks_test():
    """The dequantize scale must be load-bearing on every path — including
    the scan/decode ReplayBlock contexts, which build fresh scope Contexts
    and must inherit ``quant_scales``.  Zeroing the scales must change the
    loss dramatically; if the plumbing dropped them, both runs would
    consume the same raw int8 values and agree (this architecture's norms
    make a silently-dropped per-tensor scale nearly invisible to the loss,
    so the loss-parity test alone cannot catch it)."""
    params, model, variables, batch = _built(depth=2, scan_layers=True)
    qvars, scales = quantize_variables(variables, model.param_dims,
                                       model.param_fan_in)
    model.quant_scales = scales
    try:
        with_scale = float(model.apply(qvars, batch).total_loss.data)
        model.quant_scales = {k: jnp.zeros_like(v) for k, v in scales.items()}
        zeroed = float(model.apply(qvars, batch).total_loss.data)
    finally:
        model.quant_scales = None
    assert abs(with_scale - zeroed) > 1e-3, \
        "zeroing the quant scales changed nothing — scales are being dropped"


def quantized_scan_unrolled_equivalence_test():
    """Scan-over-layers resolves every depth's params under the depth-0
    canonical names, so scales must be depth-shared (joint amax): the
    quantized model's loss must be IDENTICAL under scan_layers True/False.
    Before the shared-scale fix, scan silently applied depth-0's channel
    pattern to every depth while unrolled used per-depth scales — the two
    paths disagreed (a per-depth corruption test alone cannot see it
    because the scan never reads depth>0 scale entries at all)."""
    losses = {}
    for scan in (True, False):
        params, model, variables, batch = _built(depth=4, scan_layers=scan)
        qvars, scales = quantize_variables(variables, model.param_dims,
                                           model.param_fan_in)
        # sibling depths share one scale object, and the canonical name
        # (what the scan replay looks up) is present
        import re
        canon_keys = [k for k in scales if "block0_" in k]
        assert canon_keys
        deeper = [k for k in scales if re.search(r"block[1-9]", k)]
        assert deeper, "depth>0 scale entries missing"
        for k in deeper:
            c = re.sub(r"block\d+_", "block0_", k)
            assert scales[c] is scales[k], (k, "scale not depth-shared")
        model.quant_scales = scales
        try:
            losses[scan] = float(model.apply(qvars, batch).total_loss.data)
        finally:
            model.quant_scales = None
    assert losses[True] == losses[False], losses


def stale_scales_ignore_full_precision_weights_test():
    """A Model whose quant_scales were set by a quantized wrapper must
    apply cleanly to FULL-PRECISION variables: the dtype gate in
    materialize_param scales only int8 data."""
    params, model, variables, batch = _built()
    full = float(model.apply(variables, batch).total_loss.data)
    _, scales = quantize_variables(variables, model.param_dims,
                                       model.param_fan_in)
    model.quant_scales = scales  # stale: variables below are NOT quantized
    try:
        again = float(model.apply(variables, batch).total_loss.data)
    finally:
        model.quant_scales = None
    assert again == full, (full, again)


def quantized_decode_internal_consistency_test():
    """Under the SAME quantized weights, the KV-cached sampler and the
    full-forward sampler produce identical greedy tokens — the cache
    machinery sees quantized layers transparently."""
    params, model, variables, batch = _built()
    qvars, scales = quantize_variables(variables, model.param_dims,
                                       model.param_fan_in)
    model.quant_scales = scales
    try:
        prompt = np.asarray(batch["token_x"])[:, :4, 0]
        cached = sample_text(model, qvars, prompt, initial_pos=4,
                             temperature=0.0, use_cache=True)
        full = sample_text(model, qvars, prompt, initial_pos=4,
                           temperature=0.0, use_cache=False)
    finally:
        model.quant_scales = None
    np.testing.assert_array_equal(cached, full)


def quantized_sharded_decode_parity_test():
    """int8 weights under a dp x tp mesh: sharded greedy decode equals the
    single-device quantized decode exactly (the int8 arrays + their scales
    ride the same NamedSharding machinery as full-precision weights)."""
    import jax
    import pytest
    from homebrewnlp_tpu.core import sharding as shardlib
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    params, model, variables, batch = _built(
        heads=4, train_batch_size=4,
        mesh_shape_override={"data": 2, "model": 4})
    qvars, scales = quantize_variables(variables, model.param_dims,
                                       model.param_fan_in)
    model.quant_scales = scales
    try:
        prompt = np.asarray(batch["token_x"])[:, :4, 0]
        single = sample_text(model, qvars, prompt, initial_pos=4,
                             temperature=0.0)
        mesh = shardlib.build_mesh(params)
        sharded_q = shardlib.shard_params(params, qvars, model.param_dims,
                                          mesh)
        assert any(v.dtype == jnp.int8 for v in sharded_q.values())
        out = sample_text(model, sharded_q, prompt, initial_pos=4,
                          temperature=0.0, mesh=mesh)
    finally:
        model.quant_scales = None
    np.testing.assert_array_equal(single, out)


def interface_serve_quantized_weights_test():
    """The config flag wires quantization through the serving interface:
    variables become int8 where eligible and completions run end-to-end."""
    params, model, variables, batch = _built(train_batch_size=1)
    params.serve_quantized_weights = True
    iface = InterfaceWrapper(params, model, variables)
    assert any(v.dtype == jnp.int8 for v in iface.variables.values())
    out = iface.complete_tokens(np.asarray([5, 6, 7], np.int32),
                                temperature=0.0)
    assert out.shape[0] == 16 // params.token_patch_size * \
        params.token_patch_size or out.size > 0
    assert (np.asarray(out) >= 0).all()
    assert (np.asarray(out) < params.vocab_size).all()
