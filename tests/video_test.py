"""Video (jannet) mode tests: full model fwd/bwd with frames+tokens+masks,
multi-axis attention cycling, video pipeline decode/window semantics."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.model import Model


def _video_params(**overrides):
    cfg = {
        "model_mode": "jannet", "use_video": True, "use_language": True,
        "sequence_length": 4, "time_patch": 1, "patch_size": 4,
        "frame_height": 8, "frame_width": 8, "color_channels": 3,
        "language_token_per_frame": 4, "token_patch_size": 1,
        "features_per_head": 8, "heads": 2, "depth": 1,
        "train_batch_size": 2, "vocab_size": 32, "experts": 1,
        "three_axes": False, "memory_reduction_strategy": "none",
        "calc_accuracy": False,
        "block_config": [
            {"layer": ["norm-shift-scale-features-group",
                       "attention-biased_attention_map-absolute-input_as_value"]}],
        "group_linear_factor": 2,
    }
    cfg.update(overrides)
    return ModelParameter(cfg)


def _video_batch(params, rng):
    p = params
    b, tps = p.train_batch_size, p.time_patch_size
    if p.three_axes:
        fshape = (b, tps + 1, p.frame_height_patch, p.frame_width_patch,
                  p.channel_color_size)
    else:
        fshape = (b, tps + 1, p.frame_height_patch * p.frame_width_patch,
                  p.channel_color_size)
    frame = rng.integers(0, 255, fshape).astype(np.int32)
    tokens = rng.integers(0, p.vocab_size,
                          (b, tps, p.language_token_patch, p.token_patch_size))
    return {
        "frame": jnp.asarray(frame),
        "token_x": jnp.asarray(tokens.astype(np.int32)),
        "token_y": jnp.asarray(tokens.astype(np.int32)),
        "cat_mask_x": jnp.ones((b, tps), jnp.float32),
        "cat_mask_y": jnp.ones((b, tps), jnp.float32),
        "vid_msk_src": jnp.ones((b, tps), jnp.float32),
        "vid_msk_tgt": jnp.ones((b, tps), jnp.float32),
        "txt_msk": jnp.ones((b, tps, p.language_token_patch,
                             p.token_patch_size), jnp.float32),
    }


def unpatchify_roundtrip_test():
    """render's inverse must exactly undo the input pipeline's patchify
    (data/video.py:60), including patch_size > 1."""
    params = _video_params(patch_size=4, frame_height=8, frame_width=16)
    hp, wp, ps, c = (params.frame_height_patch, params.frame_width_patch,
                     params.patch_size, params.color_channels)
    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (params.frame_height, params.frame_width, c))
    packed = img.reshape(hp, ps, wp, ps, c).transpose(1, 3, 0, 2, 4)
    packed = packed.reshape(hp * wp, params.channel_color_size)
    from homebrewnlp_tpu.infer.interface import unpatchify
    restored = unpatchify(packed[None], params)[0]
    np.testing.assert_array_equal(restored, img)
    # three_axes view of the same memory unpatchifies identically
    restored3 = unpatchify(
        packed.reshape(hp, wp, params.channel_color_size)[None], params)[0]
    np.testing.assert_array_equal(restored3, img)


def video_sampling_and_render_test(tmp_path):
    """Autoregressive frame continuation + avi render (reference
    inference.py:25-73, interface.py:13-58)."""
    params = _video_params(initial_autoregressive_position=1,
                           use_autoregressive_sampling=True)
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _video_batch(params, rng)
    variables = {k: jnp.asarray(v) for k, v in m.init(batch).items()}
    from homebrewnlp_tpu.infer.sampler import sample_video
    frames01, tokens = sample_video(m, variables, batch, initial_pos=1)
    assert frames01.shape == batch["frame"].shape
    assert np.all(np.isfinite(frames01))
    assert 0.0 <= frames01[:, 1:].min() and frames01[:, 1:].max() <= 1.0
    assert tokens is not None and tokens.shape == batch["token_x"].shape
    # the sampled positions must differ from the prompt with overwhelming
    # probability (random init still produces non-trivial frame outputs)
    assert not np.allclose(frames01[:, 2], np.asarray(batch["frame"])[:, 2] / 255.0)
    from homebrewnlp_tpu.infer.interface import render_video
    out = render_video(frames01[0], ["hi"] * frames01.shape[1], params,
                       str(tmp_path / "clip"))
    import os
    assert os.path.exists(out) and os.path.getsize(out) > 0


def video_forward_backward_test():
    params = _video_params()
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _video_batch(params, rng)
    variables = m.init(batch)
    def loss_fn(v):
        info = m.apply(v, batch)
        return info.total_loss.data
    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(variables)
    assert np.isfinite(float(loss))
    gnorm = sum(float(jnp.sum(g.astype(jnp.float32) ** 2)) for g in grads.values())
    assert np.isfinite(gnorm) and gnorm > 0


def video_loss_components_test():
    params = _video_params()
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _video_batch(params, rng)
    variables = m.init(batch)
    info = m.apply(variables, batch)
    assert info.video_loss is not None and np.isfinite(float(info.video_loss.data))
    assert info.token_loss is not None and np.isfinite(float(info.token_loss.data))
    # frame head output dims: [batch, seq, height(minus txt ctx), width, colors]
    assert info.frame_out is not None


def multi_axis_attention_cycles_test():
    """attention_idx round-robins over sequence/height/width for video
    (reference utils_mtf.py:418-422); pure-video mode has all three axes."""
    params = _video_params(depth=3, use_language=False, three_axes=True,
                           language_token_per_frame=0, frame_width=12,
                           experts=1)
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _video_batch(params, rng)
    variables = m.init(batch)
    # bias embeds must exist for three distinct mixing axes across depth
    bias_shapes = {tuple(v.shape) for k, v in variables.items()
                   if "attention" in k and "embed" in k}
    assert len(bias_shapes) == 3, bias_shapes


def bit_fold_pipeline_test():
    """bit-folded input unpacks to the same frames in the model _input
    (reference model/__init__.py:45-57, inputs.py:183-197)."""
    from homebrewnlp_tpu.data.video import decode_frame_record
    from homebrewnlp_tpu.data.tfrecord import encode_example
    import cv2
    params = _video_params(use_bit_fold_input_pipeline=True, bit_fold_value=8,
                           color_quantization_value=256)
    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (8, 8, 3)).astype(np.uint8)
    ok, enc = cv2.imencode(".png", img)
    assert ok
    payload = encode_example({"frame": enc.tobytes(), "concat": [0],
                              "skip_frame": [0]})
    frame, concat, skip, _, _ = decode_frame_record(params, payload, False)
    assert frame.dtype == np.uint32
    expect = (params.frame_height_patch, params.frame_width_patch,
              params.channel_color_size) if params.three_axes else \
        (params.frame_height_patch * params.frame_width_patch,
         params.channel_color_size)
    assert frame.shape == expect
    # unfold (model _input semantics) must reproduce the unfolded decode
    params2 = _video_params(use_bit_fold_input_pipeline=False)
    frame2, *_ = decode_frame_record(params2, payload, False)
    fold = 32 // params.bit_fold_value
    unpacked = np.stack([(frame >> (8 * i)) & 0xFF for i in range(fold)],
                        axis=-2).reshape(frame2.shape)
    np.testing.assert_array_equal(unpacked, frame2)


def video_dataset_test(tmp_path):
    from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example
    from homebrewnlp_tpu.data.video import VideoDataset
    import cv2
    params = _video_params()
    rng = np.random.default_rng(0)
    path = str(tmp_path / "vid_0_100.tfrecord")
    with RecordWriter(path) as w:
        for i in range(12):
            img = rng.integers(0, 255, (8, 8, 3)).astype(np.uint8)
            ok, enc = cv2.imencode(".png", img)
            w.write(encode_example({
                "frame": enc.tobytes(), "concat": [0], "skip_frame": [0],
                "tokens": list(rng.integers(0, 32, 4)), "mask": [3]}))
    params.dataset_configs = [{"path": path, "type": "video", "weight": 1}]
    ds = VideoDataset(params, sub_batch_size=2, repeat=True)
    batch = next(iter(ds))
    p = params
    expect = (2, p.time_patch_size + 1, p.frame_height_patch,
              p.frame_width_patch, p.channel_color_size) if p.three_axes else \
        (2, p.time_patch_size + 1, p.frame_height_patch * p.frame_width_patch,
         p.channel_color_size)
    assert batch["frame"].shape == expect
    assert batch["token_x"].shape == (2, p.time_patch_size,
                                      p.language_token_patch, p.token_patch_size)
    assert batch["vid_msk_src"].dtype == bool


def mixed_dataset_test(tmp_path):
    from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example
    from homebrewnlp_tpu.data.video import mixed_dataset
    import cv2
    params = _video_params()
    rng = np.random.default_rng(0)
    vpath = str(tmp_path / "vid_0_100.tfrecord")
    with RecordWriter(vpath) as w:
        for i in range(12):
            img = rng.integers(0, 255, (8, 8, 3)).astype(np.uint8)
            ok, enc = cv2.imencode(".png", img)
            w.write(encode_example({"frame": enc.tobytes(), "concat": [0],
                                    "skip_frame": [0],
                                    "tokens": list(rng.integers(0, 32, 4)),
                                    "mask": [3]}))
    tpath = str(tmp_path / "txt_0_600.tfrecord")
    with RecordWriter(tpath) as w:
        w.write(encode_example({"text": bytes(rng.integers(0, 32, 600).astype(np.uint8).tolist())}))
    params.dataset_configs = [{"path": vpath, "type": "video", "weight": 1},
                              {"path": tpath, "type": "text", "weight": 1}]
    it = mixed_dataset(params, sub_batch_size=2)
    keys = {"frame", "token_x", "token_y", "txt_msk", "vid_msk_src",
            "vid_msk_tgt", "cat_mask_x", "cat_mask_y"}
    for _ in range(4):
        batch = next(it)
        assert keys <= set(batch.keys())
        assert batch["frame"].dtype == np.int32
