"""Pallas blocked learned-map mixer (parallel/map_mixer.py) parity guards.

The flagship mixer route (spatial.py `_maybe_map_mixer`) must match the
dense einsum path numerically — loss to 4 decimals, updated params to
tolerance — through both dispatch arms (fused XLA reference off-TPU and the
real kernel bodies in interpret mode), must skip causally-dead blocks
correctly at multi-block shapes, and must decline LOUDLY (naming why) at
unsupported shapes while keeping the dense result.
"""
import numpy as np
import pytest

from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer

FLAGS = "biased_attention_map-absolute-input_as_value-shared"


def _cfg(knob, seq=128, **over):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": seq, "features_per_head": 16, "heads": 2,
        "depth": 2, "train_batch_size": 2, "vocab_size": 64,
        "group_linear_factor": 2,
        "intermediate_feed_forward_multiplier_multiplier": 0.5,
        "memory_reduction_strategy": "none",
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    f"attention-{FLAGS}"]}],
        "optimizer": "adam-learning_rate", "learning_rate": 0.003,
        "weight_decay": 0.0, "calculation_dtype": "float32",
        "storage_dtype": "float32", "slice_dtype": "float32",
        "use_map_mixer_kernel": knob, "model_path": "/tmp/map_mixer_test",
    }
    cfg.update(over)
    return ModelParameter(cfg)


def _step(knob, seq=128, mesh=None, **over):
    import jax
    import jax.numpy as jnp
    params = _cfg(knob, seq, **over)
    model = Model(params)
    if mesh is not None:
        from homebrewnlp_tpu.core import sharding as shardlib
        mesh = shardlib.build_mesh(params, jax.devices()[:4])
    trainer = Trainer(params, model, mesh=mesh)
    rng = np.random.default_rng(0)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, seq, 1))
    batch = {"token_x": jnp.asarray(x),
             "token_y": jnp.asarray((x + 1) % params.vocab_size)}
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch, rng=jax.random.PRNGKey(3))
    return state, metrics


def _assert_step_parity(state_d, metrics_d, state_k, metrics_k, rtol=1e-4):
    # the ISSUE-level guard: loss to 4 decimals; params pin the backward
    assert abs(float(metrics_k["loss"]) - float(metrics_d["loss"])) < 1e-4, \
        (float(metrics_k["loss"]), float(metrics_d["loss"]))
    for name in state_d.variables:
        np.testing.assert_allclose(
            np.asarray(state_k.variables[name]),
            np.asarray(state_d.variables[name]), rtol=rtol, atol=1e-6,
            err_msg=name)


def map_mixer_route_matches_dense_test():
    state_d, metrics_d = _step(False)
    state_k, metrics_k = _step(True)
    _assert_step_parity(state_d, metrics_d, state_k, metrics_k)


def map_mixer_interpret_kernels_match_dense_test(monkeypatch):
    """The real pallas kernel bodies (interpret mode off-TPU), not the XLA
    reference arm: forward + custom_vjp backward through a full train
    step."""
    state_d, metrics_d = _step(False)
    monkeypatch.setenv("HBNLP_MAP_MIXER_INTERPRET", "1")
    state_k, metrics_k = _step(True)
    _assert_step_parity(state_d, metrics_d, state_k, metrics_k)


def map_mixer_sharded_matches_unsharded_test():
    # data x model mesh: the shard_map route (batch on 'data', heads on
    # 'model' — the bias map shards by head) must match the unmeshed step
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    state_m, metrics_m = _step(True, heads=4, mesh=True, tpu_size=4,
                               mesh_shape_override={"data": 2, "model": 2})
    state_u, metrics_u = _step(True, heads=4)
    _assert_step_parity(state_u, metrics_u, state_m, metrics_m, rtol=2e-4)


def map_mixer_kernel_blocked_causal_test():
    """Direct flat-core parity at a multi-block shape: interior blocks,
    diagonal-crossing blocks, and fully-dead skipped blocks all live in one
    [256, 256] map at 64-wide tiles; grads pin the dval/dbias kernels."""
    import jax
    import jax.numpy as jnp
    from homebrewnlp_tpu.parallel.map_mixer import _xla_reference, map_mixer
    rng = np.random.default_rng(1)
    h, s, f, b = 2, 256, 16, 2
    bias = jnp.asarray(rng.normal(size=(h, s, s)), jnp.float32)
    v4 = jnp.asarray(rng.normal(size=(b, s, h, f)), jnp.float32)
    vt = v4.transpose(0, 2, 1, 3).reshape(b * h, s, f)
    for causal in (True, False):
        def k_loss(bias_, vt_):
            return jnp.sum(map_mixer(bias_, vt_, causal, 64, 64, True) ** 2)

        def r_loss(bias_, v_):
            return jnp.sum(_xla_reference(bias_, v_, causal) ** 2)

        out_k = map_mixer(bias, vt, causal, 64, 64, True)
        out_r = _xla_reference(bias, v4, causal)
        np.testing.assert_allclose(
            np.asarray(out_k.reshape(b, h, s, f).transpose(0, 2, 1, 3)),
            np.asarray(out_r), rtol=1e-5, atol=1e-5,
            err_msg=f"causal={causal}")
        db_k, dv_k = jax.grad(k_loss, argnums=(0, 1))(bias, vt)
        db_r, dv_r = jax.grad(r_loss, argnums=(0, 1))(bias, v4)
        # atol 1e-3: the partial-buffer batch sum reorders the f32
        # accumulation vs the reference einsum (values are O(10-100))
        np.testing.assert_allclose(np.asarray(db_k), np.asarray(db_r),
                                   rtol=1e-4, atol=1e-3,
                                   err_msg=f"dbias causal={causal}")
        np.testing.assert_allclose(
            np.asarray(dv_k.reshape(b, h, s, f).transpose(0, 2, 1, 3)),
            np.asarray(dv_r), rtol=1e-4, atol=1e-3,
            err_msg=f"dval causal={causal}")


def map_mixer_loud_fallback_test(capsys):
    """Unsupported shapes decline LOUDLY, naming why, and keep the dense
    result: seq 96 trips the 128-multiple tile gate."""
    from homebrewnlp_tpu.model import spatial
    spatial._MAP_MIXER_FALLBACK_SEEN.clear()
    _, metrics_k = _step(True, seq=96)
    out = capsys.readouterr().out
    assert "map-mixer kernel fallback" in out, out
    assert "128-multiple" in out, out
    _, metrics_d = _step(False, seq=96)
    assert abs(float(metrics_k["loss"]) - float(metrics_d["loss"])) < 1e-6


def map_mixer_knob_off_is_silent_test(capsys):
    from homebrewnlp_tpu.model import spatial
    spatial._MAP_MIXER_FALLBACK_SEEN.clear()
    _step(False)
    assert "map-mixer kernel fallback" not in capsys.readouterr().out
