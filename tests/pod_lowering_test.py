"""AOT pod lowering (scripts/pod_lowering.py): the full-width shipped
configs compile and partition for the pods they target, without pod
hardware — jax AOT against a detached TPU ``TopologyDescription`` runs the
real XLA/Mosaic TPU compiler and reports exact per-chip buffer sizes.

This is the existence proof for the 1B long-context target
(configs/1b_long_context.json at its configured tpu_size 128): full d8192 /
depth 26 / seq 32,768, dp x sp x tp mesh, real optimizer, ring attention +
stash + revnet — compiled end-to-end and measured under the v5p HBM budget.
The reference could launch its flagship on the pod it targeted
(/root/reference/src/main.py:107-147); this asserts the equivalent
statically.

Heavy (~4-5 min/target: the TPU compiler partitioning a 986M-param step 128
ways); kept to the two targets the round-4 verdict names.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from backend import make_params  # noqa: F401  (CPU mesh env bootstrap)


def _topologies_available() -> bool:
    """Probe in a SUBPROCESS with a hard timeout: ``get_topology_desc``
    does not reliably raise when the TPU plugin is absent — with a stale
    tunnel env it can block on plugin discovery indefinitely, and this
    probe runs at collection time, which must never hang the whole
    suite."""
    import subprocess
    import sys

    os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5p-8")
    code = ("from jax.experimental import topologies; "
            "topologies.get_topology_desc(platform='tpu', "
            "topology_name='v5p:2x2x1')")
    try:
        return subprocess.run(
            [sys.executable, "-c", code], timeout=60,
            capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


@pytest.mark.skipif(not _topologies_available(),
                    reason="detached TPU topology support (libtpu) missing")
def pod_lowering_1b_full_width_test():
    """The 1B long-context config at FULL width compiles for a 128-chip
    v5p mesh ({data 1, model 16, sequence 8}) and fits per-chip HBM."""
    import pod_lowering

    report = pod_lowering.lower_target("configs/1b_long_context.json",
                                       "v5p:4x4x8")
    assert report["devices"] == 128
    assert report["mesh"] == {"data": 1, "model": 16, "sequence": 8}
    # full width, not a shrunk stand-in
    assert report["n_params"] > 900e6, report["n_params"]
    assert report["per_chip"]["fits"], report["per_chip"]
    # the ring attention hops must appear as collective-permutes in the
    # compiled HLO — the sequence axis is real, not decorative
    assert report["collectives"].get("collective-permute", {}).get("count", 0) > 0, \
        report["collectives"]


@pytest.mark.skipif(not _topologies_available(),
                    reason="detached TPU topology support (libtpu) missing")
def pod_lowering_flagship_64_test():
    """The flagship 32big_mixer at tpu_size 64 (dp 8 x tp 8) compiles and
    fits (VERDICT r4 next-round #1's second target)."""
    import pod_lowering

    report = pod_lowering.lower_target("configs/32big_mixer.json",
                                       "v5p:4x4x4",
                                       overrides={"tpu_size": 64})
    assert report["devices"] == 64
    assert report["mesh"] == {"data": 8, "model": 8}
    assert report["per_chip"]["fits"], report["per_chip"]
    assert report["collectives"].get("all-reduce", {}).get("count", 0) > 0
