"""Attention-output stashing (stash_attention_outputs).

The revnet/momentum backward re-runs each block's forward inside
``jax.vjp`` only to rebuild residuals; with stashing, the strategy forward
rules collect every flash layer's (out, lse) and the backward replay feeds
them to ``flash_precomputed`` — exact flash-2 gradients with NO forward
kernel re-execution (measured +23% on the 16k bench, docs/PERFORMANCE.md).
The replayed q/k/v differ from the originals by revnet-reconstruction
ulps, so updated parameters match the unstashed run to that tolerance —
the same approximation class as revnet gradients themselves.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from backend import make_params
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer


def _step(stash, strategy, scan, blocks=None, seq=128, seed=0):
    params = make_params(
        sequence_length=seq, features_per_head=16, heads=2, depth=2,
        train_batch_size=2, vocab_size=32,
        block_config=blocks or [
            {"layer": ["norm-shift-scale-features-group",
                       "attention-dot_product-embedded-absolute"]}],
        memory_reduction_strategy=strategy, scan_layers=scan,
        use_flash_attention=True, stash_attention_outputs=stash,
        optimizer="sm3-learning_rate", learning_rate=0.01)
    model = Model(params)
    trainer = Trainer(params, model)
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 32, (2, seq, 1))
    batch = {"token_x": jnp.asarray(x), "token_y": jnp.asarray((x + 1) % 32)}
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch)
    return state, metrics


@pytest.mark.parametrize("strategy", ["revnet", "momentum"])
@pytest.mark.parametrize("scan", [True, False])
def stash_step_parity_test(strategy, scan):
    """Same loss, same updated params (to reconstruction ulps) with the
    stash on vs off, for both strategies, scanned and unrolled."""
    s0, m0 = _step(False, strategy, scan)
    s1, m1 = _step(True, strategy, scan)
    np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                               rtol=1e-6)
    for n in s0.variables:
        np.testing.assert_allclose(np.asarray(s0.variables[n]),
                                   np.asarray(s1.variables[n]),
                                   rtol=2e-4, atol=1e-5, err_msg=n)


def stash_multiple_attention_layers_test():
    """Two flash calls per block: the per-block stash list must collect and
    provide in the same order."""
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "attention-dot_product-embedded-absolute",
                         "attention-dot_product-context-absolute"]}]
    s0, m0 = _step(False, "revnet", True, blocks=blocks)
    s1, m1 = _step(True, "revnet", True, blocks=blocks)
    np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                               rtol=1e-6)
    for n in s0.variables:
        np.testing.assert_allclose(np.asarray(s0.variables[n]),
                                   np.asarray(s1.variables[n]),
                                   rtol=2e-4, atol=1e-5, err_msg=n)


def stash_gate_indivisible_seq_test():
    """seq not 128-divisible: the symmetric collect/provide gate declines
    and the plain replay runs — training still works."""
    _, m = _step(True, "revnet", True, seq=96)
    assert np.isfinite(float(m["loss"]))


def stash_non_flash_block_test():
    """A block without flash attention stashes an empty tuple; mixing it
    with attention blocks keeps structures consistent.

    Off-TPU the stashed-vs-replayed grads additionally carry jax-0.4.37
    pallas INTERPRET-mode reduction-order noise (measured margin ~3.5e-4
    on one of 512 elements vs the 2e-4 silicon tolerance — the classified
    environment gap from the ROADMAP re-anchor); silicon keeps 2e-4."""
    rtol = 5e-4 if jax.default_backend() != "tpu" else 2e-4
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "feed_forward-in:relu"]},
              {"layer": ["norm-shift-scale-features-group",
                         "attention-dot_product-embedded-absolute"]}]
    s0, m0 = _step(False, "revnet", True, blocks=blocks)
    s1, m1 = _step(True, "revnet", True, blocks=blocks)
    np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                               rtol=1e-6)
    for n in s0.variables:
        np.testing.assert_allclose(np.asarray(s0.variables[n]),
                                   np.asarray(s1.variables[n]),
                                   rtol=rtol, atol=1e-5, err_msg=n)


def stash_auto_resolution_test():
    """The "auto" default (round 5): off below 2048 ctx and off for
    non-128-multiple sequences, on for the long-context shapes whose stash
    fits the HBM fraction, off when the stash would be too large; explicit
    booleans pass through untouched; other strings rejected at config
    load."""
    from homebrewnlp_tpu.model.blocks import resolve_stash

    def p(**kw):
        base = dict(features_per_head=128, heads=8, depth=16,
                    train_batch_size=1, use_flash_attention=True)
        base.update(kw)
        return make_params(**base)

    assert resolve_stash(p(sequence_length=16384,
                           stash_attention_outputs="auto"))  # 16k recipe
    assert not resolve_stash(p(sequence_length=512,
                               stash_attention_outputs="auto"))  # short ctx
    assert not resolve_stash(p(sequence_length=16384 + 64,
                               stash_attention_outputs="auto"))  # gate %128
    # far over the HBM fraction (batch 64 x 32k: ~70GB of stash on a 16GB
    # planning figure)
    assert not resolve_stash(p(sequence_length=32768, train_batch_size=64,
                               stash_attention_outputs="auto"))
    assert resolve_stash(p(sequence_length=512,
                           stash_attention_outputs=True))  # explicit wins
    assert not resolve_stash(p(sequence_length=16384,
                               stash_attention_outputs=False))
    # per-device sizing: a global batch that over-fills one chip still
    # stashes when sharded 8 ways (the scaled-out 16k recipe keeps its win)
    import jax
    from homebrewnlp_tpu.core import sharding as shardlib
    big = p(sequence_length=16384, train_batch_size=8,
            stash_attention_outputs="auto",
            mesh_shape_override={"data": 8})
    if len(jax.devices()) >= 8:
        mesh = shardlib.build_mesh(big)
        assert not resolve_stash(big)          # global estimate: too big
        assert resolve_stash(big, mesh)        # per-device: fits
    # a non-boolean string is a config error, not a silent truthy enable
    with pytest.raises(ValueError):
        p(stash_attention_outputs="false")


def ring_stash_parity_test():
    """Sequence-parallel (zigzag ring) stashing: the strategy backward's
    recompute skips the whole ring — P hops of compute AND ppermutes —
    when the per-layer (out, lse) are stashed.  Updated params match the
    unstashed sharded step at reconstruction tolerance."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    def run(stash):
        params = ModelParameter({
            "model_mode": "gpt", "use_video": False, "use_language": True,
            "sequence_length": 64, "features_per_head": 8, "heads": 2,
            "depth": 2, "train_batch_size": 4, "vocab_size": 32,
            "memory_reduction_strategy": "revnet",
            "block_config": [
                {"layer": ["norm-shift-scale-features-group",
                           "attention-dot_product-context"]}],
            "group_linear_factor": 2, "tpu_size": 8,
            "sequence_parallel": 4,
            "stash_attention_outputs": stash,
            "optimizer": "sm3-learning_rate", "learning_rate": 0.01,
            "weight_decay": 0.0})
        model = Model(params)
        mesh = shardlib.build_mesh(params)
        assert mesh.shape["sequence"] == 4
        trainer = Trainer(params, model, mesh=mesh)
        rng = np.random.default_rng(0)
        x = rng.integers(0, 32, (4, 64, 1))
        batch = {"token_x": jnp.asarray(x),
                 "token_y": jnp.asarray((x + 1) % 32)}
        state = trainer.init_state(batch)
        state, metrics = trainer.step(state, batch)
        return state, metrics

    s0, m0 = run(False)
    s1, m1 = run(True)
    np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                               rtol=1e-6)
    for n in s0.variables:
        np.testing.assert_allclose(np.asarray(s0.variables[n], np.float32),
                                   np.asarray(s1.variables[n], np.float32),
                                   rtol=2e-4, atol=1e-5, err_msg=n)
