"""Multi-replica serving tier (marker: router; docs/SERVING.md).

Device-free sweep: the router dispatch policy on fake transports — prefix
affinity stickiness + the overload override, least-loaded fallback,
per-replica breaker open/skip/probe/reclose with a fake clock, the
one-cross-replica-retry rule, 503-when-all-open, and the /metrics
relabel-merge.  Plus the replica fleet supervisor on stub process targets
(relaunch with backoff, budget exhaustion raises).

Device sweep (one test): the real tier end to end — two replica
subprocesses of a tiny paged-engine model behind the router — answering
completions deterministically, merging /health, and exporting
replica-labeled block-pool gauges on one scrape.

Standalone-runnable (late-marker set, scripts/run_late_markers.sh):
``python -m pytest tests/router_test.py -q``
"""
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from homebrewnlp_tpu.infer.router import (Replica, Router,
                                          relabel_exposition)
from homebrewnlp_tpu.infer.serving_guard import HTTPStatusError

pytestmark = pytest.mark.router


def _router(n=3, t=None, transport=None, **kw):
    t = t if t is not None else [0.0]
    reps = [Replica(i, 9000 + i, breaker_threshold=2, breaker_cooldown_s=5.0,
                    clock=lambda: t[0]) for i in range(n)]
    kw.setdefault("affinity_tokens", 4)
    r = Router(reps, transport=transport or (lambda *a: (200, {"ok": True})),
               clock=lambda: t[0], **kw)
    return r, reps, t


# ------------------------------------------------------------ dispatch policy

def affinity_sticks_and_yields_to_load_test():
    """Same prompt prefix -> same replica; a different prefix goes least-
    loaded; an overloaded sticky replica is overridden."""
    router, reps, _ = _router()
    body = {"tokens": [1, 2, 3, 4, 9, 9], "max_tokens": 4}
    first = router.pick("/token_completion", body)
    reps[(first.index + 1) % 3].inflight = 0
    first.inflight = 2                      # busier, but within slack
    again = router.pick("/token_completion",
                        {"tokens": [1, 2, 3, 4, 7], "max_tokens": 2})
    assert again is first                    # prefix (first 4 tokens) sticks
    # beyond the slack the router yields to least-loaded
    first.inflight = 10
    moved = router.pick("/token_completion",
                        {"tokens": [1, 2, 3, 4, 8], "max_tokens": 2})
    assert moved is not first
    # a cold prefix dispatches least-loaded
    reps[2].inflight = 0
    reps[0].inflight = reps[1].inflight = 5
    cold = router.pick("/token_completion",
                       {"tokens": [42, 42, 42, 42], "max_tokens": 2})
    assert cold is reps[2]


def breaker_skip_retry_and_reclose_test():
    """Failures open a replica's breaker (dispatch skips it), a forward
    retries ONCE on another replica, all-open answers 503 + Retry-After,
    and the half-open probe recloses after the cooldown."""
    calls = []

    def transport(replica, path, body, timeout):
        calls.append(replica.index)
        if replica.index == 0:
            return 500, {"error": "boom", "code": "server_error"}
        return 200, {"ok": replica.index}

    router, reps, t = _router(n=2, transport=transport)
    reps[0].inflight = 0
    reps[1].inflight = 1                    # replica 0 preferred
    out = router.forward("/encode", {"prompt": "x"})
    assert out == {"ok": 1} and calls == [0, 1]   # failed, retried on 1
    out = router.forward("/encode", {"prompt": "x"})
    assert calls == [0, 1, 0, 1]
    assert reps[0].breaker.state == "open"  # threshold 2 reached
    calls.clear()
    out = router.forward("/encode", {"prompt": "x"})
    assert calls == [1]                     # open replica skipped entirely
    # all open -> 503 with Retry-After, no transport call
    reps[1].breaker.state = "open"
    reps[1].breaker.open_until = t[0] + 3.0
    calls.clear()
    with pytest.raises(HTTPStatusError) as exc:
        router.forward("/encode", {"prompt": "x"})
    assert exc.value.status == 503 and calls == []
    assert exc.value.retry_after >= 1.0
    # cooldown elapses: half-open admits the probe; replica 1's success
    # recloses it
    t[0] = 10.0
    out = router.forward("/encode", {"prompt": "x"})
    assert out == {"ok": 1}
    assert reps[1].breaker.state == "closed"


def unreachable_replica_counts_and_retries_test():
    """Connection-level failures convert to 502, count into the breaker,
    and retry on a healthy replica; client errors (4xx) do NOT count as
    replica failures."""
    def transport(replica, path, body, timeout):
        if replica.index == 0:
            raise ConnectionRefusedError("down")
        if body.get("bad"):
            return 400, {"error": "bad prompt", "code": "bad_request"}
        return 200, {"ok": replica.index}

    router, reps, _ = _router(n=2, transport=transport)
    reps[1].inflight = 5                    # replica 0 preferred
    assert router.forward("/encode", {}) == {"ok": 1}
    assert reps[0].failures == 1
    # a 400 answers the client untouched and leaves the breaker closed
    reps[0].breaker.state = "open"          # force traffic to replica 1
    reps[0].breaker.open_until = 100.0
    with pytest.raises(HTTPStatusError) as exc:
        router.forward("/encode", {"bad": True})
    assert exc.value.status == 400
    assert reps[1].breaker.state == "closed" and reps[1].failures == 0


def relabel_exposition_test():
    """Sample lines gain replica="<i>" (label-set-aware), HELP/TYPE lines
    dedupe across replicas, malformed lines drop."""
    text = ("# HELP hbnlp_x total\n# TYPE hbnlp_x counter\n"
            "hbnlp_x 3\n"
            'hbnlp_y{path="/completion"} 1.5\n'
            "garbage line without value-number-structure{{{\n")
    seen = set()
    out0 = relabel_exposition(text, 0, seen)
    out1 = relabel_exposition(text, 1, seen)
    assert 'hbnlp_x{replica="0"} 3' in out0
    assert 'hbnlp_y{replica="0",path="/completion"} 1.5' in out0
    assert "# HELP hbnlp_x total" in out0
    # second replica: samples relabeled, meta deduped
    assert 'hbnlp_x{replica="1"} 3' in out1
    assert not any(line.startswith("#") for line in out1)
    assert not any("garbage" in line for line in out0 + out1)


def router_health_merge_test():
    """/health aggregates per-replica state and stays "ok" while any
    replica is dispatchable; every breaker open -> "unavailable"."""
    router, reps, t = _router(n=2)
    payload = router.health(probe=lambda r: json.dumps({"status": "ok"}))
    assert payload["status"] == "ok"
    assert [e["replica"] for e in payload["replicas"]] == [0, 1]
    assert all(e["health"] == {"status": "ok"}
               for e in payload["replicas"])
    # unreachable probe is recorded per replica, not fatal
    def flaky(r):
        if r.index == 0:
            raise ConnectionRefusedError("down")
        return json.dumps({"status": "ok"})
    payload = router.health(probe=flaky)
    assert payload["status"] == "ok"
    assert "unreachable" in payload["replicas"][0]
    assert payload["tier"]["reachable"] == 1
    # NOTHING reachable = unavailable even with closed breakers: replicas
    # still loading their model must not read as a routable tier
    def down(r):
        raise ConnectionRefusedError("starting up")
    payload = router.health(probe=down)
    assert payload["status"] == "unavailable"
    ok, ready = router.ready(probe=down)
    assert not ok and ready["replicas_ready"] == 0
    ok, ready = router.ready(probe=lambda r: "{}" if r.index == 1
                             else (_ for _ in ()).throw(OSError("down")))
    assert ok and ready["replicas_ready"] == 1
    for r in reps:
        r.breaker.state = "open"
        r.breaker.open_until = t[0] + 10
    payload = router.health(probe=flaky)
    assert payload["status"] == "unavailable"


# ---------------------------------------------------- disagg owner failover

def kill_the_owner_degrades_to_cold_prefill_test():
    """Disaggregated tier, owner death mid-traffic: when the global prefix
    index names an owner that is GONE (connection refused) or breaker-open,
    the request falls back to cold prefill on another replica, the stale
    index entries are invalidated, and the client gets EXACTLY one answer
    — never a 500, never a duplicate."""
    from homebrewnlp_tpu.infer.router import KV_BLOCKS_PATH

    answered = []                            # successful completion answers
    dead = set()

    def transport(replica, path, body, timeout, headers=None):
        if replica.index in dead:
            raise ConnectionRefusedError(f"replica {replica.index} killed")
        if path == KV_BLOCKS_PATH:
            if body.get("op") == "export":
                toks = body["tokens"]
                return 200, {"version": 1, "block_tokens": 4,
                             "blocks": [{"key": toks[i:i + 4],
                                         "leaves": {"t/k": {"bytes": 8}}}
                                        for i in range(0, len(toks), 4)]}
            return 200, {"injected": 1, "skipped": 0}
        answered.append(replica.index)
        return 200, {"tokens": [9], "replica": replica.index}

    t = [0.0]
    reps = [Replica(i, 9000 + i, breaker_threshold=2, breaker_cooldown_s=5.0,
                    clock=lambda: t[0]) for i in range(3)]
    router = Router(reps, transport=transport, clock=lambda: t[0],
                    classes=["prefill", "decode", "decode"], block_tokens=4)
    toks = list(range(1, 10))                # 2 whole blocks + 1
    # warm: cold run lands on the prefill replica, migration hands the
    # blocks (and ownership) to a decode replica
    router.forward("/token_completion", {"tokens": toks})
    out = router.forward("/token_completion", {"tokens": toks})
    owner = out["replica"]
    assert reps[owner].cls == "decode"
    assert router.gindex.lookup(toks)[0] == owner
    # KILL the owner: the very next request must still answer, exactly once
    dead.add(owner)
    answered.clear()
    out = router.forward("/token_completion", {"tokens": toks})
    assert out["replica"] != owner
    assert answered == [out["replica"]]      # exactly-one-answer invariant
    # stale entries dropped and ownership re-learned on the survivor
    assert router.gindex.lookup(toks)[0] == out["replica"]
    assert all(v != owner for v in router.gindex._map.values())
    # breaker-open owner (not yet dead at the transport level) also
    # degrades without a transport call reaching it
    victim = out["replica"]
    for _ in range(2):
        reps[victim].breaker.record_failure()
    assert reps[victim].breaker.tick() == "open"
    answered.clear()
    out = router.forward("/token_completion", {"tokens": toks})
    assert out["replica"] != victim and answered == [out["replica"]]
    assert all(v != victim for v in router.gindex._map.values())


def symmetric_tier_never_consults_kv_blocks_test():
    """Classless (or single-class) replica lists leave the global index
    off: forward() is byte-identical to the pre-disagg router."""
    from homebrewnlp_tpu.infer.router import KV_BLOCKS_PATH
    paths = []

    def transport(replica, path, body, timeout, headers=None):
        paths.append(path)
        return 200, {"ok": replica.index}

    router, _, _ = _router(transport=transport)
    assert router.gindex is None
    router.forward("/token_completion", {"tokens": list(range(12))})
    assert KV_BLOCKS_PATH not in paths


# ------------------------------------------------------------ fleet stubs

def _stub_replica_ok(cfg, port, index):
    time.sleep(600)


def _stub_replica_dies(cfg, port, index):
    sys.exit(3)


def replica_fleet_relaunch_and_budget_test():
    """Dead replicas relaunch with backoff; the budget bounds crash LOOPS
    and raises when exhausted (a fleet silently shrinking to zero is worse
    than a loud failure)."""
    from homebrewnlp_tpu.distributed.replica_fleet import ReplicaFleet

    class _P:
        _raw_config = {"model_path": "/tmp/fleet_test"}
        serve_child_max_restarts = 1
        serve_child_restart_backoff_s = 0.05

    fleet = ReplicaFleet(_P(), 2, base_port=0, target=_stub_replica_ok)
    try:
        fleet.start()
        deadline = time.monotonic() + 30
        while fleet.alive() < 2 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert fleet.alive() == 2
        # kill one replica: poll relaunches it within the backoff window
        fleet._procs[0].terminate()
        fleet._procs[0].join(timeout=10)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            fleet.poll()
            if (fleet._procs[0] is not None and fleet._procs[0].is_alive()
                    and fleet._restarts[0] == 1):
                break
            time.sleep(0.05)
        assert fleet.alive() == 2 and fleet._restarts[0] == 1
    finally:
        fleet.stop()
    # a replica that keeps dying exhausts its budget loudly
    fleet = ReplicaFleet(_P(), 1, base_port=0, target=_stub_replica_dies)
    try:
        fleet.start()
        with pytest.raises(RuntimeError, match="relaunches were exhausted"):
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                fleet.poll()
                time.sleep(0.05)
    finally:
        fleet.stop()


# ------------------------------------------------------------- end to end

def replica_tier_end_to_end_test():
    """Two real replica subprocesses (tiny paged-engine model) behind the
    router: deterministic completions through the tier, merged /health,
    and ONE /metrics scrape carrying replica-labeled engine + block-pool
    series next to the router's own dispatch counters."""
    import socket
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.router import serve_replicated

    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 16, "features_per_head": 8, "heads": 2,
        "depth": 1, "train_batch_size": 1, "vocab_size": 64,
        "group_linear_factor": 2,
        "intermediate_feed_forward_multiplier_multiplier": 0.5,
        "memory_reduction_strategy": "none",
        "block_config": [
            {"layer": ["norm-shift-scale-features-group",
                       "attention-biased_attention_map-absolute-"
                       "input_as_value-shared"]}],
        "decode_loop": "stepped", "decode_chunk_tokens": 4,
        "serve_engine": "continuous", "serve_slots": 2,
        "kv_paging": "on", "kv_block_tokens": 4, "serve_replicas": 2,
        "model_path": "/tmp/router_tier_test",
    }
    params = ModelParameter(cfg)
    params.train = False
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=serve_replicated, args=(params,),
                         kwargs=dict(port=port, stop=stop), daemon=True)
    t.start()

    def req(path, payload=None, timeout=120):
        if payload is None:
            r = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
        else:
            r = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, resp.read().decode()

    try:
        deadline = time.monotonic() + 420
        while True:
            try:
                _, body = req("/health")
                h = json.loads(body)
                if all("health" in r for r in h["replicas"]):
                    break
            except Exception:
                pass
            assert time.monotonic() < deadline, "tier never came up"
            time.sleep(1.0)
        assert h["status"] == "ok" and h["tier"]["replicas"] == 2
        payload = {"tokens": [1, 2, 3], "max_tokens": 4, "temperature": 0.0}
        st, body = req("/token_completion", payload)
        assert st == 200
        first = json.loads(body)["tokens"]
        # replicas share init seed and greedy decode: answers are
        # deterministic whichever replica serves the retry
        st, body = req("/token_completion", payload)
        assert st == 200 and json.loads(body)["tokens"] == first
        st, text = req("/metrics")
        assert st == 200
        assert 'replica="0"' in text and 'replica="1"' in text
        assert "hbnlp_router_requests_total" in text
        assert "hbnlp_kv_blocks_total" in text
        assert "hbnlp_serve_slots_total" in text
    finally:
        stop.set()
        t.join(timeout=60)
