"""Pallas flash attention (interpret mode on CPU) vs dense reference."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from homebrewnlp_tpu.parallel.flash_attention import (_xla_reference,
                                                      flash_attention)

# jax-0.4.37's pallas INTERPRET mode (how these kernels run on the CPU
# rig) evaluates the streaming-softmax accumulation with different
# reduction associativity than compiled TPU kernels; at the wide-head
# gradient shapes the measured margin is ~3.5e-4 vs the 2e-4 silicon
# tolerance (ROADMAP re-anchor: a classified jax-0.4.37 environment gap,
# not a kernel bug — the same test passes the tighter bound on TPU).
# Widen ONLY off-TPU so silicon keeps the strict gate.
_INTERPRET = jax.default_backend() != "tpu"
GRAD_RTOL = 5e-4 if _INTERPRET else 2e-4
GRAD_ATOL = 5e-5 if _INTERPRET else 2e-5


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq,block", [(64, 16), (128, 32)])
def flash_matches_dense_test(causal, seq, block):
    rng = np.random.default_rng(0)
    b, h, d = 2, 2, 16
    q = jnp.asarray(rng.standard_normal((b, seq, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, seq, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, seq, h, d)).astype(np.float32))
    scale = d ** -0.5
    out = flash_attention(q, k, v, scale, causal, block, block, True)
    ref = _xla_reference(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def flash_uneven_blocks_test():
    """block_q != block_k and diagonal frontier correctness."""
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 64, 1, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out = flash_attention(q, k, v, 0.5, True, 16, 32, True)
    ref = _xla_reference(q, k, v, 0.5, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def flash_grad_test():
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    g1 = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, 0.35, True, 16, 16, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, 0.35, True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def flash_grad_uneven_blocks_test(causal):
    """The pallas dq / dkv kernels at block_q != block_k (diagonal frontier
    crosses block boundaries unevenly) against dense autodiff."""
    rng = np.random.default_rng(3)
    b, s, h, d = 1, 64, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    g1 = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, 0.35, causal, 16, 32, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, 0.35, causal) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


def flash_bwd_xla_fallback_test(monkeypatch):
    """HBNLP_FLASH_BWD_XLA=1 routes the backward through the kept XLA-scan
    path; gradients agree with the pallas kernels."""
    import os
    rng = np.random.default_rng(4)
    b, s, h, d = 1, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def grads():
        return jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, 0.35, True, 16, 16, True) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    g_pallas = grads()
    monkeypatch.setenv("HBNLP_FLASH_BWD_XLA", "1")
    jax.clear_caches()
    g_xla = grads()
    monkeypatch.delenv("HBNLP_FLASH_BWD_XLA")
    jax.clear_caches()
    for a, b_ in zip(g_pallas, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


def bwd_block_override_parity_test():
    """bwd_block_q/bwd_block_k override the backward kernels' tiles
    independently of the forward's (attention() uses a wider forward k tile
    that exceeds the dq kernel's scoped VMEM in the full model): gradients
    must match dense autodiff and the same-tile baseline exactly."""
    rng = np.random.default_rng(7)
    b, s, h, d = 1, 128, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def g(bwd_q=None, bwd_k=None):
        return jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, 0.35, True, 32, 64, True,
                            bwd_block_q=bwd_q, bwd_block_k=bwd_k) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    g_same = g()
    g_over = g(bwd_q=16, bwd_k=32)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, 0.35, True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_, c in zip(g_over, g_same, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("bq,bk", [(16, 16), (16, 32), (32, 16)])
def fused_bwd_matches_split_test(causal, bq, bk, monkeypatch):
    """The one-pass fused backward kernel (default) against the split
    dq / dk/dv kernels and dense autodiff, across uneven tiles (the
    diagonal frontier crossing block boundaries both ways) and both
    causal modes."""
    rng = np.random.default_rng(11)
    b, s, h, d = 1, 96, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def grads():
        return jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, 0.35, causal, bq, bk, True) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    g_fused = grads()
    monkeypatch.setenv("HBNLP_FLASH_BWD_SPLIT", "1")
    jax.clear_caches()
    g_split = grads()
    monkeypatch.delenv("HBNLP_FLASH_BWD_SPLIT")
    jax.clear_caches()
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, 0.35, causal) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_, c in zip(g_fused, g_split, g_ref):
        # fused vs split: same dots/rounding points, only the dq partial-sum
        # order differs (VMEM sequential vs XLA reduce over nk)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-4, atol=2e-5)


def fused_bwd_uneven_lengths_test():
    """_bwd_flat with sq != sk (the ring-hop contract allows it): fused vs
    split parity on a rectangular non-causal pair."""
    from homebrewnlp_tpu.parallel.flash_attention import _bwd_flat
    rng = np.random.default_rng(12)
    bh, sq, sk, d = 2, 32, 64, 8
    f32 = np.float32
    qt = jnp.asarray(rng.standard_normal((bh, sq, d)).astype(f32))
    kt = jnp.asarray(rng.standard_normal((bh, sk, d)).astype(f32))
    vt = jnp.asarray(rng.standard_normal((bh, sk, d)).astype(f32))
    dot = jnp.asarray(rng.standard_normal((bh, sq, d)).astype(f32))
    # consistent (lse, delta) residuals from the dense form
    scores = jnp.einsum("zqd,zkd->zqk", qt, kt) * 0.35
    m = scores.max(-1)
    p_un = jnp.exp(scores - m[..., None])
    l = p_un.sum(-1)
    lse = m + jnp.log(l)
    out = jnp.einsum("zqk,zkd->zqd", p_un / l[..., None], vt)
    delta = jnp.sum(dot * out, -1, keepdims=True)

    import os
    res_fused = _bwd_flat(qt, kt, vt, dot, lse[..., None], delta, 0.35,
                          False, 16, 16, True)
    os.environ["HBNLP_FLASH_BWD_SPLIT"] = "1"
    try:
        jax.clear_caches()
        res_split = _bwd_flat(qt, kt, vt, dot, lse[..., None], delta, 0.35,
                              False, 16, 16, True)
    finally:
        del os.environ["HBNLP_FLASH_BWD_SPLIT"]
    jax.clear_caches()
    for a, b_ in zip(res_fused, res_split):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-5)


def bwd_tile_env_rounding_test(monkeypatch):
    """HBNLP_BWD_BQ/BK retuning overrides round to power-of-two divisors of
    the sequence (non-divisor junk must never reach the kernels — the grids
    and _causal_split assume block-aligned tiles) with a floor of 128."""
    from homebrewnlp_tpu.parallel.flash_attention import _bwd_tiles
    assert _bwd_tiles(16384, 1024) == (1024, 1024)
    monkeypatch.setenv("HBNLP_BWD_BQ", "2048")
    assert _bwd_tiles(16384, 1024) == (2048, 1024)
    monkeypatch.setenv("HBNLP_BWD_BQ", "1536")   # non-power-of-two junk
    assert _bwd_tiles(16384, 1024) == (1024, 1024)
    monkeypatch.setenv("HBNLP_BWD_BQ", "7")      # degenerate: floored to 128
    assert _bwd_tiles(16384, 1024) == (128, 1024)
    monkeypatch.setenv("HBNLP_BWD_BK", "512")
    assert _bwd_tiles(16384, 1024)[1] == 512


def fused_group_kernel_parity_test(monkeypatch):
    """HBNLP_FUSED_GROUP=2 routes the group-of-k fused backward (a kept
    measured dead end — see _fused_group); gradients must match the flat
    fused kernel and dense autodiff."""
    rng = np.random.default_rng(13)
    b, s, h, d = 1, 96, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def grads():
        return jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, 0.35, True, 16, 16, True) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    g_flat = grads()
    monkeypatch.setenv("HBNLP_FUSED_GROUP", "2")
    jax.clear_caches()
    # guard against a vacuous pass: the env must actually select the group
    # kernel for this shape (s=96, blocks 16 -> nk=6, divisible by 2)
    from homebrewnlp_tpu.parallel.flash_attention import (_fused_group,
                                                          _use_fused_bwd)
    assert _fused_group(6) == 2
    assert _use_fused_bwd(2, 96, 96, 8, 16)
    g_group = grads()
    monkeypatch.delenv("HBNLP_FUSED_GROUP")
    jax.clear_caches()
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, 0.35, True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_, c in zip(g_group, g_flat, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-4, atol=2e-5)


def flash_wide_head_dim_test():
    """d=256 head dim through forward + fused backward (the shipped shapes
    use d=128; the kernels must not silently assume it)."""
    rng = np.random.default_rng(14)
    b, s, h, d = 1, 64, 1, 256
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out = flash_attention(q, k, v, d ** -0.5, True, 32, 32, True)
    ref = _xla_reference(q, k, v, d ** -0.5, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g1 = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, d ** -0.5, True, 32, 32, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, d ** -0.5, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=GRAD_RTOL, atol=GRAD_ATOL)


def fused_bwd_random_shapes_property_test():
    """Property sweep: random (seq, tiles, causal, dtype) combinations
    through the fused backward vs dense autodiff — shape-dependent logic
    (frontier clamps, dead-cell zero-fill, partial-slice counts, uneven
    tile ratios) must hold everywhere, not just at the tuned points."""
    rng = np.random.default_rng(99)
    for trial in range(6):
        s = int(rng.choice([48, 64, 80, 96, 128]))
        divisors = [b for b in (8, 16, 32) if s % b == 0]
        bq = int(rng.choice(divisors))
        bk = int(rng.choice(divisors))
        causal = bool(rng.integers(0, 2))
        b, h, d = int(rng.integers(1, 3)), int(rng.integers(1, 3)), 8
        q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
        g1 = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, 0.3, causal, bq, bk, True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: jnp.sum(
            _xla_reference(q, k, v, 0.3, causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-5,
                err_msg=f"trial={trial} s={s} bq={bq} bk={bk} causal={causal}")
