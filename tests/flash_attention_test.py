"""Pallas flash attention (interpret mode on CPU) vs dense reference."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from homebrewnlp_tpu.parallel.flash_attention import (_xla_reference,
                                                      flash_attention)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq,block", [(64, 16), (128, 32)])
def flash_matches_dense_test(causal, seq, block):
    rng = np.random.default_rng(0)
    b, h, d = 2, 2, 16
    q = jnp.asarray(rng.standard_normal((b, seq, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, seq, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, seq, h, d)).astype(np.float32))
    scale = d ** -0.5
    out = flash_attention(q, k, v, scale, causal, block, block, True)
    ref = _xla_reference(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def flash_uneven_blocks_test():
    """block_q != block_k and diagonal frontier correctness."""
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 64, 1, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out = flash_attention(q, k, v, 0.5, True, 16, 32, True)
    ref = _xla_reference(q, k, v, 0.5, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def flash_grad_test():
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    g1 = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, 0.35, True, 16, 16, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, 0.35, True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def flash_grad_uneven_blocks_test(causal):
    """The pallas dq / dkv kernels at block_q != block_k (diagonal frontier
    crosses block boundaries unevenly) against dense autodiff."""
    rng = np.random.default_rng(3)
    b, s, h, d = 1, 64, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    g1 = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, 0.35, causal, 16, 32, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, 0.35, causal) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


def flash_bwd_xla_fallback_test(monkeypatch):
    """HBNLP_FLASH_BWD_XLA=1 routes the backward through the kept XLA-scan
    path; gradients agree with the pallas kernels."""
    import os
    rng = np.random.default_rng(4)
    b, s, h, d = 1, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def grads():
        return jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, 0.35, True, 16, 16, True) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    g_pallas = grads()
    monkeypatch.setenv("HBNLP_FLASH_BWD_XLA", "1")
    jax.clear_caches()
    g_xla = grads()
    monkeypatch.delenv("HBNLP_FLASH_BWD_XLA")
    jax.clear_caches()
    for a, b_ in zip(g_pallas, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


def bwd_block_override_parity_test():
    """bwd_block_q/bwd_block_k override the backward kernels' tiles
    independently of the forward's (attention() uses a wider forward k tile
    that exceeds the dq kernel's scoped VMEM in the full model): gradients
    must match dense autodiff and the same-tile baseline exactly."""
    rng = np.random.default_rng(7)
    b, s, h, d = 1, 128, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def g(bwd_q=None, bwd_k=None):
        return jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, 0.35, True, 32, 64, True,
                            bwd_block_q=bwd_q, bwd_block_k=bwd_k) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    g_same = g()
    g_over = g(bwd_q=16, bwd_k=32)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        _xla_reference(q, k, v, 0.35, True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_, c in zip(g_over, g_same, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-4, atol=2e-5)
