"""Tier-1 lint: every ModelParameter knob has a docs/CONFIG.md row
(scripts/check_config_docs.py — PRs 1-3 hand-maintained this invariant;
now it is mechanical)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import check_config_docs as ccd  # noqa: E402


def config_docs_complete_test():
    missing = ccd.missing_knobs()
    assert missing == [], (f"config knobs without a docs/CONFIG.md row: "
                           f"{missing}")


def lint_detects_missing_row_test(tmp_path):
    """The lint actually bites: a knob without a table row is reported, a
    documented one is not, derived state after the update loop is ignored."""
    cfg = tmp_path / "config.py"
    lines = ["class ModelParameter:",
             "    def __init__(self, config):",
             "        self._raw_config = dict(config)"]
    lines += [f"        self.knob_{i} = {i}" for i in range(60)]
    lines += ["        self.documented_knob = 1",
              "        self.forgotten_knob = 2",
              "        for k, v in config.items():",
              "            self.__dict__[k] = v",
              "        self.derived_state = self.documented_knob * 2"]
    cfg.write_text("\n".join(lines) + "\n")
    md = tmp_path / "CONFIG.md"
    md.write_text("| Key | Default |\n|---|---|\n"
                  + "".join(f"| `knob_{i}` | `{i}` |\n" for i in range(60))
                  + "| `documented_knob` | `1` |\n")
    missing = ccd.missing_knobs(str(cfg), str(md))
    assert missing == ["forgotten_knob"]
