"""Trainer tests: convergence smoke, macro-batching semantics, grad
accumulation, multi-device sharded execution (8 virtual CPU devices)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from backend import make_params
from homebrewnlp_tpu.core import sharding as shardlib
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer


def _make_batch(rng, params, macro=1):
    shape = (params.train_batch_size, params.sequence_length, 1)
    if macro > 1:
        shape = (macro,) + shape
    x = rng.integers(0, params.vocab_size, shape)
    return {'token_x': jnp.asarray(x),
            'token_y': jnp.asarray((x + 1) % params.vocab_size)}


def convergence_smoke_test():
    """Loss decreases on a learnable synthetic task with the flagship
    optimizer chain + revnet (the 32big_mixer recipe in miniature)."""
    params = make_params(
        memory_reduction_strategy="revnet",
        optimizer="adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
        learning_rate=0.01, weight_decay=1e-4,
        learning_rate_config={"linear_warmup": {"final_step": 32}})
    m = Model(params)
    tr = Trainer(params, m)
    rng = np.random.default_rng(0)
    state = tr.init_state(_make_batch(rng, params))
    first = None
    for i in range(60):
        state, metrics = tr.step(state, _make_batch(rng, params),
                                 jax.random.PRNGKey(i))
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first * 0.7, (first, last)
    assert int(state.step) == 60


def macro_batching_equals_sequential_test():
    """macro_batching=2 in one device step == two sequential steps
    (reference src/run/train.py semantics)."""
    cfg = dict(optimizer="momentum:0.9:1:1-learning_rate", learning_rate=0.01,
               weight_decay=0.0, depth=1, train_batch_size=4)
    rng = np.random.default_rng(0)

    params_a = make_params(**cfg)
    m_a = Model(params_a)
    tr_a = Trainer(params_a, m_a)
    b1 = _make_batch(rng, params_a)
    b2 = _make_batch(rng, params_a)
    state_a = tr_a.init_state(b1)
    state_a, _ = tr_a.step(state_a, b1, jax.random.PRNGKey(0))
    state_a, _ = tr_a.step(state_a, b2, jax.random.PRNGKey(1))

    params_b = make_params(macro_batching=2, **cfg)
    m_b = Model(params_b)
    tr_b = Trainer(params_b, m_b)
    macro = {k: jnp.stack([b1[k], b2[k]]) for k in b1}
    state_b = tr_b.init_state(macro)
    state_b, metrics = tr_b.step(state_b, macro, jax.random.PRNGKey(0))

    assert int(state_b.step) == 2
    for k in state_a.variables:
        np.testing.assert_allclose(np.asarray(state_a.variables[k], np.float32),
                                   np.asarray(state_b.variables[k], np.float32),
                                   rtol=2e-5, atol=1e-6, err_msg=k)
    assert "first_loss" in metrics and "last_loss" in metrics


def grad_accumulation_test():
    """grad_accumulation averages gradients before one update — a capability
    the reference rejects at config time (src/dataclass.py:189-191)."""
    cfg = dict(optimizer="learning_rate", learning_rate=0.1, weight_decay=0.0,
               depth=1, train_batch_size=4, calc_accuracy=True)
    rng = np.random.default_rng(0)
    params_a = make_params(**cfg)
    m_a = Model(params_a)
    tr_a = Trainer(params_a, m_a)
    b1 = _make_batch(rng, params_a)
    b2 = _make_batch(rng, params_a)

    # manual: average grads of two sub-batches, single SGD step
    state = tr_a.init_state(b1)
    g1 = jax.grad(lambda v: m_a.apply(v, b1).total_loss.data)(state.variables)
    g2 = jax.grad(lambda v: m_a.apply(v, b2).total_loss.data)(state.variables)
    expected = {k: np.asarray(state.variables[k]
                              - 0.1 * (g1[k].astype(jnp.float32)
                                       + g2[k].astype(jnp.float32)) / 2)
                for k in state.variables}

    params_b = make_params(grad_accumulation=2, macro_batching=2, **cfg)
    m_b = Model(params_b)
    tr_b = Trainer(params_b, m_b)
    macro = {k: jnp.stack([b1[k], b2[k]]) for k in b1}
    state_b = tr_b.init_state(macro)
    init_vars = {k: np.asarray(v) for k, v in state_b.variables.items()}
    state_b, metrics = tr_b.step(state_b, macro, jax.random.PRNGKey(0))
    for k in expected:
        np.testing.assert_allclose(np.asarray(state_b.variables[k], np.float32),
                                   expected[k], rtol=2e-4, atol=1e-6, err_msg=k)
    # metrics fidelity through the accumulation scan: accuracy / token_loss /
    # global_grad_norm report real values, not placeholder zeros
    infos = [m_b.apply(init_vars, b) for b in (b1, b2)]
    want_acc = np.mean([float(i.accuracy.data) for i in infos])
    want_tok = np.mean([float(i.token_loss.data) for i in infos])
    np.testing.assert_allclose(float(metrics["accuracy"]), want_acc, rtol=1e-5)
    np.testing.assert_allclose(float(metrics["token_loss"]), want_tok,
                               rtol=1e-5)
    assert float(metrics["global_grad_norm"]) > 0


def sharded_train_step_test():
    """2-D (data×model) mesh on 8 virtual CPU devices: sharded step runs and
    matches the unsharded step numerically."""
    assert len(jax.devices()) == 8, "conftest must provide 8 cpu devices"
    cfg = dict(optimizer="momentum:0.9:1:1-learning_rate", learning_rate=0.01,
               weight_decay=0.0, depth=1, heads=2, train_batch_size=8,
               tpu_size=8)
    rng = np.random.default_rng(0)

    params_a = make_params(**cfg)
    m_a = Model(params_a)
    tr_a = Trainer(params_a, m_a)
    batch = _make_batch(rng, params_a)
    state_a = tr_a.init_state(batch)
    state_a, metrics_a = tr_a.step(state_a, batch, jax.random.PRNGKey(0))

    params_b = make_params(**cfg)
    m_b = Model(params_b)
    mesh = shardlib.build_mesh(params_b)
    assert mesh.shape["model"] == 2 and mesh.shape["data"] == 4
    tr_b = Trainer(params_b, m_b, mesh=mesh)
    state_b = tr_b.init_state(batch)
    state_b, metrics_b = tr_b.step(state_b, batch, jax.random.PRNGKey(0))

    np.testing.assert_allclose(float(metrics_a["loss"]), float(metrics_b["loss"]),
                               rtol=1e-5)
    for k in state_a.variables:
        np.testing.assert_allclose(np.asarray(state_a.variables[k], np.float32),
                                   np.asarray(state_b.variables[k], np.float32),
                                   rtol=2e-5, atol=1e-6, err_msg=k)


def sharding_spec_test():
    """heads-bearing weights shard over 'model'; batch over 'data';
    anonymized dims replicate (the anonymize-analogue)."""
    from homebrewnlp_tpu.core.dims import Dim
    params = make_params(heads=2, tpu_size=8)
    mesh = shardlib.build_mesh(params)
    spec = shardlib.spec_for_dims(params, (Dim("heads", 2), Dim("features_per_head", 16)), mesh)
    assert spec == jax.sharding.PartitionSpec("model")
    spec = shardlib.spec_for_dims(params, (Dim("batch", 8), Dim("sequence", 16),
                                           Dim("heads", 2)), mesh)
    assert spec == jax.sharding.PartitionSpec("data", None, "model")
    spec = shardlib.spec_for_dims(params, (Dim("_heads", 2), Dim("vocab", 32)), mesh)
    assert spec == jax.sharding.PartitionSpec()


def async_feeder_equivalence_test():
    """_AsyncFeeder (async_input_transfer): same items in the same order as
    plain iteration, transfer started exactly one batch ahead, StopIteration
    after the final item — and a placed batch steps bit-identically to a
    raw one (place_batch is a transfer, never a transform)."""
    from homebrewnlp_tpu.run.train_loop import _AsyncFeeder

    placed = []

    def place(b):
        placed.append(b["i"])
        return b

    items = [{"i": i} for i in range(4)]
    feeder = _AsyncFeeder(iter(items), place)
    got = []
    for b in feeder:
        got.append(b["i"])
        # by the time batch N is handed out, N+1's transfer already started
        assert placed[:len(got) + 1] == list(range(min(len(got) + 1,
                                                       len(items))))
    assert got == [0, 1, 2, 3]

    # a pipeline ERROR while prefetching N+1 must not cost batch N (whose
    # transfer already completed): the feeder hands N out and re-raises on
    # the NEXT call — same deferred treatment as StopIteration
    def boom():
        yield {"i": 0}
        raise RuntimeError("shard gone")
    feeder = _AsyncFeeder(boom(), place)
    assert next(feeder)["i"] == 0
    with pytest.raises(RuntimeError, match="shard gone"):
        next(feeder)

    params = make_params(optimizer="momentum:0.9:1:1-learning_rate",
                         learning_rate=0.01, depth=1)
    m = Model(params)
    tr = Trainer(params, m)
    rng = np.random.default_rng(0)
    batch = _make_batch(rng, params)
    state_raw = tr.init_state(batch)
    state_placed = tr.init_state(batch)
    s0, m0 = tr.step(state_raw, batch, jax.random.PRNGKey(0))
    s1, m1 = tr.step(state_placed, tr.place_batch(batch),
                     jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(m0["loss"]),
                                  np.asarray(m1["loss"]))
    for k in s0.variables:
        np.testing.assert_array_equal(np.asarray(s0.variables[k]),
                                      np.asarray(s1.variables[k]),
                                      err_msg=k)


def async_feeder_sharded_place_once_test():
    """On a mesh, place_batch output is recognised by step (no second
    shard_batch pass) and the sharded step matches feeding the raw batch."""
    cfg = dict(optimizer="momentum:0.9:1:1-learning_rate", learning_rate=0.01,
               weight_decay=0.0, depth=1, heads=2, train_batch_size=8,
               tpu_size=8)
    rng = np.random.default_rng(0)
    params = make_params(**cfg)
    m = Model(params)
    mesh = shardlib.build_mesh(params)
    tr = Trainer(params, m, mesh=mesh)
    batch = _make_batch(rng, params)
    state_a = tr.init_state(batch)
    state_b = tr.init_state(batch)
    placed = tr.place_batch(batch)
    assert tr._batch_placed(placed)
    assert not tr._batch_placed(batch)
    s_a, m_a = tr.step(state_a, batch, jax.random.PRNGKey(0))
    s_b, m_b = tr.step(state_b, placed, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(m_a["loss"]),
                                  np.asarray(m_b["loss"]))
