"""End-to-end CLI tests: dataset -> main.py train -> checkpoint -> restore ->
sample/query machinery, exercising the whole L0..L8 stack on CPU (the
reference's 32ctx smoke-test recipe in miniature, BASELINE.md 'Smoke')."""
import json
import os
import subprocess
import sys

import numpy as np

from backend import MIXER_BLOCKS
from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_dataset(tmp_path, n_files=3, tokens_per_file=4096):
    data_dir = tmp_path / "data"
    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(n_files):
        # learnable byte stream: repeating alphabet with noise
        base = np.tile(np.arange(32, dtype=np.uint8), tokens_per_file // 32 + 1)
        noise = rng.integers(0, 32, tokens_per_file).astype(np.uint8)
        tokens = np.where(rng.random(tokens_per_file) < 0.05, noise,
                          base[:tokens_per_file])
        with RecordWriter(str(data_dir / f"p_{i}_{tokens_per_file}.tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))
    return data_dir


def _config(tmp_path, data_dir, **overrides):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 16, "heads": 2,
        "depth": 2, "train_batch_size": 8, "vocab_size": 32,
        "calc_accuracy": True, "memory_reduction_strategy": "revnet",
        "block_config": MIXER_BLOCKS,
        "group_linear_factor": 2,
        "intermediate_feed_forward_multiplier_multiplier": 0.5,
        "optimizer": "adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
        "learning_rate": 0.01, "weight_decay": 0.0001,
        "learning_rate_config": {"linear_warmup": {"final_step": 16}},
        "macro_batching": 1, "train_steps": 30, "interleaved_datasets": 2,
        "use_checkpointing": True, "steps_per_checkpoint": 50,
        "max_checkpoints_keep": 2, "data_seed": 1337,
        "sampling_temperature": 0.0, "use_autoregressive_sampling": True,
        "initial_autoregressive_position": 4,
        "dataset_configs": [{"path": str(data_dir / "*"), "type": "text",
                             "weight": 1}],
        "model_path": str(tmp_path / "run"),
    }
    cfg.update(overrides)
    path = tmp_path / "config.json"
    with open(path, "w") as f:
        json.dump(cfg, f)
    return path


def _run_cli(config_path, run_mode, timeout=420, input_text=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "main.py"), "--model",
         str(config_path), "--run_mode", run_mode],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
        input=input_text)


def train_and_resume_test(tmp_path):
    data_dir = _make_dataset(tmp_path)
    config_path = _config(tmp_path, data_dir)
    r = _run_cli(config_path, "train")
    assert r.returncode == 0, r.stderr[-3000:]
    assert "total parameters" in r.stdout
    run_dir = tmp_path / "run"
    ckpts = [d for d in os.listdir(run_dir) if d.startswith("ckpt_")]
    assert ckpts, os.listdir(run_dir)
    assert os.path.exists(run_dir / "DataLog.log")
    assert os.path.exists(run_dir / "model_size.info")
    assert any(f.startswith("events.out.tfevents") for f in os.listdir(run_dir))
    metrics = [json.loads(l) for l in open(run_dir / "metrics.jsonl")]
    assert metrics[-1]["loss"] < metrics[0]["loss"]

    # resume: step picks up from the checkpoint, data log has the run
    with open(config_path) as f:
        cfg = json.load(f)
    cfg["train_steps"] = 40
    with open(config_path, "w") as f:
        json.dump(cfg, f)
    r2 = _run_cli(config_path, "train")
    assert r2.returncode == 0, r2.stderr[-3000:]
    assert "restored checkpoint" in r2.stdout
    log_lines = open(run_dir / "DataLog.log").read().strip().splitlines()
    assert len(log_lines) == 2


def sample_mode_test(tmp_path):
    data_dir = _make_dataset(tmp_path, n_files=2, tokens_per_file=2048)
    config_path = _config(tmp_path, data_dir, train_steps=10, num_of_sample=2,
                          use_checkpointing=True)
    r = _run_cli(config_path, "train")
    assert r.returncode == 0, r.stderr[-3000:]
    r = _run_cli(config_path, "sample")
    assert r.returncode == 0, r.stderr[-3000:]
    assert "loaded checkpoint" in r.stdout
    assert "--- sample 0 ---" in r.stdout


def debug_mode_similarity_test(tmp_path):
    data_dir = _make_dataset(tmp_path, n_files=2, tokens_per_file=2048)
    config_path = _config(tmp_path, data_dir, train_steps=5,
                          equal_debugging_items_per_check=3)
    r = _run_cli(config_path, "train")
    assert r.returncode == 0, r.stderr[-3000:]
    r = _run_cli(config_path, "debug")
    assert r.returncode == 0, r.stderr[-3000:]
    assert "debug similarity: 1.000" in r.stdout
