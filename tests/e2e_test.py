"""End-to-end CLI tests: dataset -> main.py train -> checkpoint -> restore ->
sample/query machinery, exercising the whole L0..L8 stack on CPU (the
reference's 32ctx smoke-test recipe in miniature, BASELINE.md 'Smoke')."""
import json
import os
import subprocess
import sys

import numpy as np

from backend import MIXER_BLOCKS
from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_dataset(tmp_path, n_files=3, tokens_per_file=4096):
    data_dir = tmp_path / "data"
    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(n_files):
        # learnable byte stream: repeating alphabet with noise
        base = np.tile(np.arange(32, dtype=np.uint8), tokens_per_file // 32 + 1)
        noise = rng.integers(0, 32, tokens_per_file).astype(np.uint8)
        tokens = np.where(rng.random(tokens_per_file) < 0.05, noise,
                          base[:tokens_per_file])
        with RecordWriter(str(data_dir / f"p_{i}_{tokens_per_file}.tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))
    return data_dir


def _config(tmp_path, data_dir, **overrides):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 16, "heads": 2,
        "depth": 2, "train_batch_size": 8, "vocab_size": 32,
        "calc_accuracy": True, "memory_reduction_strategy": "revnet",
        "block_config": MIXER_BLOCKS,
        "group_linear_factor": 2,
        "intermediate_feed_forward_multiplier_multiplier": 0.5,
        "optimizer": "adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
        "learning_rate": 0.01, "weight_decay": 0.0001,
        "learning_rate_config": {"linear_warmup": {"final_step": 16}},
        "macro_batching": 1, "train_steps": 30, "interleaved_datasets": 2,
        "use_checkpointing": True, "steps_per_checkpoint": 50,
        "max_checkpoints_keep": 2, "data_seed": 1337,
        "sampling_temperature": 0.0, "use_autoregressive_sampling": True,
        "initial_autoregressive_position": 4,
        "dataset_configs": [{"path": str(data_dir / "*"), "type": "text",
                             "weight": 1}],
        "model_path": str(tmp_path / "run"),
    }
    cfg.update(overrides)
    path = tmp_path / "config.json"
    with open(path, "w") as f:
        json.dump(cfg, f)
    return path


def _cpu_env():
    return dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                XLA_FLAGS="--xla_force_host_platform_device_count=1")


def _run_cli(config_path, run_mode, timeout=420, input_text=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "main.py"), "--model",
         str(config_path), "--run_mode", run_mode],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
        input=input_text)


def train_and_resume_test(tmp_path):
    data_dir = _make_dataset(tmp_path)
    config_path = _config(tmp_path, data_dir)
    r = _run_cli(config_path, "train")
    assert r.returncode == 0, r.stderr[-3000:]
    assert "total parameters" in r.stdout
    run_dir = tmp_path / "run"
    ckpts = [d for d in os.listdir(run_dir) if d.startswith("ckpt_")]
    assert ckpts, os.listdir(run_dir)
    assert os.path.exists(run_dir / "DataLog.log")
    assert os.path.exists(run_dir / "model_size.info")
    assert any(f.startswith("events.out.tfevents") for f in os.listdir(run_dir))
    metrics = [json.loads(l) for l in open(run_dir / "metrics.jsonl")]
    assert metrics[-1]["loss"] < metrics[0]["loss"]

    # resume: step picks up from the checkpoint, data log has the run
    with open(config_path) as f:
        cfg = json.load(f)
    cfg["train_steps"] = 40
    with open(config_path, "w") as f:
        json.dump(cfg, f)
    r2 = _run_cli(config_path, "train")
    assert r2.returncode == 0, r2.stderr[-3000:]
    assert "restored checkpoint" in r2.stdout
    log_lines = open(run_dir / "DataLog.log").read().strip().splitlines()
    assert len(log_lines) == 2


def debug_flags_e2e_test(tmp_path):
    """The reference's debug config keys drive real behaviour: save_graph
    dumps the lowered step, debug_train_step logs each step,
    use_random_dataloader randomizes the seed and shuffles windows,
    combine_assignments explains itself (run.py:171,252; inputs.py:540-563;
    optimizer/__init__.py:184)."""
    data_dir = _make_dataset(tmp_path)
    config_path = _config(tmp_path, data_dir, train_steps=6, save_graph=True,
                          debug_train_step=True, use_random_dataloader=True,
                          combine_assignments=True, use_checkpointing=False)
    r = _run_cli(config_path, "train")
    assert r.returncode == 0, r.stderr[-3000:]
    assert "debug_train_step: dispatched step" in r.stdout
    assert "data_seed auto-generated" in r.stdout
    assert "combine_assignments" in r.stdout
    hlo = (tmp_path / "run" / "train_step.stablehlo.txt").read_text()
    assert "stablehlo" in hlo or "mhlo" in hlo or "func.func" in hlo
    # a shuffled run must not poison the deterministic resume log
    assert not (tmp_path / "run" / "DataLog.log").exists()


def random_dataloader_shuffles_test(tmp_path):
    """use_random_dataloader: same files, different window order run-to-run
    (unseeded shuffle), but no window lost within the shuffle horizon."""
    from backend import make_params
    from homebrewnlp_tpu.data.inputs import TextDataset

    # 2049 tokens -> 128 windows/file -> 256 total = 64 full batches of 4,
    # so no windows fall into a dropped partial tail batch (which would
    # legitimately change the emitted multiset under shuffling)
    data_dir = _make_dataset(tmp_path, n_files=2, tokens_per_file=2049)
    base = dict(sequence_length=16, train_batch_size=4, shuffle_buffer=32,
                shuffle_input_filenames=False,
                dataset_configs=[{"path": str(data_dir / "*"),
                                  "type": "text", "weight": 1}])

    def windows(params):
        out = []
        for b in TextDataset(params, 4, repeat=False):
            out.extend(bytes(r.tobytes()) for r in b["token_x"])
        return out

    det = windows(make_params(**base))
    rand1 = windows(make_params(use_random_dataloader=True, **base))
    rand2 = windows(make_params(use_random_dataloader=True, **base))
    assert sorted(det) == sorted(rand1) == sorted(rand2)  # same multiset
    assert rand1 != det and rand2 != det and rand1 != rand2  # shuffled


def sample_mode_test(tmp_path):
    data_dir = _make_dataset(tmp_path, n_files=2, tokens_per_file=2048)
    config_path = _config(tmp_path, data_dir, train_steps=10, num_of_sample=2,
                          use_checkpointing=True)
    r = _run_cli(config_path, "train")
    assert r.returncode == 0, r.stderr[-3000:]
    r = _run_cli(config_path, "sample")
    assert r.returncode == 0, r.stderr[-3000:]
    assert "loaded checkpoint" in r.stdout
    assert "--- sample 0 ---" in r.stdout


def debug_mode_similarity_test(tmp_path):
    data_dir = _make_dataset(tmp_path, n_files=2, tokens_per_file=2048)
    config_path = _config(tmp_path, data_dir, train_steps=5,
                          equal_debugging_items_per_check=3)
    r = _run_cli(config_path, "train")
    assert r.returncode == 0, r.stderr[-3000:]
    r = _run_cli(config_path, "debug")
    assert r.returncode == 0, r.stderr[-3000:]
    assert "debug similarity: 1.000" in r.stdout


def video_train_e2e_test(tmp_path):
    """Video (jannet) mode through the full CLI path: synthetic clips + VTT
    subtitles -> scripts/video2records.py -> main.py train.  Pins the
    make_dataset video wiring (mixed_dataset/VideoDataset) — round 2 found
    the train loop built TextDataset unconditionally, so video training via
    the CLI crashed despite the dataset classes existing."""
    cv2 = __import__("pytest").importorskip("cv2")
    import subprocess

    src = tmp_path / "src"
    os.makedirs(src, exist_ok=True)
    rng = np.random.default_rng(0)
    w = cv2.VideoWriter(str(src / "clip.mp4"),
                        cv2.VideoWriter_fourcc(*"mp4v"), 8.0, (32, 32))
    base = rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
    for t in range(120):
        w.write(np.roll(base, t, axis=1))
    w.release()
    lines = ["WEBVTT", ""]
    for k in range(0, 24, 4):
        lines += [f"00:00:{k // 2:02d}.000 --> 00:00:{k // 2 + 2:02d}.000",
                  f"w{k} w{k+1} w{k+2} w{k+3}", ""]
    (src / "clip.vtt").write_text("\n".join(lines))

    records = tmp_path / "video_records"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "video2records.py"),
         str(src / "clip.mp4"), "--output-dir", str(records), "--fps", "2",
         "--width", "32", "--height", "32", "--subtitles",
         "--language-tokens-per-frame", "4", "--padding-token", "0"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr

    cfg = {
        "model_mode": "jannet", "use_video": True, "use_language": True,
        "three_axes": False, "sequence_length": 4, "time_patch": 1,
        "patch_size": 16, "frame_height": 32, "frame_width": 32,
        "color_channels": 3, "language_token_per_frame": 4,
        "token_patch_size": 1, "features_per_head": 16, "heads": 2,
        "depth": 1, "train_batch_size": 2, "vocab_size": 256, "experts": 1,
        "calc_accuracy": True, "memory_reduction_strategy": "none",
        "block_config": [
            {"layer": ["norm-shift-scale-features-group",
                       "attention-biased_attention_map-absolute-input_as_value"]}],
        "group_linear_factor": 2, "optimizer": "adam-learning_rate",
        "learning_rate": 0.003, "weight_decay": 0.0,
        "learning_rate_config": {"linear_warmup": {"final_step": 8}},
        "dataset_configs": [
            {"path": str(records / "*"), "type": "video", "weight": 1}],
        "train_steps": 8, "use_checkpointing": False, "interleaved_datasets": 1,
        "calculation_dtype": "float32", "storage_dtype": "float32",
        "slice_dtype": "float32", "model_path": str(tmp_path / "run"),
    }
    config_path = tmp_path / "video.json"
    config_path.write_text(json.dumps(cfg))
    proc = _run_cli(str(config_path), "train")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "'steps': 8" in proc.stdout, proc.stdout


def query_repl_e2e_test(tmp_path):
    """The interactive query REPL through the CLI (reference
    interface.py:177-220): train a tiny model, then drive `--run_mode query`
    over stdin with one prompt + temperature and check a completion comes
    back before the empty-line exit."""
    data_dir = _make_dataset(tmp_path)
    config_path = _config(tmp_path, data_dir, train_steps=5)
    proc = _run_cli(str(config_path), "train")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_cli(str(config_path), "query",
                    input_text="abcabc\n0.0\n\n")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "query mode" in proc.stdout, proc.stdout
    # the REPL must actually have prompted and produced a completion
    assert "temperature" in proc.stdout, proc.stdout
    after = proc.stdout.split("temperature", 1)[1]
    assert len(after.strip()) > 0, proc.stdout


def cli_mode_set_test():
    """Every run mode in RUN_MODE_FNS is reachable from the CLI: the argparse
    choices and the dispatch table must stay in sync (regression for
    --run_mode debug_old being rejected at the CLI while the alias existed in
    the table, reference /root/reference/main.py:21)."""
    import re
    from homebrewnlp_tpu.run.modes import RUN_MODE_FNS

    with open(os.path.join(REPO, "main.py")) as f:
        src = f.read()
    m = re.search(r"\"--run_mode\".*?choices=\[([^\]]*)\]", src, re.S)
    assert m, "could not locate --run_mode choices in main.py"
    choices = set(re.findall(r"\"(\w+)\"", m.group(1)))
    assert choices == set(RUN_MODE_FNS), (choices, set(RUN_MODE_FNS))


def val_loss_e2e_test(tmp_path):
    """eval_interval + eval_holdout_files: the train loop runs the periodic
    forward-only eval on the held-out file tail and records val/loss +
    val/accuracy in metrics.jsonl (the driver metric's loss half,
    BASELINE.json 'tokens/sec/chip + val loss')."""
    data_dir = _make_dataset(tmp_path, n_files=4)
    config_path = _config(tmp_path, data_dir, train_steps=20,
                          eval_interval=10, eval_steps=2,
                          eval_holdout_files=1)
    r = _run_cli(config_path, "train")
    assert r.returncode == 0, r.stderr[-3000:]
    metrics_path = tmp_path / "run" / "metrics.jsonl"
    entries = [json.loads(line) for line in open(metrics_path)]
    val_entries = [e for e in entries if "val/loss" in e]
    assert val_entries, entries
    assert all(np.isfinite(e["val/loss"]) for e in val_entries)
    assert "val/accuracy" in val_entries[0]
    # the eval set is fixed: two evals at the same params would agree, and
    # any recorded value must be a plausible xent for a 32-way vocab
    assert 0.0 < val_entries[0]["val/loss"] < 20.0


def bpe_workflow_e2e_test(tmp_path):
    """The full BPE user journey (reference: train_tokenizer.pyx ->
    text2tfrecord.py BPE mode -> training): train a tokenizer with the
    native C++ trainer, encode a corpus into int64 token records with
    text2records --gpt2-bpe, and train a tiny model on them through
    main.py — the token-id (vs byte) data path end to end."""
    import glob
    import json
    import subprocess

    root = os.path.join(os.path.dirname(__file__), "..")
    corpus = tmp_path / "corpus.txt"
    text = ("the quick brown fox jumps over the lazy dog. " * 200
            + "pack my box with five dozen liquor jugs. " * 200)
    corpus.write_text(text * 4)

    tok_json = tmp_path / "tokenizer.json"
    r = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "train_tokenizer.py"),
         str(corpus), "--vocab-size", "384", "--output", str(tok_json),
         "--backend", "native", "--processes", "1"],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr[-2000:]
    assert tok_json.exists()

    rec_dir = tmp_path / "records"
    r = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "text2records.py"),
         str(corpus), "--output-dir", str(rec_dir), "--prefix", "bpe",
         "--gpt2-bpe", str(tok_json), "--chunk-tokens", "4096"],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr[-2000:]
    files = glob.glob(str(rec_dir / "*.tfrecord"))
    assert files and all("int64" in os.path.basename(f) for f in files), files

    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 8, "heads": 2,
        "depth": 2, "train_batch_size": 2, "vocab_size": 384,
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    "feed_forward-in:relu"]}],
        "memory_reduction_strategy": "none",
        "optimizer": "adam-learning_rate", "learning_rate": 1e-3,
        "train_steps": 8, "use_checkpointing": False,
        "calculation_dtype": "float32", "storage_dtype": "float32",
        "slice_dtype": "float32", "optimizer_slice_dtype": "float32",
        "dataset_configs": [{"path": str(rec_dir / "*.tfrecord"),
                             "weight": 1.0}],
        "model_path": str(tmp_path / "run"),
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "main.py"),
         "--model", str(cfg_path), "--run_mode", "train"],
        capture_output=True, text=True, timeout=420, env=_cpu_env())
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    assert "'final_step': 8" in r.stdout or '"final_step": 8' in r.stdout, \
        r.stdout[-800:]


def analyze_mode_test(tmp_path):
    """--run_mode analyze: parameter-count report without training (the
    reference only ran analyze_model as a train-startup side effect)."""
    cfg = _config(tmp_path, _make_dataset(tmp_path))
    r = _run_cli(cfg, "analyze", timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "total parameters:" in r.stdout, r.stdout[-500:]
    assert os.path.exists(os.path.join(str(tmp_path), "run",
                                       "model_size.info")), "report not dumped"
