"""Optimizer-chain semantics tests against hand-rolled numpy references.

The chain members' exact formulas (SM3 min-bucket, AGC, Nesterov momentum,
debiased Adam, grafting) are the reference's loss-parity-critical parts
(SURVEY.md §7 hard part 1); each is locked down numerically here.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from backend import make_params
from homebrewnlp_tpu.core.dims import Dim
from homebrewnlp_tpu.optim import Optimizer, is_large_tensor, parse_chain
from homebrewnlp_tpu.optim.learning_rate import get_learning_rate


def _run_chain(optimizer, shapes, steps=3, seed=0, lr=0.01, **cfg):
    params = make_params(optimizer=optimizer, learning_rate=lr, weight_decay=0.0,
                         **cfg)
    rng = np.random.default_rng(seed)
    variables = {name: jnp.asarray(rng.standard_normal(shape).astype(np.float32))
                 for name, shape in shapes.items()}
    dims = {name: tuple(Dim(f"d{i}", s) for i, s in enumerate(shape))
            for name, shape in shapes.items()}
    opt = Optimizer(params, dims)
    state = opt.init(variables)
    grads_hist = []
    for step in range(steps):
        grads = {name: jnp.asarray(rng.standard_normal(v.shape).astype(np.float32))
                 for name, v in variables.items()}
        grads_hist.append({k: np.asarray(v) for k, v in grads.items()})
        variables, state, _ = opt.update(variables, grads, state,
                                         jnp.asarray(step, jnp.int32))
    return variables, grads_hist, params


def sgd_learning_rate_test():
    """optimizer='learning_rate' is plain SGD: v -= lr * g."""
    shapes = {"w": (4, 5)}
    out, grads, params = _run_chain("learning_rate", shapes, steps=2, lr=0.1)
    rng = np.random.default_rng(0)
    w = rng.standard_normal((4, 5)).astype(np.float32)
    for g in grads:
        w = w - 0.1 * g["w"]
    np.testing.assert_allclose(np.asarray(out["w"]), w, rtol=1e-5)


def momentum_nesterov_test():
    """momentum:0.9:1:1 (Nesterov) semantics (optimizers.py:118-128)."""
    shapes = {"w": (3, 3)}
    out, grads, _ = _run_chain("momentum:0.9:1:1-learning_rate", shapes,
                               steps=3, lr=0.1)
    rng = np.random.default_rng(0)
    w = rng.standard_normal((3, 3)).astype(np.float32)
    state = np.zeros_like(w)
    for g in grads:
        state = 0.9 * state + g["w"]
        upd = g["w"] + 0.9 * state
        w = w - 0.1 * upd
    np.testing.assert_allclose(np.asarray(out["w"]), w, rtol=1e-5)


def sm3_test():
    """SM3 per-dim min-bucket accumulators (optimizers.py:60-76)."""
    shapes = {"w": (4, 6)}
    out, grads, _ = _run_chain("sm3-learning_rate", shapes, steps=3, lr=0.01)
    rng = np.random.default_rng(0)
    w = rng.standard_normal((4, 6)).astype(np.float32)
    r = np.zeros(4, np.float32)
    c = np.zeros(6, np.float32)
    for g in grads:
        acc = np.minimum(r[:, None], c[None, :]) + g["w"] ** 2
        r = acc.max(1)
        c = acc.max(0)
        upd = g["w"] / np.maximum(np.sqrt(acc), 1e-5)
        w = w - 0.01 * upd
    np.testing.assert_allclose(np.asarray(out["w"]), w, rtol=1e-5)


def adam_test():
    shapes = {"w": (5,)}
    out, grads, _ = _run_chain("adam-learning_rate", shapes, steps=3, lr=0.01,
                               opt_beta1=0.9, opt_beta2=0.999)
    rng = np.random.default_rng(0)
    w = rng.standard_normal((5,)).astype(np.float32)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t, g in enumerate(grads):
        v = 0.999 * v + 0.001 * g["w"] ** 2
        m = 0.9 * m + 0.1 * g["w"]
        # reference debias: 1/(1 - beta^(step+1))
        vh = v / (1 - 0.999 ** (t + 1))
        upd = m / np.maximum(np.sqrt(vh), 1e-5) / (1 - 0.9 ** (t + 1))
        w = w - 0.01 * upd
    np.testing.assert_allclose(np.asarray(out["w"]), w, rtol=2e-5)


def adaptive_clip_test():
    """AGC: g * min(||w|| * clip / ||g||, 1) (optimizers.py:79-84)."""
    shapes = {"w": (8, 8)}
    out, grads, _ = _run_chain("adaptive_clip:0.01-learning_rate", shapes,
                               steps=1, lr=1.0)
    rng = np.random.default_rng(0)
    w = rng.standard_normal((8, 8)).astype(np.float32)
    g = grads[0]["w"]
    gn = np.minimum(1 / np.sqrt((g ** 2).sum()), 1e6)
    wn = np.maximum(np.sqrt((w ** 2).sum()), 1e-3)
    w_exp = w - g * min(wn * gn * 0.01, 1.0)
    np.testing.assert_allclose(np.asarray(out["w"]), w_exp, rtol=1e-5)


def graft_test():
    """graft:adam = direction of g, magnitude of adam's update."""
    shapes = {"w": (6, 6)}
    out, grads, _ = _run_chain("graft:adam-learning_rate", shapes, steps=1, lr=1.0)
    rng = np.random.default_rng(0)
    w = rng.standard_normal((6, 6)).astype(np.float32)
    g = grads[0]["w"]
    v = 0.001 * g ** 2 / (1 - 0.999)
    m = 0.1 * g / (1 - 0.9)
    adam_upd = m / np.maximum(np.sqrt(v), 1e-5)
    upd = g / np.sqrt((g ** 2).sum()) * np.sqrt((adam_upd ** 2).sum())
    np.testing.assert_allclose(np.asarray(out["w"]), w - upd, rtol=1e-4)


def value_and_global_clip_test():
    shapes = {"w": (4,)}
    out, grads, _ = _run_chain("value_clip:0.001-learning_rate", shapes, steps=1, lr=1.0)
    rng = np.random.default_rng(0)
    w = rng.standard_normal((4,)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               w - np.clip(grads[0]["w"], -0.001, 0.001), rtol=1e-5)
    out, grads, _ = _run_chain("global_l2norm_clip:1.0-learning_rate",
                               {"a": (4,), "b": (3,)}, steps=1, lr=1.0)


def lr_schedule_test():
    """linear_warmup / exponential_decay / bounds DSL
    (reference learning_rate.py:27-63)."""
    params = make_params(learning_rate=0.01,
                         learning_rate_config={
                             "linear_warmup": {"final_step": 100},
                             "exponential_decay": {"start_step": 200, "factor": 0.99},
                             "lower_bound": {"factor": 1e-4}})
    lr = lambda s: float(get_learning_rate(params, jnp.asarray(s)))
    assert abs(lr(50) - 0.005) < 1e-7
    assert abs(lr(100) - 0.01) < 1e-7
    assert abs(lr(150) - 0.01) < 1e-7
    assert abs(lr(210) - 0.01 * 0.99 ** 10) < 1e-7
    assert lr(10 ** 6) == pytest.approx(1e-4)


def weight_decay_heuristics_test():
    """Name/shape heuristics for weight-decay eligibility (reference :49-61)."""
    params = make_params()
    h, k = params.head_dim, params.key_dim
    inter = params.intermediate[0]
    cases = [
        ("gpt0/body0/block0_0_0/bottleneck_group_linear_0/orthogonal_var0/var0",
         (h, k, inter), True),
        ("gpt0/body0/block0_0_0/norm_0/normal_var0/var0", (h, k), False),
        ("gpt0/body0/block0_1_0/attention_0/embed0/normal_var0/var0",
         (h, Dim("sequence", 16), Dim("_sequence", 16)), False),
        ("gpt0/input0/orthogonal_var0/var0",
         (Dim("language_token_patch", 1), inter, h, k), False),
        ("gpt0/output0/embed0/orthogonal_var0/var0",
         (h, k, Dim("language_token_patch", 1), Dim("vocab", 32)), False),
        ("gpt0/body0/block0_0_0/rezero_0/var0", (), False),
    ]
    for name, dims, expected in cases:
        size = int(np.prod([d.size for d in dims])) if dims else 1
        assert is_large_tensor(params, name, dims, size) == expected, name


def chain_parse_test():
    chain = parse_chain("adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate")
    assert [c[0] for c in chain] == ["adaptive_clip", "sm3", "momentum", "learning_rate"]
    assert chain[2][1] == ("0.9", "1", "1")
    with pytest.raises(ValueError):
        parse_chain("not_an_optimizer")
