"""Fault injection for the fleet manager's preemption-recovery loop
(scripts/run_manager.py — the reference's run_manager.py:94-146 semantics:
poll health, and on an unhealthy TPU kill the process group, recreate the
TPU, relaunch).  The reference had no tests for this path at all; here the
TPU lifecycle is simulated with shell commands against counter files and
the sleeps are patched out, so a full preemption round-trip runs in
seconds."""
import importlib.util
import os
import sys
import types


def _load_run_manager():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "run_manager.py")
    spec = importlib.util.spec_from_file_location("run_manager_under_test",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def preemption_recovery_test(tmp_path, monkeypatch):
    rm = _load_run_manager()
    monkeypatch.setattr(rm.time, "sleep", lambda *_: None)
    monkeypatch.setattr(rm.random, "randint", lambda *_: 0)

    d = str(tmp_path)
    # health: healthy except on its 3rd invocation (0-based call index 2 —
    # the second POLL tick, after one healthy tick) -> simulated preemption
    health = (f"c=$(cat {d}/hc 2>/dev/null || echo 0); "
              f"echo $((c+1)) > {d}/hc; [ \"$c\" -ne 2 ]")
    create = f"echo created >> {d}/creates.log"
    delete = f"echo deleted >> {d}/deletes.log"
    # first launch: park; second launch (marker exists): exit 0 -> done
    run_cmd = (f"if [ -f {d}/relaunched ]; then exit 0; "
               f"else touch {d}/relaunched; exec sleep 600; fi")

    args = types.SimpleNamespace(
        run_command=run_cmd, model_path=d, create_cmd=create,
        health_cmd=health, delete_cmd=delete, poll_interval=0,
        poll_jitter=0, stall_timeout=0, max_restarts=5)
    rm.Manager(args).run()

    log = open(os.path.join(d, "run.log")).read()
    assert "restarting (#1)" in log, log
    assert "training exited rc=0; done" in log, log
    # preemption path: initial create + recreate (delete then create again)
    assert len(open(f"{d}/creates.log").read().splitlines()) == 2
    assert len(open(f"{d}/deletes.log").read().splitlines()) == 2  # recreate + final
    assert os.path.exists(f"{d}/relaunched")


def stall_watchdog_test(tmp_path, monkeypatch):
    """A run whose metrics.jsonl heartbeat goes stale counts as stalled and
    is restarted even though the TPU reports healthy (beyond the reference,
    which only watched TPU health)."""
    rm = _load_run_manager()
    # tiny REAL sleeps: a no-op sleep lets the poll loop outrun the
    # relaunched child's exit and burn through max_restarts.  rm.time is the
    # global time module — bind the ORIGINAL sleep before patching it
    real_sleep = rm.time.sleep
    monkeypatch.setattr(rm.time, "sleep",
                        lambda t=0: real_sleep(min(t, 0.2) if t else 0.2))
    monkeypatch.setattr(rm.random, "randint", lambda *_: 0)

    d = str(tmp_path)
    hb = os.path.join(d, "metrics.jsonl")
    open(hb, "w").write("{}\n")
    os.utime(hb, (0, 0))  # heartbeat frozen in 1970 -> always stale
    run_cmd = (f"if [ -f {d}/relaunched ]; then exit 0; "
               f"else touch {d}/relaunched; exec sleep 600; fi")
    args = types.SimpleNamespace(
        run_command=run_cmd, model_path=d, create_cmd="", health_cmd="",
        delete_cmd="", poll_interval=0, poll_jitter=0, stall_timeout=1,
        max_restarts=3)

    # after the relaunch, let the run count as done on its clean exit even
    # though the heartbeat file stays stale: exit-while-healthy breaks first
    rm.Manager(args).run()
    log = open(os.path.join(d, "run.log")).read()
    assert "stalled=True" in log, log
    assert "training exited rc=0; done" in log, log
