"""Pluggable-storage seam: the checkpoint/metrics/DataLog consumers survive
object-store semantics (prefix listing, non-atomic replace, no append) via
the mem:// in-memory filesystem — the mock for the reference's gs:// paths
(reference inputs.py:524-559, run_manager.py:26-56)."""
import json

import jax.numpy as jnp
import numpy as np

from backend import make_params
from homebrewnlp_tpu.utils import fs
from homebrewnlp_tpu.train import checkpoint as ckpt
from homebrewnlp_tpu.train.metrics import MetricLogger
from homebrewnlp_tpu.data.inputs import append_runs_log, read_runs_log


def _fresh(path="mem://bucket/run"):
    memfs = fs.MemFS()
    fs.register("mem", memfs)
    return memfs, path


def exercise_primitives(base):
    """Shared object-store contract sequence — run over mem:// here and
    over the faked gs:// backend in fs_gcs_test.py."""
    with fs.open_(fs.join(base, "a/b.txt"), "w") as f:
        f.write("hello")
    assert fs.exists(fs.join(base, "a/b.txt"))
    assert fs.isdir(fs.join(base, "a"))
    assert fs.listdir(base) == ["a"]
    # append emulation (read-modify-write)
    with fs.open_(fs.join(base, "a/b.txt"), "a") as f:
        f.write(" world")
    with fs.open_(fs.join(base, "a/b.txt")) as f:
        assert f.read() == "hello world"
    # glob
    assert fs.glob(fs.join(base, "a/*.txt")) == [fs.join(base, "a/b.txt")]
    # replace moves whole trees (copy+delete order)
    fs.replace(fs.join(base, "a"), fs.join(base, "c"))
    assert not fs.exists(fs.join(base, "a/b.txt"))
    with fs.open_(fs.join(base, "c/b.txt")) as f:
        assert f.read() == "hello world"


def exercise_glob_not_recursive(base):
    """'*' must not cross '/' on object stores (LocalFS.glob parity):
    nested stale objects must not match a dataset's 'dir/*' pattern."""
    for key in ("a_10.tfrecord", "b_20.tfrecord", "old/c_30.tfrecord",
                "tmp/partial.bin"):
        with fs.open_(fs.join(base, key), "w") as f:
            f.write("x")
    got = fs.glob(fs.join(base, "*"))
    assert got == [fs.join(base, "a_10.tfrecord"),
                   fs.join(base, "b_20.tfrecord")], got
    assert fs.glob(fs.join(base, "*.tfrecord")) == got


def fs_primitives_test():
    _, base = _fresh()
    exercise_primitives(base)


def glob_not_recursive_test():
    _, base = _fresh("mem://bucket/data")
    exercise_glob_not_recursive(base)


def replace_copies_marker_last_test():
    """Non-atomic replace orders index.json after every data file, so a
    crash mid-copy can never leave a marker that indexes missing files."""
    memfs, base = _fresh("mem://bucket/order")
    for key in ("tmp/arr_0.bin", "tmp/shards_0.json", "tmp/index.json",
                "tmp/zzz.bin"):
        with fs.open_(fs.join(base, key), "w") as f:
            f.write("x")
    writes = []
    orig = memfs._write
    memfs._write = lambda k, d: (writes.append(k), orig(k, d))
    fs.replace(fs.join(base, "tmp"), fs.join(base, "ckpt_1"))
    copied = [w for w in writes if "/ckpt_1/" in w]
    assert copied[-1].endswith("index.json"), copied


def checkpoint_on_object_store_test():
    _, base = _fresh("mem://bucket/ckpts")
    rng = np.random.default_rng(0)
    variables = {"w/a": jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
                 "w/b": jnp.asarray(rng.standard_normal(7), jnp.bfloat16)}
    opt_state = {"w/a": {"m": jnp.zeros((4, 3))}}
    ckpt.save(base, 10, variables, opt_state, max_keep=2)
    ckpt.save(base, 20, variables, opt_state, max_keep=2)
    assert ckpt.list_checkpoints(base) == [10, 20]
    got_v, got_o, step, _ = ckpt.restore(base)
    assert step == 20
    np.testing.assert_array_equal(np.asarray(got_v["w/a"], np.float32),
                                  np.asarray(variables["w/a"]))
    np.testing.assert_array_equal(
        np.asarray(got_v["w/b"], np.float32),
        np.asarray(variables["w/b"], np.float32))
    assert "m" in got_o["w/a"]
    # max_keep pruning through the seam
    ckpt.save(base, 30, variables, opt_state, max_keep=2)
    assert ckpt.list_checkpoints(base) == [20, 30]


def incomplete_checkpoint_ignored_test():
    """Non-atomic replace on object stores: a checkpoint directory without
    its completeness marker (index.json, written last) must be invisible."""
    memfs, base = _fresh("mem://bucket/partial")
    variables = {"w": jnp.ones(3)}
    ckpt.save(base, 5, variables, {}, max_keep=5)
    # simulate a crash mid-replace: data file landed, marker didn't
    memfs._write(base + "/ckpt_9/arr_000000.bin", b"\x00" * 12)
    assert ckpt.list_checkpoints(base) == [5]
    _, _, step, _ = ckpt.restore(base)
    assert step == 5


def metrics_and_datalog_on_object_store_test():
    _, base = _fresh("mem://bucket/run2")
    logger = MetricLogger(base)
    logger.log(1, {"loss": 2.5})
    logger.log(2, {"loss": 2.0})
    logger.close()
    with fs.open_(fs.join(base, "metrics.jsonl")) as f:
        rows = [json.loads(l) for l in f.read().splitlines()]
    assert rows[0]["loss"] == 2.5 and rows[1]["step"] == 2
    events = [n for n in fs.listdir(base) if n.startswith("events.out")]
    assert events, fs.listdir(base)

    params = make_params(model_path=base, dataset_configs=[])
    append_runs_log(params, 7, 1)
    log = read_runs_log(params)
    assert log[-1]["steps"] == 7
    append_runs_log(params, 3, 1)  # append emulation keeps prior entries
    assert [e["steps"] for e in read_runs_log(params)] == [7, 3]
