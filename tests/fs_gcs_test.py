"""GCSFS (utils/fs.py gs:// backend) against a faked google-cloud-storage
client — the real GCSFS code (bucket/blob splitting, prefix listing +
filtering, upload/download/delete) runs end-to-end; only the wire client is
substituted (this image has no egress, VERDICT r4 missing #4).  The same
production consumers exercised over mem:// in fs_test.py run here over
gs://: sharded checkpoint save/restore/prune and non-recursive glob."""
import sys
import types

import jax.numpy as jnp
import numpy as np
import pytest

from backend import make_params  # noqa: F401  (CPU env bootstrap)
from homebrewnlp_tpu.train import checkpoint as ckpt
from homebrewnlp_tpu.utils import fs


class NotFound(Exception):
    """Same NAME as google.api_core.exceptions.NotFound: the real client
    does NOT raise FileNotFoundError for missing blobs, and GCSFS._read's
    translation keys on the exception type name — the fake must exercise
    that path, not bypass it."""


class _FakeBlob:
    def __init__(self, store, bucket_name, name):
        self._store = store
        self._key = (bucket_name, name)
        self.name = name

    def download_as_bytes(self):
        if self._key not in self._store:
            raise NotFound(f"404 blob {self._key} not found")
        return self._store[self._key]

    def upload_from_string(self, data):
        self._store[self._key] = bytes(data)

    def delete(self):
        # faithful to the real client: deleting a missing blob raises
        # NotFound (GCSFS._delete must treat that as idempotent success)
        if self._key not in self._store:
            raise NotFound(f"404 blob {self._key} not found")
        self._store.pop(self._key)


class _FakeBucket:
    def __init__(self, store, name):
        self._store = store
        self.name = name

    def blob(self, name):
        return _FakeBlob(self._store, self.name, name)

    def list_blobs(self, prefix=""):
        # the real API pages transparently behind this iterator; GCSFS only
        # iterates, so the contract exercised is name-prefix listing
        return [_FakeBlob(self._store, self.name, n)
                for (b, n) in sorted(self._store)
                if b == self.name and n.startswith(prefix)]


class _FakeClient:
    def __init__(self):
        self._store = {}

    def bucket(self, name):
        return _FakeBucket(self._store, name)


@pytest.fixture()
def gcs(monkeypatch):
    """Install the fake google.cloud.storage and a fresh GCSFS for gs://."""
    storage_mod = types.ModuleType("google.cloud.storage")
    storage_mod.Client = _FakeClient
    cloud_mod = types.ModuleType("google.cloud")
    cloud_mod.storage = storage_mod
    monkeypatch.setitem(sys.modules, "google.cloud.storage", storage_mod)
    monkeypatch.setitem(sys.modules, "google.cloud", cloud_mod)
    gcsfs = fs.GCSFS()
    fs.register("gs", gcsfs)
    try:
        yield gcsfs
    finally:
        fs.register("gs", fs.GCSFS)  # restore lazy-class registration


def gcs_primitives_test(gcs):
    from fs_test import exercise_primitives
    exercise_primitives("gs://bucket/run")
    fs.remove("gs://bucket/run/c/b.txt")
    assert not fs.exists("gs://bucket/run/c/b.txt")


def gcs_glob_not_recursive_test(gcs):
    from fs_test import exercise_glob_not_recursive
    exercise_glob_not_recursive("gs://bucket/data")


def gcs_missing_blob_is_file_not_found_test(gcs):
    """The real client's NotFound translates to FileNotFoundError at the
    seam, so gs:// behaves like every other backend for consumers that
    catch the stdlib type."""
    with pytest.raises(FileNotFoundError):
        gcs._read("gs://bucket/absent/object")
    with pytest.raises(FileNotFoundError):
        with fs.open_("gs://bucket/absent/object") as f:
            f.read()


def gcs_delete_idempotent_test(gcs):
    """A retried DELETE whose first attempt committed server-side (response
    lost) sees NotFound — that is success, not a fatal error mid-prune."""
    gcs._write("gs://bucket/run/x", b"d")
    gcs._delete("gs://bucket/run/x")
    gcs._delete("gs://bucket/run/x")  # the lost-response retry: no raise
    assert not fs.exists("gs://bucket/run/x")


def gcs_checkpoint_roundtrip_test(gcs):
    """Sharded checkpoints on gs://: save, prune, completeness marker,
    restore — the production path the reference ran on GCS."""
    base = "gs://bucket/ckpts"
    rng = np.random.default_rng(0)
    variables = {"w/a": jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
                 "w/b": jnp.asarray(rng.standard_normal(7), jnp.bfloat16)}
    opt_state = {"w/a": {"m": jnp.zeros((4, 3))}}
    ckpt.save(base, 10, variables, opt_state, max_keep=2)
    ckpt.save(base, 20, variables, opt_state, max_keep=2)
    ckpt.save(base, 30, variables, opt_state, max_keep=2)
    assert ckpt.list_checkpoints(base) == [20, 30]
    got_v, got_o, step, _ = ckpt.restore(base)
    assert step == 30
    np.testing.assert_array_equal(np.asarray(got_v["w/a"], np.float32),
                                  np.asarray(variables["w/a"]))
    assert "m" in got_o["w/a"]
    # a data object without its marker is invisible (crash mid-replace)
    gcs._write("gs://bucket/ckpts/ckpt_99/arr_000000.bin", b"\x00" * 8)
    assert ckpt.list_checkpoints(base) == [20, 30]
