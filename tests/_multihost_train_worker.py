"""Worker for the 2-process full-train-loop test.

Run as: python _multihost_train_worker.py <port> <pid> <nproc> <cfg.json>
with JAX_PLATFORMS=cpu and 4 virtual devices per process.  Runs the REAL
``run.train_loop.train`` over the 8-device multi-controller mesh: each
process loads its own dataset slice, shard_batch assembles the global batch,
and only the chief writes metrics/checkpoints.  Prints the final loss so the
parent can assert both processes computed the same trajectory.
"""
import json
import sys


def main() -> int:
    port, pid, nproc, cfg_path = (int(sys.argv[1]), int(sys.argv[2]),
                                  int(sys.argv[3]), sys.argv[4])
    import os
    os.environ["HBNLP_COORDINATOR"] = f"localhost:{port}"
    os.environ["HBNLP_NUM_PROCESSES"] = str(nproc)
    os.environ["HBNLP_PROCESS_ID"] = str(pid)
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    # the real bootstrap: explicit-flag discovery + gloo CPU collectives
    # (XLA's default CPU client refuses multi-process computations)
    from homebrewnlp_tpu.distributed import bootstrap
    assert bootstrap.maybe_initialize()
    import jax
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.run.train_loop import train

    with open(cfg_path) as f:
        cfg = json.load(f)
    params = ModelParameter(cfg)
    result = train(params, log_every=2)
    print(f"WORKER {pid} FINAL {result['final_loss']:.6f} "
          f"steps {result['final_step']}")

    if cfg.get("mesh_shape_override", {}).get("model", 1) > 1:
        # model axis spans both processes: verify the distributed checkpoint
        # reassembles to the live (allgathered) parameter values
        import numpy as np
        from jax.experimental import multihost_utils
        from homebrewnlp_tpu.core import sharding as shardlib
        from homebrewnlp_tpu.model import Model
        from homebrewnlp_tpu.train import Trainer, checkpoint as ckpt
        from homebrewnlp_tpu.run.train_loop import make_dataset

        # barrier: the chief rewrites DataLog in train()'s finally block;
        # without the sync the other process may read the stale log and
        # build a different dataset slice
        multihost_utils.sync_global_devices("post_train_phase")
        params2 = ModelParameter(cfg)
        params2.current_step = 0
        mesh = shardlib.build_mesh(params2)
        model = Model(params2)
        trainer = Trainer(params2, model, mesh=mesh)
        batch = next(iter(make_dataset(params2, mesh=mesh)))
        state = trainer.init_state(batch)
        sharded = [k for k, v in state.variables.items()
                   if not v.is_fully_addressable]
        assert sharded, "expected model-sharded params to span processes"
        ckpt.save(cfg["model_path"] + "_dist", 7, state.variables,
                  state.opt_state)
        restored = ckpt.restore(cfg["model_path"] + "_dist")
        assert restored is not None and restored[2] == 7
        for k, v in state.variables.items():
            want = np.asarray(multihost_utils.process_allgather(
                v, tiled=True))
            got = np.asarray(restored[0][k])
            assert got.shape == want.shape, (k, got.shape, want.shape)
            assert np.array_equal(got, want), k
        print(f"WORKER {pid} DISTCKPT OK ({len(sharded)} spanning arrays)")

        # resume-into-train: place the restored host arrays back onto the
        # cross-process shardings (the train loop's restore path) and step
        import jax.numpy as jnp
        from homebrewnlp_tpu.train import TrainState
        st = TrainState(
            shardlib.place_tree(state.variables, restored[0]),
            shardlib.place_tree(state.opt_state, restored[1]),
            jnp.asarray(restored[2], jnp.int32))
        st, metrics = trainer.step(st, batch)
        loss = float(metrics["loss"])
        assert np.isfinite(loss), loss
        print(f"WORKER {pid} DISTRESUME OK {loss:.6f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
