"""Worker for the 2-process full-train-loop test.

Run as: python _multihost_train_worker.py <port> <pid> <nproc> <cfg.json>
with JAX_PLATFORMS=cpu and 4 virtual devices per process.  Runs the REAL
``run.train_loop.train`` over the 8-device multi-controller mesh: each
process loads its own dataset slice, shard_batch assembles the global batch,
and only the chief writes metrics/checkpoints.  Prints the final loss so the
parent can assert both processes computed the same trajectory.
"""
import json
import sys


def main() -> int:
    port, pid, nproc, cfg_path = (int(sys.argv[1]), int(sys.argv[2]),
                                  int(sys.argv[3]), sys.argv[4])
    import jax
    jax.distributed.initialize(f"localhost:{port}", num_processes=nproc,
                               process_id=pid)
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.run.train_loop import train

    with open(cfg_path) as f:
        cfg = json.load(f)
    params = ModelParameter(cfg)
    result = train(params)
    print(f"WORKER {pid} FINAL {result['final_loss']:.6f} "
          f"steps {result['final_step']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
