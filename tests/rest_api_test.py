"""REST serving test: the stdlib-fallback server answers the reference's
four endpoints (/completion /token_completion /encode /decode)."""
import json
import threading
import urllib.request

import numpy as np

from backend import make_params
from homebrewnlp_tpu.infer.interface import InterfaceWrapper
from homebrewnlp_tpu.infer.rest_api import _handlers
from homebrewnlp_tpu.model import Model


def _interface():
    params = make_params(train_batch_size=1, sequence_length=16,
                         initial_autoregressive_position=4, vocab_size=256,
                         use_autoregressive_sampling=True)
    params.train = False
    m = Model(params)
    import jax.numpy as jnp
    batch = {"token_x": np.zeros((1, 16, 1), np.int32),
             "token_y": np.zeros((1, 16, 1), np.int32)}
    variables = {k: jnp.asarray(v) for k, v in m.init(batch).items()}
    return InterfaceWrapper(params, m, variables)


def endpoints_test():
    handlers = _handlers(_interface())
    out = handlers["/encode"]({"prompt": "ab"})
    assert out["tokens"] == [97, 98]
    out = handlers["/decode"]({"tokens": [104, 105]})
    assert out["prompt"] == "hi"
    out = handlers["/token_completion"]({"tokens": [1, 2, 3], "temperature": 0.0})
    assert len(out["tokens"]) == 16
    out = handlers["/completion"]({"prompt": "ab", "temperature": 0.0})
    assert isinstance(out["completion"], str)


def isolated_serving_test():
    """Process-isolated serving (the default): HTTP runs in a subprocess,
    requests cross Manager IPC to the device loop in this process — the
    reference's uvicorn-subprocess + Manager-dict design."""
    import socket
    from homebrewnlp_tpu.infer import rest_api

    interface = _interface()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve,
                         args=(interface.params, interface),
                         kwargs={"port": port, "isolate": True, "stop": stop},
                         daemon=True)
    t.start()

    def post(path, payload, timeout=60):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        deadline = 30
        import time
        for _ in range(deadline * 4):
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return json.loads(resp.read())
            except (ConnectionError, urllib.error.URLError):
                time.sleep(0.25)
        raise TimeoutError(path)

    out = post("/encode", {"prompt": "hi"})
    assert out["tokens"] == [104, 105]
    out = post("/token_completion", {"tokens": [1, 2, 3], "temperature": 0.0})
    assert len(out["tokens"]) == 16
    # client errors surface as HTTP 400 JSON (rejected at the HTTP edge,
    # before costing a device call), not a wedged device loop
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/token_completion",
        data=json.dumps({"tokens": "bogus"}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=60)
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        body = json.loads(e.read())
        assert "error" in body and body.get("code") == "bad_request"
    # and the loop still answers afterwards
    assert post("/decode", {"tokens": [104, 105]})["prompt"] == "hi"
    # clean shutdown: the loop notices the stop event within its poll and
    # joins without a Manager-teardown traceback
    stop.set()
    t.join(timeout=15)
    assert not t.is_alive()


def http_server_test():
    """Full HTTP round-trip through the stdlib fallback server."""
    from http.server import ThreadingHTTPServer
    from homebrewnlp_tpu.infer import rest_api

    interface = _interface()
    handlers = rest_api._handlers(interface)

    # build the same handler the serve() fallback uses, on an ephemeral port
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            fn = handlers.get(self.path)
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            payload = json.dumps(fn(body)).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/encode",
            data=json.dumps({"prompt": "hi"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["tokens"] == [104, 105]
    finally:
        server.shutdown()


def complete_batch_unit_test():
    """_complete_batch: N mixed completion requests -> ONE decode call, with
    per-item errors isolated, and greedy outputs identical to the serial
    path."""
    from homebrewnlp_tpu.infer import rest_api

    interface = _interface()
    serial = [interface.complete_tokens(np.asarray(t, np.int32), 0.0)
              for t in ([1, 2, 3], [7, 8], [4, 5, 6, 7])]
    interface.decode_calls = 0
    items = [("/token_completion", {"tokens": [1, 2, 3], "temperature": 0.0}),
             ("/token_completion", {"tokens": "bogus"}),
             ("/token_completion", {"tokens": [7, 8], "temperature": 0.0}),
             ("/token_completion", {"tokens": [4, 5, 6, 7],
                                    "temperature": 0.0})]
    outs = rest_api._complete_batch(interface, items)
    assert interface.decode_calls == 1, interface.decode_calls
    assert "_error" in outs[1]
    for got, want in zip([outs[0], outs[2], outs[3]], serial):
        assert got["tokens"] == [int(t) for t in want], (got, want)


def batched_serving_concurrency_test():
    """N concurrent clients share decode calls: while the first request
    compiles/decodes, the rest queue and drain into one batched call —
    strictly fewer device calls than serial (VERDICT r3 #6)."""
    import socket
    import concurrent.futures
    from homebrewnlp_tpu.infer import rest_api

    interface = _interface()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve,
                         args=(interface.params, interface),
                         kwargs={"port": port, "isolate": True, "stop": stop},
                         daemon=True)
    t.start()

    def post(payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/token_completion",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        import time
        for _ in range(120):
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    return json.loads(resp.read())
            except (ConnectionError, urllib.error.URLError):
                time.sleep(0.25)
        raise TimeoutError

    try:
        n = 8
        with concurrent.futures.ThreadPoolExecutor(n) as pool:
            futs = [pool.submit(post, {"tokens": [1, 2, i],
                                       "temperature": 0.0})
                    for i in range(n)]
            outs = [f.result(timeout=300) for f in futs]
        assert all(len(o["tokens"]) == 16 for o in outs), outs
        assert interface.decode_calls < n, interface.decode_calls
        # identical prompts must agree regardless of which batch they rode
        assert outs[1]["tokens"] == post({"tokens": [1, 2, 1],
                                          "temperature": 0.0})["tokens"]
    finally:
        stop.set()
        t.join(timeout=15)
