"""Native C++ BPE trainer (native/bpe_trainer.cpp via data/native_bpe.py).

Checks the merge algorithm against a tiny pure-python oracle and that the
emitted tokenizer.json loads with the ``tokenizers`` library and round-trips
text, matching the reference tokenizer construction
(/root/reference/scripts/train_tokenizer.pyx:180-220).
"""
import collections
import json
import os
import re
import string
import tempfile

import pytest

from homebrewnlp_tpu.data import native_bpe

pytestmark = pytest.mark.skipif(not native_bpe.available(),
                                reason="g++ toolchain unavailable")

SPLIT = string.digits + " \t\n\r\x0b\x0c" + string.punctuation


def _oracle_merges(text: bytes, n_merges: int):
    """Reference BPE trainer: full pair recount each step."""
    words = collections.Counter()
    for run in re.split("[" + re.escape(SPLIT) + "]",
                        text.decode("latin-1")):
        if len(run) > 1:
            words[tuple(ord(c) for c in run)] += 1
    merges = []
    next_id = 256
    for _ in range(n_merges):
        pairs = collections.Counter()
        for word, count in words.items():
            for a, b in zip(word, word[1:]):
                pairs[(a, b)] += count
        if not pairs:
            break
        best = max(pairs.items(), key=lambda kv: (kv[1], -kv[0][0] * (1 << 32) - kv[0][1]))
        (a, b), count = best
        if count < 1:
            break
        merges.append((a, b))
        new_words = collections.Counter()
        for word, cnt in words.items():
            out = []
            i = 0
            while i < len(word):
                if i + 1 < len(word) and word[i] == a and word[i + 1] == b:
                    out.append(next_id)
                    i += 2
                else:
                    out.append(word[i])
                    i += 1
            new_words[tuple(out)] += cnt
        words = new_words
        next_id += 1
    return merges


def _train(text: bytes, vocab_size: int):
    with tempfile.NamedTemporaryFile(suffix=".txt", delete=False) as f:
        f.write(text)
        path = f.name
    try:
        return native_bpe.train_merges([path], vocab_size).merges
    finally:
        os.unlink(path)


def native_matches_oracle_test():
    text = (b"the cat sat on the mat. the cat ate the rat!\n" * 50
            + b"lowering lowered lowest slower slowest\n" * 20)
    merges = _train(text, 256 + 12)
    oracle = _oracle_merges(text, 12)
    # same multiset of merge counts is too weak; demand identical pairs where
    # counts are distinct (ties may legally order differently)
    assert merges[0] == oracle[0]
    assert len(merges) == len(oracle)
    assert set(merges) == set(oracle)


def merge_counts_monotone_under_unique_counts_test():
    # distinct pair frequencies -> fully deterministic order
    text = b"aaab " * 97 + b"ccdd " * 31 + b"eeff " * 7
    merges = _train(text, 256 + 3)
    oracle = _oracle_merges(text, 3)
    assert merges == oracle


def isolated_split_prevents_cross_boundary_merges_test():
    # digits/punct/whitespace are their own pre-tokens: no pair may span them
    text = b"ab1ab,ab ab\nab" * 100
    merges = _train(text, 256 + 8)
    for a, b in merges:
        for tok in (a, b):
            if tok < 256:
                assert chr(tok) not in SPLIT


def unicode_alphabet_and_merges_test():
    # non-ASCII codepoints join the alphabet with ids 256+ and participate in
    # merges as codepoints (NOT utf-8 bytes), so encode-time text matches
    tokenizers = pytest.importorskip("tokenizers")
    text = ("café café café 世界世界 "
            * 50).encode("utf-8")
    with tempfile.NamedTemporaryFile(suffix=".txt", delete=False) as f:
        f.write(text)
        corpus = f.name
    out = corpus + ".tokenizer.json"
    try:
        result = native_bpe.train_merges([corpus], 256 + 64)
        cps = [cp for cp, _ in result.alphabet]
        # é is U+00E9 < 256 (base alphabet); CJK chars join the discovered one
        assert ord("é") not in cps
        assert ord("世") in cps and ord("界") in cps
        assert cps == sorted(cps)
        native_bpe.train_tokenizer_file([corpus], 256 + 64, out)
        tok = tokenizers.Tokenizer.from_file(out)
        enc = tok.encode("café")
        # "café" repeats 150x: must become a single learned token, and the
        # unk token (id 1) must not appear
        assert 1 not in enc.ids
        assert len(enc.ids) == 1
        assert tok.decode(enc.ids, skip_special_tokens=False) == "café"
    finally:
        os.unlink(corpus)
        if os.path.exists(out):
            os.unlink(out)


def range_parallel_counting_matches_serial_test():
    # >4MB corpus so the range splitter produces multiple 1MB+ chunks; the
    # boundary-ownership rule must give bit-identical counts vs one thread
    rng_words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta",
                 "longword" * 3, "x"]
    import random
    random.seed(0)
    text = " ".join(random.choice(rng_words)
                    for _ in range(700_000)).encode()
    assert len(text) > 4 << 20
    with tempfile.NamedTemporaryFile(suffix=".txt", delete=False) as f:
        f.write(text)
        path = f.name
    try:
        serial = native_bpe.train_merges([path], 256 + 10, n_threads=1)
        parallel = native_bpe.train_merges([path], 256 + 10, n_threads=8)
        assert serial == parallel
    finally:
        os.unlink(path)


def tokenizer_json_loads_and_roundtrips_test():
    tokenizers = pytest.importorskip("tokenizers")
    text = b"hello world hello there hello hello world\n" * 40
    with tempfile.NamedTemporaryFile(suffix=".txt", delete=False) as f:
        f.write(text)
        corpus = f.name
    out = corpus + ".tokenizer.json"
    try:
        vocab = native_bpe.train_tokenizer_file([corpus], 256 + 20, out)
        assert vocab > 256
        with open(out) as fh:
            doc = json.load(fh)
        assert doc["model"]["type"] == "BPE"
        tok = tokenizers.Tokenizer.from_file(out)
        enc = tok.encode("hello world")
        assert enc.ids, "no tokens produced"
        # multi-char tokens must have been learned ("hello" repeats 160x)
        assert len(enc.ids) < len("hello world")
        assert "".join(tok.decode([i], skip_special_tokens=False)
                       for i in enc.ids) == "hello world"
    finally:
        os.unlink(corpus)
        if os.path.exists(out):
            os.unlink(out)
