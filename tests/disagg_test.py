"""Disaggregated prefill/decode serving (marker: disagg; docs/SERVING.md
'Disaggregated tier').

Device-free sweep: the wire-format discipline (bf16 + int8-scale leaves
round-trip bit-exactly, crc corruption and geometry mismatches rejected
loudly with zero side effects), the router-resident global prefix index,
the class-topology parser, and the router's class-aware dispatch state
machine (miss -> prefill owner, hit -> route-to-owner or migrate, owner
death -> cold fallback) driven with fake transports.

Device sweep: greedy bit-parity of a decode-class executor consuming
STREAMED blocks against the same prompt prefilled locally — the streamed
admission takes the ordinary prefix-hit path (prefill skipped over the
injected span) — plus the two-replica REST round trip over the real
``/kv/blocks`` seam.

Standalone-runnable (tier-1 truncates at 870s on this box;
``scripts/run_late_markers.sh`` runs this suite in the late-marker set):
``python -m pytest tests/disagg_test.py -q``
"""
import base64
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer import kv_transfer
from homebrewnlp_tpu.infer.router import (GlobalPrefixIndex, KV_BLOCKS_PATH,
                                          Replica, Router,
                                          parse_replica_classes)
from homebrewnlp_tpu.infer.scheduler import (EngineController, EngineRequest,
                                             SlotScheduler)
from homebrewnlp_tpu.infer.serving_guard import HTTPStatusError

pytestmark = pytest.mark.disagg


# ------------------------------------------------------------ device harness

def _interface(**kw):
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp
    cfg = dict(block_config=MIXER_BLOCKS, memory_reduction_strategy="none",
               sequence_length=32, train_batch_size=1,
               decode_loop="stepped", decode_chunk_tokens=5)
    cfg.update(kw)
    params = make_params(**cfg)
    params.train = False
    model = Model(params)
    seq = params.sequence_dim.size
    batch = {"token_x": np.zeros((1, seq, 1), np.int32),
             "token_y": np.zeros((1, seq, 1), np.int32)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return InterfaceWrapper(params, model, variables)


def _paged_controller(iface, slots=4, block_tokens=4, pool_blocks=None):
    from homebrewnlp_tpu.infer.paged import PagedEngineExecutor
    ex = PagedEngineExecutor(iface, slots=slots, block_tokens=block_tokens,
                             pool_blocks=pool_blocks)
    answers = {}
    sched = SlotScheduler(ex.slots, clock=time.monotonic)
    ctl = EngineController(
        ex, sched, clock=time.monotonic, decode_chunk=5, prefill_chunk=8,
        answer=lambda req, oc: answers.__setitem__(req.rid, oc))
    return ex, ctl, answers


def _serve(ctl, answers, reqs, rounds=80):
    ctl.round(reqs)
    for _ in range(rounds):
        if all(r.rid in answers for r in reqs):
            return
        ctl.round()
    raise AssertionError(f"unanswered: "
                         f"{[r.rid for r in reqs if r.rid not in answers]}")


def _req(rid, toks, rl):
    return EngineRequest(rid=rid, path="/token_completion",
                         toks=np.asarray(toks, np.int32), response_len=rl)


# -------------------------------------------------------------- wire format

def wire_roundtrip_bf16_test():
    """Export a served prompt's cached blocks, inject them into a FRESH
    executor, re-export: every leaf's bytes survive bit-exactly, and the
    destination tree holds the same root chain."""
    iface = _interface()
    ex_a, ctl_a, ans_a = _paged_controller(iface)
    prompt = list(range(1, 17)) + [21, 22]   # 16 shared tokens = 4 blocks
    _serve(ctl_a, ans_a, [_req("p", prompt, 4)])
    payload = kv_transfer.export_blocks(ex_a, prompt)
    assert len(payload["blocks"]) == 4
    assert payload["block_tokens"] == 4
    assert kv_transfer.payload_bytes(payload) > 0
    for blk in payload["blocks"]:
        for meta in blk["leaves"].values():
            assert meta["crc_algo"] in ("crc32", "crc32c-masked")
    ex_b, _, _ = _paged_controller(iface)
    res = kv_transfer.inject_blocks(ex_b, json.loads(json.dumps(payload)))
    assert res == {"injected": 4, "skipped": 0, "blocks": 4}
    back = kv_transfer.export_blocks(ex_b, prompt)
    assert [b["key"] for b in back["blocks"]] \
        == [b["key"] for b in payload["blocks"]]
    for sent, got in zip(payload["blocks"], back["blocks"]):
        assert set(sent["leaves"]) == set(got["leaves"])
        for name in sent["leaves"]:
            assert sent["leaves"][name]["data"] \
                == got["leaves"][name]["data"], name
    # re-injecting the same payload: existing children win, nothing moves
    again = kv_transfer.inject_blocks(ex_b, payload)
    assert again == {"injected": 0, "skipped": 4, "blocks": 4}


def wire_roundtrip_int8_scale_leaves_test():
    """int8 KV deployments stream BOTH the int8 rows and their f32 scale
    siblings; the round trip is bit-exact for both."""
    iface = _interface(decode_cache_dtype="int8")
    ex_a, ctl_a, ans_a = _paged_controller(iface)
    prompt = list(range(1, 14)) + [40]
    _serve(ctl_a, ans_a, [_req("p", prompt, 4)])
    payload = kv_transfer.export_blocks(ex_a, prompt)
    assert payload["blocks"]
    dtypes = {name: meta["dtype"]
              for name, meta in payload["blocks"][0]["leaves"].items()}
    assert any(n.endswith("_scale") for n in dtypes), dtypes
    assert "int8" in set(dtypes.values()), dtypes
    for name, dt in dtypes.items():
        if name.endswith("_scale"):
            assert dt == "float32", (name, dt)
    ex_b, _, _ = _paged_controller(iface)
    res = kv_transfer.inject_blocks(ex_b, payload)
    assert res["injected"] == len(payload["blocks"])
    back = kv_transfer.export_blocks(ex_b, prompt)
    for sent, got in zip(payload["blocks"], back["blocks"]):
        for name in sent["leaves"]:
            assert sent["leaves"][name]["data"] \
                == got["leaves"][name]["data"], name


def corrupt_payload_rejected_loudly_test():
    """A flipped byte, a bad version, mismatched geometry, and a wrong
    leaf set must each raise ValueError BEFORE any pool mutation."""
    iface = _interface()
    ex_a, ctl_a, ans_a = _paged_controller(iface)
    prompt = list(range(1, 17))
    _serve(ctl_a, ans_a, [_req("p", prompt, 3)])
    payload = kv_transfer.export_blocks(ex_a, prompt)
    assert payload["blocks"]

    def fresh():
        ex, _, _ = _paged_controller(iface)
        return ex

    # crc corruption: flip one byte of one leaf, keep the recorded crc
    bad = json.loads(json.dumps(payload))
    name = sorted(bad["blocks"][0]["leaves"])[0]
    meta = bad["blocks"][0]["leaves"][name]
    raw = bytearray(base64.b64decode(meta["data"]))
    raw[0] ^= 0xFF
    meta["data"] = base64.b64encode(bytes(raw)).decode("ascii")
    ex = fresh()
    with pytest.raises(ValueError, match="verification|truncated"):
        kv_transfer.inject_blocks(ex, bad)
    assert len(ex.tree) == 0                 # zero side effects
    # truncation is caught by the length check even without the crc
    bad = json.loads(json.dumps(payload))
    meta = bad["blocks"][0]["leaves"][name]
    meta["data"] = base64.b64encode(
        base64.b64decode(meta["data"])[:-2]).decode("ascii")
    with pytest.raises(ValueError, match="truncated"):
        kv_transfer.inject_blocks(fresh(), bad)
    # wire-version and geometry refusals
    with pytest.raises(ValueError, match="version"):
        kv_transfer.inject_blocks(fresh(), dict(payload, version=99))
    with pytest.raises(ValueError, match="block_tokens"):
        kv_transfer.inject_blocks(fresh(), dict(payload, block_tokens=8))
    # a leaf set from some other deployment
    bad = json.loads(json.dumps(payload))
    bad["blocks"][0]["leaves"]["target/not_a_leaf"] = \
        dict(bad["blocks"][0]["leaves"][name])
    with pytest.raises(ValueError, match="leaves"):
        kv_transfer.inject_blocks(fresh(), bad)


def streamed_blocks_greedy_bit_parity_test():
    """The decode-side contract: after injection, admitting the SAME
    prompt takes the prefix-hit path (prefill skipped over the streamed
    span) and the greedy output is bit-identical to a cold local
    prefill."""
    iface = _interface()
    ex_a, ctl_a, ans_a = _paged_controller(iface)
    prompt = list(range(1, 17)) + [25]       # 4 full blocks + 1
    _serve(ctl_a, ans_a, [_req("p", prompt, 6)])
    payload = kv_transfer.export_blocks(ex_a, prompt)
    assert len(payload["blocks"]) == 4

    ex_b, ctl_b, ans_b = _paged_controller(iface)
    res = kv_transfer.inject_blocks(ex_b, payload)
    assert res["injected"] == 4
    st0 = dict(ex_b.pool_stats())
    assert st0["blocks_cached"] >= 4
    _serve(ctl_b, ans_b, [_req("q", prompt, 6)])
    st1 = ex_b.pool_stats()
    assert st1["prefix_hits"] == st0["prefix_hits"] + 1
    assert st1["prefix_hit_tokens"] - st0["prefix_hit_tokens"] == 16
    kind, got = ans_b["q"]
    assert kind == "ok"
    want = np.asarray(iface.complete_tokens(np.asarray(prompt, np.int32),
                                            0.0, 6))
    np.testing.assert_array_equal(np.asarray(got), want)
    np.testing.assert_array_equal(np.asarray(ans_a["p"][1]), want)


def index_digest_reports_tree_paths_test():
    iface = _interface()
    ex, ctl, answers = _paged_controller(iface)
    assert kv_transfer.index_digest(ex)["paths"] == []
    prompt = list(range(1, 17))
    _serve(ctl, answers, [_req("p", prompt, 3)])
    digest = kv_transfer.index_digest(ex)
    assert digest["block_tokens"] == 4
    assert prompt in digest["paths"]
    capped = kv_transfer.index_digest(ex, max_paths=0)
    assert capped["paths"] == []


# --------------------------------------------------------- global index unit

def global_prefix_index_test():
    g = GlobalPrefixIndex(block_tokens=4, cap=8)
    g.record(list(range(12)), owner=2)       # 3 whole-block prefixes
    assert len(g) == 3
    owner, depth = g.lookup(list(range(14)))  # longer prompt, same prefix
    assert owner == 2 and depth == 12
    owner, depth = g.lookup(list(range(6)))   # shorter: 1-block prefix
    assert owner == 2 and depth == 4
    assert g.lookup([9, 9, 9, 9]) == (None, 0)
    assert g.lookup([0, 1]) == (None, 0)      # sub-block span never matches
    assert g.invalidate_owner(2) == 3 and len(g) == 0
    # absorb: a digest with matching geometry folds in; mismatched is a no-op
    g.absorb(1, {"block_tokens": 4, "paths": [list(range(8))]})
    assert g.lookup(list(range(8)))[0] == 1
    g.absorb(3, {"block_tokens": 16, "paths": [list(range(64))]})
    assert g.lookup(list(range(64)))[0] == 1  # still the 8-token entry
    # LRU cap: the oldest untouched prefixes fall off
    for start in range(100, 100 + 8 * 4, 4):
        g.record(list(range(start, start + 4)), owner=0)
    assert len(g) == 8


def parse_replica_classes_test():
    assert parse_replica_classes("") == []
    assert parse_replica_classes("prefill:1,decode:2") \
        == ["prefill", "decode", "decode"]
    assert parse_replica_classes("decode, prefill") == ["decode", "prefill"]
    for bad in ("chonk:2", "prefill:0", "prefill:x", "prefill:-1"):
        with pytest.raises(ValueError):
            parse_replica_classes(bad)


# --------------------------------------------------- router dispatch (fakes)

def _disagg_router(classes, transport, n=3, **kw):
    reps = [Replica(i, 9000 + i, clock=lambda: 0.0) for i in range(n)]
    return Router(reps, transport=transport, clock=lambda: 0.0,
                  classes=classes, block_tokens=4, **kw), reps


def _tokens(n):
    return list(range(1, n + 1))


class _Fabric:
    """Fake replica fabric: records every (replica, path, op) call and
    answers /kv/blocks + /token_completion like a healthy replica."""

    def __init__(self):
        self.calls = []
        self.fail = set()       # replica indices that refuse connections
        self.empty_export = set()

    def __call__(self, replica, path, body, timeout, headers=None):
        op = body.get("op") if path == KV_BLOCKS_PATH else None
        self.calls.append((replica.index, path, op))
        if replica.index in self.fail:
            raise ConnectionRefusedError(f"replica {replica.index} down")
        if path == KV_BLOCKS_PATH:
            if op == "export":
                if replica.index in self.empty_export:
                    return 200, {"version": 1, "block_tokens": 4,
                                 "blocks": []}
                toks = body["tokens"]
                return 200, {
                    "version": 1, "block_tokens": 4,
                    "blocks": [{"key": toks[i:i + 4],
                                "leaves": {"target/k": {"bytes": 64}}}
                               for i in range(0, len(toks), 4)]}
            if op == "import":
                return 200, {"injected": len(body.get("blocks") or []),
                             "skipped": 0}
            if op == "index":
                return 200, {"block_tokens": 4, "paths": []}
        return 200, {"tokens": [7], "replica": replica.index}

    def forwards(self, kind=None):
        return [(i, p, o) for i, p, o in self.calls
                if (kind is None or o == kind)]


def disagg_miss_then_migrate_then_route_to_owner_test():
    """The full lifecycle: a cold prefix goes to the prefill class (miss),
    the next request migrates the blocks to a decode replica, and the
    third routes straight to that owner — no second transfer."""
    fab = _Fabric()
    router, reps = _disagg_router(["prefill", "decode", "decode"], fab)
    toks = _tokens(9)                        # 2 whole blocks + 1
    out = router.forward("/token_completion", {"tokens": toks})
    assert out["replica"] == 0               # prefill class owns the cold run
    assert router.gindex.lookup(toks)[0] == 0
    fab.calls.clear()
    out = router.forward("/token_completion", {"tokens": toks})
    assert out["replica"] in (1, 2)          # answered by a decode replica
    assert fab.forwards("export") == [(0, KV_BLOCKS_PATH, "export")]
    assert [i for i, _, o in fab.forwards("import")] == [out["replica"]]
    assert router.gindex.lookup(toks)[0] == out["replica"]
    fab.calls.clear()
    out2 = router.forward("/token_completion", {"tokens": toks})
    assert out2["replica"] == out["replica"]  # route-to-owner
    assert fab.forwards("export") == []       # blocks already live there


def disagg_short_prompt_skips_prefill_class_test():
    """Sub-block prompts carry nothing transferable: they go straight to
    the decode class so long decodes never queue behind prefills."""
    fab = _Fabric()
    router, _ = _disagg_router(["prefill", "decode", "decode"], fab)
    out = router.forward("/token_completion", {"tokens": [1, 2, 3]})
    assert out["replica"] in (1, 2)
    assert fab.forwards("export") == []


def disagg_shallow_hit_treated_as_cold_test():
    """A hit covering no more than half the span (typically a shared
    system head) is prefill-class work: migrating the sliver would move
    the heavy prefill onto a decode replica."""
    fab = _Fabric()
    router, _ = _disagg_router(["prefill", "decode", "decode"], fab)
    router.gindex.record(_tokens(4), owner=1)   # only the shared head
    out = router.forward("/token_completion", {"tokens": _tokens(13)})
    assert out["replica"] == 0                  # prefill class, no migration
    assert fab.forwards("export") == []
    assert router.gindex.lookup(_tokens(13))[0] == 0  # re-learned deeper


def disagg_owner_breaker_open_cold_fallback_test():
    """A hit naming an owner whose breaker is OPEN degrades to cold
    prefill elsewhere and drops the stale entries — never a 500."""
    fab = _Fabric()
    router, reps = _disagg_router(["prefill", "decode", "decode"], fab)
    toks = _tokens(9)
    router.gindex.record(toks, owner=1)
    for _ in range(3):
        reps[1].breaker.record_failure()
    assert reps[1].breaker.tick() == "open"
    out = router.forward("/token_completion", {"tokens": toks})
    assert out["replica"] != 1
    assert router.gindex.lookup(toks)[0] == out["replica"]  # re-learned


def disagg_migration_failure_cold_fallback_test():
    """The owner dying mid-stream (export leg refused) must not surface:
    the decode replica cold-prefills, the dead owner's entries drop."""
    fab = _Fabric()
    router, reps = _disagg_router(["prefill", "decode", "decode"], fab)
    toks = _tokens(9)
    router.forward("/token_completion", {"tokens": toks})  # owner: replica 0
    fab.fail.add(0)
    fab.calls.clear()
    out = router.forward("/token_completion", {"tokens": toks})
    assert out["replica"] in (1, 2)
    assert router.gindex.lookup(toks)[0] == out["replica"]
    # an owner whose tree already evicted the blocks (empty export) also
    # degrades cleanly
    fab.fail.clear()
    router.gindex.record(toks, owner=0)
    fab.empty_export.add(0)
    out = router.forward("/token_completion", {"tokens": toks})
    assert out["replica"] in (1, 2)


def disagg_all_replicas_open_still_503_test():
    fab = _Fabric()
    router, reps = _disagg_router(["prefill", "decode"], fab, n=2)
    for rep in reps:
        for _ in range(3):
            rep.breaker.record_failure()
    with pytest.raises(HTTPStatusError) as exc:
        router.forward("/token_completion", {"tokens": _tokens(9)})
    assert exc.value.status == 503


def disagg_index_sync_absorbs_replica_digests_test():
    """sync_global_index folds each replica's /kv/blocks index digest in
    on the poll cadence (self-throttled), so restarts and evictions
    reconcile without request traffic."""
    calls = []

    def transport(replica, path, body, timeout, headers=None):
        calls.append((replica.index, body.get("op")))
        if replica.index == 1:
            return 200, {"block_tokens": 4, "paths": [_tokens(8)]}
        return 200, {"block_tokens": 4, "paths": []}

    clock = [0.0]
    reps = [Replica(i, 9000 + i, clock=lambda: clock[0]) for i in range(2)]
    router = Router(reps, transport=transport, clock=lambda: clock[0],
                    classes=["prefill", "decode"], block_tokens=4,
                    index_sync_interval_s=5.0)
    assert router.sync_global_index() == 2
    assert router.gindex.lookup(_tokens(8))[0] == 1
    assert router.sync_global_index() == 0   # throttled
    clock[0] += 6.0
    assert router.sync_global_index() == 2


def symmetric_router_unchanged_test():
    """No classes (or a single class) => gindex is None and forward never
    touches /kv/blocks — the symmetric tier is byte-identical to today."""
    fab = _Fabric()
    router, _ = _disagg_router(None, fab)
    assert router.gindex is None and not router.disagg
    router.forward("/token_completion", {"tokens": _tokens(9)})
    assert all(p != KV_BLOCKS_PATH for _, p, _ in fab.calls)
    router2, _ = _disagg_router(["decode", "decode", "decode"], fab)
    assert router2.gindex is None


# ------------------------------------------------------- REST two replicas

def _spawn_rest(iface, port):
    from homebrewnlp_tpu.infer import rest_api
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve, args=(iface.params, iface),
                         kwargs={"port": port, "isolate": True,
                                 "stop": stop}, daemon=True)
    t.start()
    return stop, t


def _post(port, path, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    for _ in range(240):
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())
        except (ConnectionError, urllib.error.URLError, OSError):
            time.sleep(0.25)
    raise TimeoutError(path)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def kv_blocks_rest_roundtrip_two_replicas_test():
    """The real seam: two isolated serving deployments, blocks exported
    over HTTP from the replica that prefilled and injected into the other,
    whose completion then answers bit-identically having skipped prefill —
    and both hbnlp_disagg_* replica counters move."""
    prompt = list(range(1, 17)) + [25]
    # ONE interface for both deployments (a second in-process Model would
    # renumber scope parameters): each serve() builds its own executor, so
    # the pools/trees are fully independent — exactly a replica pair's
    # state, minus the process boundary
    iface = _interface(serve_engine="continuous", serve_slots=2,
                       serve_batch_size=2, kv_paging="on",
                       kv_block_tokens=4)
    want = [int(x) for x in iface.complete_tokens(
        np.asarray(prompt, np.int32), 0.0, 6)]
    pa, pb = _free_port(), _free_port()
    # stagger the deployments: tracing is not concurrency-safe (scope
    # naming is a process-global counter), so B starts only after A's
    # warm-up compile answered /health — real replicas are processes and
    # never share a tracer
    stop_a, ta = _spawn_rest(iface, pa)
    stop_b = tb = None
    try:
        status, health = _post(pa, "/health", {})
        assert status == 200 and health["engine"]["kv_transfer"]
        stop_b, tb = _spawn_rest(iface, pb)
        status, _ = _post(pb, "/health", {})
        assert status == 200
        status, out = _post(pa, "/token_completion",
                            {"tokens": prompt, "max_tokens": 6,
                             "temperature": 0.0})
        assert status == 200 and out["tokens"] == want
        status, payload = _post(pa, KV_BLOCKS_PATH,
                                {"op": "export", "tokens": prompt})
        assert status == 200 and len(payload["blocks"]) == 4
        status, res = _post(pb, KV_BLOCKS_PATH, dict(payload, op="import"))
        assert status == 200 and res["injected"] == 4
        status, digest = _post(pb, KV_BLOCKS_PATH, {"op": "index"})
        assert status == 200 and prompt[:16] in digest["paths"]
        status, out_b = _post(pb, "/token_completion",
                              {"tokens": prompt, "max_tokens": 6,
                               "temperature": 0.0})
        assert status == 200 and out_b["tokens"] == want
        # a corrupt import answers 400, not a 500 or a silent injection
        # (fresh keys — a replayed key would hit the existing-child-wins
        # skip before validation ever sees the corrupt bytes)
        bad = json.loads(json.dumps(payload))
        for blk in bad["blocks"]:
            blk["key"] = [t + 100 for t in blk["key"]]
        name = sorted(bad["blocks"][0]["leaves"])[0]
        meta = bad["blocks"][0]["leaves"][name]
        raw = bytearray(base64.b64decode(meta["data"]))
        raw[0] ^= 0xFF
        meta["data"] = base64.b64encode(bytes(raw)).decode("ascii")
        status, err = _post(pb, KV_BLOCKS_PATH, dict(bad, op="import"))
        assert status == 400, err
        assert "verification" in err.get("error", "") \
            or "truncated" in err.get("error", ""), err
        for port, series in ((pa, "hbnlp_disagg_exported_blocks_total"),
                             (pb, "hbnlp_disagg_injected_blocks_total")):
            req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
            with urllib.request.urlopen(req, timeout=30) as resp:
                text = resp.read().decode()
            assert f"{series} 4" in text, text[:2000]
    finally:
        stop_a.set()
        if stop_b is not None:
            stop_b.set()
        ta.join(timeout=15)
        if tb is not None:
            tb.join(timeout=15)
    assert not ta.is_alive()
    assert tb is not None and not tb.is_alive()
