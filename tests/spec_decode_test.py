"""Speculative decoding on the slot engine (marker: specdecode;
docs/SERVING.md 'Speculative decoding').

Substrate: a width-m ``apply_decode`` (the spec VERIFY step) must compute
the same function as m sequential width-1 steps — same argmax, same KV
rows — and models with sequence-recurrent caches must REFUSE multi-position
decode (their state cannot roll back on draft rejection).

Engine: greedy bit-parity of the draft-and-verify executor against the
plain engine token-for-token, through three regimes — a PERFECT draft (the
target itself: full acceptance incl. bonus tokens), a deliberately-bad
random draft (acceptance ~0: every round survives on the verify's own
token), and the acceptance-collapse self-disable (loud event, permanent
reversion to the plain chunk program, still bit-correct).  Mixed
co-residency (greedy + temperature>0 at draft depth 0) answers correctly.

Analysis: the spec chunk step's compiled module — every leaf of BOTH cache
pools donated+aliased, no full-pool-shaped copy (the graft-lint
``spec_chunk_step`` audit).

End to end: a real-IPC REST roundtrip on ``spec_decode="auto"`` with an
attached draft, asserting answers match the direct interface call and the
``hbnlp_spec_*`` acceptance series scrape on /metrics.

Standalone-runnable (tier-1 truncates at 870s on this box):
``python -m pytest tests/spec_decode_test.py -q``
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer.scheduler import (EngineController, EngineRequest,
                                             SlotScheduler, spec_depth)

pytestmark = pytest.mark.specdecode

SEQ = 32
PROMPTS = [[1, 2, 3], [7, 8], [4, 5, 6, 7, 9], [10]]
RLS = [6, 20, 3, None]


def _interface(**kw):
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp
    cfg = dict(block_config=MIXER_BLOCKS, memory_reduction_strategy="none",
               sequence_length=SEQ, train_batch_size=1,
               decode_loop="stepped", decode_chunk_tokens=5)
    cfg.update(kw)
    params = make_params(**cfg)
    params.train = False
    model = Model(params)
    seq = params.sequence_dim.size
    batch = {"token_x": np.zeros((1, seq, 1), np.int32),
             "token_y": np.zeros((1, seq, 1), np.int32)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return InterfaceWrapper(params, model, variables)


def _draft_triple(features_per_head=8, seed_cfg=()):
    """A narrow draft at the harness scale (fph 8 is the narrowest width
    the factorized vocab supports) — random init, so acceptance ~0."""
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp
    cfg = dict(block_config=MIXER_BLOCKS, memory_reduction_strategy="none",
               sequence_length=SEQ, train_batch_size=1,
               features_per_head=features_per_head)
    cfg.update(dict(seed_cfg))
    dparams = make_params(**cfg)
    dparams.train = False
    dmodel = Model(dparams)
    zeros = np.zeros((1, SEQ, 1), np.int32)
    dvars = {k: jnp.asarray(v) for k, v in
             dmodel.init({"token_x": zeros, "token_y": zeros}).items()}
    return dparams, dmodel, dvars


def _controller(ex, answers, events=None, slots=4):
    sched = SlotScheduler(slots)
    return EngineController(
        ex, sched, decode_chunk=5, prefill_chunk=8,
        answer=lambda req, oc: answers.__setitem__(req.rid, oc),
        hooks=(lambda event, **k: events.append((event, k)))
        if events is not None else None), sched


def _run(ctl, answers, want, budget=80):
    for _ in range(budget):
        if all(r in answers for r in want):
            return
        ctl.round()
    raise AssertionError(f"unanswered: {set(want) - set(answers)}")


# ------------------------------------------------------- substrate parity

def multiposition_verify_matches_sequential_test():
    """Width-m apply_decode == m sequential width-1 steps: same logits (to
    float-reassociation ulps), same argmax, same KV cache rows."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.model import Model
    params = make_params(block_config=MIXER_BLOCKS,
                         memory_reduction_strategy="none",
                         sequence_length=SEQ, train_batch_size=4)
    params.train = False
    model = Model(params)
    zeros = np.zeros((4, SEQ, 1), np.int32)
    variables = {k: jnp.asarray(v) for k, v in
                 model.init({"token_x": zeros, "token_y": zeros}).items()}
    from homebrewnlp_tpu.infer.sampler import decode_cache_shapes
    rng = np.random.default_rng(0)
    token_x = jnp.asarray(rng.integers(0, params.vocab_size,
                                       (4, SEQ, 1)).astype(np.int32))
    shapes = decode_cache_shapes(model, variables,
                                 np.zeros((4, SEQ, 1), np.int32))
    zeros = {k: jnp.zeros(v.shape, v.dtype) for k, v in shapes.items()}
    q0 = jnp.asarray(np.array([0, 2, 5, 1], np.int32))
    m = 5
    seq_logits, c = [], zeros
    for i in range(m):
        pos = q0 + i
        cur = jnp.take_along_axis(token_x, pos[:, None, None], axis=1)
        lg, c = model.apply_decode(variables, cur, pos, c)
        seq_logits.append(np.asarray(lg))
    seq_logits = np.concatenate(seq_logits, axis=1)
    vtok = jnp.take_along_axis(
        token_x, (q0[:, None] + jnp.arange(m))[:, :, None], axis=1)
    ver_logits, vc = model.apply_decode(variables, vtok, q0, zeros)
    ver_logits = np.asarray(ver_logits)
    np.testing.assert_allclose(seq_logits, ver_logits, atol=1e-5)
    assert (seq_logits.argmax(-1) == ver_logits.argmax(-1)).all()
    for k in c:
        np.testing.assert_allclose(np.asarray(c[k], np.float32),
                                   np.asarray(vc[k], np.float32), atol=1e-4)


def recurrent_caches_refuse_multiposition_test():
    """A cumsum-mixing model must refuse width>1 decode (rollback is
    impossible for running state) — the guard the spec executor's
    construction probe relies on for its auto-fallback."""
    import jax
    import jax.numpy as jnp
    blocks = [{"layer": ["norm-shift-scale-features-group", "cumsum"]}]
    iface = _interface(block_config=blocks)
    from homebrewnlp_tpu.infer.sampler import decode_cache_shapes
    shapes = decode_cache_shapes(iface.model, iface.variables,
                                 np.zeros((1, SEQ, 1), np.int32))
    aval = jax.ShapeDtypeStruct
    with pytest.raises(NotImplementedError, match="cumsum"):
        jax.eval_shape(
            lambda v, t, c: iface.model.apply_decode(
                v, t, jnp.zeros(1, jnp.int32), c),
            iface.variables, aval((1, 2, 1), jnp.int32),
            {k: aval(v.shape, v.dtype) for k, v in shapes.items()})


# --------------------------------------------------------- engine parity

def spec_perfect_draft_bit_parity_test():
    """With the target itself as draft, acceptance is ~100% (bonus-token
    path exercised) and output matches the plain stepped loop
    token-for-token — including late admission into a recycled slot."""
    from homebrewnlp_tpu.infer.engine import SpecEngineExecutor
    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.0)
    ref = [np.asarray(iface.complete_tokens(np.asarray(p, np.int32), 0.0,
                                            rl))
           for p, rl in zip(PROMPTS, RLS)]
    ex = SpecEngineExecutor(iface, slots=4,
                            draft=(iface.params, iface.model,
                                   iface.variables))
    answers, events = {}, []
    ctl, _ = _controller(ex, answers, events)
    ctl.round([EngineRequest(rid=f"r{i}", path="/token_completion",
                             toks=np.asarray(p, np.int32), response_len=rl)
               for i, (p, rl) in enumerate(zip(PROMPTS, RLS))])
    _run(ctl, answers, [f"r{i}" for i in range(len(PROMPTS))])
    for i, want in enumerate(ref):
        kind, got = answers[f"r{i}"]
        assert kind == "ok", (i, kind)
        np.testing.assert_array_equal(np.asarray(got), want)
    s = ex.spec_summary()
    assert s["enabled"] and s["drafted"] > 0
    assert s["accept_rate"] == 1.0, s      # the draft IS the target
    verifies = [k for e, k in events if e == "spec_verify"]
    assert verifies and all(v["accepted"] == v["drafted"] for v in verifies)
    # late admission into a recycled slot (admit splice zeroes BOTH pools)
    ctl.round([EngineRequest(rid="late", path="/token_completion",
                             toks=np.asarray([3, 1, 4], np.int32),
                             response_len=4)])
    _run(ctl, answers, ["late"])
    np.testing.assert_array_equal(
        np.asarray(answers["late"][1]),
        np.asarray(iface.complete_tokens(np.asarray([3, 1, 4], np.int32),
                                         0.0, 4)))


def spec_bad_draft_bit_parity_and_self_disable_test():
    """A random draft (acceptance ~0) must still be bit-correct — every
    round advances on the verify's own token — and must trip the
    spec_min_accept_rate self-disable: loud event, hbnlp_spec_state flip
    (scheduler forwards it), and the executor permanently reverts to the
    plain chunk program, still serving bit-identically."""
    from homebrewnlp_tpu.infer.engine import SpecEngineExecutor
    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.5)
    ref = [np.asarray(iface.complete_tokens(np.asarray(p, np.int32), 0.0,
                                            rl))
           for p, rl in zip(PROMPTS, RLS)]
    ex = SpecEngineExecutor(iface, slots=4, draft=_draft_triple())
    answers, events = {}, []
    ctl, _ = _controller(ex, answers, events)
    ctl.round([EngineRequest(rid=f"r{i}", path="/token_completion",
                             toks=np.asarray(p, np.int32), response_len=rl)
               for i, (p, rl) in enumerate(zip(PROMPTS, RLS))])
    _run(ctl, answers, [f"r{i}" for i in range(len(PROMPTS))])
    for i, want in enumerate(ref):
        kind, got = answers[f"r{i}"]
        assert kind == "ok", (i, kind)
        np.testing.assert_array_equal(np.asarray(got), want)
    disabled = [k for e, k in events if e == "spec_disabled"]
    assert disabled and disabled[0]["rate"] < 0.5
    assert not ex._spec_enabled
    assert ex.spec_summary()["accept_rate"] < 0.5
    # post-disable: the plain program serves the next request bit-identically
    ctl.round([EngineRequest(rid="after", path="/token_completion",
                             toks=np.asarray([3, 1, 4], np.int32),
                             response_len=4)])
    _run(ctl, answers, ["after"])
    np.testing.assert_array_equal(
        np.asarray(answers["after"][1]),
        np.asarray(iface.complete_tokens(np.asarray([3, 1, 4], np.int32),
                                         0.0, 4)))


def spec_int8_kv_bit_parity_test():
    """int8 KV composition: the verify's width-m scatter lands m quantized
    rows AND m sibling scale rows per slot (per-position scales — the
    width-m quantization of each row is the same per-row formula the
    sequential walk applies), and the spec engine stays token-for-token
    equal to the plain engine on the same int8 pool."""
    from homebrewnlp_tpu.infer.engine import SpecEngineExecutor
    iface = _interface(spec_draft_tokens=3, spec_min_accept_rate=0.0,
                       decode_cache_dtype="int8")
    prompts, rls = PROMPTS[:3], [6, 12, 3]
    ref = [np.asarray(iface.complete_tokens(np.asarray(p, np.int32), 0.0,
                                            rl))
           for p, rl in zip(prompts, rls)]
    ex = SpecEngineExecutor(iface, slots=3,
                            draft=(iface.params, iface.model,
                                   iface.variables))
    answers = {}
    ctl, _ = _controller(ex, answers, slots=3)
    ctl.round([EngineRequest(rid=f"r{i}", path="/token_completion",
                             toks=np.asarray(p, np.int32), response_len=rl)
               for i, (p, rl) in enumerate(zip(prompts, rls))])
    _run(ctl, answers, [f"r{i}" for i in range(len(prompts))])
    for i, want in enumerate(ref):
        kind, got = answers[f"r{i}"]
        assert kind == "ok", (i, kind)
        np.testing.assert_array_equal(np.asarray(got), want)
    assert ex.spec_summary()["drafted"] > 0


def spec_mixed_temperature_coresidency_test():
    """temperature>0 requests ride the same verify at draft depth 0 (one
    sampled token per round) co-resident with greedy spec rows; the greedy
    row stays bit-identical and the sampled row answers with the right
    extent."""
    from homebrewnlp_tpu.infer.engine import SpecEngineExecutor
    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.0)
    ex = SpecEngineExecutor(iface, slots=2,
                            draft=(iface.params, iface.model,
                                   iface.variables))
    answers = {}
    ctl, _ = _controller(ex, answers, slots=2)
    ctl.round([EngineRequest(rid="g", path="/token_completion",
                             toks=np.asarray([1, 2], np.int64),
                             response_len=6),
               EngineRequest(rid="t", path="/token_completion",
                             toks=np.asarray([5], np.int64),
                             response_len=6, temperature=0.8)])
    _run(ctl, answers, ["g", "t"])
    assert answers["g"][0] == "ok" and answers["t"][0] == "ok"
    np.testing.assert_array_equal(
        np.asarray(answers["g"][1]),
        np.asarray(iface.complete_tokens(np.asarray([1, 2], np.int32),
                                         0.0, 6)))
    assert len(answers["t"][1]) == 1 + 6


def spec_depth_eligibility_test():
    """scheduler.spec_depth: greedy-with-default-filters drafts at k,
    anything the accept rule cannot serve bit-identically drafts at 0."""
    defaults = (0, 1.0, 1.0)
    base = dict(rid="r", path="/token_completion", toks=np.asarray([1]))
    assert spec_depth(EngineRequest(**base), defaults, 4) == 4
    assert spec_depth(EngineRequest(**base, temperature=0.5), defaults,
                      4) == 0
    assert spec_depth(EngineRequest(**base, top_k=5), defaults, 4) == 0
    assert spec_depth(EngineRequest(**base, top_p=0.9), defaults, 4) == 0
    assert spec_depth(EngineRequest(**base, rep_penalty=1.2), defaults,
                      4) == 0
    # non-default CONFIG fallbacks disqualify requests that omit the knob
    assert spec_depth(EngineRequest(**base), (5, 1.0, 1.0), 4) == 0


def spec_draft_requires_continuous_engine_test():
    """spec_decode="draft" promises speculation or no serving at all:
    combined with serve_engine="batch" (which cannot speculate) the
    resolver refuses loudly instead of silently serving without drafts."""
    from homebrewnlp_tpu.infer import rest_api
    iface = _interface(serve_engine="batch", spec_decode="draft")
    with pytest.raises(RuntimeError, match="continuous"):
        rest_api._resolve_engine(iface.params, iface)
    # "auto" + batch is fine: speculate-when-possible never blocks serving
    iface2 = _interface(serve_engine="batch", spec_decode="auto")
    assert rest_api._resolve_engine(iface2.params, iface2) is None


def load_draft_config_roundtrip_test(tmp_path):
    """infer/spec.load_draft: a config-JSON draft builds at the target's
    sequence geometry (no checkpoint -> loud random-init note), and a
    geometry mismatch refuses with a named error."""
    from homebrewnlp_tpu.infer import spec as spec_mod
    iface = _interface()
    cfg = {"model_mode": "gpt", "use_video": False, "use_language": True,
           "sequence_length": 64,  # overridden to the target's geometry
           "features_per_head": 8, "heads": 2, "depth": 2,
           "train_batch_size": 1, "vocab_size": 32,
           "group_linear_factor": 2,
           "intermediate_feed_forward_multiplier_multiplier": 0.5,
           "block_config": MIXER_BLOCKS,
           "memory_reduction_strategy": "none",
           "model_path": str(tmp_path / "draft_run")}
    cfg_path = tmp_path / "draft.json"
    cfg_path.write_text(json.dumps(cfg))
    iface.params.spec_draft_model_path = str(cfg_path)
    dparams, dmodel, dvars = spec_mod.load_draft(iface.params)
    assert dparams.sequence_length == iface.params.sequence_length
    assert dparams.vocab_size == iface.params.vocab_size
    assert dvars  # initialised (random — no checkpoint committed here)
    # geometry mismatch: a draft over a different vocabulary must refuse
    bad = dict(cfg, vocab_size=64)
    from homebrewnlp_tpu.config import ModelParameter
    with pytest.raises(ValueError, match="vocab_size"):
        spec_mod.check_draft_compatible(iface.params, ModelParameter(bad))


# ------------------------------------------------------------- HLO audit

def spec_hlo_audit_test():
    """The spec chunk step's compiled module: every leaf of BOTH cache
    pools (target + draft) donated+aliased, no full-pool-shaped copy —
    enforced repo-wide by graft-lint as spec_chunk_step."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.analysis import entry_points, hlo_lint
    params, model, variables, token_x, _ = entry_points.build_audit_model()
    hlo, ctx = entry_points.lower_spec_step(model, variables,
                                            jnp.asarray(token_x))
    assert hlo_lint.input_output_alias_count(hlo) >= ctx["donated_leaves"]
    # both pools contribute leaves: the carry donates more than the plain
    # engine's single pool
    assert ctx["donated_leaves"] > 3 + len(
        [k for k in ctx["cache_shapes"] if not k.startswith("draft/")])
    findings = hlo_lint.audit("spec_chunk_step", hlo,
                              expected_aliases=ctx["donated_leaves"],
                              protected_shapes=ctx["protected"],
                              bf16_param_shapes=ctx["bf16_params"],
                              budget={})
    assert findings == [], [str(f) for f in findings]


# ------------------------------------------------------- REST roundtrip

def spec_rest_roundtrip_test():
    """End to end over real IPC with spec_decode=auto and an attached
    draft: completions bit-match the direct interface call, /health
    reports the spec engine, and the acceptance series scrape on
    /metrics."""
    import socket
    from homebrewnlp_tpu.infer import rest_api
    iface = _interface(serve_engine="continuous", serve_slots=4,
                       serve_batch_size=4, spec_decode="auto",
                       spec_draft_tokens=4, spec_min_accept_rate=0.0)
    # perfect draft (the target) so the scrape shows real acceptance
    iface.draft = (iface.params, iface.model, iface.variables)
    ref = np.asarray(iface.complete_tokens(np.asarray([1, 2, 3], np.int32),
                                           0.0, 6))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve,
                         args=(iface.params, iface),
                         kwargs={"port": port, "isolate": True,
                                 "stop": stop},
                         daemon=True)
    t.start()

    def post(path, payload, timeout=120):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        for _ in range(240):
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())
            except (ConnectionError, urllib.error.URLError, OSError):
                time.sleep(0.25)
        raise TimeoutError(path)

    try:
        status, health = post("/health", {})
        assert status == 200
        engine = health["engine"]
        assert engine["mode"] == "continuous" and engine["slots"] == 4
        assert engine["spec"]["enabled"] and \
            engine["spec"]["draft_tokens"] == 4
        status, out = post("/token_completion",
                           {"tokens": [1, 2, 3], "max_tokens": 6,
                            "temperature": 0.0})
        assert status == 200
        assert out["tokens"] == [int(x) for x in ref]
        req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
        deadline = time.monotonic() + 30
        while True:
            with urllib.request.urlopen(req, timeout=30) as resp:
                text = resp.read().decode()
            if "hbnlp_spec_accepted_tokens_total" in text:
                break
            assert time.monotonic() < deadline, text[:2000]
            time.sleep(0.5)
        assert "hbnlp_spec_state 1" in text
        assert "hbnlp_spec_accept_rate_bucket" in text
        assert "hbnlp_spec_accepted_tokens_per_verify" in text
        # perfect draft: every drafted token accepted
        drafted = [ln for ln in text.splitlines()
                   if ln.startswith("hbnlp_spec_drafted_tokens_total")]
        accepted = [ln for ln in text.splitlines()
                    if ln.startswith("hbnlp_spec_accepted_tokens_total")]
        assert drafted and accepted
        assert float(drafted[0].split()[-1]) == \
            float(accepted[0].split()[-1]) > 0
    finally:
        stop.set()
        t.join(timeout=15)
    assert not t.is_alive()
