"""Multi-host input assembly (VERDICT round-1 missing #1).

Spawns TWO real jax processes (multi-controller, CPU, 4 virtual devices
each) and verifies shard_batch assembles distinct per-process dataset slices
into one global sharded batch via jax.make_array_from_process_local_data —
the rebuild's equivalent of the reference's per-host infeed placement
(/root/reference/src/run/dataloader_placement.py:153-227).
"""
import os
import re
import socket
import subprocess
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def two_process_assembly_test():
    port = _free_port()
    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS=flags + " --xla_force_host_platform_device_count=4")
    worker = os.path.join(HERE, "_multihost_worker.py")
    procs = [subprocess.Popen([sys.executable, worker, str(port), str(pid), "2"],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for pid in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid}: OK" in out, out


def single_process_macro_axis_test():
    """shard_batch shards the batch axis (axis 1 under macro-batching), never
    the macro axis."""
    import jax
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib

    cfg = {"model_mode": "gpt", "use_video": False, "use_language": True,
           "sequence_length": 16, "features_per_head": 8, "heads": 2,
           "depth": 1, "train_batch_size": 8, "vocab_size": 256,
           "tpu_size": 8, "macro_batching": 2,
           "mesh_shape_override": {"data": 8},
           "model_path": "/tmp/macro_axis_run"}
    params = ModelParameter(cfg)
    mesh = shardlib.build_mesh(params)
    batch = {"token_x": np.zeros((2, 8, 16, 1), np.int32)}
    out = shardlib.shard_batch(params, batch, mesh)["token_x"]
    spec = out.sharding.spec
    assert len(spec) >= 2 and spec[0] is None and spec[1] == "data", spec
