"""Multi-host input assembly (VERDICT round-1 missing #1).

Spawns TWO real jax processes (multi-controller, CPU, 4 virtual devices
each) and verifies shard_batch assembles distinct per-process dataset slices
into one global sharded batch via jax.make_array_from_process_local_data —
the rebuild's equivalent of the reference's per-host infeed placement
(/root/reference/src/run/dataloader_placement.py:153-227).
"""
import os
import re
import subprocess
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    from homebrewnlp_tpu.distributed.bootstrap import free_port
    return free_port()



STARVATION_RCS = (-6, 134)  # gloo SIGABRT: 'another task died'


def starvation_retry_reason(rcs, outs=()):
    """Classify a failed fleet attempt: returns the retry-reason line to
    stamp into the test log when the exit-code shape matches the known
    1-core scheduler-starvation flake (the coordination-service heartbeat
    starves, so gloo SIGABRTs the fleet with 'another task died'), else
    None — an unclassified failure is a real regression and the caller
    decides whether to retry.  Shared by _spawn_workers and the direct
    fleet call sites that need their own spawn loop (forensics_test's
    SIGKILL e2e) so the retry policy and its logging cannot drift between
    copies."""
    if not any(rc in STARVATION_RCS for rc in rcs):
        return None
    marker = any("another task died" in (o or "") for o in outs)
    return (f"worker rcs={rcs} — heartbeat starvation (SIGABRT -6 = "
            "'another task died'"
            + ("; marker seen in worker output" if marker else "")
            + "; 1-core scheduler contention, not product behavior)")


def _spawn_workers(worker: str, extra_args, env_devcount: int = 4,
                   n_procs: int = 2, timeout: int = 420, retries: int = 1):
    """Launch n multi-controller worker processes on a shared coordinator
    port with a virtual CPU mesh; returns [(proc, output), ...].

    This is THE shared fleet-spawning helper for every multi-process test
    path (multihost, distributed, elastic suites): it owns the one
    contention-flake retry, so the policy and its logging cannot drift
    between copies.  A 1-core CI box oversubscribed by N jax processes
    occasionally starves the coordination-service heartbeat, which SIGABRTs
    the entire fleet with 'another task died' — scheduler starvation, not
    product behavior.  Under tier-1 contention this was the one remaining
    flake (every suite passes standalone); the whole fleet retries once and
    correctness assertions run on the surviving attempt's output.  Each
    retry logs WHY (per-worker exit codes + the first failing worker's
    tail) so a starvation retry is distinguishable from a real regression
    in the test log."""
    last = None
    for attempt in range(retries + 1):
        port = _free_port()
        env = dict(os.environ)
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                   XLA_FLAGS=flags +
                   f" --xla_force_host_platform_device_count={env_devcount}")
        procs = [subprocess.Popen(
            [sys.executable, worker, str(port), str(pid), str(n_procs)]
            + [str(a) for a in extra_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
            for pid in range(n_procs)]
        results = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            results.append((p, out))
        if all(p.returncode == 0 for p, _ in results):
            return results
        last = results
        if attempt < retries:
            rcs = [p.returncode for p, _ in results]
            outs = [out for _, out in results]
            reason = starvation_retry_reason(rcs, outs) or (
                f"worker rcs={rcs} (unclassified — single-core heartbeat "
                "starvation is still the most likely cause under tier-1 "
                "contention)")
            first_bad = next(out for p, out in results if p.returncode)
            print(f"FLEET RETRY {attempt + 1}/{retries}: {reason}.  "
                  f"First failing worker tail:\n{first_bad[-600:]}",
                  flush=True)
    return last


def two_process_assembly_test():
    results = _spawn_workers(os.path.join(HERE, "_multihost_worker.py"), [],
                             timeout=300)
    for pid, (p, out) in enumerate(results):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid}: OK" in out, out


def four_process_assembly_test():
    """4 controllers x 4 virtual devices = a 16-device pod: the per-process
    slice layout and cross-process gather must hold beyond the 2-process
    case (process-group derivation at wider DCN fan-out)."""
    results = _spawn_workers(os.path.join(HERE, "_multihost_worker.py"), [],
                             n_procs=4, timeout=300)
    for pid, (p, out) in enumerate(results):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid}: OK" in out, out


def single_process_macro_axis_test():
    """shard_batch shards the batch axis (axis 1 under macro-batching), never
    the macro axis."""
    import jax
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.core import sharding as shardlib

    cfg = {"model_mode": "gpt", "use_video": False, "use_language": True,
           "sequence_length": 16, "features_per_head": 8, "heads": 2,
           "depth": 1, "train_batch_size": 8, "vocab_size": 256,
           "tpu_size": 8, "macro_batching": 2,
           "mesh_shape_override": {"data": 8},
           "model_path": "/tmp/macro_axis_run"}
    params = ModelParameter(cfg)
    mesh = shardlib.build_mesh(params)
    batch = {"token_x": np.zeros((2, 8, 16, 1), np.int32)}
    out = shardlib.shard_batch(params, batch, mesh)["token_x"]
    spec = out.sharding.spec
    assert len(spec) >= 2 and spec[0] is None and spec[1] == "data", spec


def two_process_train_loop_test(tmp_path):
    """The REAL train loop over two jax processes: per-process dataset
    slices, global-batch assembly, chief-only artifact writes, identical
    loss trajectory on both controllers."""
    import json

    from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example

    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    rng = np.random.default_rng(0)
    for i in range(4):  # >= 2 files per process slice
        base = np.tile(np.arange(32, dtype=np.uint8), 4096 // 32)
        noise = rng.integers(0, 32, 4096).astype(np.uint8)
        tokens = np.where(rng.random(4096) < 0.05, noise, base)
        with RecordWriter(str(data_dir / f"p_{i}_4096.tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))

    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 16, "heads": 2,
        "depth": 2, "train_batch_size": 8, "vocab_size": 32,
        "calc_accuracy": False, "memory_reduction_strategy": "revnet",
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    "feed_forward-in:relu"]}],
        "group_linear_factor": 2, "tpu_size": 8,
        "mesh_shape_override": {"data": 8},
        "optimizer": "adam-learning_rate", "learning_rate": 0.003,
        "weight_decay": 0.0,
        "learning_rate_config": {"linear_warmup": {"final_step": 8}},
        "train_steps": 12, "interleaved_datasets": 2,
        "use_checkpointing": True, "steps_per_checkpoint": 10,
        "data_seed": 7,
        "dataset_configs": [{"path": str(data_dir / "*"), "type": "text",
                             "weight": 1}],
        "model_path": str(tmp_path / "run"),
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    results = _spawn_workers(os.path.join(HERE, "_multihost_train_worker.py"),
                             [cfg_path])
    finals = []
    for pid, (p, out) in enumerate(results):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        line = [l for l in out.splitlines() if l.startswith(f"WORKER {pid}")]
        assert line, out
        finals.append(float(line[0].split("FINAL")[1].split()[0]))
    # both controllers ran the same global computation
    assert finals[0] == finals[1], finals
    # chief-only artifacts: one metrics file, checkpoints exist, and no
    # duplicate-writer corruption in the jsonl
    run_dir = tmp_path / "run"
    metrics = [json.loads(l) for l in open(run_dir / "metrics.jsonl")]
    assert metrics and all(np.isfinite(m["loss"]) for m in metrics)
    assert any(d.startswith("ckpt_") for d in os.listdir(run_dir))


def two_process_model_sharded_checkpoint_test(tmp_path):
    """Model-axis sharding ACROSS processes (mesh model=8 over 2 controllers,
    the v5p full-model-parallel shape): the train loop runs, and a
    distributed checkpoint writes each process's owned shards which restore()
    reassembles bit-exact against the allgathered live values."""
    import json

    from homebrewnlp_tpu.data.tfrecord import RecordWriter, encode_example

    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    rng = np.random.default_rng(1)
    for i in range(4):
        tokens = rng.integers(0, 32, 4096).astype(np.uint8)
        with RecordWriter(str(data_dir / f"p_{i}_4096.tfrecord")) as w:
            w.write(encode_example({"text": tokens.tobytes()}))

    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 16, "heads": 8,
        "depth": 1, "train_batch_size": 8, "vocab_size": 32,
        "calc_accuracy": False, "memory_reduction_strategy": "none",
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    "feed_forward-in:relu"]}],
        "group_linear_factor": 2, "tpu_size": 8,
        "mesh_shape_override": {"data": 1, "model": 8},
        "optimizer": "adam-learning_rate", "learning_rate": 0.003,
        "weight_decay": 0.0,
        "learning_rate_config": {"linear_warmup": {"final_step": 8}},
        "train_steps": 4, "interleaved_datasets": 2,
        "use_checkpointing": False, "data_seed": 11,
        "dataset_configs": [{"path": str(data_dir / "*"), "type": "text",
                             "weight": 1}],
        "model_path": str(tmp_path / "run"),
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    results = _spawn_workers(os.path.join(HERE, "_multihost_train_worker.py"),
                             [cfg_path])
    losses = []
    for pid, (p, out) in enumerate(results):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"WORKER {pid} DISTCKPT OK" in out, out[-2000:]
        line = [l for l in out.splitlines()
                if l.startswith(f"WORKER {pid} DISTRESUME OK")]
        assert line, out[-2000:]
        losses.append(float(line[0].rsplit(None, 1)[1]))
    # the post-restore step computes the same global loss on both controllers
    assert losses[0] == losses[1], losses
