"""Ring attention vs dense reference on a real multi-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from homebrewnlp_tpu.parallel.ring_attention import dense_reference, ring_attention


def _mesh(seq_shards, data=1):
    devs = np.asarray(jax.devices()[:data * seq_shards]).reshape(data, seq_shards)
    return Mesh(devs, ("data", "sequence"))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq_shards", [2, 4])
def ring_matches_dense_test(causal, seq_shards):
    mesh = _mesh(seq_shards)
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 32, 4, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal))(q, k, v)
    ref = dense_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def ring_gradients_test():
    mesh = _mesh(4)
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 16, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_reference(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def ring_with_2d_mesh_test():
    """data x sequence mesh: batch and sequence sharded simultaneously."""
    mesh = _mesh(4, data=2)
    rng = np.random.default_rng(2)
    b, s, h, d = 4, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    ref = dense_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def zigzag_shuffle_roundtrip_test():
    """_to_zigzag places chunk (d, 2P-1-d) on device d; _from_zigzag inverts
    it exactly."""
    from jax.sharding import NamedSharding
    from homebrewnlp_tpu.parallel.ring_attention import (_from_zigzag,
                                                         _to_zigzag)
    P_shards = 4
    mesh = _mesh(P_shards)
    s = 32
    x = jnp.arange(s, dtype=jnp.float32).reshape(1, s, 1, 1)

    def shuffle(x):
        return _to_zigzag(x, "sequence", P_shards)

    def unshuffle(x):
        return _from_zigzag(x, "sequence", P_shards)

    spec = P(None, "sequence", None, None)
    zz = jax.jit(jax.shard_map(shuffle, mesh=mesh, in_specs=(spec,),
                               out_specs=spec, check_vma=False))(x)
    zz_np = np.asarray(zz).reshape(-1)
    cs = s // (2 * P_shards)
    expect = []
    for d in range(P_shards):
        expect.extend(range(d * cs, (d + 1) * cs))                    # early
        expect.extend(range((2 * P_shards - 1 - d) * cs,
                            (2 * P_shards - d) * cs))                 # late
    np.testing.assert_array_equal(zz_np, np.asarray(expect, np.float32))
    back = jax.jit(jax.shard_map(unshuffle, mesh=mesh, in_specs=(spec,),
                                 out_specs=spec, check_vma=False))(zz)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


@pytest.mark.parametrize("seq_shards", [3])
def ring_zigzag_odd_shards_test(seq_shards):
    """The zigzag chunk->owner map stays a bijection at odd P; parity incl.
    gradients."""
    mesh = _mesh(seq_shards)
    rng = np.random.default_rng(3)
    b, s, h, d = 1, 24, 2, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
               for _ in range(3))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_reference(q, k, v) ** 2)

    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dense_reference(q, k, v)),
                               rtol=2e-5, atol=2e-5)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def ring_pallas_hops_match_dense_test():
    """The zigzag hop pairs routed through the pallas flash kernels
    (interpret mode on CPU): forward parity vs dense_reference, and vs the
    XLA chunk-scan path.  Chunks must be 128-divisible for the kernels, so
    the shapes here are larger than the other ring tests'."""
    mesh = _mesh(2)
    rng = np.random.default_rng(3)
    b, s, h, d = 1, 512, 2, 32     # cs = s/(2P)·2 = 128-divisible chunks
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out_p = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, use_pallas=True))(q, k, v)
    out_x = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, use_pallas=False))(q, k, v)
    ref = dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)


def ring_pallas_hops_gradients_test():
    """Kernel-path backward: per-hop pallas dq/dk/dv pieces with GLOBAL
    lse/delta must reproduce dense autodiff."""
    mesh = _mesh(2)
    rng = np.random.default_rng(4)
    b, s, h, d = 1, 512, 1, 32
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, use_pallas=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_reference(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


def ring_pallas_indivisible_chunks_fall_back_test():
    """Chunks not 128-divisible: the kernel gate declines even with
    use_pallas=True and the XLA path keeps parity (no crash)."""
    mesh = _mesh(2)
    rng = np.random.default_rng(5)
    b, s, h, d = 1, 64, 2, 16      # cs = 16: not kernel-tileable
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, use_pallas=True))(q, k, v)
    ref = dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def ring_pallas_hops_bf16_test():
    """The production dtype: bf16 q/k/v rotate raw through the kernel hops
    (out/grad partials stay f32 across hops — only the final cast rounds).
    Parity vs the dense reference computed from the same bf16 inputs, at
    bf16-appropriate tolerances."""
    mesh = _mesh(2)
    rng = np.random.default_rng(6)
    b, s, h, d = 1, 512, 2, 32
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, use_pallas=True))(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=0.05, atol=0.05)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, use_pallas=True)
                       .astype(jnp.float32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_reference(q, k, v).astype(jnp.float32) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b_, np.float32),
                                   rtol=0.1, atol=0.25)
