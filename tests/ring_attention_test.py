"""Ring attention vs dense reference on a real multi-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from homebrewnlp_tpu.parallel.ring_attention import dense_reference, ring_attention


def _mesh(seq_shards, data=1):
    devs = np.asarray(jax.devices()[:data * seq_shards]).reshape(data, seq_shards)
    return Mesh(devs, ("data", "sequence"))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq_shards", [2, 4])
def ring_matches_dense_test(causal, seq_shards):
    mesh = _mesh(seq_shards)
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 32, 4, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal))(q, k, v)
    ref = dense_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def ring_gradients_test():
    mesh = _mesh(4)
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 16, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_reference(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def ring_with_2d_mesh_test():
    """data x sequence mesh: batch and sequence sharded simultaneously."""
    mesh = _mesh(4, data=2)
    rng = np.random.default_rng(2)
    b, s, h, d = 4, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    ref = dense_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
