"""Full-model tests: forward/backward across memory strategies, revnet /
momentumnet custom-vjp gradient correctness vs direct autodiff, macro-batch
equivalence.  (The reference has no such tests — SURVEY.md §4 calls out the
gap; these protect the trickiest machinery we have.)
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from backend import make_params
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.model.blocks import momentum_sequence, rev_sequence


def _batch(rng, params):
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {'token_x': jnp.asarray(x),
            'token_y': jnp.asarray((x + 1) % params.vocab_size)}


@pytest.mark.parametrize("strategy", ["none", "checkpoint", "revnet", "momentum"])
def forward_backward_test(strategy):
    params = make_params(memory_reduction_strategy=strategy)
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _batch(rng, params)
    variables = m.init(batch)
    loss, grads = jax.jit(jax.value_and_grad(
        lambda v: m.apply(v, batch).total_loss.data))(variables)
    assert np.isfinite(float(loss))
    for k, g in grads.items():
        assert np.all(np.isfinite(np.asarray(g, np.float32))), k
    # at least one non-zero gradient per block
    gnorm = sum(float(jnp.sum(g.astype(jnp.float32) ** 2)) for g in grads.values())
    assert gnorm > 0


def checkpoint_matches_none_test():
    """Gradient checkpointing must be bit-identical to plain backprop."""
    grads = {}
    for strategy in ("none", "checkpoint"):
        rng = np.random.default_rng(0)
        params = make_params(memory_reduction_strategy=strategy)
        m = Model(params)
        batch = _batch(rng, params)
        variables = m.init(batch)
        _, g = jax.jit(jax.value_and_grad(
            lambda v: m.apply(v, batch).total_loss.data))(variables)
        grads[strategy] = g
    for k in grads["none"]:
        np.testing.assert_allclose(np.asarray(grads["none"][k], np.float32),
                                   np.asarray(grads["checkpoint"][k], np.float32),
                                   rtol=1e-5, atol=1e-6)


def _toy_fns(n, width, key):
    """Simple parameterised blocks y = tanh(x @ W) for sequence tests."""
    keys = jax.random.split(key, n)
    subsets = tuple({"w": jax.random.normal(k, (width, width)) * 0.3} for k in keys)

    def mk(i):
        def f(subset, x):
            return jnp.tanh(x @ subset["w"])
        return f
    return tuple(mk(i) for i in range(n)), subsets


def rev_sequence_grad_test():
    """custom-vjp reversible stack == direct autodiff of the same recurrence."""
    key = jax.random.PRNGKey(0)
    fns, subsets = _toy_fns(4, 8, key)
    x = jax.random.normal(jax.random.fold_in(key, 99), (3, 8))

    def rev_custom(subsets, x):
        a, b = rev_sequence(fns, subsets, x, x)
        return jnp.sum((a + b) ** 2)

    def rev_direct(subsets, x):
        a, b = x, x
        for f, s in zip(fns, subsets):
            a, b = b, a + f(s, b)
        return jnp.sum((a + b) ** 2)

    v1, g1 = jax.value_and_grad(rev_custom, argnums=(0, 1))(subsets, x)
    v2, g2 = jax.value_and_grad(rev_direct, argnums=(0, 1))(subsets, x)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    for t1, t2 in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(t1), np.asarray(t2), rtol=1e-4, atol=1e-5)


def momentum_sequence_grad_test():
    key = jax.random.PRNGKey(1)
    fns, subsets = _toy_fns(4, 8, key)
    x = jax.random.normal(jax.random.fold_in(key, 98), (3, 8))
    alpha = 0.9

    def mom_custom(subsets, x):
        a, b = momentum_sequence(fns, alpha, subsets, x, x)
        return jnp.sum((a + b) ** 2)

    def mom_direct(subsets, x):
        xx, v = x, x
        for f, s in zip(fns, subsets):
            v = v * alpha + f(s, xx) * (1 - alpha)
            xx = xx + v
        return jnp.sum((xx + v) ** 2)

    v1, g1 = jax.value_and_grad(mom_custom, argnums=(0, 1))(subsets, x)
    v2, g2 = jax.value_and_grad(mom_direct, argnums=(0, 1))(subsets, x)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    for t1, t2 in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(t1), np.asarray(t2), rtol=1e-4, atol=1e-5)


def revnet_model_grads_match_direct_test():
    """End-to-end: revnet strategy grads == differentiating the same rev
    recurrence without the custom vjp (strategy none can't be compared — the
    function differs — so compare against an inline non-custom rev stack)."""
    params = make_params(memory_reduction_strategy="revnet", depth=2)
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _batch(rng, params)
    variables = m.init(batch)

    loss_custom, g_custom = jax.jit(jax.value_and_grad(
        lambda v: m.apply(v, batch).total_loss.data))(variables)

    # monkeypatch rev_sequence's custom vjp away by calling the raw python body
    from homebrewnlp_tpu.model import blocks as blocks_mod
    orig = blocks_mod.rev_sequence

    def plain_rev(fns, subsets, x1, x2):
        for f, s in zip(fns, subsets):
            x1, x2 = x2, x1 + f(s, x2)
        return x1, x2

    blocks_mod.rev_sequence = plain_rev
    try:
        loss_plain, g_plain = jax.jit(jax.value_and_grad(
            lambda v: m.apply(v, batch).total_loss.data))(variables)
    finally:
        blocks_mod.rev_sequence = orig

    np.testing.assert_allclose(float(loss_custom), float(loss_plain), rtol=1e-5)
    for k in g_custom:
        np.testing.assert_allclose(np.asarray(g_custom[k], np.float32),
                                   np.asarray(g_plain[k], np.float32),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def shared_grads_accumulate_test():
    """Shared attention-map embeds receive gradient contributions from every
    depth: grad magnitude should not vanish with depth."""
    params = make_params(depth=3, memory_reduction_strategy="revnet")
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = _batch(rng, params)
    variables = m.init(batch)
    _, grads = jax.jit(jax.value_and_grad(
        lambda v: m.apply(v, batch).total_loss.data))(variables)
    shared = [k for k in grads if 'attention' in k and 'embed' in k]
    assert shared and all(float(jnp.sum(jnp.abs(grads[k].astype(jnp.float32)))) > 0
                          for k in shared)
