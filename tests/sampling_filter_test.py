"""Top-k / nucleus (top-p) sampling filters (beyond-reference serving
surface: the reference samples the full distribution only,
/root/reference/src/run/inference.py:88-92)."""
import jax
import jax.numpy as jnp
import numpy as np

from backend import make_params
from homebrewnlp_tpu.infer.sampler import _filter_logits, sample_text
from homebrewnlp_tpu.model import Model

ATTN_BLOCKS = [{"layer": ["norm-shift-scale-features-group",
                          "attention-dot_product-context-in:relu"]}]


def filter_logits_masks_test():
    """Unit semantics on raw logits: top-k keeps exactly the k largest,
    top-p keeps the smallest prefix of the sorted distribution with mass
    >= p, disabled values are identity."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((2, 1, 1, 16)).astype(np.float32))
    tb = jnp.asarray([1.0, 1.0], jnp.float32)

    # disabled -> identity
    out = _filter_logits(logits, tb, jnp.asarray([0, 0], jnp.int32),
                         jnp.asarray([1.0, 1.0], jnp.float32))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(logits))

    # top-k=3 keeps exactly the 3 largest per row
    out = np.asarray(_filter_logits(logits, tb,
                                    jnp.asarray([3, 3], jnp.int32),
                                    jnp.asarray([1.0, 1.0], jnp.float32)))
    for b in range(2):
        row = np.asarray(logits)[b, 0, 0]
        kept = out[b, 0, 0] > -1e29
        assert kept.sum() == 3
        assert set(np.flatnonzero(kept)) == set(np.argsort(row)[-3:])

    # top-p: kept set is the minimal sorted prefix with mass >= p
    p = 0.5
    out = np.asarray(_filter_logits(logits, tb,
                                    jnp.asarray([0, 0], jnp.int32),
                                    jnp.asarray([p, p], jnp.float32)))
    for b in range(2):
        row = np.asarray(logits)[b, 0, 0]
        probs = np.exp(row - row.max())
        probs /= probs.sum()
        order = np.argsort(-row)
        cum = np.cumsum(probs[order])
        n_expect = int(np.searchsorted(cum, p)) + 1
        kept = np.flatnonzero(out[b, 0, 0] > -1e29)
        assert set(kept) == set(order[:n_expect]), (kept, order[:n_expect])

    # per-row: row 0 filtered to k=1, row 1 untouched
    out = np.asarray(_filter_logits(logits, tb,
                                    jnp.asarray([1, 0], jnp.int32),
                                    jnp.asarray([1.0, 1.0], jnp.float32)))
    assert (out[0, 0, 0] > -1e29).sum() == 1
    np.testing.assert_array_equal(out[1], np.asarray(logits)[1])


def filter_temperature_scaling_test():
    """Nucleus mass is computed on softmax(logits / T) — hotter rows spread
    mass, so the same top_p keeps MORE tokens."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(np.repeat(
        rng.standard_normal((1, 1, 1, 32)).astype(np.float32), 2, axis=0))
    out = np.asarray(_filter_logits(
        logits, jnp.asarray([0.3, 3.0], jnp.float32),
        jnp.asarray([0, 0], jnp.int32), jnp.asarray([0.7, 0.7], jnp.float32)))
    cold = (out[0, 0, 0] > -1e29).sum()
    hot = (out[1, 0, 0] > -1e29).sum()
    assert cold < hot, (cold, hot)


def _tiny_model(seed=0):
    params = make_params(block_config=ATTN_BLOCKS,
                         memory_reduction_strategy="none",
                         sequence_length=16, depth=2, heads=2,
                         features_per_head=8, train_batch_size=2,
                         vocab_size=32, use_autoregressive_sampling=True)
    model = Model(params)
    rng = np.random.default_rng(seed)
    token_x = rng.integers(0, params.vocab_size,
                           (2, 16, 1)).astype(np.int32)
    batch = {"token_x": jnp.asarray(token_x), "token_y": jnp.asarray(token_x)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return model, variables, token_x


def top_k1_is_greedy_test():
    """top_k=1 at high temperature must reproduce the greedy stream —
    the strongest end-to-end check that the mask reaches the loop."""
    model, variables, token_x = _tiny_model()
    prompt = token_x[:, :4, 0]
    greedy = sample_text(model, variables, prompt, initial_pos=4,
                         temperature=0.0, seed=7)
    topk1 = sample_text(model, variables, prompt, initial_pos=4,
                        temperature=1.7, top_k=1, seed=7)
    np.testing.assert_array_equal(greedy, topk1)


def top_p_tiny_is_greedy_test():
    """top_p -> 0 keeps only the crossing (max) token: greedy stream."""
    model, variables, token_x = _tiny_model()
    prompt = token_x[:, :4, 0]
    greedy = sample_text(model, variables, prompt, initial_pos=4,
                         temperature=0.0, seed=3)
    nucleus = sample_text(model, variables, prompt, initial_pos=4,
                          temperature=1.3, top_p=1e-6, seed=3)
    np.testing.assert_array_equal(greedy, nucleus)


def disabled_filters_match_plain_path_test():
    """top_k=0 / top_p=1.0 route through the plain (unfiltered) jit kind:
    same tokens as a call that never mentions the filters."""
    model, variables, token_x = _tiny_model()
    prompt = token_x[:, :4, 0]
    plain = sample_text(model, variables, prompt, initial_pos=4,
                        temperature=0.9, seed=11)
    disabled = sample_text(model, variables, prompt, initial_pos=4,
                           temperature=0.9, top_k=0, top_p=1.0, seed=11)
    np.testing.assert_array_equal(plain, disabled)


def per_row_filters_test():
    """Row 0 with top_k=1 must be greedy while row 1 stays stochastic —
    per-request filters in one batched decode call (serving)."""
    model, variables, token_x = _tiny_model()
    prompt = token_x[:, :4, 0]
    greedy = sample_text(model, variables, prompt, initial_pos=4,
                         temperature=0.0, seed=5)
    mixed = sample_text(model, variables, prompt, initial_pos=4,
                        temperature=1.7, top_k=np.asarray([1, 0], np.int32),
                        seed=5)
    np.testing.assert_array_equal(mixed[0], greedy[0])
    assert not np.array_equal(mixed[1], greedy[1])


def top_p_zero_is_greedy_test():
    """top_p=0 (a common client idiom) must be maximally restrictive —
    exactly the argmax survives — not silently disabled (the nkeep clamp)."""
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.standard_normal((2, 1, 1, 16)).astype(np.float32))
    out = np.asarray(_filter_logits(
        logits, jnp.asarray([1.0, 1.0], jnp.float32),
        jnp.asarray([0, 0], jnp.int32), jnp.asarray([0.0, 0.0], jnp.float32)))
    for b in range(2):
        kept = np.flatnonzero(out[b, 0, 0] > -1e29)
        assert list(kept) == [int(np.argmax(np.asarray(logits)[b, 0, 0]))]


def top_k_then_top_p_renormalizes_test():
    """Sequential warper order (HF): the nucleus mass renormalizes over the
    top-k survivors, so top_p can drop low-probability members OF the
    top-k set."""
    # 4 tokens: probs ~ [0.4, 0.3, 0.2, 0.1] at T=1
    base = np.log(np.asarray([0.4, 0.3, 0.2, 0.1], np.float32))
    logits = jnp.asarray(base[None, None, None, :])
    tb = jnp.asarray([1.0], jnp.float32)
    # top_k=3 keeps {0,1,2} with renormalized probs [4/9, 3/9, 2/9];
    # top_p=0.8: prefix mass before token2 = 7/9 = 0.778 < 0.8 -> token2
    # kept; check against top_p=0.7: 0.778 > 0.7 -> token2 dropped
    out_hi = np.asarray(_filter_logits(logits, tb,
                                       jnp.asarray([3], jnp.int32),
                                       jnp.asarray([0.8], jnp.float32)))
    out_lo = np.asarray(_filter_logits(logits, tb,
                                       jnp.asarray([3], jnp.int32),
                                       jnp.asarray([0.7], jnp.float32)))
    assert set(np.flatnonzero(out_hi[0, 0, 0] > -1e29)) == {0, 1, 2}
    assert set(np.flatnonzero(out_lo[0, 0, 0] > -1e29)) == {0, 1}


def batched_serving_uses_config_defaults_test():
    """complete_tokens_batch rows without explicit filters inherit the
    sampling_top_k config default (the operator's serving config must bind
    on the batched path, not only the single-request one)."""
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    model, variables, token_x = _tiny_model()
    model.params.sampling_top_k = 1   # serving default: greedy-equivalent
    try:
        iface = InterfaceWrapper.__new__(InterfaceWrapper)
        iface.params = model.params
        iface.model = model
        iface.variables = variables
        iface.mesh = None
        iface.decode_calls = 0
        iface._model_for_width = lambda w: (None, model)
        prompt = [token_x[0, :4, 0], token_x[1, :4, 0]]
        outs = iface.complete_tokens_batch(prompt, temperatures=[1.7, 1.7],
                                           seed=9)
        greedy = sample_text(model, variables, np.stack(prompt),
                             initial_pos=4, temperature=0.0, seed=9)
        for i in range(2):
            np.testing.assert_array_equal(outs[i][4:],
                                          greedy[i, 4:len(outs[i]), 0])
    finally:
        model.params.sampling_top_k = 0


def repetition_penalty_unit_test():
    """HF semantics: seen tokens' positive logits divide by r, negative
    multiply by r; unseen unchanged; r=1 identity."""
    from homebrewnlp_tpu.infer.sampler import _repetition_penalty
    logits = jnp.asarray([[[[2.0, -2.0, 1.0, -1.0]]]], jnp.float32)
    seen = jnp.asarray([[1.0, 1.0, 0.0, 0.0]], jnp.float32)
    out = np.asarray(_repetition_penalty(
        logits, seen, jnp.asarray([2.0], jnp.float32)))[0, 0, 0]
    np.testing.assert_allclose(out, [1.0, -4.0, 1.0, -1.0])
    out1 = np.asarray(_repetition_penalty(
        logits, seen, jnp.asarray([1.0], jnp.float32)))
    np.testing.assert_array_equal(out1, np.asarray(logits))


def repetition_penalty_kv_full_parity_test():
    """Greedy decode with a strong penalty: the KV sampler (carry-updated
    seen counts) and the full-forward sampler (recomputed per step) are
    independent implementations and must produce identical streams."""
    model, variables, token_x = _tiny_model()
    prompt = token_x[:, :4, 0]
    kw = dict(initial_pos=4, temperature=0.0, repetition_penalty=4.0, seed=2)
    kv = sample_text(model, variables, prompt, use_cache=True, **kw)
    full = sample_text(model, variables, prompt, use_cache=False, **kw)
    np.testing.assert_array_equal(kv, full)
    # and the penalty actually changes the greedy stream (untrained tiny
    # models repeat; a x4 penalty must break the loop)
    plain = sample_text(model, variables, prompt, initial_pos=4,
                        temperature=0.0, seed=2)
    assert not np.array_equal(kv, plain)


def repetition_penalty_empty_prompt_parity_test():
    """initial_pos=0 (empty prompt): the zero_first token at index 0 must be
    counted as seen by BOTH samplers — the kv/full parity edge the prompt
    seeding could miss."""
    model, variables, token_x = _tiny_model()
    prompt = token_x[:, :1, 0] * 0
    kw = dict(initial_pos=0, temperature=0.0, repetition_penalty=4.0, seed=6)
    kv = sample_text(model, variables, prompt, use_cache=True, **kw)
    full = sample_text(model, variables, prompt, use_cache=False, **kw)
    np.testing.assert_array_equal(kv, full)
