"""Telemetry subsystem (marker: telemetry; docs/OBSERVABILITY.md).

Unit sweep: registry semantics, Prometheus text-exposition conformance
(rendered text is parsed BACK and checked against the snapshot), histogram
bucket accounting under concurrent writers, snapshot merge/JSONL/summary
renderers, span -> histogram + chrome trace, the on-demand profiler state
machine, the MetricLogger monotonic-clock fix, and the per-layer wiring
(prefetcher, retry sites, checkpoint IO).

Integration sweep: a train smoke run emitting the data-wait / dispatch /
device-block step-phase breakdown (and ZERO phase series when
``telemetry_enabled`` is false), SIGUSR2-triggered profile capture, and —
device-free, on the serving_robustness_test harness — ``GET /metrics``
answering valid exposition from the HTTP child while the device loop is
wedged inside a decode."""
import json
import math
import os
import re
import signal
import threading
import time
import urllib.request

import numpy as np
import pytest

from homebrewnlp_tpu import telemetry
from homebrewnlp_tpu.config import ModelParameter

pytestmark = pytest.mark.telemetry


@pytest.fixture
def fresh_registry():
    prev = telemetry.set_registry(telemetry.Registry())
    yield telemetry.registry()
    telemetry.set_registry(prev)


# ---------------------------------------------------------------- unit sweep

def registry_basics_test():
    r = telemetry.Registry()
    c = r.counter("c_total", "a counter", ("site",))
    c.labels(site="gcs").inc()
    c.labels("gcs").inc(2.5)        # positional and kwargs name the same series
    with pytest.raises(ValueError):
        c.labels(site="gcs").inc(-1)  # counters only go up
    with pytest.raises(ValueError):
        c.inc()                       # labelled metric needs labels()
    g = r.gauge("g")
    g.set(3)
    g.set(1.5)
    h = r.histogram("h_seconds", buckets=(1.0, 2.0))
    h.observe(0.5)
    h.observe(1.0)   # le is INCLUSIVE: lands in the 1.0 bucket
    h.observe(99.0)  # +Inf bucket
    with pytest.raises(TypeError):
        g.observe(1.0)
    with pytest.raises(ValueError):
        r.counter("g")  # kind mismatch on re-registration
    snap = r.snapshot()
    assert snap["c_total"]["series"][("gcs",)] == 3.5
    assert snap["g"]["series"][()] == 1.5
    assert snap["h_seconds"]["series"][()]["counts"] == [2, 0, 1]
    assert snap["h_seconds"]["series"][()]["sum"] == pytest.approx(100.5)
    # same name + kind returns the same metric (idempotent registration)
    assert r.counter("c_total", labelnames=("site",)) is c


def _parse_exposition(text: str):
    """Minimal conformance parser for the text format: returns
    ({name: kind}, {(name, labelstring): value}) and asserts line shape."""
    types, series = {}, {}
    for line in text.strip().split("\n"):
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            assert kind in ("counter", "gauge", "histogram")
            types[name] = kind
        elif line.startswith("#"):
            assert line.startswith("# HELP "), line
        else:
            m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                         r"(?:\{(.*)\})? (\S+)$", line)
            assert m, f"malformed sample line: {line!r}"
            name, labels, value = m.groups()
            series[(name, labels or "")] = float(value)
    return types, series


def prometheus_exposition_conformance_test():
    """Render -> parse back -> the parsed samples match the snapshot:
    counter/gauge values, INCLUSIVE cumulative histogram buckets, +Inf
    bucket == _count, _sum, and label-value escaping."""
    r = telemetry.Registry()
    r.counter("req_total", "requests", ("path", "code")) \
        .labels(path="/x", code="200").inc(7)
    r.gauge("depth", "queue depth").set(4)
    weird = 'a"b\\c\nd'
    r.counter("esc_total", "escaping", ("v",)).labels(v=weird).inc()
    h = r.histogram("lat_seconds", "latency", ("op",), buckets=(0.1, 1, 10))
    for v in (0.05, 0.1, 0.5, 3.0, 99.0):
        h.labels(op="read").observe(v)
    text = telemetry.prometheus_text(r.snapshot())
    types, series = _parse_exposition(text)
    assert types == {"req_total": "counter", "depth": "gauge",
                     "esc_total": "counter", "lat_seconds": "histogram"}
    assert series[("req_total", 'path="/x",code="200"')] == 7
    assert series[("depth", "")] == 4
    # escaped label value appears exactly per the format rules
    assert ('esc_total', 'v="a\\"b\\\\c\\nd"') in series
    # cumulative buckets: 0.1 is inclusive (2 of 0.05,0.1), then 3 <= 1, etc.
    assert series[("lat_seconds_bucket", 'op="read",le="0.1"')] == 2
    assert series[("lat_seconds_bucket", 'op="read",le="1"')] == 3
    assert series[("lat_seconds_bucket", 'op="read",le="10"')] == 4
    assert series[("lat_seconds_bucket", 'op="read",le="+Inf"')] == 5
    assert series[("lat_seconds_count", 'op="read"')] == 5
    assert series[("lat_seconds_sum", 'op="read"')] == pytest.approx(102.65)
    cum = [series[("lat_seconds_bucket", f'op="read",le="{b}"')]
           for b in ("0.1", "1", "10", "+Inf")]
    assert cum == sorted(cum), "bucket counts must be cumulative-monotone"


def histogram_concurrent_writers_test():
    """Bucket accounting stays exact under concurrent writers: total count,
    per-bucket sums, and the sum of observations all reconcile."""
    r = telemetry.Registry()
    h = r.histogram("conc_seconds", buckets=(0.25, 0.5, 0.75))
    c = r.counter("conc_total")
    threads, per_thread = 8, 2000
    values = [i / per_thread for i in range(per_thread)]  # 0 .. 0.9995

    def work():
        child = r.histogram("conc_seconds").labels()
        for v in values:
            child.observe(v)
            c.inc()

    ts = [threading.Thread(target=work) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = r.snapshot()
    state = snap["conc_seconds"]["series"][()]
    n = threads * per_thread
    assert sum(state["counts"]) == n
    assert snap["conc_total"]["series"][()] == n
    # each quarter-bucket holds exactly threads * per_thread/4 observations
    # (le inclusive: 0.25 itself lands in the first bucket)
    expect = threads * (per_thread // 4)
    assert state["counts"] == [expect + threads, expect, expect,
                               expect - threads]
    assert state["sum"] == pytest.approx(threads * sum(values))


def merge_and_render_test():
    ra, rb = telemetry.Registry(), telemetry.Registry()
    ra.counter("n_total").inc(2)
    rb.counter("n_total").inc(3)
    ra.gauge("g").set(1)
    rb.gauge("g").set(9)
    ha = ra.histogram("h", buckets=(1,))
    hb = rb.histogram("h", buckets=(1,))
    ha.observe(0.5)
    hb.observe(2.0)
    merged = telemetry.merge_snapshots(ra.snapshot(), rb.snapshot())
    assert merged["n_total"]["series"][()] == 5     # counters sum
    assert merged["g"]["series"][()] == 9           # gauges: later wins
    assert merged["h"]["series"][()]["counts"] == [1, 1]
    assert merged["h"]["series"][()]["sum"] == 2.5
    # JSONL line round-trips through json with flat series keys
    line = telemetry.jsonl_line(merged, step=7)
    doc = json.loads(line)
    assert doc["step"] == 7
    assert doc["metrics"]["n_total"]["series"][""] == 5
    assert doc["metrics"]["h"]["series"][""]["count"] == 2
    # summarize: flat keys, histogram medians
    summary = telemetry.summarize(merged)
    assert summary["n_total"] == 5
    assert summary["h"]["count"] == 2 and summary["h"]["p50"] == 1.0
    assert telemetry.histogram_quantile((1.0,), [0, 0], 0.5) is None


def merged_histogram_inf_cumulativity_test():
    """Exposition of a MERGED snapshot stays conformant: bucket counts are
    cumulative-monotone and the +Inf bucket equals _count equals the total
    observation count across both source processes (the /metrics scrape
    path renders merge_snapshots output, so the invariant must survive the
    merge, not just a single registry)."""
    ra, rb = telemetry.Registry(), telemetry.Registry()
    ha = ra.histogram("m_seconds", "merged", ("op",), buckets=(0.1, 1, 10))
    hb = rb.histogram("m_seconds", "merged", ("op",), buckets=(0.1, 1, 10))
    for v in (0.05, 0.5, 99.0):
        ha.labels(op="w").observe(v)
    for v in (0.1, 3.0, 50.0, 7.0):       # 0.1 inclusive in first bucket
        hb.labels(op="w").observe(v)
    # the multi-snapshot prometheus_text path merges internally
    types, series = _parse_exposition(
        telemetry.prometheus_text(ra.snapshot(), rb.snapshot()))
    assert types["m_seconds"] == "histogram"
    cum = [series[("m_seconds_bucket", f'op="w",le="{b}"')]
           for b in ("0.1", "1", "10", "+Inf")]
    assert cum == [2, 3, 5, 7]            # monotone, both processes summed
    assert series[("m_seconds_bucket", 'op="w",le="+Inf"')] \
        == series[("m_seconds_count", 'op="w"')] == 7
    assert series[("m_seconds_sum", 'op="w"')] == pytest.approx(159.65)


def merge_bucket_mismatch_rejected_test():
    """Snapshots whose histograms disagree on bucket boundaries refuse to
    merge (a silent zip() would drop counts from the longer list)."""
    ra, rb = telemetry.Registry(), telemetry.Registry()
    ra.histogram("mm_seconds", buckets=(1.0, 2.0)).observe(0.5)
    rb.histogram("mm_seconds", buckets=(1.0, 2.0, 4.0)).observe(0.5)
    with pytest.raises(ValueError, match="mm_seconds.*bucket"):
        telemetry.merge_snapshots(ra.snapshot(), rb.snapshot())


def help_and_label_escaping_test():
    """Format 0.0.4 has TWO escaping rules: HELP text escapes only
    backslash and line feed (a double quote passes through verbatim);
    label values additionally escape the double quote."""
    r = telemetry.Registry()
    weird = 'say "hi"\\\n done'
    r.counter("esc2_total", weird, ("v",)).labels(v=weird).inc()
    text = telemetry.prometheus_text(r.snapshot())
    help_line = [ln for ln in text.splitlines()
                 if ln.startswith("# HELP esc2_total ")][0]
    assert help_line == '# HELP esc2_total say "hi"\\\\\\n done'
    sample = [ln for ln in text.splitlines()
              if ln.startswith("esc2_total{")][0]
    assert sample == 'esc2_total{v="say \\"hi\\"\\\\\\n done"} 1'
    # exposition stays one-line-per-sample: no raw newline leaked
    assert all("\n" not in ln for ln in (help_line, sample))


def gauge_last_wins_interleaved_test():
    """Gauge merge semantics under interleaved publishes from two
    processes: the LAST snapshot argument wins per series — even when its
    value is 0/falsy — series absent from later snapshots survive from
    earlier ones, and counters keep summing regardless of order."""
    dev, child = telemetry.Registry(), telemetry.Registry()
    g_dev = dev.gauge("depth", "queue depth", ("q",))
    g_child = child.gauge("depth", "queue depth", ("q",))
    c_dev, c_child = dev.counter("n_total"), child.counter("n_total")
    g_dev.labels(q="a").set(5)
    g_dev.labels(q="b").set(7)          # only the device loop publishes b
    c_dev.inc(2)
    snap_dev1 = dev.snapshot()
    g_child.labels(q="a").set(3)
    c_child.inc(1)
    snap_child = child.snapshot()
    g_dev.labels(q="a").set(0)          # falsy newest value must still win
    c_dev.inc(4)
    snap_dev2 = dev.snapshot()

    # scrape 1 lands between the two device publishes: child passed last
    m1 = telemetry.merge_snapshots(snap_dev1, snap_child)
    assert m1["depth"]["series"][("a",)] == 3       # later argument wins
    assert m1["depth"]["series"][("b",)] == 7       # absent later: survives
    assert m1["n_total"]["series"][()] == 3         # counters sum
    # scrape 2 sees the fresher device publish last: its 0 must still win
    m2 = telemetry.merge_snapshots(snap_child, snap_dev2)
    assert m2["depth"]["series"][("a",)] == 0
    assert m2["depth"]["series"][("b",)] == 7
    assert m2["n_total"]["series"][()] == 7
    # argument order IS the tiebreak: same snapshots, flipped, flip the gauge
    assert telemetry.merge_snapshots(
        snap_dev2, snap_child)["depth"]["series"][("a",)] == 3


def span_and_chrome_trace_test():
    r = telemetry.Registry()
    trace = telemetry.ChromeTrace(max_events=3)
    clock = iter([1.0, 1.25]).__next__
    with telemetry.span("ckpt/save", r, trace, clock=clock):
        pass
    snap = r.snapshot()
    state = snap[telemetry.SPAN_METRIC]["series"][("ckpt/save",)]
    assert sum(state["counts"]) == 1 and state["sum"] == pytest.approx(0.25)
    for i in range(5):  # bounded: only the last 3 survive (ckpt/save evicted)
        trace.add(f"s{i}", float(i), 0.5)
    events = trace.events()
    assert [e["name"] for e in events] == ["s2", "s3", "s4"]
    assert events[0]["ph"] == "X" and events[0]["dur"] == 500000.0
    phases = telemetry.StepPhases(registry=r, trace=trace)
    phases.device_block.rec(9.0, 0.125)
    assert snap is not r.snapshot()  # snapshot is a copy, not a live view
    got = r.snapshot()[telemetry.SPAN_METRIC]["series"]
    assert ("train/device_block",) in got


def on_demand_profiler_test(tmp_path):
    calls = []
    p = telemetry.OnDemandProfiler(str(tmp_path), capture_steps=3,
                                   start=lambda d: calls.append(("start", d)),
                                   stop=lambda: calls.append(("stop",)))
    p.poll(0)
    assert calls == []          # nothing requested: zero cost
    p.request()
    p.poll(10)                  # starts at the next poll
    assert p.active and calls == [("start", str(tmp_path) + "/on_demand_10")]
    p.poll(11)
    p.poll(12)
    assert p.active             # 10 + 3 not reached
    p.poll(13)
    assert not p.active and calls[-1] == ("stop",)
    p.request()
    p.poll(20)
    p.request()                 # second request while active = stop early
    p.poll(21)
    assert not p.active and calls[-1] == ("stop",)
    # a failing start is reported, never fatal, and leaves it inactive
    boom = telemetry.OnDemandProfiler(
        str(tmp_path), start=lambda d: (_ for _ in ()).throw(RuntimeError()))
    boom.request()
    boom.poll(0)
    assert not boom.active


def metric_logger_monotonic_test(tmp_path):
    """steps_per_sec comes off an injectable monotonic clock: a wall-clock
    step (NTP) between logs can no longer produce negative rates."""
    from homebrewnlp_tpu.train.metrics import MetricLogger
    t = [100.0]
    logger = MetricLogger(str(tmp_path), enable_tb=False,
                          clock=lambda: t[0])
    logger.log(1, {"loss": 1.0}, tokens_per_step=10)
    t[0] += 2.0
    logger.log(3, {"loss": 0.9}, tokens_per_step=10)
    logger.flush()
    logger.close()
    logger.close()  # idempotent: the emergency path closes eagerly
    lines = [json.loads(x) for x in
             open(os.path.join(tmp_path, "metrics.jsonl"))]
    assert "steps_per_sec" not in lines[0]
    assert lines[1]["steps_per_sec"] == pytest.approx(1.0)
    assert lines[1]["tokens_per_sec"] == pytest.approx(10.0)
    assert lines[1]["wall"] == pytest.approx(2.0)


def prefetcher_telemetry_gating_test(fresh_registry):
    from homebrewnlp_tpu.data.inputs import Prefetcher
    # no label (the telemetry_enabled=false path): ZERO registry calls
    list(Prefetcher(iter(range(4)), depth=2))
    assert fresh_registry.snapshot() == {}
    out = list(Prefetcher(iter(range(5)), depth=2, telemetry_label="train"))
    assert out == list(range(5))
    snap = fresh_registry.snapshot()
    assert snap["hbnlp_prefetch_items_total"]["series"][("train",)] == 5
    assert ("train",) in snap["hbnlp_prefetch_queue_depth"]["series"]


def retry_site_counters_test(fresh_registry):
    from homebrewnlp_tpu.utils.retry import RetryPolicy, TransientError
    policy = RetryPolicy(max_attempts=3, base_delay=0.0, sleep=lambda s: None)
    boom = [0]

    def flaky():
        boom[0] += 1
        if boom[0] < 3:
            raise TransientError("blip")
        return "ok"

    assert policy.call(flaky, site="gcs") == "ok"
    with pytest.raises(FileNotFoundError):
        policy.call(lambda: (_ for _ in ()).throw(FileNotFoundError("x")),
                    site="checkpoint")
    with pytest.raises(TransientError):
        policy.call(lambda: (_ for _ in ()).throw(TransientError("down")),
                    site="gcs")
    snap = fresh_registry.snapshot()
    assert snap["hbnlp_storage_retries_total"]["series"][("gcs",)] == 4
    fails = snap["hbnlp_storage_failures_total"]["series"]
    assert fails[("checkpoint", "permanent")] == 1
    assert fails[("gcs", "exhausted")] == 1


def checkpoint_io_metrics_test(tmp_path, fresh_registry, monkeypatch):
    """Checkpoint saves/restores record bytes, durations, and crc failures
    into the registry (always on — checkpoint cadence, not the hot path)."""
    from homebrewnlp_tpu.train import checkpoint as ckpt
    monkeypatch.setattr(ckpt, "_metrics_cache", None)  # rebind to fresh reg
    variables = {"w": np.arange(8, dtype=np.float32)}
    opt = {"m": {"w": np.zeros(8, np.float32)}}
    d = str(tmp_path / "run")
    ckpt.save(d, 3, variables, opt, max_keep=2)
    restored = ckpt.restore(d)
    assert restored is not None and restored[2] == 3
    snap = fresh_registry.snapshot()
    per_op = snap["hbnlp_checkpoint_bytes_total"]["series"]
    assert per_op[("write",)] >= 64 and per_op[("read",)] >= 64
    secs = snap["hbnlp_checkpoint_seconds"]["series"]
    assert sum(secs[("save",)]["counts"]) == 1
    assert sum(secs[("restore",)]["counts"]) == 1
    # flip one payload byte -> crc failure counter + CheckpointError
    target = os.path.join(d, "ckpt_3", "arr_000000.bin")
    blob = bytearray(open(target, "rb").read())
    blob[0] ^= 0xFF
    open(target, "wb").write(bytes(blob))
    with pytest.raises(ckpt.CheckpointError, match="verification"):
        ckpt.restore(d)
    snap = fresh_registry.snapshot()
    assert snap["hbnlp_checkpoint_crc_failures_total"]["series"][()] == 1


# -------------------------------------------------------- integration sweep

def train_step_phase_breakdown_test(tmp_path, fresh_registry):
    """Tentpole acceptance: with telemetry on, a train smoke run emits the
    data-wait / dispatch / device-block step-phase breakdown, prefetcher
    series, a telemetry.jsonl trajectory and a chrome trace; with it off,
    the registry sees ZERO calls from the whole run — INCLUDING from the
    event layer, whose flight recorder keeps recording (rare-event cadence
    only: step records at the log cadence, never per step, never into the
    registry)."""
    from robustness_test import _train_cfg, _write_records
    from homebrewnlp_tpu.run import train_loop as tl
    from homebrewnlp_tpu.telemetry import events as flight

    data_dir = _write_records(tmp_path)
    cfg = _train_cfg(tmp_path, data_dir, use_checkpointing=False)
    prev_rec = flight.set_recorder()
    try:
        result = tl.train(ModelParameter(cfg), log_every=2)
        assert result["final_step"] == cfg["train_steps"]
        assert fresh_registry.snapshot() == {}, \
            "telemetry_enabled=false must make zero registry calls " \
            "(event layer included)"
        # the flight recorder recorded UNCONDITIONALLY — but at rare-event
        # cadence: step events ride the log cadence, not the hot path
        rec = flight.recorder()
        kinds = {e["kind"] for e in rec.events()}
        assert {"run_start", "exit"} <= kinds, kinds
        steps = [e for e in rec.events() if e["kind"] == "step"]
        assert 0 < len(steps) <= cfg["train_steps"] // 2 + 1, len(steps)
        assert steps[-1]["loss"] is not None
        # ... and the blackbox dump landed on the normal exit path
        bb = os.path.join(cfg["model_path"], "blackbox_p0.jsonl")
        lines = [json.loads(x) for x in open(bb)]
        assert lines[0]["blackbox"]["tag"] == "p0"
        exits = [x for x in lines if x.get("kind") == "exit"]
        assert exits and exits[-1]["reason"] == "ok"
    finally:
        flight.set_recorder(prev_rec)

    cfg = _train_cfg(tmp_path, data_dir, use_checkpointing=False,
                     model_path=str(tmp_path / "run2"),
                     telemetry_enabled=True,
                     telemetry_jsonl_interval_s=1e-6,
                     telemetry_chrome_trace_events=1000)
    result = tl.train(ModelParameter(cfg), log_every=2)
    assert result["final_step"] == cfg["train_steps"]
    snap = fresh_registry.snapshot()
    spans = snap[telemetry.SPAN_METRIC]["series"]
    steps = cfg["train_steps"]
    for phase in ("train/data_wait", "train/dispatch", "train/device_block"):
        state = spans[(phase,)]
        # first_batch is fetched before the loop: data_wait sees steps - 1
        assert sum(state["counts"]) >= steps - 1, phase
        assert state["sum"] >= 0
    assert snap["hbnlp_prefetch_items_total"]["series"][("train",)] >= steps
    # live MFU + token throughput (docs/OBSERVABILITY.md 'Cost
    # attribution'): a real utilization in (0, 1] and every consumed token
    # counted; the build-info gauge identifies the run
    assert 0 < snap["hbnlp_train_mfu"]["series"][()] <= 1
    tokens_per_step = (cfg["train_batch_size"] * cfg["sequence_length"]
                       * max(1, cfg.get("macro_batching", 1)))
    assert snap["hbnlp_train_tokens_total"]["series"][()] \
        == steps * tokens_per_step
    build_series = snap["hbnlp_build_info"]["series"]
    assert len(build_series) == 1 and list(build_series.values()) == [1]
    # the JSONL trajectory parses and carries the span series; its header
    # line joins the file to the build that wrote it
    jsonl = os.path.join(cfg["model_path"], "telemetry.jsonl")
    lines = [json.loads(x) for x in open(jsonl)]
    assert set(lines[0]["build_info"]) == {"git_rev", "jax_version",
                                           "backend", "device_kind"}
    assert lines and telemetry.SPAN_METRIC in lines[-1]["metrics"]
    assert lines[-1]["step"] == steps
    assert "hbnlp_train_mfu" in lines[-1]["metrics"]
    # the chrome trace is valid and its spans carry durations
    trace = json.load(open(os.path.join(cfg["model_path"],
                                        "telemetry_trace.json")))
    assert len(trace) >= 3 * (steps - 1)
    assert {e["name"] for e in trace} >= {"train/data_wait",
                                          "train/dispatch",
                                          "train/device_block"}
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in trace)


def sigusr2_profile_capture_test(tmp_path, fresh_registry, monkeypatch):
    """telemetry_profile_on_signal: SIGUSR2 mid-run starts a jax.profiler
    capture at the next loop tick and stops it telemetry_profile_steps
    steps later, under <model_path>/profile/on_demand_<step>."""
    import jax
    from robustness_test import _train_cfg, _write_records
    import homebrewnlp_tpu.train.metrics as metrics_mod
    from homebrewnlp_tpu.run import train_loop as tl

    captures = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d, **k: captures.append(["start", d]))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: captures.append(["stop"]))
    orig_log = metrics_mod.MetricLogger.log
    fired = []

    def log_then_signal(self, step, *a, **k):
        orig_log(self, step, *a, **k)
        if step >= 2 and not fired:
            fired.append(step)
            signal.raise_signal(signal.SIGUSR2)

    monkeypatch.setattr(metrics_mod.MetricLogger, "log", log_then_signal)
    cfg = _train_cfg(tmp_path, _write_records(tmp_path),
                     use_checkpointing=False,
                     telemetry_profile_on_signal=True,
                     telemetry_profile_steps=2)
    result = tl.train(ModelParameter(cfg), log_every=1)
    assert result["final_step"] == cfg["train_steps"]
    assert ["stop"] in captures
    starts = [c for c in captures if c[0] == "start"]
    assert len(starts) == 1
    assert starts[0][1].startswith(os.path.join(cfg["model_path"],
                                                "profile", "on_demand_"))
    # the handler was uninstalled on the way out
    assert signal.getsignal(signal.SIGUSR2) in (signal.SIG_DFL,
                                                signal.default_int_handler)


@pytest.mark.serving
def metrics_endpoint_under_wedged_decode_test():
    """Satellite acceptance: GET /metrics serves valid Prometheus text
    exposition from the HTTP child WITHOUT crossing the device loop — it
    answers (with admission counters, queue/breaker gauges, and the device
    loop's decode histograms merged from the heartbeat-published snapshot)
    while the device loop is wedged inside a decode."""
    from serving_robustness_test import (_StubInterface, _post, _serve_params,
                                         _spawn_serve)
    from homebrewnlp_tpu.utils.fault_injection import FaultyInterface

    params = _serve_params(serve_queue_limit=2, serve_batch_size=1,
                           serve_breaker_threshold=0,
                           serve_request_deadline_s=8.0)
    release = threading.Event()
    faulty = FaultyInterface(_StubInterface(params), block_on=release,
                             block_at={1}, block_timeout_s=30.0)
    port, stop, t = _spawn_serve(faulty)

    def scrape():
        req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            assert "version=0.0.4" in resp.headers["Content-Type"]
            return resp.read().decode()

    try:
        _post(port, "/health", {})     # wait for the server to come up
        types, series = _parse_exposition(scrape())
        assert types["hbnlp_serve_admission_total"] == "counter"
        assert types["hbnlp_serve_queue_depth"] == "gauge"
        assert types["hbnlp_serve_breaker_state"] == "gauge"
        assert series[("hbnlp_serve_breaker_state", "")] == 0

        # one successful decode -> the device loop's histograms reach the
        # child through the published snapshot
        status, out, _ = _post(port, "/token_completion", {"tokens": [1, 2]})
        assert status == 200
        deadline = time.monotonic() + 10
        while True:   # published on the next device-loop poll
            types, series = _parse_exposition(scrape())
            if series.get(("hbnlp_serve_decode_seconds_count", "")):
                break
            assert time.monotonic() < deadline
            time.sleep(0.1)
        assert series[("hbnlp_serve_decode_calls_total", "")] >= 1
        assert series[("hbnlp_serve_queue_wait_seconds_count", "")] >= 1
        assert series[("hbnlp_serve_batch_size_count", "")] >= 1
        assert series[("hbnlp_serve_admission_total",
                       'decision="accepted"')] >= 1

        # wedge the device loop inside a decode; /metrics must still answer
        results = {}
        th = threading.Thread(
            target=lambda: results.update(
                w=_post(port, "/token_completion", {"tokens": [3]},
                        timeout=25)),
            daemon=True)
        th.start()
        deadline = time.monotonic() + 10
        while faulty.calls < 2:        # the wedged decode is now in flight
            assert time.monotonic() < deadline
            time.sleep(0.01)
        t0 = time.monotonic()
        types, series = _parse_exposition(scrape())
        assert time.monotonic() - t0 < 2.0, "scrape crossed the device loop"
        assert series[("hbnlp_serve_admission_total",
                       'decision="accepted"')] >= 2
        # POST works too (text exposition, so not via the JSON _post helper)
        req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics",
                                     data=b"{}",
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            _parse_exposition(resp.read().decode())
        release.set()
        th.join(timeout=15)
        assert results["w"][0] == 200
    finally:
        release.set()
        stop.set()
        t.join(timeout=15)
    assert not t.is_alive()


def in_process_metrics_handler_test(fresh_registry):
    """The non-isolated branch serves /metrics from the local registry via
    the shared handlers table (no IPC state exists in-process)."""
    from serving_robustness_test import _StubInterface, _serve_params
    from homebrewnlp_tpu.infer import rest_api
    import homebrewnlp_tpu.infer.rest_api as ra
    # rebind the lazily-cached serve metrics to the fresh registry
    prev = ra._SERVE_METRICS
    ra._SERVE_METRICS = None
    try:
        stub = _StubInterface(_serve_params())
        handlers = rest_api._handlers(stub)
        handlers["/token_completion"]({"tokens": [1, 2]})
        out = handlers["/metrics"]({})
        types, series = _parse_exposition(out["_prometheus"])
        assert types["hbnlp_serve_decode_seconds"] == "histogram"
        assert series[("hbnlp_serve_decode_seconds_count", "")] == 1
        assert series[("hbnlp_serve_tokens_per_second_count", "")] == 1
    finally:
        ra._SERVE_METRICS = prev
