"""Flight recorder + cross-process request tracing (marker ``forensics``;
docs/OBSERVABILITY.md 'Flight recorder' / 'Request tracing', ISSUE 15).

Three tiers:

- **Unit sweep** (device-free): the bounded event ring + blackbox dump
  discipline, size-capped jsonl rotation, trace-context header/coverage/
  hop math, the forensics causal merge (KV-observed orderings beating a
  skewed wall clock), the straggler detector state machine on a fake KV,
  and breaker-trip events.
- **Tracing e2e** (slow, real model): a single continuous-engine
  deployment served twice — tracing off vs on — proving greedy output
  stays BYTE-IDENTICAL, plus a real 2-replica tier where one client
  request's trace id lands in the router's, the replica HTTP child's, and
  the engine device loop's event files, with the merged per-request spans
  covering >= 95% of measured client wall time.
- **Forensics e2e** (slow): SIGKILL one rank of a 4-process elastic fleet
  (the tests/elastic_test.py worker); ``scripts/forensics.py`` over the
  surviving blackboxes reconstructs the incident — names the killed rank,
  orders the survivors' lease-lapse observations, shows the membership
  exits — with every survivor's ring flushed through the exit-144
  force-exit path.  A second fleet test artificially delays one rank and
  asserts the chief's straggler detector flags it BEFORE any lease lapse.
"""
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
sys.path.insert(0, os.path.join(HERE, "..", "scripts"))

import forensics  # noqa: E402  (scripts/forensics.py — jax-free)
from homebrewnlp_tpu.telemetry import events as flight  # noqa: E402
from homebrewnlp_tpu.telemetry import tracectx  # noqa: E402
from homebrewnlp_tpu.telemetry.events import (FlightRecorder,  # noqa: E402
                                              RotatingJsonl)

pytestmark = pytest.mark.forensics

WORKER = os.path.join(HERE, "_elastic_train_worker.py")


@pytest.fixture
def fresh_recorder():
    prev = flight.set_recorder()
    yield flight.recorder()
    flight.set_recorder(prev)


# ------------------------------------------------------------------ ring/dump

def flight_recorder_ring_test(tmp_path):
    """Bounded ring, monotone seq, dump format, throttled re-flush."""
    clock = [10.0]
    rec = FlightRecorder(capacity=4, clock=lambda: clock[0],
                         wall=lambda: clock[0] + 1000)
    for i in range(7):
        rec.record("step", step=i)
    evs = rec.events()
    assert len(evs) == 4 and [e["step"] for e in evs] == [3, 4, 5, 6]
    assert [e["seq"] for e in evs] == [4, 5, 6, 7]  # seq survives eviction
    assert rec.flush() is None                      # unconfigured: no dump
    rec.configure(str(tmp_path), "p3")
    path = rec.flush(reason="test")
    lines = [json.loads(x) for x in open(path)]
    assert lines[0]["blackbox"]["tag"] == "p3"
    assert [x["kind"] for x in lines[1:]] == ["step"] * 4
    assert all(x["proc"] == "p3" for x in lines[1:])
    # throttle: clean ring -> no dump; dirty + interval elapsed -> dump
    assert rec.maybe_flush(0.0) is None
    rec.record("exit", code=0)
    assert rec.maybe_flush(60.0) is None            # within the interval
    clock[0] += 61.0
    assert rec.maybe_flush(60.0) == path
    # capacity 0 = dump disabled (ring keeps recording in-memory)
    off = FlightRecorder()
    off.configure(str(tmp_path), "poff", capacity=0)
    off.record("x")
    assert off.flush() is None and len(off.events()) == 1
    # non-JSON field values degrade to str instead of failing the dump
    rec.record("odd", obj=object())
    assert isinstance(rec.events("odd")[0]["obj"], str)


def rotating_jsonl_test(tmp_path):
    """telemetry.jsonl growth satellite: past the cap the file rotates to
    .1/.2 keeping N generations, each opening with the header line."""
    path = str(tmp_path / "telemetry.jsonl")
    w = RotatingJsonl(path, max_mb=0.0001, keep=2, header='{"build": 1}')
    for i in range(120):
        w.write(json.dumps({"i": i, "pad": "x" * 40}))
    w.close()
    assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")          # beyond keep: deleted
    for p in (path, path + ".1", path + ".2"):
        assert json.loads(open(p).readline()) == {"build": 1}
    # an operator SHRINKING keep across a restart: orphans from the old
    # setting are reclaimed on the next rotation, not leaked forever
    for i in (3, 4, 5):
        open(f"{path}.{i}", "w").write("orphan\n")
    w2 = RotatingJsonl(path, max_mb=0.0001, keep=2, header='{"build": 1}')
    for i in range(120):
        w2.write(json.dumps({"i": i, "pad": "x" * 40}))
    w2.close()
    assert not any(os.path.exists(f"{path}.{i}") for i in (3, 4, 5))
    # cap 0 = unbounded, no rotation artifacts
    p2 = str(tmp_path / "unbounded.jsonl")
    w2 = RotatingJsonl(p2, max_mb=0.0, keep=2, header='{"build": 2}')
    for i in range(50):
        w2.write(json.dumps({"i": i}))
    w2.close()
    assert not os.path.exists(p2 + ".1")


def tracectx_unit_test(tmp_path):
    """Header extraction (case-insensitive, length-capped), span math:
    hop totals, interval-union coverage, chrome export."""
    assert tracectx.trace_id_from_headers(
        {"X-HBNLP-Trace-Id": "abc123"}) == "abc123"
    assert tracectx.trace_id_from_headers(
        {"x-hbnlp-trace-id": "abc123"}) == "abc123"
    assert tracectx.trace_id_from_headers({}) is None
    assert tracectx.trace_id_from_headers(None) is None
    assert tracectx.trace_id_from_headers(
        {"x-hbnlp-trace-id": "z" * 99}) is None     # hostile length
    # a client id becomes a server-side filename: path characters are
    # malformed, the edge mints a fresh id instead
    for evil in ("a/../b", "a.b", "a b", "..", "a\\b"):
        assert tracectx.trace_id_from_headers(
            {"x-hbnlp-trace-id": evil}) is None, evil
    a, b = tracectx.new_trace_id(), tracectx.new_trace_id()
    assert a != b and len(a) == 32
    t = tracectx.RequestTrace("tid1", rid="r1")
    t.add("queue_wait", 0.0, 1.0)
    t.add("chunk/prefill", 1.0, 0.25)
    t.add("chunk/decode", 1.25, 0.5)
    t.add("chunk/decode", 1.75, 0.25)
    assert t.hops() == {"queue_wait": 1.0, "prefill": 0.25, "decode": 0.75}
    assert abs(tracectx.coverage(t.spans, 0.0, 2.0) - 1.0) < 1e-9
    assert abs(tracectx.coverage(t.spans, 0.0, 4.0) - 0.5) < 1e-9
    # overlapping spans must not double-count
    t.add("request", 0.0, 2.0)
    assert abs(tracectx.coverage(t.spans, 0.0, 4.0) - 0.5) < 1e-9
    path = t.dump(str(tmp_path / "traces"))
    payload = json.load(open(path))
    assert payload["trace_id"] == "tid1" and payload["rid"] == "r1"
    assert payload["hops"]["decode"] == 0.75
    assert all(ev["ph"] == "X" for ev in payload["traceEvents"])


def record_span_cross_process_form_test(fresh_recorder):
    """record_span lands kind=span events with the trace id — the form
    forensics --trace merges; a None id is a no-op."""
    tracectx.record_span(None, "x", 0.0, 1.0)
    assert fresh_recorder.events() == []
    tracectx.record_span("tid", "router/forward", 5.0, 0.5, replica=1)
    ev = fresh_recorder.events("span")[0]
    assert ev["trace"] == "tid" and ev["name"] == "router/forward"
    assert ev["t0"] == 5.0 and ev["dur"] == 0.5 and ev["replica"] == 1


def breaker_trip_records_event_test(fresh_recorder):
    """Breaker transitions are flight-recorder events (tentpole: breaker
    trips in the blackbox), recorded at trip/reclose only."""
    from homebrewnlp_tpu.infer.serving_guard import CircuitBreaker
    t = [0.0]
    b = CircuitBreaker(2, 5.0, clock=lambda: t[0])
    b.record_failure()
    assert fresh_recorder.events("breaker") == []   # below threshold
    b.record_failure()
    trips = fresh_recorder.events("breaker")
    assert len(trips) == 1 and trips[0]["state"] == "open"
    t[0] = 6.0
    assert b.tick() == "half_open"
    b.record_success()
    states = [e["state"] for e in fresh_recorder.events("breaker")]
    assert states == ["open", "closed"]


# ------------------------------------------------------------- causal merge

def _write_blackbox(d, tag, events):
    with open(os.path.join(d, f"blackbox_{tag}.jsonl"), "w") as f:
        f.write(json.dumps({"blackbox": {"tag": tag}}) + "\n")
        for e in events:
            f.write(json.dumps(dict(e, proc=tag)) + "\n")


def causal_merge_beats_wall_clock_test(tmp_path):
    """The merge's whole point: p2's wall clock runs ~60s BEHIND p1's, so
    a sort-by-wall would place p2's lease scan BEFORE the p1 beat it
    observed — the KV-observed (beat -> scan) edge must win, with wall
    time only breaking the remaining ties."""
    d = str(tmp_path)
    _write_blackbox(d, "p1", [
        {"kind": "beat", "rank": 1, "beat": 1, "seq": 1, "wall": 100.0},
        {"kind": "beat", "rank": 1, "beat": 2, "seq": 2, "wall": 101.0},
    ])
    _write_blackbox(d, "p2", [
        {"kind": "lease_scan", "rank": 2, "peers": {"1": 2}, "seq": 1,
         "wall": 40.0},                              # skewed 60s early
        {"kind": "exit", "rank": 2, "code": 0, "seq": 2, "wall": 41.0},
    ])
    files = forensics.load_files(forensics.discover(d))
    order = forensics.causal_order(files)
    idx = {(e["proc"], e.get("beat"), e["kind"]): i
           for i, e in enumerate(order)}
    assert idx[("p2", None, "lease_scan")] > idx[("p1", 2, "beat")]
    assert idx[("p2", None, "exit")] > idx[("p2", None, "lease_scan")]


def forensics_analyze_names_killed_rank_test(tmp_path):
    """Incident reconstruction on synthetic blackboxes: the rank peers
    declared lapsed with no exit record of its own is the first-failing
    rank; survivors' lapse observations come out in causal order and
    their 144 force-exits are listed."""
    d = str(tmp_path)
    _write_blackbox(d, "p1", [
        {"kind": "beat", "rank": 1, "beat": 5, "seq": 1, "wall": 50.0},
    ])
    _write_blackbox(d, "p0", [
        {"kind": "lease_scan", "rank": 0, "peers": {"1": 5}, "seq": 1,
         "wall": 100.0},
        {"kind": "membership", "rank": 0, "lapsed": [1], "seq": 2,
         "cause": "peer lease(s) lapsed: p1", "wall": 108.0},
        {"kind": "exit", "rank": 0, "code": 144, "path": "force",
         "seq": 3, "wall": 108.1},
    ])
    _write_blackbox(d, "p2", [
        {"kind": "lease_scan", "rank": 2, "peers": {"1": 5}, "seq": 1,
         "wall": 39.0},
        {"kind": "membership", "rank": 2, "lapsed": [1], "seq": 2,
         "cause": "peer lease(s) lapsed: p1", "wall": 47.0},
        {"kind": "exit", "rank": 2, "code": 144, "path": "force",
         "seq": 3, "wall": 47.1},
    ])
    report = forensics.analyze(forensics.load_files(forensics.discover(d)))
    assert report["first_failing_rank"] == 1
    assert report["killed_ranks"] == [1]
    # a STALE prior-generation ring must not exonerate the victim: p1's
    # gen-0 file ends in a clean exit, but the gen-1 incident still names
    # it (events are generation-filtered to the newest membership gen)
    d2 = str(tmp_path / "gen_stale")
    os.makedirs(d2)
    _write_blackbox(d2, "p1", [
        {"kind": "beat", "rank": 1, "beat": 9, "gen": 0, "seq": 1,
         "wall": 10.0},
        {"kind": "exit", "rank": 1, "code": 144, "gen": 0, "path": "force",
         "seq": 2, "wall": 11.0},
    ])
    _write_blackbox(d2, "p0", [
        {"kind": "membership", "rank": 0, "lapsed": [1], "gen": 1,
         "cause": "peer lease(s) lapsed: p1", "seq": 1, "wall": 60.0},
        {"kind": "exit", "rank": 0, "code": 144, "gen": 1, "path": "force",
         "seq": 2, "wall": 60.1},
    ])
    stale = forensics.analyze(forensics.load_files(forensics.discover(d2)))
    assert stale["first_failing_rank"] == 1, stale["killed_ranks"]
    assert [o["observer"] for o in report["lapse_observations"]] \
        == ["p2", "p0"]                              # causal order
    assert {e["proc"] for e in report["membership_exits"]} == {"p0", "p2"}
    text = forensics.format_report(report)
    assert "FIRST-FAILING RANK: p1" in text
    # the CLI agrees
    out = subprocess.run([sys.executable,
                          os.path.join(HERE, "..", "scripts",
                                       "forensics.py"), d, "--json"],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["first_failing_rank"] == 1


def forensics_trace_mode_test(tmp_path):
    """--trace merges one request's spans across process files into the
    per-hop view."""
    d = str(tmp_path)
    _write_blackbox(d, "router", [
        {"kind": "span", "trace": "t1", "name": "router/forward",
         "t0": 1.0, "dur": 0.9, "seq": 1, "wall": 10.0},
    ])
    _write_blackbox(d, "r0", [
        {"kind": "span", "trace": "t1", "name": "queue_wait",
         "t0": 1.1, "dur": 0.2, "seq": 1, "wall": 10.1},
        {"kind": "span", "trace": "t1", "name": "chunk/decode",
         "t0": 1.3, "dur": 0.5, "seq": 2, "wall": 10.3},
        {"kind": "span", "trace": "OTHER", "name": "chunk/decode",
         "t0": 9.0, "dur": 0.5, "seq": 3, "wall": 11.0},
    ])
    files = forensics.load_files(forensics.discover(d))
    rep = forensics.trace_report(files, "t1")
    assert len(rep["spans"]) == 3
    assert rep["hops"] == {"router/forward": 0.9, "queue_wait": 0.2,
                           "decode": 0.5}
    out = subprocess.run([sys.executable,
                          os.path.join(HERE, "..", "scripts",
                                       "forensics.py"), d,
                          "--trace", "t1"],
                         capture_output=True, text=True)
    assert out.returncode == 0 and "router/forward" in out.stdout


# ------------------------------------------------------- straggler detector

class _FakeKV:
    def __init__(self):
        self.store = {}

    def put(self, key, value):
        self.store[key] = value
        return True

    def dir_get(self, prefix):
        return [(k, v) for k, v in self.store.items()
                if k.startswith(prefix)]

    def beat(self, pid, seq, step=None, gen=0):
        d = {"seq": seq, "ospid": 1000 + pid}
        if step is not None:
            d["step"] = step
        self.store[f"hbnlp/elastic/g{gen}/p{pid}"] = json.dumps(d)


def straggler_detector_test(tmp_path):
    """The chief flags a slow-but-alive rank — lease beating, published
    step lagging the fleet — BEFORE its lease lapses; ranks AT the fleet
    max (finished / sync-blocked fast ranks) are exempt, and an advance
    re-arms the flag."""
    from homebrewnlp_tpu.distributed.elastic import ElasticAgent

    clock, steps, flags = [0.0], [0], []
    kv = _FakeKV()
    rec = FlightRecorder(clock=lambda: clock[0], wall=lambda: clock[0])
    agent = ElasticAgent(
        str(tmp_path), 0, 3, gen=0, interval_s=0.5, timeout_s=60.0,
        exit_grace_s=0.1, kv_put=kv.put, kv_dir_get=kv.dir_get,
        clock=lambda: clock[0], exit_fn=lambda rc: None,
        progress=lambda: steps[0], straggler_factor=4.0,
        on_straggler=lambda r, age, med: flags.append(r), recorder=rec)
    agent._started_at = 0.0
    for t in range(1, 12):
        clock[0] = t * 0.5
        steps[0] = t                                  # chief advances
        kv.beat(1, t, step=t)                         # p1 advances
        kv.beat(2, t, step=min(t, 2))                 # p2 stalls at step 2
        agent.tick()
    assert agent.event is None                        # no lapse: alive
    assert flags == [2], flags                        # flagged exactly once
    ev = rec.events("straggler")[0]
    assert ev["rank"] == 2 and ev["step"] == 2 \
        and ev["fleet_max"] > ev["step"] and ev["stall_s"] > 0
    # recovery re-arms: p2 advances, stalls again -> a second flag
    for t in range(12, 24):
        clock[0] = t * 0.5
        steps[0] = t
        kv.beat(1, t, step=t)
        kv.beat(2, t, step=min(t, 14))                # advances, re-stalls
        agent.tick()
    assert flags == [2, 2], flags
    # the beat/scan causality anchors rode along
    assert len(rec.events("beat")) == 23
    assert rec.events("lease_scan")[-1]["peers"]["1"] == 23


def membership_force_exit_flushes_blackbox_test(tmp_path):
    """The exit-144 force-exit path (os._exit skips every finally) must
    leave the incident on disk: membership detection flushes immediately,
    and _trigger_exit records exit path=force + flushes after the
    pre-exit hook."""
    from homebrewnlp_tpu.distributed.elastic import (ElasticAgent,
                                                     MEMBERSHIP_EXIT_CODE)

    calls = []
    rec = FlightRecorder()
    rec.configure(str(tmp_path), "p0")
    agent = ElasticAgent(
        str(tmp_path), 0, 2, gen=0, exit_grace_s=0.0,
        kv_put=lambda k, v: True, kv_dir_get=lambda p: [],
        exit_fn=lambda rc: calls.append(rc),
        pre_exit=lambda: calls.append("pre"), recorder=rec)
    agent._record_event("peer lease(s) lapsed: p1", lapsed=[1])
    agent._trigger_exit()
    assert calls == ["pre", MEMBERSHIP_EXIT_CODE]
    lines = [json.loads(x) for x in
             open(os.path.join(str(tmp_path), "blackbox_p0.jsonl"))]
    kinds = [x.get("kind") for x in lines[1:]]
    assert kinds == ["membership", "exit"]
    assert lines[-1]["code"] == MEMBERSHIP_EXIT_CODE
    assert lines[-1]["path"] == "force"


# ---------------------------------------------------------- metric-docs rule
# (the positive half — repo-at-HEAD clean — rides static_analysis_test's
# existing head-clean sweep; these are the rule's own negative controls)

def metric_docs_rule_test(tmp_path):
    from homebrewnlp_tpu.analysis import ast_lint

    src_dir = tmp_path / "homebrewnlp_tpu"
    os.makedirs(src_dir)
    (src_dir / "m.py").write_text(
        "r.counter('hbnlp_fake_metric_total', 'x')\n"
        "r.gauge('hbnlp_documented_gauge', 'y')\n"
        "r.histogram('hbnlp_suppressed_seconds', "
        "'z')  # graft-lint: allow[metric-docs]\n"
        "r.counter(SOME_NAME, 'variables are out of scope')\n")
    md = tmp_path / "OBS.md"
    md.write_text("| `hbnlp_documented_gauge` | gauge | ... |\n")
    found = ast_lint.metric_docs_findings(
        root=str(tmp_path), subdirs=("homebrewnlp_tpu",),
        obs_md=str(md))
    assert len(found) == 1 and "hbnlp_fake_metric_total" in found[0].message
    assert found[0].rule == "metric-docs"
    # adding the row clears it
    md.write_text("| `hbnlp_documented_gauge` | ... |\n"
                  "| `hbnlp_fake_metric_total` | ... |\n")
    assert ast_lint.metric_docs_findings(
        root=str(tmp_path), subdirs=("homebrewnlp_tpu",),
        obs_md=str(md)) == []


# --------------------------------------------------------------- tracing e2e

_TIER_CFG = {
    "model_mode": "gpt", "use_video": False, "use_language": True,
    "sequence_length": 16, "features_per_head": 8, "heads": 2,
    "depth": 1, "train_batch_size": 1, "vocab_size": 64,
    "group_linear_factor": 2,
    "intermediate_feed_forward_multiplier_multiplier": 0.5,
    "memory_reduction_strategy": "none",
    "block_config": [
        {"layer": ["norm-shift-scale-features-group",
                   "attention-biased_attention_map-absolute-"
                   "input_as_value-shared"]}],
    "decode_loop": "stepped", "decode_chunk_tokens": 2,
    "serve_engine": "continuous", "serve_slots": 2,
}


def _serve_single(cfg, port):
    """One in-process continuous-engine deployment (isolate=True: real
    Manager + HTTP child), stoppable."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer import rest_api
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp

    params = ModelParameter(cfg)
    params.train = False
    model = Model(params)
    seq, tps = params.sequence_dim.size, params.token_patch_dim.size
    zeros = np.zeros((1, seq, tps), np.int32)
    variables = {k: jnp.asarray(v) for k, v in
                 model.init({"token_x": zeros, "token_y": zeros}).items()}
    interface = InterfaceWrapper(params, model, variables)
    stop = threading.Event()
    t = threading.Thread(target=rest_api.serve, args=(params, interface),
                         kwargs=dict(port=port, isolate=True, stop=stop),
                         daemon=True)
    t.start()
    return stop, t


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(port, path, payload, headers=None, timeout=180):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _wait_up(port, deadline_s=420):
    t0 = time.monotonic()
    while True:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=10) as resp:
                return json.loads(resp.read())
        except Exception:
            assert time.monotonic() - t0 < deadline_s, "server never came up"
            time.sleep(0.5)


@pytest.mark.slow
def tracing_parity_and_export_test(tmp_path, fresh_recorder):
    """Acceptance: with tracing enabled, served greedy output stays
    BYTE-IDENTICAL (the tracer only observes), and the per-request
    Chrome-trace export lands with queue-wait + chunk spans."""
    payload = {"tokens": [3, 1, 4, 1, 5], "max_tokens": 6,
               "temperature": 0.0}
    outs = {}
    for mode, trace_on in (("off", False), ("on", True)):
        cfg = dict(_TIER_CFG, trace_requests=trace_on,
                   model_path=str(tmp_path / mode))
        os.makedirs(cfg["model_path"], exist_ok=True)
        port = _free_port()
        stop, t = _serve_single(cfg, port)
        try:
            _wait_up(port)
            _post(port, "/token_completion", payload)   # warmup compile
            tid = tracectx.new_trace_id()
            st, body = _post(port, "/token_completion", payload,
                             headers={tracectx.TRACE_HEADER: tid})
            assert st == 200
            outs[mode] = (body["tokens"], tid, cfg["model_path"])
        finally:
            stop.set()
            t.join(timeout=60)
    assert outs["on"][0] == outs["off"][0], \
        "tracing must not change served greedy output"
    # the traced request exported its per-request chrome JSON with the
    # client's OWN id (header adoption at the HTTP edge)
    _, tid, mp = outs["on"]
    trace_path = os.path.join(mp, "traces", f"trace_{tid}.json")
    assert os.path.exists(trace_path), os.listdir(mp)
    payload_json = json.load(open(trace_path))
    names = {s["name"] for s in payload_json["spans"]}
    assert "queue_wait" in names and "request" in names
    assert any(n.startswith("chunk/") for n in names)
    assert payload_json["hops"].get("decode", 0) > 0
    # the untraced deployment exported nothing
    assert not os.path.exists(os.path.join(outs["off"][2], "traces"))
    # device-loop + HTTP-child blackboxes landed (flushed on stop/SIGTERM)
    assert os.path.exists(os.path.join(mp, "blackbox_serve.jsonl"))


@pytest.mark.slow
def trace_propagation_replica_tier_test(tmp_path, fresh_recorder):
    """The headline tracing e2e: through a REAL 2-replica tier, one trace
    id appears in the router's, a replica HTTP child's, and the engine
    device loop's event files, and the merged per-request spans cover
    >= 95% of measured client wall time."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.router import serve_replicated

    model_path = str(tmp_path / "tier")
    os.makedirs(model_path)
    cfg = dict(_TIER_CFG, serve_replicas=2, trace_requests=True,
               model_path=model_path)
    params = ModelParameter(cfg)
    params.train = False
    port = _free_port()
    stop = threading.Event()
    t = threading.Thread(target=serve_replicated, args=(params,),
                         kwargs=dict(port=port, stop=stop), daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 420
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/health",
                        timeout=10) as resp:
                    h = json.loads(resp.read())
                if all("health" in r for r in h["replicas"]):
                    break
            except Exception:
                pass
            assert time.monotonic() < deadline, "tier never came up"
            time.sleep(1.0)
        payload = {"tokens": [1, 2, 3], "max_tokens": 8,
                   "temperature": 0.0}
        _post(port, "/token_completion", payload)       # warmup compiles
        _post(port, "/token_completion", payload)
        tid = tracectx.new_trace_id()
        t0 = time.monotonic()
        st, body = _post(port, "/token_completion", payload,
                         headers={tracectx.TRACE_HEADER: tid})
        t1 = time.monotonic()
        assert st == 200 and body["tokens"]
    finally:
        stop.set()
        t.join(timeout=120)
    files = forensics.load_files(forensics.discover(model_path))
    tags = set(files)
    assert "router" in tags, tags
    assert any(re.fullmatch(r"r\d+_http", tag) for tag in tags), tags
    assert any(re.fullmatch(r"r\d+", tag) for tag in tags), tags
    # ONE trace id, three processes' event files
    with_trace = {tag for tag, evs in files.items()
                  if any(e.get("trace") == tid for e in evs)}
    assert "router" in with_trace, with_trace
    assert any(re.fullmatch(r"r\d+_http", tag) for tag in with_trace), \
        with_trace
    assert any(re.fullmatch(r"r\d+", tag) for tag in with_trace), with_trace
    # the merged per-request trace covers >= 95% of client wall time
    spans = []
    for evs in files.values():
        spans.extend(tracectx.spans_from_events(evs, tid))
    assert spans
    cov = tracectx.coverage(spans, t0, t1)
    assert cov >= 0.95, (cov, sorted((s["proc"], s["name"]) for s in spans))
    # forensics --trace reconstructs the hop chain
    rep = forensics.trace_report(files, tid, model_path=model_path)
    assert rep["hops"].get("router/forward", 0) > 0
    assert rep["hops"].get("decode", 0) > 0
    assert rep["exported"] is not None              # the replica's export


# -------------------------------------------------------------- forensics e2e

def _fleet_cfg(tmp_path, data_dir, **over):
    cfg = {
        "model_mode": "gpt", "use_video": False, "use_language": True,
        "sequence_length": 32, "features_per_head": 8, "heads": 2,
        "depth": 1, "train_batch_size": 12, "vocab_size": 32,
        "tpu_size": 4, "calc_accuracy": False,
        "block_config": [{"layer": ["norm-shift-scale-features-group",
                                    "feed_forward-in:relu"]}],
        "memory_reduction_strategy": "none",
        "optimizer": "adam-learning_rate", "learning_rate": 1e-3,
        "weight_decay": 0.0, "mesh_shape_override": {"data": 4},
        "train_steps": 200, "use_checkpointing": True,
        "steps_per_checkpoint": 8, "checkpoint_async": True,
        "max_checkpoints_keep": 50, "interleaved_datasets": 2,
        "data_seed": 7, "storage_retry_base_delay": 0.0,
        "distributed_barrier_timeout_s": 30.0,
        "elastic_training": True, "elastic_lease_interval_s": 0.5,
        "elastic_lease_timeout_s": 5.0, "elastic_exit_grace_s": 0.0,
        "dataset_configs": [{"path": str(data_dir / "*"), "type": "text",
                             "weight": 1}],
        "model_path": str(tmp_path / "run"),
    }
    cfg.update(over)
    return cfg


def _spawn_fleet(cfg_path, n, extra=()):
    port = _free_port()
    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS=flags + " --xla_force_host_platform_device_count=1")
    return [subprocess.Popen(
        [sys.executable, WORKER, str(port), str(pid), str(n),
         str(cfg_path), *[str(a) for a in extra]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(n)]


@pytest.mark.slow
def forensics_fleet_sigkill_e2e_test(tmp_path):
    """The headline forensics acceptance: SIGKILL one rank of a 4-process
    elastic fleet; every survivor's ring flushes through the exit-144
    FORCE-exit path (exit_grace 0 -> the agent's os._exit, never the
    finally), and scripts/forensics.py over the surviving blackboxes
    reconstructs the incident: names the killed rank, orders the
    lease-lapse observations across survivors, and shows the membership
    exits.

    The kill is timed into a provably-quiet window: the step delay
    exceeds the lease timeout, and the kill fires only after the lease
    mirror shows EVERY rank past the step-4 sync point (the log-cadence
    float sync drains all pending collectives) — every survivor then
    host-sleeps with idle gloo sockets, so the lease scans (beating on
    the agent daemon thread) detect the lapse and force-exit BEFORE any
    collective touches the dead rank's closed sockets — the
    clean-144-everywhere shape.  On the 1-core CI box, scheduler
    starvation can delay detection past the sleep window, in which case a
    survivor's next collective hits the closed sockets and gloo SIGABRTs
    it ('another task died') — the documented contention flake every
    fleet test retries once on (multihost_test._spawn_workers policy);
    this test does the same with a fresh run dir.  (The controller-level
    handling of that messier collateral shape is tests/elastic_test.py's
    e2e.)"""
    from elastic_test import _write_records

    last = None
    for attempt in range(2):
        run_dir = tmp_path / f"attempt{attempt}"
        os.makedirs(run_dir)
        data_dir = run_dir / "data"
        _write_records(data_dir, 12, 4096)
        cfg = _fleet_cfg(run_dir, data_dir)
        model_path = cfg["model_path"]
        cfg_path = run_dir / "cfg.json"
        cfg_path.write_text(json.dumps(cfg))

        procs = _spawn_fleet(cfg_path, 4, extra=("--step-delay", "15.0"))
        victim_pidfile = os.path.join(model_path, "pids", "g0_p1.pid")
        leases = os.path.join(model_path, "elastic", "leases.json")

        def _fleet_past_sync() -> bool:
            """Every rank's mirrored step-ENTRY >= 5: all hosts passed
            the step-4 float sync (which drains every pending collective)
            and are sleeping inside their attempt of step 5."""
            try:
                mirror = json.load(open(leases))
            except (OSError, json.JSONDecodeError):
                return False
            entries = mirror.get("leases", {})
            return len(entries) == 4 and all(
                e.get("step", 0) >= 5 for e in entries.values())

        killed = False
        deadline = time.monotonic() + 420
        try:
            while time.monotonic() < deadline:
                if not killed and os.path.exists(victim_pidfile) \
                        and _fleet_past_sync():
                    time.sleep(1.0)  # everyone ~1s into a 15s host sleep
                    os.kill(int(open(victim_pidfile).read()),
                            signal.SIGKILL)
                    killed = True
                if all(p.poll() is not None for p in procs):
                    break
                time.sleep(0.25)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        outs = [p.communicate(timeout=30)[0] for p in procs]
        assert killed
        rcs = [p.returncode for p in procs]
        assert rcs[1] == -signal.SIGKILL, (rcs, outs[1][-1500:])
        survivors = [i for i in range(4) if i != 1]
        last = (rcs, outs, model_path, survivors)
        if all(rcs[i] == 144 for i in survivors):
            break
        if attempt == 0:
            # Same classified guard as multihost_test._spawn_workers: this
            # site needs its own spawn loop (mid-flight SIGKILL timing), so
            # it shares the classifier rather than the spawner — the reason
            # stamped here is the same line every fleet retry logs.
            from multihost_test import starvation_retry_reason
            reason = starvation_retry_reason(
                [rcs[i] for i in survivors], [outs[i] for i in survivors])
            if reason:
                print(f"FLEET RETRY: {reason} — gloo SIGABRT before the "
                      "lease scan fired; retrying with a fresh run dir",
                      flush=True)
                continue
        break
    rcs, outs, model_path, survivors = last
    assert all(rcs[i] == 144 for i in survivors), \
        (rcs, "\n".join(o[-1200:] for o in outs))
    # every survivor's blackbox flushed through the force-exit path
    files = forensics.load_files(forensics.discover(model_path))
    for i in survivors:
        evs = files.get(f"p{i}")
        assert evs, sorted(files)
        exits = [e for e in evs if e["kind"] == "exit"]
        assert exits and exits[-1]["code"] == 144, exits
        assert exits[-1]["path"] == "force", exits
        assert any(e["kind"] == "membership" and 1 in e["lapsed"]
                   for e in evs), f"p{i} recorded no membership event"
    # the merged reconstruction names the killed rank and the exits
    report = forensics.analyze(files)
    assert report["first_failing_rank"] == 1, report["killed_ranks"]
    observers = [o["observer"] for o in report["lapse_observations"]]
    assert len(observers) >= 2 \
        and set(observers) <= {"p0", "p2", "p3"}, observers
    assert {e["proc"] for e in report["membership_exits"]} \
        == {f"p{i}" for i in survivors}
    out = subprocess.run([sys.executable,
                          os.path.join(HERE, "..", "scripts",
                                       "forensics.py"), model_path],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "FIRST-FAILING RANK: p1" in out.stdout, out.stdout[-2000:]


@pytest.mark.slow
def straggler_flagged_in_fleet_test(tmp_path):
    """Acceptance: the straggler detector flags an artificially-delayed
    rank in a REAL fleet before its lease lapses — the run completes
    cleanly (no membership exit), with the flag in the chief's output and
    blackbox.

    The delayed rank WEDGES once for ~15s (GC-pause / storage-stall
    shape) rather than running proportionally slower: synchronous
    training equalizes fleet-average step rates (collectives gate
    everyone), so a same-order slowdown is invisible by construction —
    the detectable straggler is the one whose step stalls for many
    fleet-median step intervals while its lease keeps beating."""
    from elastic_test import _write_records
    from multihost_test import _spawn_workers

    data_dir = tmp_path / "data"
    _write_records(data_dir, 12, 4096)
    cfg = _fleet_cfg(
        tmp_path, data_dir, tpu_size=3, train_batch_size=12,
        mesh_shape_override={"data": 3}, train_steps=8,
        use_checkpointing=False, checkpoint_async=False,
        elastic_lease_interval_s=0.25, elastic_lease_timeout_s=120.0,
        elastic_straggler_factor=3.0)
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    results = _spawn_workers(
        WORKER, [str(cfg_path), "--straggle-rank", "2",
                 "--straggle-delay", "15.0", "--straggle-step", "3"],
        env_devcount=1, n_procs=3, timeout=420)
    assert all(p.returncode == 0 for p, _ in results), \
        "\n".join(o[-1500:] for _, o in results)
    chief_out = results[0][1]
    assert "ELASTIC: straggler suspected p2" in chief_out, chief_out[-2500:]
    assert "membership change" not in chief_out
    # the flag landed in the chief's blackbox too — before any lease
    # event (there was none: every rank finished rc 0)
    evs = forensics.load_files(
        [os.path.join(cfg["model_path"], "blackbox_p0.jsonl")])["p0"]
    st = [e for e in evs if e["kind"] == "straggler"]
    assert st and st[0]["rank"] == 2, [e["kind"] for e in evs]
    assert not [e for e in evs if e["kind"] == "membership"]
