"""Fused-norm custom_vjp (model/normalization.py) regression tests.

The fused core computes variance as E[x^2] - mu^2 (one shared read of x);
unlike the subtractive form this can cancel to a small negative value when
|mu| >> std — the clamp keeps rsqrt finite.  The backward is hand-written;
pin it against autodiff of the composed expression.
"""
import jax
import jax.numpy as jnp
import numpy as np

from backend import make_params  # noqa: F401  (sets up the CPU mesh env)
from homebrewnlp_tpu.model.normalization import _norm_core


def _composed(x, scale, shift, axes, eps):
    mu = jnp.mean(x, axes, keepdims=True)
    c = x - mu
    inv = jax.lax.rsqrt(jnp.mean(c * c, axes, keepdims=True) + eps)
    return c * inv * scale + shift


def large_mean_no_nan_test():
    """|mu| >> std must not produce NaN (catastrophic cancellation in
    E[x^2] - mu^2 goes slightly negative; the clamp catches it)."""
    x = jnp.full((4, 64), 300.0, jnp.float32) + jnp.linspace(0, 1e-3, 64)
    one = jnp.ones((1, 1), jnp.float32)
    y = _norm_core(x, one, one, (1,), 1e-5, False, False)
    assert bool(jnp.isfinite(y).all())
    g = jax.grad(lambda a: _norm_core(a, one, one, (1,), 1e-5, False,
                                      False).sum())(x)
    assert bool(jnp.isfinite(g).all())


def fused_matches_autodiff_test():
    """Forward and all three gradients match autodiff of the composed
    expression, for group (last-axis) and full-feature reductions."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 16, 2, 8)) * 2 + 0.5, jnp.float32)
    scale = jnp.asarray(rng.standard_normal((1, 1, 2, 8)) + 1, jnp.float32)
    shift = jnp.asarray(rng.standard_normal((1, 1, 2, 8)), jnp.float32)
    for axes in ((3,), (2, 3)):
        y1 = _composed(x, scale, shift, axes, 1e-5)
        y2 = _norm_core(x, scale, shift, axes, 1e-5, True, True)
        np.testing.assert_allclose(y2, y1, atol=5e-6)
        g1 = jax.grad(lambda *a: _composed(*a, axes, 1e-5).sum(),
                      argnums=(0, 1, 2))(x, scale, shift)
        g2 = jax.grad(lambda *a: _norm_core(*a, axes, 1e-5, True, True).sum(),
                      argnums=(0, 1, 2))(x, scale, shift)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(b, a, atol=1e-4, rtol=1e-5)
