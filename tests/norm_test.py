"""Fused-norm custom_vjp (model/normalization.py) regression tests.

The fused core computes variance as E[x^2] - mu^2 (one shared read of x);
unlike the subtractive form this can cancel to a small negative value when
|mu| >> std — the clamp keeps rsqrt finite.  The backward is hand-written;
pin it against autodiff of the composed expression.
"""
import jax
import jax.numpy as jnp
import numpy as np

from backend import make_params  # noqa: F401  (sets up the CPU mesh env)
from homebrewnlp_tpu.model.normalization import _norm_core


def _composed(x, scale, shift, axes, eps):
    mu = jnp.mean(x, axes, keepdims=True)
    c = x - mu
    inv = jax.lax.rsqrt(jnp.mean(c * c, axes, keepdims=True) + eps)
    return c * inv * scale + shift


def large_mean_no_nan_test():
    """|mu| >> std must not produce NaN (catastrophic cancellation in
    E[x^2] - mu^2 goes slightly negative; the clamp catches it)."""
    x = jnp.full((4, 64), 300.0, jnp.float32) + jnp.linspace(0, 1e-3, 64)
    one = jnp.ones((1, 1), jnp.float32)
    y = _norm_core(x, one, one, (1,), 1e-5, False, False)
    assert bool(jnp.isfinite(y).all())
    g = jax.grad(lambda a: _norm_core(a, one, one, (1,), 1e-5, False,
                                      False).sum())(x)
    assert bool(jnp.isfinite(g).all())


def fused_matches_autodiff_test():
    """Forward and all three gradients match autodiff of the composed
    expression, for group (last-axis) and full-feature reductions."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 16, 2, 8)) * 2 + 0.5, jnp.float32)
    scale = jnp.asarray(rng.standard_normal((1, 1, 2, 8)) + 1, jnp.float32)
    shift = jnp.asarray(rng.standard_normal((1, 1, 2, 8)), jnp.float32)
    for axes in ((3,), (2, 3)):
        y1 = _composed(x, scale, shift, axes, 1e-5)
        y2 = _norm_core(x, scale, shift, axes, 1e-5, True, True)
        np.testing.assert_allclose(y2, y1, atol=5e-6)
        g1 = jax.grad(lambda *a: _composed(*a, axes, 1e-5).sum(),
                      argnums=(0, 1, 2))(x, scale, shift)
        g2 = jax.grad(lambda *a: _norm_core(*a, axes, 1e-5, True, True).sum(),
                      argnums=(0, 1, 2))(x, scale, shift)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(b, a, atol=1e-4, rtol=1e-5)


def pallas_backward_matches_xla_test():
    """The one-pass pallas backward (interpret mode on CPU) matches the XLA
    backward bit-for-bit-ish on both the group (trailing-axis) and
    full-feature layouts, with every scale/shift combination."""
    from homebrewnlp_tpu.model.normalization import (_norm_bwd_pallas,
                                                     _norm_bwd_xla)
    rng = np.random.default_rng(3)
    # 1024 rows -> multiple grid blocks, so the per-block partial-sum
    # outputs and the outside sum(0) are exercised (nb > 1), not just the
    # degenerate single-block case
    x = jnp.asarray(rng.standard_normal((128, 8, 2, 128)) * 2 + 0.3,
                    jnp.float32)
    dy = jnp.asarray(rng.standard_normal(x.shape), jnp.float32)
    scale = jnp.asarray(rng.standard_normal((1, 1, 2, 128)) + 1, jnp.float32)
    shift = jnp.asarray(rng.standard_normal((1, 1, 2, 128)), jnp.float32)
    one = jnp.ones((1, 1, 1, 1), jnp.float32)
    for axes in ((3,), (2, 3)):
        mu = jnp.mean(x, axes, keepdims=True)
        var = jnp.mean(jnp.square(x), axes, keepdims=True) - jnp.square(mu)
        inv = jax.lax.rsqrt(jnp.maximum(var, 0.0) + 1e-5)
        for has_scale, has_shift in ((True, True), (True, False),
                                     (False, True)):
            res = (x, scale if has_scale else one,
                   shift if has_shift else one, mu, inv)
            out_p = _norm_bwd_pallas(axes, 1e-5, has_scale, has_shift, res,
                                     dy, interpret=True)
            assert out_p is not None, (axes, has_scale, has_shift)
            out_x = _norm_bwd_xla(axes, 1e-5, has_scale, has_shift, res, dy)
            for a, b in zip(out_p, out_x):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=2e-4, rtol=1e-5)


def pallas_backward_layout_gates_test():
    """Unsupported layouts return None (caller falls back to XLA)."""
    from homebrewnlp_tpu.model.normalization import _norm_bwd_pallas
    x = jnp.ones((4, 4, 2, 64), jnp.float32)  # f=64 not lane-aligned
    one = jnp.ones((1, 1, 2, 64), jnp.float32)
    mu = jnp.zeros((4, 4, 2, 1), jnp.float32)
    res = (x, one, one, mu, mu + 1)
    assert _norm_bwd_pallas((3,), 1e-5, True, True, res, x,
                            interpret=True) is None
    # non-trailing reduce axes
    x2 = jnp.ones((4, 128, 2, 128), jnp.float32)
    res2 = (x2, jnp.ones((1, 128, 1, 1)), jnp.ones((1, 128, 1, 1)),
            jnp.zeros(()), jnp.ones(()))
    assert _norm_bwd_pallas((1,), 1e-5, True, True, res2, x2,
                            interpret=True) is None
