"""Checkpoint restore across mesh changes (VERDICT r1 weak #7).

A checkpoint saved from an 8-device data x model mesh must restore bit-exact
onto 4-device and 1-device layouts — the reference achieved topology
portability by copying mesh slices to master values in its sharded Saver
(/root/reference/src/run/run.py:160-175); here saves are host-side full
arrays so any mesh can load them, and shard_params re-lays them out.
"""
import jax
import jax.numpy as jnp
import numpy as np

from backend import make_params
from homebrewnlp_tpu.core import sharding as shardlib
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer, TrainState, checkpoint as ckpt


def _batch(params, rng):
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": jnp.asarray(x),
            "token_y": jnp.asarray((x + 1) % params.vocab_size)}


def _make(tmp_path, n_devices):
    cfg = dict(heads=4, depth=2, train_batch_size=8, tpu_size=n_devices,
               optimizer="adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
               model_path=str(tmp_path))
    params = make_params(**cfg)
    model = Model(params)
    mesh = shardlib.build_mesh(params, jax.devices()[:n_devices]) \
        if n_devices > 1 else None
    return params, model, Trainer(params, model, mesh=mesh)


def mesh_change_restore_test(tmp_path):
    rng = np.random.default_rng(0)
    params, model, trainer = _make(tmp_path, 8)
    batch = _batch(params, rng)
    state = trainer.init_state(batch)
    # a real step so optimizer slots hold non-trivial values
    state, _ = trainer.step(state, batch, jax.random.PRNGKey(0))
    ckpt.save(str(tmp_path), 1, state.variables, state.opt_state, max_keep=2)
    want_vars = {k: np.asarray(v) for k, v in state.variables.items()}
    want_opt = jax.tree_util.tree_map(np.asarray, state.opt_state)

    for n_dev in (4, 1):
        restored = ckpt.restore(str(tmp_path))
        assert restored is not None
        variables, opt_state, step, _ = restored
        assert step == 1
        p2, m2, tr2 = _make(tmp_path, n_dev)
        tr2.init_state(_batch(p2, rng))  # establish model plan + optimizer
        if tr2.mesh is not None:
            variables = shardlib.shard_params(p2, variables, m2.param_dims,
                                              tr2.mesh)
        variables = {k: jnp.asarray(v) for k, v in variables.items()}
        for k, want in want_vars.items():
            got = np.asarray(variables[k])
            np.testing.assert_array_equal(got, want, err_msg=f"{n_dev}d {k}")
        got_opt = jax.tree_util.tree_map(np.asarray, opt_state)
        jax.tree_util.tree_map(np.testing.assert_array_equal, got_opt,
                               want_opt)
        # restored state steps without error on the new mesh
        st = TrainState(variables,
                        jax.tree_util.tree_map(jnp.asarray, opt_state),
                        jnp.asarray(step, jnp.int32))
        st, metrics = tr2.step(st, _batch(p2, rng), jax.random.PRNGKey(1))
        assert np.isfinite(float(metrics["loss"]))


def mesh_change_same_trajectory_test(tmp_path):
    """One further step from the restored checkpoint yields identical params
    on the 8-device mesh and on a single device (f32 everywhere)."""
    rng = np.random.default_rng(1)
    params, model, trainer = _make(tmp_path, 8)
    batch = _batch(params, rng)
    state = trainer.init_state(batch)
    state, _ = trainer.step(state, batch, jax.random.PRNGKey(0))
    ckpt.save(str(tmp_path), 1, state.variables, state.opt_state)
    batch2 = _batch(params, rng)

    results = []
    for n_dev in (8, 1):
        variables, opt_state, step, _ = ckpt.restore(str(tmp_path))
        p2, m2, tr2 = _make(tmp_path, n_dev)
        tr2.init_state(_batch(p2, np.random.default_rng(9)))
        if tr2.mesh is not None:
            variables = shardlib.shard_params(p2, variables, m2.param_dims,
                                              tr2.mesh)
        st = TrainState({k: jnp.asarray(v) for k, v in variables.items()},
                        jax.tree_util.tree_map(jnp.asarray, opt_state),
                        jnp.asarray(step, jnp.int32))
        st, _ = tr2.step(st, batch2, jax.random.PRNGKey(7))
        results.append({k: np.asarray(v) for k, v in st.variables.items()})
    for k in results[0]:
        np.testing.assert_allclose(results[0][k], results[1][k], atol=1e-6,
                                   err_msg=k)
