"""Pipeline parallelism (GPipe over the 'pipe' mesh axis) parity tests.

The reference has no pipeline parallelism (SURVEY.md §2.10); these tests pin
the new capability: a pipelined train step must produce the same loss and the
same updated parameters as the plain data-parallel step, for every memory
strategy, since GPipe changes the schedule but not the math.
"""
import jax
import numpy as np
import pytest

from homebrewnlp_tpu.config import ModelParameter
from homebrewnlp_tpu.core import sharding as shardlib
from homebrewnlp_tpu.model import Model
from homebrewnlp_tpu.train import Trainer

BLOCKS = [{"layer": ["norm-shift-scale-features-group",
                     "feed_forward-relu"]},
          {"layer": ["norm-shift-scale-features-group",
                     "attention-dot_product-context"]}]


def _cfg(**over):
    cfg = dict(model_mode="gpt", sequence_length=32, features_per_head=16,
               heads=4, depth=4, train_batch_size=8, vocab_size=64,
               block_config=BLOCKS, calc_accuracy=False,
               calculation_dtype="float32", storage_dtype="float32",
               slice_dtype="float32", optimizer_slice_dtype="float32",
               optimizer="momentum:0.9:1:0-learning_rate", learning_rate=0.05,
               weight_decay=0.0, model_path="/tmp/pp_test")
    cfg.update(over)
    return cfg


def _batch(params, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, params.vocab_size,
                     (params.train_batch_size, params.sequence_length, 1))
    return {"token_x": x, "token_y": (x + 1) % params.vocab_size}


def _run_step(cfg_overrides, mesh_override):
    params = ModelParameter(_cfg(**cfg_overrides,
                                 mesh_shape_override=mesh_override))
    model = Model(params)
    mesh = shardlib.build_mesh(params)
    trainer = Trainer(params, model, mesh=mesh)
    batch = _batch(params)
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch)
    loss = float(metrics["loss"])
    varlist = {k: np.asarray(v) for k, v in state.variables.items()}
    return loss, varlist, mesh


@pytest.mark.parametrize("strategy", ["none", "checkpoint", "revnet", "momentum"])
def pipeline_matches_plain_test(strategy):
    loss_a, vars_a, _ = _run_step({"memory_reduction_strategy": strategy},
                                  {"data": 2})
    loss_b, vars_b, mesh = _run_step({"memory_reduction_strategy": strategy},
                                     {"data": 2, "pipe": 4})
    assert dict(mesh.shape)["pipe"] == 4
    np.testing.assert_allclose(loss_a, loss_b, rtol=2e-5, atol=2e-5)
    assert vars_a.keys() == vars_b.keys()
    for k in vars_a:
        np.testing.assert_allclose(vars_a[k], vars_b[k], rtol=2e-4, atol=2e-4,
                                   err_msg=k)


def pipeline_microbatches_test():
    """More microbatches than stages still exact."""
    loss_a, vars_a, _ = _run_step({"train_batch_size": 16}, {"data": 2})
    loss_b, vars_b, _ = _run_step({"train_batch_size": 16,
                                   "pipeline_microbatches": 4},
                                  {"data": 2, "pipe": 2})
    np.testing.assert_allclose(loss_a, loss_b, rtol=2e-5, atol=2e-5)
    for k in vars_a:
        np.testing.assert_allclose(vars_a[k], vars_b[k], rtol=2e-4, atol=2e-4,
                                   err_msg=k)


def pipeline_with_model_axis_test():
    """pipe x model mesh: tensor parallelism nests inside each stage."""
    loss_a, vars_a, _ = _run_step({}, {"data": 1})
    loss_b, vars_b, _ = _run_step({}, {"pipe": 2, "model": 4})
    np.testing.assert_allclose(loss_a, loss_b, rtol=2e-5, atol=2e-5)
    for k in vars_a:
        np.testing.assert_allclose(vars_a[k], vars_b[k], rtol=2e-4, atol=2e-4,
                                   err_msg=k)


def pipeline_rejects_bad_depth_test():
    with pytest.raises(ValueError, match="divide into"):
        ModelParameter(_cfg(depth=3, mesh_shape_override={"pipe": 2}))


def pipeline_rejects_stale_stages_test():
    """Explicit pipeline_stages with an override mesh lacking 'pipe' must
    error, not silently run unpipelined."""
    with pytest.raises(ValueError, match="pipe"):
        ModelParameter(_cfg(pipeline_stages=4,
                            mesh_shape_override={"data": 8}))


def pipeline_with_dropout_test():
    """Stochastic layers exercise the per-stage/per-tick rng fold."""
    blocks = [{"layer": ["norm-shift-scale-features-group",
                         "dropout-dropout_rate0.2", "feed_forward-relu"]}]
    params = ModelParameter(_cfg(block_config=blocks,
                                 mesh_shape_override={"data": 2, "pipe": 4}))
    params.train = True
    model = Model(params)
    mesh = shardlib.build_mesh(params)
    trainer = Trainer(params, model, mesh=mesh)
    batch = _batch(params)
    state = trainer.init_state(batch)
    state, metrics = trainer.step(state, batch, jax.random.PRNGKey(7))
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.parametrize("strategy", ["none", "checkpoint", "revnet", "momentum"])
def one_f_one_b_matches_plain_test(strategy):
    """The fused 1F1B schedule (pipeline_schedule='1f1b': loss head inside
    the last stage, per-stage manual vjp, O(stages) stash) must produce the
    same loss and updated parameters as the plain data-parallel step."""
    loss_a, vars_a, _ = _run_step({"memory_reduction_strategy": strategy,
                                   "train_batch_size": 16},
                                  {"data": 2})
    loss_b, vars_b, _ = _run_step({"memory_reduction_strategy": strategy,
                                   "pipeline_schedule": "1f1b",
                                   "pipeline_microbatches": 4,
                                   "train_batch_size": 16},
                                  {"data": 2, "pipe": 2})
    np.testing.assert_allclose(loss_b, loss_a, rtol=2e-5)
    for k in vars_a:
        np.testing.assert_allclose(vars_b[k], vars_a[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


@pytest.mark.parametrize("strategy", ["none", "revnet"])
def interleaved_one_f_one_b_matches_plain_test(strategy):
    """Interleaved 1F1B (pipeline_interleave=2: each device owns two
    non-adjacent depth chunks, ring-wrapped schedule) must reproduce the
    plain data-parallel step exactly like the non-interleaved schedule."""
    loss_a, vars_a, _ = _run_step({"memory_reduction_strategy": strategy,
                                   "train_batch_size": 16},
                                  {"data": 2})
    loss_b, vars_b, _ = _run_step({"memory_reduction_strategy": strategy,
                                   "pipeline_schedule": "1f1b",
                                   "pipeline_interleave": 2,
                                   "pipeline_microbatches": 4,
                                   "train_batch_size": 16},
                                  {"data": 2, "pipe": 2})
    np.testing.assert_allclose(loss_b, loss_a, rtol=2e-5)
    for k in vars_a:
        np.testing.assert_allclose(vars_b[k], vars_a[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def one_f_one_b_schedule_properties_test():
    """Static schedule invariants: every (F, B) unit exactly once, stash
    stays within S slots per stage, and the fused schedule starts the first
    backward S ticks in (GPipe's autodiff backward cannot start before all
    M forwards, i.e. tick M+S-1)."""
    from homebrewnlp_tpu.parallel.pipeline_1f1b import (FWD, BWD, IDLE,
                                                        build_schedule,
                                                        bubble_ticks)
    for M, S in ((8, 4), (4, 4), (5, 2), (2, 3)):
        kinds, mbs, chunks = build_schedule(M, S)
        assert int(chunks.max()) == 0
        seen = {("F", m, s): 0 for m in range(M) for s in range(S)}
        seen.update({("B", m, s): 0 for m in range(M) for s in range(S)})
        in_flight = [0] * S
        peak = [0] * S
        first_bwd = None
        for t in range(kinds.shape[0]):
            for s in range(S):
                k = kinds[t, s]
                if k == IDLE:
                    continue
                m = int(mbs[t, s])
                seen[("F" if k == FWD else "B", m, s)] += 1
                if k == FWD:
                    in_flight[s] += 1
                    peak[s] = max(peak[s], in_flight[s])
                else:
                    in_flight[s] -= 1
                    if first_bwd is None:
                        first_bwd = t
        assert all(v == 1 for v in seen.values()), (M, S)
        # 1F1B memory bound: stage s holds at most S - s microbatches
        assert all(peak[s] <= S - s for s in range(S)), (M, S, peak)
        # first backward fires as soon as the pipeline fills (tick S: right
        # after the last stage's first forward), not after all M forwards
        # like GPipe's autodiff backward (tick >= M+S-1)
        assert first_bwd == S, (M, S, first_bwd)
        assert bubble_ticks(kinds) >= 0


def interleaved_schedule_properties_test():
    """Interleaved (virtual-chunk) 1F1B: every (F/B, microbatch, chunk,
    stage) unit exactly once, dataflow dependencies respected (including the
    ring wraps), and a smaller bubble FRACTION than non-interleaved at the
    same M, S."""
    from homebrewnlp_tpu.parallel.pipeline_1f1b import (FWD, BWD, IDLE,
                                                        build_schedule,
                                                        bubble_ticks)
    for M, S, V in ((8, 4, 2), (4, 2, 2), (8, 2, 4), (6, 3, 2)):
        kinds, mbs, chunks = build_schedule(M, S, V)
        fwd_t = {}
        bwd_t = {}
        for t in range(kinds.shape[0]):
            for s in range(S):
                k = kinds[t, s]
                if k == IDLE:
                    continue
                key = (int(mbs[t, s]), int(chunks[t, s]), s)
                tbl = fwd_t if k == FWD else bwd_t
                assert key not in tbl, ("duplicate unit", key)
                tbl[key] = t
        assert len(fwd_t) == M * V * S and len(bwd_t) == M * V * S
        for (m, c, s), t in fwd_t.items():
            if s > 0:
                assert fwd_t[(m, c, s - 1)] < t, ("F dep", m, c, s)
            elif c > 0:
                assert fwd_t[(m, c - 1, S - 1)] < t, ("F wrap dep", m, c)
        for (m, c, s), t in bwd_t.items():
            assert fwd_t[(m, c, s)] < t, ("B own-F dep", m, c, s)
            if s < S - 1:
                assert bwd_t[(m, c, s + 1)] < t, ("B dep", m, c, s)
            elif c < V - 1:
                assert bwd_t[(m, c + 1, 0)] < t, ("B wrap dep", m, c)
        k1, _, _ = build_schedule(M, S, 1)
        frac_v = bubble_ticks(kinds) / kinds.size
        frac_1 = bubble_ticks(k1) / k1.size
        assert frac_v < frac_1, (M, S, V, frac_v, frac_1)
