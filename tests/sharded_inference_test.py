"""Sharded (multi-chip) inference: serving runs on the same device mesh as
training, the reference's non-train-modes-through-the-SimdMeshImpl design
(/root/reference/src/run/run.py:200-308).

Greedy decode over a dp x tp mesh must produce IDENTICAL tokens to the
single-device samplers: variables shard over 'model' (heads), the batch over
'data', and the KV caches inherit the attention activation layout via the
constraint in model/decode.py (so tensor parallelism splits cache HBM 1/tp).
"""
import jax
import jax.numpy as jnp
import numpy as np

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.core import sharding as shardlib
from homebrewnlp_tpu.infer.sampler import sample_text
from homebrewnlp_tpu.model import Model


def _model_and_vars(**overrides):
    cfg = dict(heads=4, train_batch_size=4, sequence_length=16,
               use_autoregressive_sampling=True,
               mesh_shape_override={"data": 2, "model": 4})
    cfg.update(overrides)
    params = make_params(**cfg)
    model = Model(params)
    rng = np.random.default_rng(0)
    seq = params.sequence_dim.size
    tps = params.token_patch_dim.size
    token_x = rng.integers(0, params.vocab_size,
                           (params.train_batch_size, seq, tps)).astype(np.int32)
    batch = {"token_x": token_x, "token_y": token_x.copy()}
    variables = model.init(batch)
    return params, model, variables, token_x


def _parity(use_cache, **overrides):
    params, model, variables, token_x = _model_and_vars(**overrides)
    single = {k: jnp.asarray(v) for k, v in variables.items()}
    ref = sample_text(model, single, token_x[:, :4, 0], initial_pos=4,
                      temperature=0.0, use_cache=use_cache)

    mesh = shardlib.build_mesh(params)
    assert mesh.shape["model"] == 4 and mesh.shape["data"] == 2
    sharded_vars = shardlib.shard_params(params, variables, model.param_dims,
                                         mesh)
    # weights carrying a heads dim actually shard over 'model'
    heads_sharded = [k for k, v in sharded_vars.items()
                     if any(s.spec for s in [v.sharding] if "model" in str(s.spec))]
    assert heads_sharded, "no variable sharded over the model axis"
    out = sample_text(model, sharded_vars, token_x[:, :4, 0], initial_pos=4,
                      temperature=0.0, use_cache=use_cache, mesh=mesh)
    np.testing.assert_array_equal(ref, out)


def kv_sampler_sharded_parity_test():
    _parity(use_cache=True)


def full_sampler_sharded_parity_test():
    _parity(use_cache=False)


def kv_sampler_sharded_revnet_scan_parity_test():
    """The stacked decode-cache scan path under the mesh (depth scan carries
    sharded KV caches)."""
    _parity(use_cache=True, memory_reduction_strategy="revnet", depth=2,
            scan_layers=True)


def kv_sampler_sharded_int8_cache_parity_test():
    """int8 KV caches under the mesh: the quantized buffer and its sibling
    f32 scale cache both ride the sharding constraint."""
    _parity(use_cache=True, decode_cache_dtype="int8",
            calculation_dtype="float32")


def inference_mesh_folds_pipe_and_sequence_test():
    """'pipe'/'sequence' axes fold into 'data' for serving (decode has no
    pipeline or ring schedule): the training topology's devices all
    participate, as dp x tp."""
    params = make_params(heads=2, mesh_shape_override={
        "data": 1, "pipe": 2, "model": 2, "sequence": 2})
    mesh = shardlib.inference_mesh(params)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    assert mesh.devices.size == 8
    assert len({d.id for d in mesh.devices.flat}) == 8


def inference_mesh_passthrough_test():
    """No pipe/sequence axes: the serving mesh is the training mesh."""
    params = make_params(heads=4, mesh_shape_override={"data": 2, "model": 4})
    mesh = shardlib.inference_mesh(params)
    assert dict(mesh.shape) == {"data": 2, "model": 4}
