"""Init-statistics and weight-sharing tests.

Port of /root/reference/tests/variable_test.py: normal/orthogonal/embedding
init statistics (incl. the analytic expected-std formula for orthogonal init,
variable_test.py:75-88) and `shared`-flag identity across body blocks
(:123-142).
"""
import numpy as np
import pytest

from backend import make_params, tolerance, OpHarness
from homebrewnlp_tpu.config import BlockArgs
from homebrewnlp_tpu.core import scope
from homebrewnlp_tpu.model import backend as model_backend
from homebrewnlp_tpu.model.utils import get_intermediate
from homebrewnlp_tpu.core.dims import deduplicate


def _orthogonal_expected_std(params, shape, in_dims):
    size = int(np.prod([d.size for d in shape]))
    intermediate = int(np.prod([d.size for d in in_dims]))
    min_fan = min(size // intermediate, intermediate)
    std = ((min_fan * (1 - min_fan / size) ** 2
            + (size - min_fan) * (min_fan / size) ** 2) / size) ** 0.5
    return std


@pytest.mark.parametrize("features_per_head", [16, 64])
@pytest.mark.parametrize("heads", [1, 4])
@pytest.mark.parametrize("case", ["ff_in", "ff_out", "group_in", "group_out"])
@pytest.mark.parametrize("scale_by_depth", [True, False])
def orthogonal_init_test(features_per_head, heads, case, scale_by_depth):
    params = make_params(features_per_head=features_per_head, heads=heads,
                         scale_by_depth=scale_by_depth)
    args = BlockArgs(params, None, [''], is_last=True)
    group = get_intermediate(args(['group']))
    in_dims, out_dims = {
        "ff_in": (params.feature_dims, params.intermediate),
        "ff_out": (params.intermediate, params.feature_dims),
        "group_in": (group, params.feature_dims),
        "group_out": (params.feature_dims, group),
    }[case]
    shape = deduplicate(list(in_dims) + list(out_dims))

    ctx = scope.Context("init", seed=0)
    with scope.context(ctx):
        var = model_backend.orthogonal_var(args, shape, list(in_dims))
    out = np.asarray(var.data, np.float32)

    expected = _orthogonal_expected_std(params, shape, in_dims)
    if scale_by_depth:
        expected /= params.depth ** 0.5
    tol = max(tolerance(params), 0.02 * expected + 1e-4)
    assert abs(np.std(out) - expected) < max(tol, 0.05 * expected), \
        (np.std(out), expected)


@pytest.mark.parametrize("stddev,mean", [(0.02, 0.0), (0.02, 1.0), (0.004, 0.0)])
def normal_init_test(stddev, mean):
    params = make_params(features_per_head=64, heads=4,
                         train_batch_size=8, sequence_length=64)
    args = BlockArgs(params, None, [''])
    shape = [params.head_dim, params.key_dim, params.sequence_dim]
    ctx = scope.Context("init", seed=0)
    with scope.context(ctx):
        var = model_backend.normal_var(args, shape, stddev, mean)
    out = np.asarray(var.data, np.float32)
    assert abs(np.std(out) - stddev) < stddev * 0.05
    assert abs(np.mean(out) - mean) < stddev * 0.05


def shared_variable_identity_test():
    """`shared` vars in different body blocks resolve to one parameter
    (reference variable_test.py:123-142)."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.model import Model
    params = make_params(depth=4)
    m = Model(params)
    rng = np.random.default_rng(0)
    batch = {'token_x': jnp.asarray(rng.integers(0, 32, (4, 16, 1))),
             'token_y': jnp.asarray(rng.integers(0, 32, (4, 16, 1)))}
    variables = m.init(batch)
    # each depth's attention block uses the depth-0 embeds: exactly 2
    # attention-map embeddings exist in total (2 attention calls per block)
    attn_embeds = [k for k in variables if 'attention' in k and 'embed' in k]
    assert len(attn_embeds) == 2, attn_embeds
    assert all('block0_1_' in k for k in attn_embeds)
    # non-shared vars exist once per depth
    bottlenecks = [k for k in variables if 'bottleneck' in k and 'orthogonal_var0' in k]
    assert len(bottlenecks) == params.depth


def dtype_grid_init_test():
    """Init works across the reference's storage/slice/calc dtype grid."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.model import Model
    rng = np.random.default_rng(0)
    batch = {'token_x': jnp.asarray(rng.integers(0, 32, (2, 16, 1))),
             'token_y': jnp.asarray(rng.integers(0, 32, (2, 16, 1)))}
    for storage in ("bfloat16", "float32"):
        for slice_ in ("bfloat16", "float32"):
            for calc in ("bfloat16", "float32"):
                params = make_params(storage_dtype=storage, slice_dtype=slice_,
                                     calculation_dtype=calc, depth=1,
                                     train_batch_size=2)
                m = Model(params)
                variables = m.init(batch)
                assert all(v.dtype == params.slice_dtype for v in variables.values())
                info = m.apply(variables, batch)
                assert np.isfinite(float(info.total_loss.data))
