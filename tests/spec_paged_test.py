"""Spec-on-paged: the composed deployment (marker: specpaged;
docs/SERVING.md 'Engine architecture').

Composition: the Engine assembles orthogonal carry components instead of
forking programs — the draft pool + verify width (spec) and the block
tables (paged) compose into ``spec_paged_chunk_step`` through the ONE
donated builder.  The unit matrix here lowers every registered
composition and audits its compiled module: each component's pool leaves
stay donated+aliased in every composition they ride, with no
full-pool-shaped copy (composing must never cost a resident duplicate).

Engine: greedy bit-parity of the composed executor against the PLAIN slot
engine token-for-token, through the regimes where the two components
interact — a prefix-hit admission resuming into a recycled slot (the
shared span restores BOTH pools' rows), copy-on-write divergence inside a
shared block mid-draft (both pools copy through the same tables; the
parent's physical block stays bit-identical in each), and total-rejection
rounds (a random draft: every round survives on the verify's own token).
The acceptance-collapse self-disable RECOMPOSES down to the paged
composition — block tables keep their layout, serving stays bit-correct.

Standalone-runnable (tier-1 truncates at 870s on this box):
``python -m pytest tests/spec_paged_test.py -q``
"""
import numpy as np
import pytest

from backend import MIXER_BLOCKS, make_params
from homebrewnlp_tpu.infer.scheduler import (EngineController, EngineRequest,
                                             SlotScheduler)

pytestmark = pytest.mark.specpaged

SEQ = 32
PROMPTS = [[1, 2, 3], [7, 8], [4, 5, 6, 7, 9], [10]]
RLS = [6, 20, 3, None]


def _interface(**kw):
    from homebrewnlp_tpu.infer.interface import InterfaceWrapper
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp
    cfg = dict(block_config=MIXER_BLOCKS, memory_reduction_strategy="none",
               sequence_length=SEQ, train_batch_size=1,
               decode_loop="stepped", decode_chunk_tokens=5)
    cfg.update(kw)
    params = make_params(**cfg)
    params.train = False
    model = Model(params)
    seq = params.sequence_dim.size
    batch = {"token_x": np.zeros((1, seq, 1), np.int32),
             "token_y": np.zeros((1, seq, 1), np.int32)}
    variables = {k: jnp.asarray(v) for k, v in model.init(batch).items()}
    return InterfaceWrapper(params, model, variables)


def _draft_triple(features_per_head=8):
    """A narrow random-init draft (acceptance ~0 — every verify round is a
    total rejection), mirroring spec_decode_test's harness draft."""
    from homebrewnlp_tpu.model import Model
    import jax.numpy as jnp
    dparams = make_params(block_config=MIXER_BLOCKS,
                          memory_reduction_strategy="none",
                          sequence_length=SEQ, train_batch_size=1,
                          features_per_head=features_per_head)
    dparams.train = False
    dmodel = Model(dparams)
    zeros = np.zeros((1, SEQ, 1), np.int32)
    dvars = {k: jnp.asarray(v) for k, v in
             dmodel.init({"token_x": zeros, "token_y": zeros}).items()}
    return dparams, dmodel, dvars


def _composed(iface, draft, slots=4, block_tokens=4, pool_blocks=None,
              min_accept_rate=0.0, events=None):
    from homebrewnlp_tpu.infer.paged import SpecPagedEngineExecutor
    ex = SpecPagedEngineExecutor(iface, slots, draft, draft_tokens=4,
                                 min_accept_rate=min_accept_rate,
                                 block_tokens=block_tokens,
                                 pool_blocks=pool_blocks)
    answers = {}
    sched = SlotScheduler(ex.slots)
    ctl = EngineController(
        ex, sched, decode_chunk=5, prefill_chunk=8,
        answer=lambda req, oc: answers.__setitem__(req.rid, oc),
        hooks=(lambda event, **k: events.append((event, k)))
        if events is not None else None)
    return ex, ctl, sched, answers


def _serve(ctl, answers, reqs, rounds=120):
    ctl.round(reqs)
    for _ in range(rounds):
        if all(r.rid in answers for r in reqs):
            return
        ctl.round()
    raise AssertionError(f"unanswered: "
                         f"{[r.rid for r in reqs if r.rid not in answers]}")


def _req(rid, toks, rl):
    return EngineRequest(rid=rid, path="/token_completion",
                         toks=np.asarray(toks, np.int32), response_len=rl)


def _ref(iface, toks, rl):
    return np.asarray(iface.complete_tokens(np.asarray(toks, np.int32),
                                            0.0, rl))


def _block_content(ex, phys):
    """Physical block ``phys``'s rows in BOTH pools (target + draft): the
    composed carry is (token_x, tpools, dpools, key, seen), and the two
    pools ride the same tables — a COW must leave the parent's block
    bit-identical in each."""
    from homebrewnlp_tpu.infer.paged import classify_cache_leaves
    from homebrewnlp_tpu.infer.sampler import decode_cache_shapes
    probe = np.zeros((ex.slots, ex.seq, ex.tps), np.int32)
    out = {}
    for tag, model, variables, pools in (
            ("t", ex.model_w, ex.variables, ex._carry[1]),
            ("d", ex.draft_model_w, ex.draft_variables, ex._carry[2])):
        info = classify_cache_leaves(
            decode_cache_shapes(model, variables, probe), ex.seq)
        for name, leaf in pools.items():
            baxis, sax = info[name]
            if sax is None:
                continue
            out[f"{tag}/{name}"] = np.take(np.asarray(leaf), phys,
                                           axis=baxis).copy()
    return out


# --------------------------------------------------------- engine parity

def spec_paged_perfect_draft_bit_parity_test():
    """Composed-vs-plain greedy bit-parity token-for-token with the target
    itself as draft (acceptance 1.0, bonus path exercised) on an UNDERSIZED
    pool: three admission waves cycle blocks through the free list, so late
    requests draft-and-verify in reclaimed dirty blocks."""
    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.0)
    ex, ctl, sched, answers = _composed(
        iface, (iface.params, iface.model, iface.variables), pool_blocks=16)
    assert ex.engine.name == "spec_paged_chunk_step"
    assert ex.sharing
    waves = [
        list(zip(PROMPTS, RLS)),
        [([3, 1, 4], 8), ([2, 7, 1, 8], 10)],
        [([11, 12, 13, 14, 15], 7), ([9], 20)],
    ]
    n = 0
    for wave in waves:
        reqs = [_req(f"r{n + i}", toks, rl)
                for i, (toks, rl) in enumerate(wave)]
        n += len(wave)
        _serve(ctl, answers, reqs)
    n = 0
    for wave in waves:
        for toks, rl in wave:
            kind, got = answers[f"r{n}"]
            assert kind == "ok", (n, kind)
            np.testing.assert_array_equal(np.asarray(got),
                                          _ref(iface, toks, rl), str(n))
            n += 1
    s = ex.spec_summary()
    assert s["enabled"] and s["drafted"] > 0
    assert s["accept_rate"] == 1.0, s        # the draft IS the target
    stats = ex.pool_stats()
    assert stats["blocks_total"] == 16
    assert stats["blocks_in_use"] == 0       # everything released


def spec_paged_prefix_hit_recycled_slot_parity_test():
    """A prefix-hit admission INTO A RECYCLED SLOT: the second request's
    shared 16-token span resumes from the radix cache (prefill skipped, q
    starts past the span) in a slot whose previous occupant's rows — in
    BOTH pools — were evicted by the admit splice; output is BIT-IDENTICAL
    to a cold decode of the same prompt."""
    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.0)
    ex, ctl, sched, answers = _composed(
        iface, (iface.params, iface.model, iface.variables), slots=2)
    sysp = list(range(1, 17))                # 16 shared tokens, 4 blocks
    a, b = sysp + [21, 22], sysp + [23]
    _serve(ctl, answers, [_req("a", a, 6)])
    # churn both slots so b's admission recycles one with a dead occupant
    _serve(ctl, answers, [_req("x0", [5, 6], 4), _req("x1", [8, 9], 4)])
    st0 = dict(ex.pool_stats())
    _serve(ctl, answers, [_req("b", b, 6)])
    st1 = ex.pool_stats()
    assert st1["prefix_hits"] == st0["prefix_hits"] + 1
    assert st1["prefix_hit_tokens"] - st0["prefix_hit_tokens"] == 16
    for rid, toks in (("a", a), ("b", b)):
        np.testing.assert_array_equal(np.asarray(answers[rid][1]),
                                      _ref(iface, toks, 6), rid)
    assert ex.spec_summary()["accept_rate"] == 1.0


def spec_paged_cow_mid_draft_parent_blocks_unchanged_test():
    """Copy-on-write divergence MID-DRAFT: a child sharing two tokens of a
    promoted block diverges inside it while drafting is active; the write
    lands in the child's private copy through the shared tables, the
    parent's physical block stays bit-identical in BOTH pools, and the
    child's output matches a cold decode."""
    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.0)
    ex, ctl, sched, answers = _composed(
        iface, (iface.params, iface.model, iface.variables))
    parent = [5, 6, 7, 8, 9, 10]             # blocks: [5,6,7,8] + partial
    _serve(ctl, answers, [_req("parent", parent, 4)])
    assert ex.pool_stats()["blocks_cached"] >= 1
    full, _, _ = ex.tree.lookup(parent[:4])
    assert len(full) == 1
    phys = full[0].block
    before = _block_content(ex, phys)
    assert any(k.startswith("t/") for k in before), before.keys()
    assert any(k.startswith("d/") for k in before), before.keys()
    child = [5, 6, 99, 98, 97]               # diverges inside the block
    cow0 = ex.pool_stats()["cow_copies"]
    _serve(ctl, answers, [_req("child", child, 5)])
    assert ex.pool_stats()["cow_copies"] > cow0
    after = _block_content(ex, phys)
    for name in before:
        np.testing.assert_array_equal(before[name], after[name], name)
    np.testing.assert_array_equal(np.asarray(answers["child"][1]),
                                  _ref(iface, child, 5))


def spec_paged_total_rejection_bit_parity_test():
    """A random draft over the block pool (acceptance ~0): every verify
    round is a total rejection that advances on the verify's own sampled
    token, rejected draft rows in BOTH pools self-heal by overwrite before
    the next gather reads them, and output stays bit-identical to the
    plain slot engine."""
    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.0)
    ex, ctl, sched, answers = _composed(iface, _draft_triple())
    reqs = [_req(f"r{i}", p, rl)
            for i, (p, rl) in enumerate(zip(PROMPTS, RLS))]
    _serve(ctl, answers, reqs)
    for i, (p, rl) in enumerate(zip(PROMPTS, RLS)):
        kind, got = answers[f"r{i}"]
        assert kind == "ok", (i, kind)
        np.testing.assert_array_equal(np.asarray(got), _ref(iface, p, rl),
                                      str(i))
    s = ex.spec_summary()
    assert s["enabled"] and s["drafted"] > 0
    assert s["accept_rate"] < 0.5, s         # the draft is noise
    assert ex.pool_stats()["blocks_in_use"] == 0


def spec_paged_self_disable_recomposes_to_paged_test():
    """Acceptance collapse on the composed deployment: the self-disable
    drops the SPEC component only — the Engine recomposes to
    ``paged_chunk_step`` (block tables keep their layout, prefix sharing
    stays live) and serving continues bit-identically."""
    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.5)
    events = []
    ex, ctl, sched, answers = _composed(iface, _draft_triple(),
                                        min_accept_rate=0.5, events=events)
    reqs = [_req(f"r{i}", p, rl)
            for i, (p, rl) in enumerate(zip(PROMPTS, RLS))]
    _serve(ctl, answers, reqs)
    for i, (p, rl) in enumerate(zip(PROMPTS, RLS)):
        kind, got = answers[f"r{i}"]
        assert kind == "ok", (i, kind)
        np.testing.assert_array_equal(np.asarray(got), _ref(iface, p, rl),
                                      str(i))
    disabled = [k for e, k in events if e == "spec_disabled"]
    assert disabled and disabled[0]["rate"] < 0.5
    assert not ex._spec_enabled
    assert ex.engine.name == "paged_chunk_step"     # recomposed, not reset
    assert ex.engine.paged is not None
    # post-disable: the paged composition serves on — including a prefix
    # hit against blocks the SPEC composition promoted before the flip
    _serve(ctl, answers, [_req("after", PROMPTS[0] + [15], 5)])
    np.testing.assert_array_equal(np.asarray(answers["after"][1]),
                                  _ref(iface, PROMPTS[0] + [15], 5))


# ------------------------------------------------ resolution + composition

def spec_paged_knob_resolution_test():
    """kv_paging=on x spec_decode=draft — the previously-refused pair —
    resolves the composed executor when a draft is attached; without one
    the hard pair still refuses loudly (never a silent drop of an explicit
    requirement), and auto+auto falls back component-wise."""
    from homebrewnlp_tpu.config import ModelParameter
    from homebrewnlp_tpu.infer.paged import (PagedEngineExecutor,
                                             SpecPagedEngineExecutor)
    from homebrewnlp_tpu.infer.rest_api import _resolve_engine

    iface = _interface(spec_draft_tokens=4, spec_min_accept_rate=0.0)

    def resolve(**kw):
        params = ModelParameter(iface.params, serve_slots=2, **kw)
        params.train = False
        return _resolve_engine(params, iface)

    with pytest.raises(RuntimeError):        # no draft anywhere to load
        resolve(kv_paging="on", spec_decode="draft", kv_block_tokens=4)
    iface.draft = (iface.params, iface.model, iface.variables)
    ex = resolve(kv_paging="on", spec_decode="draft", kv_block_tokens=4)
    assert type(ex) is SpecPagedEngineExecutor
    assert ex.engine.name == "spec_paged_chunk_step"
    assert ex.engine.components == {"spec": True, "paged": True}
    # component-wise fallback: paging geometry the pool cannot carry drops
    # the paged component under auto, keeping spec on plain slots
    auto = resolve(kv_paging="auto", spec_decode="draft", kv_block_tokens=7)
    assert not isinstance(auto, PagedEngineExecutor)
    assert auto.engine.name == "spec_chunk_step"


def engine_recomposition_unit_test():
    """Engine rows: component flags map to registry names both ways, and
    dropping a component is recomposition (the survivor keeps its
    geometry), not a migration to a hand-written pair."""
    from homebrewnlp_tpu.analysis import entry_points
    from homebrewnlp_tpu.infer.engine import ENGINE_PROGRAMS, Engine
    _, model, _, _, _ = entry_points.build_audit_model()
    _, dmodel, _, _, _ = entry_points.build_audit_model(
        entry_points.DRAFT_AUDIT_OVERRIDES, seed=1)
    full = Engine(model, None, draft_model=dmodel, k=3, paged=(4, 16))
    assert full.name == "spec_paged_chunk_step"
    assert full.components == {"spec": True, "paged": True}
    dropped = Engine(model, None, paged=full.paged)
    assert dropped.name == "paged_chunk_step"
    assert dropped.paged == (4, 16)          # geometry survives the drop
    assert Engine(model, None).name == "engine_chunk_step"
    assert Engine(model, None, draft_model=dmodel,
                  k=3).name == "spec_chunk_step"
    assert set(ENGINE_PROGRAMS) == {
        "engine_chunk_step", "spec_chunk_step", "paged_chunk_step",
        "spec_paged_chunk_step"}


# ----------------------------------------------- carry-composition matrix

def carry_composition_alias_matrix_test():
    """The unit matrix over the two orthogonal components: EVERY
    registered composition lowers through the one builder, and every pool
    leaf of every composition stays donated+aliased with no
    full-pool-shaped copy — composing components must never cost a
    resident duplicate of any pool (the HLO audit per composition)."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.analysis import entry_points, hlo_lint
    from homebrewnlp_tpu.infer.engine import ENGINE_PROGRAMS, program_name

    _, model, variables, token_x, _ = entry_points.build_audit_model()
    _, dmodel, dvars, _, _ = entry_points.build_audit_model(
        entry_points.DRAFT_AUDIT_OVERRIDES, seed=1)
    tx = jnp.asarray(token_x)
    lower = {
        "engine_chunk_step":
            lambda: entry_points.lower_engine_step(model, variables, tx),
        "paged_chunk_step":
            lambda: entry_points.lower_paged_step(model, variables, tx),
        "spec_chunk_step":
            lambda: entry_points.lower_spec_step(
                model, variables, tx, draft_model=dmodel,
                draft_variables=dvars),
        "spec_paged_chunk_step":
            lambda: entry_points.lower_spec_paged_step(
                model, variables, tx, draft_model=dmodel,
                draft_variables=dvars),
    }
    leaves = {}
    for name, parts in ENGINE_PROGRAMS.items():
        assert program_name(**parts) == name
        hlo, ctx = lower[name]()
        assert hlo_lint.input_output_alias_count(hlo) \
            >= ctx["donated_leaves"], name
        findings = hlo_lint.audit(name, hlo,
                                  expected_aliases=ctx["donated_leaves"],
                                  protected_shapes=ctx["protected"],
                                  bf16_param_shapes=ctx["bf16_params"],
                                  budget={})
        assert findings == [], (name, [str(f) for f in findings])
        leaves[name] = ctx["donated_leaves"]
    # each component ADDS its own donated pool leaves to any base it
    # composes onto — no composition donates less than its parts
    assert leaves["spec_chunk_step"] > leaves["engine_chunk_step"]
    assert leaves["spec_paged_chunk_step"] > leaves["paged_chunk_step"]
    assert leaves["spec_paged_chunk_step"] >= leaves["spec_chunk_step"]
