"""Test env: force CPU with 8 virtual devices BEFORE jax initialises.

Mirrors the reference's PlacementMeshImpl-on-cpu:0 test harness
(/root/reference/tests/backend.py:45-59) but with a real 8-device mesh so
NamedSharding layouts and collectives are exercised (SURVEY.md §4 notes the
reference never tests multi-core behavior; we do).
"""
import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""  # make any jax re-init skip the axon TPU
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
