"""Test env: force CPU with 8 virtual devices BEFORE jax initialises.

Mirrors the reference's PlacementMeshImpl-on-cpu:0 test harness
(/root/reference/tests/backend.py:45-59) but with a real 8-device mesh so
NamedSharding layouts and collectives are exercised (SURVEY.md §4 notes the
reference never tests multi-core behavior; we do).
"""
import os
import sys

# The axon TPU plugin registers itself from sitecustomize at interpreter
# startup (it imports jax), so mutating os.environ here is too late once the
# accelerator tunnel is live.  Re-exec pytest exactly once with a clean
# CPU-only 8-device env instead; capture must be released first or the new
# process's output lands in the dead process's capture file.
flags = os.environ.get("XLA_FLAGS", "")
# only the live axon plugin needs the re-exec; everywhere else jax is not yet
# imported when this module loads, so in-process env mutation suffices
_needs_reexec = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def pytest_configure(config):
    if not _needs_reexec or os.environ.get("_HBNLP_TEST_REEXEC"):
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    new_flags = flags if "host_platform_device_count" in flags \
        else flags + " --xla_force_host_platform_device_count=8"
    env = dict(os.environ,
               _HBNLP_TEST_REEXEC="1",
               PALLAS_AXON_POOL_IPS="",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=new_flags)
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)


if not _needs_reexec:
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
