"""Shared test harness.

Mirrors the reference's statistical-test style
(/root/reference/tests/backend.py): layers are exercised on random input and
asserted on distributional properties (mean/std), not golden values, across
dtype grids.  RELU_STD and the size-scaled tolerance formula come from
tests/backend.py:13,71-73 of the reference.
"""
from __future__ import annotations

import typing

import numpy as np

from homebrewnlp_tpu.config import BlockArgs, ModelParameter
from homebrewnlp_tpu.core import scope
from homebrewnlp_tpu.core.dims import Dim
from homebrewnlp_tpu.core.tensor import NamedTensor, nt

RELU_STD = 1 / 1.42

MIXER_BLOCKS = [
    {'layer': ['norm-shift-scale-features-group',
               'bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:shift-mid:scale-mid:features']},
    {'layer': ['norm-shift-scale-features-group',
               'attention-biased_attention_map-absolute-input_as_value-shared',
               'norm-shift-scale-features-group', 'activation-gelu',
               'attention-biased_attention_map-absolute-input_as_value-shared']}]


def make_params(**kwargs) -> ModelParameter:
    cfg = {'model_mode': 'gpt', 'use_video': False, 'use_language': True,
           'sequence_length': 16, 'features_per_head': 16, 'heads': 2,
           'depth': 2, 'train_batch_size': 4, 'vocab_size': 32,
           'group_linear_factor': 2,
           'intermediate_feed_forward_multiplier_multiplier': 0.5,
           'block_config': MIXER_BLOCKS,
           'memory_reduction_strategy': 'none'}
    cfg.update(kwargs)
    return ModelParameter(cfg)


def tolerance(params: ModelParameter) -> float:
    fp16 = any("16" in str(d) for d in (params.calculation_dtype,
                                        params.slice_dtype, params.storage_dtype))
    return 1 / (params.train_batch_size * params.sequence_length
                * params.features) ** (0.05 if fp16 else 1 / 3)


class OpHarness:
    """Build one layer fn on a standard random input and inspect the output,
    creating parameters through a real init context."""

    def __init__(self, params: ModelParameter, extras: typing.Optional[list] = None,
                 seed: int = 0):
        self.params = params
        self.extras = [''] if extras is None else extras
        self.rng = np.random.default_rng(seed)

    def input_tensor(self) -> NamedTensor:
        p = self.params
        dims = [p.batch_dim, p.sequence_dim] + list(p.feature_dims)
        data = self.rng.standard_normal([d.size for d in dims]).astype(np.float32)
        return nt(data.astype(p.calculation_dtype), dims)

    def run(self, fn, *args, **kwargs):
        ctx = scope.Context("init", seed=0)
        with scope.context(ctx):
            out = fn(*args, **kwargs)
        self.ctx = ctx
        return out

    def run_layer(self, layer_fn) -> np.ndarray:
        inp = self.input_tensor()
        args = BlockArgs(self.params, inp, list(self.extras))
        out = self.run(layer_fn, args)
        return np.asarray(out.data, dtype=np.float32)
