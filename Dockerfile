# Container for the offline data-prep pipeline (video/text -> TFRecords),
# equivalent of the reference's video-pipeline image
# (/root/reference/scripts/Dockerfile + install_packages.sh).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        ffmpeg g++ make && \
    rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir numpy opencv-python-headless tokenizers zstandard

WORKDIR /workspace
COPY homebrewnlp_tpu/ homebrewnlp_tpu/
COPY native/ native/
COPY scripts/ scripts/

ENTRYPOINT ["python3"]
