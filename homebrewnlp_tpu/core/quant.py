"""Weight-only int8 quantization — shared by serving AND training.

Promoted from ``infer/quant.py`` (which remains as an import shim): the
eligibility rules, per-channel scale-axis selection and the
``quantize_variables`` entry the serving path has always used now live in
``core`` next to the scope/materialize machinery that consumes the scales,
and a TRAINING entry point joins them:

* Serving (``serve_quantized_weights``, unchanged semantics): quantize a
  loaded checkpoint ONCE on the host; ``core.scope.materialize_param``
  dequantizes at use so the convert+scale chain fuses into the consuming
  dot's operand read (batch-1 decode streams half the weight bytes,
  measured 99.3% argmax agreement on a trained checkpoint —
  docs/PERFORMANCE.md 'Decoding').

* Training (``train_quantized_matmuls``, PR 11): the jitted step
  re-quantizes the LIVE master weights every step on-device
  (:func:`quantize_for_training`) and the forward's largest GEMMs consume
  the int8 grid through :func:`ste_dequantize` — a straight-through
  estimator whose forward is the exact serving dequant chain (int8 ->
  convert -> scale, under ``jax.named_scope("dequant")`` so graft-lint can
  audit that no OTHER float promotion of an int8 operand exists) and whose
  backward passes the cotangent to the master weight unchanged (the
  round/clip grid has zero gradient a.e.; STE is the standard
  quantization-aware-training rule).  Master weights, the optimizer, and
  every update stay full precision — only what the matmuls READ is
  quantized, so the step's quality is measured exactly like serving
  quantization: >= 99% teacher-forcing argmax agreement, val loss within
  noise (tests/train_quant_test.py), and bit-identical losses when the
  knob is off.

Granularity (both paths): per-channel symmetric scales over every axis the
consuming einsum does NOT contract (``Model.param_fan_in``, recorded at
init); sibling depths of a block config share ONE scale (joint amax) so
the scan-over-layers replay resolves the same scale array under depth-0
canonical names — see the measured-quality discussion in the original
docstring, preserved below at :func:`quantize_variables`.
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp
import numpy as np

# quantize only tensors with at least this many elements AND >= 2 dims:
# the big matmul weights are the bandwidth term; norms/biases/rezero
# scalars are noise (and most are accuracy-sensitive)
MIN_QUANT_SIZE = 1 << 16


def eligible(name: str, value, dims) -> bool:
    if np.ndim(value) < 2 or np.size(value) < MIN_QUANT_SIZE:
        return False
    # embeddings feed gathers (position embeddings) or the output logits
    # head; the logits matmul IS bandwidth-heavy but its quantization error
    # lands directly on the sampled distribution — keep full precision
    # (measured: the decode step is dominated by the body matvecs)
    return "embed" not in name


def _scale_axes(dims, fan_in_names, ndim: int) -> typing.Tuple[int, ...]:
    """Axes the amax reduces over — i.e. where a single scale must cover the
    whole axis.  A per-channel scale is only sound along axes the consuming
    einsum does NOT contract (it must commute out of the sum), so reduce
    exactly over the recorded fan-in (contracted) axes.  Fall back to
    everything-but-last when the fan-in record is missing or degenerate
    (keeps the scale array a negligible fraction of the weight)."""
    if dims and fan_in_names:
        contracted = tuple(i for i, d in enumerate(dims)
                           if d.name in fan_in_names)
        n_contracted = 1
        for i in contracted:
            n_contracted *= dims[i].size
        if contracted and n_contracted >= 64:
            return contracted
    # fallback: per-channel along the last axis only.  Finer schemes were
    # measured WORSE on a trained MoE checkpoint (docstring): per-(channel,
    # expert) scales on the 4-dim expert weights dropped teacher-forcing
    # agreement 91% → 85% despite being mathematically commutable — the
    # per-expert amax acts as mild smoothing the finer grid loses
    return tuple(range(ndim - 1))


def _canonical(name: str) -> str:
    from ..model.backend import _BLOCK_RE
    return _BLOCK_RE.sub(
        lambda m: f"{m.group(1)}block0_{m.group(3)}_{m.group(4)}/", name)


def _scale_groups(variables: typing.Dict[str, typing.Any],
                  param_dims: typing.Optional[dict],
                  param_fan_in: typing.Optional[dict]
                  ) -> typing.Dict[str, typing.Tuple[list, tuple]]:
    """``{canonical name: ([member names], scale axes)}`` over the eligible
    weights — sibling depths of one block config share ONE group (joint
    amax): the scan-over-layers replay resolves every depth under the
    depth-0 canonical names, so per-depth scales would silently apply
    depth-0's channel pattern to all depths."""
    groups: typing.Dict[str, list] = {}
    for name, value in variables.items():
        dims = (param_dims or {}).get(name, ())
        if eligible(name, value, dims):
            groups.setdefault(_canonical(name), []).append(name)
    out = {}
    for canon, names in groups.items():
        dims = (param_dims or {}).get(names[0], ())
        axes = _scale_axes(dims, (param_fan_in or {}).get(names[0], ()),
                           np.ndim(variables[names[0]]))
        out[canon] = (names, axes)
    return out


def _quantize_group(variables: typing.Dict[str, typing.Any],
                    names: typing.Sequence[str],
                    axes: typing.Tuple[int, ...],
                    stop_grad: bool = False
                    ) -> typing.Tuple[typing.Dict[str, jax.Array],
                                      jax.Array]:
    """``({name: int8 weight}, shared scale)`` for ONE depth-shared group —
    the single definition of the grid (joint amax over the group,
    ``amax/127`` symmetric scale, clip to ±127) serving AND training share,
    so the two paths cannot silently desynchronize.  ``stop_grad`` stops
    the amax/round chain for the in-step training path (the scale follows
    the weights; it is not a gradient path)."""
    def _w(name):
        w = jnp.asarray(variables[name], jnp.float32)
        return jax.lax.stop_gradient(w) if stop_grad else w

    amax = None
    for name in names:
        a = jnp.max(jnp.abs(_w(name)), axis=axes, keepdims=True)
        amax = a if amax is None else jnp.maximum(amax, a)
    scale = (jnp.maximum(amax, 1e-30) / 127.0).astype(jnp.float32)
    qdata = {name: jnp.clip(jnp.round(_w(name) / scale), -127,
                            127).astype(jnp.int8)
             for name in names}
    return qdata, scale


def quantize_variables(variables: typing.Dict[str, typing.Any],
                       param_dims: typing.Optional[dict] = None,
                       param_fan_in: typing.Optional[dict] = None
                       ) -> typing.Tuple[typing.Dict[str, jax.Array],
                                         typing.Dict[str, jax.Array]]:
    """(quantized variables, scales): eligible weights become int8 arrays
    with per-channel f32 scales such that ``w ≈ w_q * scale``; everything
    else passes through unchanged.  ``param_fan_in`` (Model.param_fan_in)
    names each weight's contracted dims so the scales can be per-channel
    over EVERY non-contracted axis — per-expert × per-column for MoE
    weights, not just per-last-axis.

    Measured on a TRAINED 1000-step checkpoint (the MoE mixer, loss 1.41
    on held-out text): per-tensor scales degrade teacher-forcing argmax
    agreement to 73% / loss +0.59; depth-shared per-channel scales measure
    **99.3% agreement with the loss unchanged to four decimals** — at
    2.31 → 1.38 ms/token decode (with int8 caches) at the flagship.  The
    scales dict carries each group's array under every member name AND the
    canonical name."""
    qvars: typing.Dict[str, jax.Array] = dict(variables)
    scales: typing.Dict[str, jax.Array] = {}
    for canon, (names, axes) in _scale_groups(variables, param_dims,
                                              param_fan_in).items():
        qdata, scale = _quantize_group(variables, names, axes)
        for name in names:
            qvars[name] = qdata[name]
            scales[name] = scale
        scales[canon] = scale
    return qvars, scales


# ---- training path (train_quantized_matmuls) -------------------------------

@jax.custom_vjp
def ste_dequantize(master: jax.Array, qdata: jax.Array,
                   scale: jax.Array) -> jax.Array:
    """Dequantized weight with a straight-through gradient to ``master``.

    Forward VALUE is exactly the serving dequant chain — ``qdata`` (int8)
    converted and multiplied by ``scale`` — so the compiled step reads the
    quantized grid, not the master; backward passes the output cotangent
    to ``master`` unchanged (round/clip has zero gradient a.e.; the
    straight-through estimator is the standard QAT rule) and zero to
    ``scale`` (scales follow the master's amax, they are re-derived each
    step, not learned)."""
    del master
    return (qdata.astype(jnp.float32) * scale)


def _ste_fwd(master, qdata, scale):
    # residuals carry the live master/scale only for their dtype/shape —
    # both are step inputs, so nothing extra stays resident
    return ste_dequantize(master, qdata, scale), (master, scale)


def _ste_bwd(res, ct):
    master, scale = res
    # int8 qdata gets a symbolic-zero (float0) cotangent automatically
    return (ct.astype(master.dtype), None, jnp.zeros_like(scale))


ste_dequantize.defvjp(_ste_fwd, _ste_bwd)


def quantize_for_training(variables: typing.Dict[str, jax.Array],
                          param_dims: typing.Optional[dict],
                          param_fan_in: typing.Optional[dict],
                          calc_dtype) -> typing.Dict[str, jax.Array]:
    """Per-step fake-quantized view of the live master weights.

    Runs INSIDE the jitted train step: one amax pass per eligible weight
    group (depth-shared, per-channel — identical grid to the serving
    path), then each eligible weight is replaced by its
    :func:`ste_dequantize` value in ``calc_dtype``.  Ineligible leaves
    pass through untouched, so the returned dict is a drop-in for
    ``model.apply``.  The quantize lives under ``named_scope("quantize_
    weights")`` and the dequant under ``named_scope("dequant")`` — the
    join keys graft-lint's int8-promotion audit checks, and the scopes the
    cost ledger attributes the (small) extra work to."""
    out = dict(variables)
    for canon, (names, axes) in _scale_groups(variables, param_dims,
                                              param_fan_in).items():
        with jax.named_scope("quantize_weights"):
            # stop_grad: the scale follows the weights, it is not a
            # gradient path (matches _ste_bwd's zero scale cotangent)
            qdata, scale = _quantize_group(variables, names, axes,
                                           stop_grad=True)
        with jax.named_scope("dequant"):
            for name in names:
                out[name] = ste_dequantize(
                    variables[name], qdata[name], scale).astype(calc_dtype)
    return out
