"""Named tensors over jax.numpy.

The TPU-native substrate replacing Mesh-TensorFlow tensors and the reference's
wrapper layer (/root/reference/src/mtf_wrapper.py, src/utils_mtf.py).  A
``NamedTensor`` is a jax array plus a tuple of ``Dim``s; dim names drive
einsum contraction, broadcasting, reductions and sharding annotations.  All
ops are pure jnp — autodiff is native ``jax.grad`` (the reference needed a
hand-written reverse sweep, src/optimizer/__init__.py:143-174, because mtf
lacked tracing AD).
"""
from __future__ import annotations

import dataclasses
import string
import typing

import jax
import jax.numpy as jnp
import numpy as np

from .dims import (DIM_LIST, Dim, SHAPE, deduplicate, dim_name, index_of,
                   shape_size, shape_sub)

Array = jax.Array


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class NamedTensor:
    data: Array
    dims: typing.Tuple[Dim, ...]

    def __post_init__(self):
        object.__setattr__(self, "dims", tuple(self.dims))

    def tree_flatten(self):
        return (self.data,), self.dims

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux)

    # -- convenience -------------------------------------------------------
    @property
    def shape(self) -> typing.Tuple[Dim, ...]:
        return self.dims

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def size(self) -> int:
        return shape_size(self.dims)

    def dim(self, name: typing.Union[str, Dim]) -> Dim:
        return self.dims[index_of(self.dims, name)]

    def axis(self, name: typing.Union[str, Dim]) -> int:
        return index_of(self.dims, name)

    def __repr__(self):
        return f"NamedTensor({list(self.dims)}, {self.data.dtype})"

    # arithmetic sugar
    def __add__(self, other):
        return add(self, other)

    def __radd__(self, other):
        return add(other, self)

    def __sub__(self, other):
        return subtract(self, other)

    def __rsub__(self, other):
        return subtract(other, self)

    def __mul__(self, other):
        return multiply(self, other)

    def __rmul__(self, other):
        return multiply(other, self)

    def __truediv__(self, other):
        return divide(self, other)

    def __rtruediv__(self, other):
        return divide(other, self)

    def __neg__(self):
        return unary(jnp.negative, self)


NT = NamedTensor
TensorLike = typing.Union[NT, float, int, Array]


def nt(data: Array, dims: SHAPE) -> NT:
    dims = tuple(dims)
    assert tuple(data.shape) == tuple(d.size for d in dims), (data.shape, dims)
    return NamedTensor(data, dims)


def zeros(dims: SHAPE, dtype=jnp.float32) -> NT:
    return nt(jnp.zeros([d.size for d in dims], dtype), dims)


def ones(dims: SHAPE, dtype=jnp.float32) -> NT:
    return nt(jnp.ones([d.size for d in dims], dtype), dims)


def zeros_like(t: NT) -> NT:
    return nt(jnp.zeros_like(t.data), t.dims)


def ones_like(t: NT) -> NT:
    return nt(jnp.ones_like(t.data), t.dims)


def constant(value: float, dtype=jnp.float32) -> NT:
    return nt(jnp.asarray(value, dtype), ())


def cast(t: NT, dtype) -> NT:
    return nt(t.data.astype(dtype), t.dims)


def stop_gradient(t: NT) -> NT:
    return nt(jax.lax.stop_gradient(t.data), t.dims)


# -- einsum ---------------------------------------------------------------

def _symbols(all_dims: DIM_LIST) -> typing.Dict[Dim, str]:
    letters = string.ascii_letters
    if len(all_dims) > len(letters):
        raise ValueError("too many distinct dims for einsum")
    return {d: letters[i] for i, d in enumerate(all_dims)}


def einsum(inputs: typing.Sequence[NT], output_shape: SHAPE) -> NT:
    """Named einsum: dims shared by name+size contract unless in the output.

    Replaces /root/reference/src/mtf_wrapper.py einsum; maps directly to one
    MXU-friendly XLA dot/contraction.
    """
    inputs = list(inputs)
    output_shape = list(output_shape)
    all_dims = deduplicate([d for t in inputs for d in t.dims] +
                           list(output_shape))
    sym = _symbols(all_dims)
    in_specs = ",".join("".join(sym[d] for d in t.dims) for t in inputs)
    out_spec = "".join(sym[d] for d in output_shape)
    dtype = jnp.result_type(*[t.dtype for t in inputs])
    # bf16 matmuls accumulate in f32 on the MXU; CPU's DotThunk can't emit
    # mixed bf16->f32 dots, so only request it on TPU backends.  The
    # ``matmul_accumulation`` config knob rides the scope context: "bf16"
    # drops the f32 request (faster MXU accumulation, quality-guarded —
    # config.py), "f32"/"auto" keep it where the backend supports it
    prefer = None
    if dtype == jnp.bfloat16 and jax.default_backend() not in ("cpu",):
        from . import scope  # function-level: scope imports this module
        policy = (getattr(scope.current(), "matmul_accumulation", None)
                  if scope.in_context() else None)
        if policy != "bf16":
            prefer = jnp.float32
    data = jnp.einsum(f"{in_specs}->{out_spec}",
                      *[t.data for t in inputs],
                      preferred_element_type=prefer)
    return nt(data.astype(dtype), output_shape)


# -- broadcasting binary ops ---------------------------------------------

def _as_nt(x: TensorLike, like: typing.Optional[NT] = None) -> NT:
    if isinstance(x, NamedTensor):
        return x
    dtype = like.dtype if like is not None else jnp.float32
    return nt(jnp.asarray(x, dtype), ())


def _align(t: NT, out_dims: DIM_LIST) -> Array:
    """View of t.data transposed/expanded to out_dims order (size-1 on missing)."""
    perm = [t.axis(d) for d in out_dims if d in t.dims]
    data = jnp.transpose(t.data, perm) if perm != list(range(len(perm))) else t.data
    shape = [d.size if d in t.dims else 1 for d in out_dims]
    return jnp.reshape(data, shape)


def binary(op, a: TensorLike, b: TensorLike) -> NT:
    a = _as_nt(a, b if isinstance(b, NamedTensor) else None)
    b = _as_nt(b, a)
    out_dims = deduplicate(list(a.dims) + list(b.dims))
    return nt(op(_align(a, out_dims), _align(b, out_dims)), out_dims)


def add(a, b):
    return binary(jnp.add, a, b)


def subtract(a, b):
    return binary(jnp.subtract, a, b)


def multiply(a, b):
    return binary(jnp.multiply, a, b)


def divide(a, b):
    return binary(jnp.divide, a, b)


def maximum(a, b):
    return binary(jnp.maximum, a, b)


def minimum(a, b):
    return binary(jnp.minimum, a, b)


def mod(a, b):
    return binary(jnp.mod, a, b)


def floordiv(a, b):
    return binary(jnp.floor_divide, a, b)


def pow_(a, b):
    return binary(jnp.power, a, b)


def _cmp(op):
    def fn(a, b, dtype=None):
        out = binary(op, a, b)
        return cast(out, dtype) if dtype is not None else out
    return fn


greater_equal = _cmp(jnp.greater_equal)
greater = _cmp(jnp.greater)
less = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)


def weighted_add(left: TensorLike, right: TensorLike, alpha: TensorLike) -> NT:
    """left * alpha + right * (1 - alpha) (reference: src/utils_mtf.py:332)."""
    return add(multiply(left, alpha), multiply(right, subtract(1, alpha)))


# -- unary ----------------------------------------------------------------

def unary(op, t: NT) -> NT:
    return nt(op(t.data), t.dims)


def exp(t):
    return unary(jnp.exp, t)


def log(t):
    return unary(jnp.log, t)


def sqrt(t):
    return unary(jnp.sqrt, t)


def rsqrt(t):
    return unary(jax.lax.rsqrt, t)


def square(t):
    return unary(jnp.square, t)


def reciprocal(t):
    return unary(jnp.reciprocal, t)


def negative(t):
    return unary(jnp.negative, t)


def sign(t):
    return unary(jnp.sign, t)


def abs_(t):
    return unary(jnp.abs, t)


def sigmoid(t):
    return unary(jax.nn.sigmoid, t)


def tanh(t):
    return unary(jnp.tanh, t)


def softplus(t):
    return unary(jax.nn.softplus, t)


def sin(t):
    return unary(jnp.sin, t)


def relu(t):
    return unary(jax.nn.relu, t)


def rsqrt_eps(t: NT, epsilon: float = 1e-6) -> NT:
    return rsqrt(add(t, epsilon))


# -- reductions -----------------------------------------------------------

def _reduce(op, t: NT, reduced_dim=None, output_shape=None) -> NT:
    if output_shape is None:
        if reduced_dim is None:
            output_shape = []
        else:
            output_shape = shape_sub(t.dims, reduced_dim)
    output_shape = list(output_shape)
    axes = tuple(i for i, d in enumerate(t.dims) if d not in output_shape)
    data = op(t.data, axis=axes) if axes else t.data
    # reorder remaining axes to match output_shape order
    remaining = [d for d in t.dims if d in output_shape]
    if remaining != output_shape:
        perm = [remaining.index(d) for d in output_shape]
        data = jnp.transpose(data, perm)
    return nt(data, output_shape)


def reduce_sum(t, reduced_dim=None, output_shape=None):
    return _reduce(jnp.sum, t, reduced_dim, output_shape)


def reduce_mean(t, reduced_dim=None, output_shape=None):
    return _reduce(jnp.mean, t, reduced_dim, output_shape)


def reduce_max(t, reduced_dim=None, output_shape=None):
    return _reduce(jnp.max, t, reduced_dim, output_shape)


def reduce_min(t, reduced_dim=None, output_shape=None):
    return _reduce(jnp.min, t, reduced_dim, output_shape)


def reduce_logsumexp(t, reduced_dim) -> NT:
    axis = t.axis(reduced_dim)
    return nt(jax.nn.logsumexp(t.data, axis=axis), shape_sub(t.dims, reduced_dim))


# -- shape ops ------------------------------------------------------------

def rename_dim(t: NT, old: typing.Union[str, Dim], new_name: str) -> NT:
    i = t.axis(old)
    dims = list(t.dims)
    dims[i] = Dim(new_name, dims[i].size)
    return nt(t.data, dims)


def replace_dim(t: NT, old: typing.Union[str, Dim], new: Dim) -> NT:
    i = t.axis(old)
    assert t.dims[i].size == new.size
    dims = list(t.dims)
    dims[i] = new
    return nt(t.data, dims)


def transpose_to(t: NT, dims: SHAPE) -> NT:
    dims = list(dims)
    perm = [t.axis(d) for d in dims]
    return nt(jnp.transpose(t.data, perm), dims)


def reshape(t: NT, new_dims: SHAPE) -> NT:
    """Order-preserving reshape (split/merge), mtf.reshape analogue."""
    new_dims = list(new_dims)
    assert shape_size(new_dims) == t.size, (t.dims, new_dims)
    return nt(jnp.reshape(t.data, [d.size for d in new_dims]), new_dims)


def slice_(t: NT, start: int, end: int, dim: typing.Union[str, Dim]) -> NT:
    """Slice along a named dim (reference: src/utils_mtf.py utils_slice).

    The reference anonymize->slice->unanonymize dance exists because mtf can't
    slice a sharded dim; under GSPMD a plain lax.slice is legal on any layout.
    """
    i = t.axis(dim)
    if start == 0 and end == t.dims[i].size:
        return t
    idx = [slice(None)] * len(t.dims)
    idx[i] = slice(start, end)
    dims = list(t.dims)
    dims[i] = Dim(dims[i].name, end - start)
    return nt(t.data[tuple(idx)], dims)


def concat(tensors: typing.Sequence[NT], dim: typing.Union[str, Dim]) -> NT:
    name = dim_name(dim)
    axis = index_of(tensors[0].dims, name)
    data = jnp.concatenate([t.data for t in tensors], axis=axis)
    dims = list(tensors[0].dims)
    dims[axis] = Dim(name, sum(t.dims[index_of(t.dims, name)].size for t in tensors))
    return nt(data, dims)


def pad(t: NT, dim: typing.Union[str, Dim], before: int, after: int, value=0.0) -> NT:
    i = t.axis(dim)
    widths = [(0, 0)] * len(t.dims)
    widths[i] = (before, after)
    dims = list(t.dims)
    dims[i] = Dim(dims[i].name, dims[i].size + before + after)
    return nt(jnp.pad(t.data, widths, constant_values=value), dims)


def unbind(t: NT, dim: typing.Union[str, Dim]) -> typing.List[NT]:
    """Split a dim into a list of tensors without it (src/utils_mtf.py unbind)."""
    i = t.axis(dim)
    dims = shape_sub(t.dims, t.dims[i])
    return [nt(jnp.take(t.data, j, axis=i), dims) for j in range(t.dims[i].size)]


def range_(dim: Dim, dtype=jnp.float32) -> NT:
    return nt(jnp.arange(dim.size, dtype=dtype), [dim])


def one_hot(t: NT, dim: Dim, dtype=jnp.float32) -> NT:
    return nt(jax.nn.one_hot(t.data, dim.size, dtype=dtype), list(t.dims) + [dim])


def cumsum(t: NT, dim: typing.Union[str, Dim]) -> NT:
    return nt(jnp.cumsum(t.data, axis=t.axis(dim)), t.dims)


def argmax(t: NT, reduced_dim) -> NT:
    axis = t.axis(reduced_dim)
    return nt(jnp.argmax(t.data, axis=axis), shape_sub(t.dims, t.dims[axis]))


def top_1(t: NT, reduced_dim) -> typing.Tuple[NT, NT]:
    axis = t.axis(reduced_dim)
    dims = shape_sub(t.dims, t.dims[axis])
    idx = jnp.argmax(t.data, axis=axis)
    val = jnp.max(t.data, axis=axis)
    return nt(val, dims), nt(idx, dims)


def gather_axis0(embedding: NT, indices: NT) -> NT:
    """out[idx..., emb_rest...] = embedding[indices[idx...], emb_rest...]

    jnp.take with native gradient replaces the reference's hand-written
    Gather/ScatterAdd mtf Operations (src/model/embedding.py:39-125).
    """
    out_dims = list(indices.dims) + list(embedding.dims[1:])
    return nt(jnp.take(embedding.data, indices.data, axis=0), out_dims)


def dropout(t: NT, train: bool, keep_prob: float, key: typing.Optional[Array]) -> NT:
    if not train or keep_prob >= 1.0 or key is None:
        return t
    mask = jax.random.bernoulli(key, keep_prob, t.data.shape)
    return nt(jnp.where(mask, t.data / keep_prob, 0).astype(t.dtype), t.dims)


def add_n(tensors: typing.Sequence[TensorLike]) -> NT:
    out = tensors[0]
    for t in tensors[1:]:
        out = add(out, t)
    return out


def to_np(t: NT) -> np.ndarray:
    return np.asarray(t.data)
