"""Deterministic hierarchical naming + two-phase (init/apply) parameter store.

Replaces the reference's global ``NAME_INDICES`` variable-scope counters
(/root/reference/src/utils_core.py:16-19,57-67) and TF1 variable reuse.  Names
are hierarchical rather than global so any subtree (e.g. one reversible block)
can be re-traced in isolation inside a ``jax.custom_vjp`` backward pass and
still resolve the same parameter names.

Two phases, haiku-style but in-tree:
  * init: layer code runs once eagerly; ``get_param`` materialises numpy
    values from per-name seeded initializers and records them.
  * apply: same code path; ``get_param`` fetches arrays from the provided
    dict (casting storage/slice dtype -> calculation dtype).

All scope state lives in a context stack that exists only at trace time, so
everything stays compatible with jit/grad/vmap.
"""
from __future__ import annotations

import contextlib
import dataclasses
import typing
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import NamedTensor, nt

Params = typing.Dict[str, jax.Array]


@dataclasses.dataclass
class _Frame:
    name: str
    counters: typing.Dict[str, int] = dataclasses.field(default_factory=dict)


class Context:
    """One build context: either collecting params (init) or reading them."""

    def __init__(self, mode: str, params: typing.Optional[Params] = None,
                 seed: int = 0, rng_key: typing.Optional[jax.Array] = None,
                 record_touched: bool = False, mesh: typing.Any = None,
                 decode: typing.Any = None):
        assert mode in ("init", "apply")
        self.mode = mode
        self.params: Params = {} if params is None else params
        self.seed = seed
        self.rng_key = rng_key
        # jax.sharding.Mesh when running sharded; layers may specialise
        # (e.g. ring attention over a 'sequence' axis)
        self.mesh = mesh
        # model.decode.DecodeState during incremental (KV-cached) decoding
        self.decode = decode
        # model.decode.PrefillState during single-pass prompt prefill: the
        # FULL-length forward runs normally while the sequence-mixing ops
        # additionally capture their decode caches (KV rows, cumsum totals,
        # conv windows) so the sampler can skip the per-token prompt walk
        self.prefill = None
        self.stack: typing.List[_Frame] = [_Frame("")]
        self.touched: typing.Optional[typing.List[str]] = [] if record_touched else None
        # name -> tuple[Dim] recorded at init; consumed by the optimizer's
        # shape-based heuristics and the sharding planner
        self.param_dims: typing.Dict[str, tuple] = {}
        # name -> tuple of contracted-dim NAMES (the linear's fan-in),
        # recorded at init when the initializer knows them; consumed by
        # serving quantization to pick safe per-channel scale axes
        self.param_fan_in: typing.Dict[str, tuple] = {}
        # arbitrary cross-layer caches (shared-variable machinery etc.)
        self.cache: typing.Dict[str, typing.Any] = {}
        # when not None, layers append (scope_path, {stat: scalar}) tuples
        # (e.g. MoE routing stats).  Only set by forward-only probe passes
        # where no lax.scan/custom_vjp separates the layer trace from the
        # consumer — ReplayBlock propagates it into its per-block contexts.
        self.stats_sink: typing.Optional[list] = None
        # matmul-accumulation policy for bf16 einsums ("auto"/"f32"/"bf16",
        # config.matmul_accumulation); consumed by core.tensor.einsum and
        # propagated by ReplayBlock like quant_scales
        self.matmul_accumulation: typing.Optional[str] = None
        self._rng_count = 0

    # -- naming ------------------------------------------------------------
    def enter(self, name: str) -> str:
        frame = self.stack[-1]
        idx = frame.counters.get(name, 0)
        frame.counters[name] = idx + 1
        scoped_name = f"{name}{idx}"
        self.stack.append(_Frame(scoped_name))
        return scoped_name

    def exit(self):
        self.stack.pop()

    def path(self) -> str:
        return "/".join(f.name for f in self.stack[1:])

    def full_name(self, leaf: str) -> str:
        frame = self.stack[-1]
        idx = frame.counters.get(leaf, 0)
        frame.counters[leaf] = idx + 1
        p = self.path()
        return f"{p}/{leaf}{idx}" if p else f"{leaf}{idx}"

    # -- rng ---------------------------------------------------------------
    def next_rng(self) -> typing.Optional[jax.Array]:
        if self.rng_key is None:
            return None
        self._rng_count += 1
        return jax.random.fold_in(self.rng_key, self._rng_count)


_CTX: typing.List[Context] = []


def current() -> Context:
    if not _CTX:
        raise RuntimeError("no active build Context; wrap model code in `with context(...)`")
    return _CTX[-1]


def in_context() -> bool:
    return bool(_CTX)


@contextlib.contextmanager
def context(ctx: Context):
    _CTX.append(ctx)
    try:
        yield ctx
    finally:
        _CTX.pop()


@contextlib.contextmanager
def name_scope(name: str):
    ctx = current()
    scoped_name = ctx.enter(name)
    try:
        # mirror the scope frame into jax's name stack: every op traced
        # inside lands in compiled-HLO ``metadata={op_name=...}`` and in
        # jaxpr ``source_info.name_stack`` with its block/layer identity —
        # the substrate the cost ledger (analysis/cost_ledger.py) and trace
        # attribution (scripts/attribute_step.py) join on.  Metadata only:
        # the compiled program is unchanged.
        with jax.named_scope(scoped_name):
            yield
    finally:
        ctx.exit()


def scoped(name: str, fn: typing.Callable, *args, **kwargs):
    """Run fn under a uniquified name scope (src/utils_core.py:16 analogue)."""
    with name_scope(name):
        return fn(*args, **kwargs)


def name_seed(name: str, seed: int) -> np.random.Generator:
    """Per-parameter deterministic RNG derived from (config seed, name)."""
    return np.random.default_rng(np.random.Philox(key=[seed & (2 ** 64 - 1),
                                                       zlib.crc32(name.encode())]))


def get_param(name_leaf: str, dims, initializer, slice_dtype, calc_dtype
              ) -> NamedTensor:
    """Create (init) or fetch (apply) a parameter as a NamedTensor.

    ``initializer(rng, sizes) -> np.ndarray`` runs in float32; stored in
    slice_dtype (the mtf VariableDType.slice_dtype analogue,
    /root/reference/src/dataclass.py:253-255), computed in calc_dtype.
    """
    ctx = current()
    name = ctx.full_name(name_leaf)
    dims = tuple(dims)
    sizes = tuple(d.size for d in dims)
    if ctx.mode == "init":
        if name in ctx.params:
            raise ValueError(f"duplicate parameter {name}")
        value = np.asarray(initializer(name_seed(name, ctx.seed), sizes),
                           dtype=np.float32)
        assert value.shape == sizes, (name, value.shape, sizes)
        # init stores host numpy (the "master" copy, mtf Saver-style);
        # device placement + sharding happen at train setup, so init never
        # touches an accelerator.
        ctx.params[name] = value.astype(slice_dtype)
        ctx.param_dims[name] = dims
        fan_in = getattr(initializer, "fan_in_names", None)
        if fan_in:
            ctx.param_fan_in[name] = tuple(fan_in)
    if name not in ctx.params:
        raise KeyError(f"parameter {name} missing from provided params")
    if ctx.touched is not None and name not in ctx.touched:
        ctx.touched.append(name)
    data = ctx.params[name]
    assert tuple(data.shape) == sizes, (name, data.shape, sizes)
    return nt(materialize_param(ctx, name, data, calc_dtype), dims)


def materialize_param(ctx: Context, name: str, data, calc_dtype):
    """Parameter value in calculation dtype; int8-quantized serving weights
    (infer/quant.py) dequantize here — the convert+scale chain fuses into
    the consuming dot's operand read, so the HBM traffic stays int8.

    The dtype gate (not just name-in-scales) makes a stale ``quant_scales``
    harmless: applying the same Model to full-precision variables after a
    quantized InterfaceWrapper touched it must not scale unquantized
    weights."""
    scales = getattr(ctx, "quant_scales", None)
    if scales and data.dtype == jnp.int8 and name in scales:
        # named region: graft-lint's int8-promotion audit allows s8->float
        # converts ONLY inside dequant-tagged scopes (hlo_lint.py), so the
        # serving dequant must carry the same tag the training-side
        # ste_dequantize does (core/quant.py)
        with jax.named_scope("dequant"):
            scaled = data.astype(jnp.float32) * scales[name]
            return scaled.astype(calc_dtype)
    return data.astype(calc_dtype)
