"""Named-dimension algebra.

TPU-native replacement for Mesh-TensorFlow's ``mtf.Dimension``/``mtf.Shape``
(reference: /root/reference/src/utils_mtf.py).  Dimensions are (name, size)
pairs; two dims are equal iff both name and size match, exactly like mtf.
Dim *names* carry all semantics in this framework:

- einsum contraction is driven by shared dim names (core/tensor.py),
- sharding is driven by a dim-name -> mesh-axis map (core/sharding.py),
- "anonymized" dims (leading ``_``) never match a mesh axis and are therefore
  replicated — the same trick the reference uses to force replication
  (/root/reference/src/utils_mtf.py:84-96,207-232), except here it is purely a
  sharding annotation: XLA GSPMD inserts the all-gather, we never reshape.
"""
from __future__ import annotations

import dataclasses
import typing

import numpy as np


@dataclasses.dataclass(frozen=True, order=True)
class Dim:
    name: str
    size: int

    def __repr__(self) -> str:
        return f"{self.name}={self.size}"


DIM_LIST = typing.List[Dim]
SHAPE = typing.Sequence[Dim]


def anonymize_dim(dim: typing.Union[Dim, str], size: typing.Optional[int] = None) -> Dim:
    """Leading-underscore copy of a dim; replicated under the layout rules.

    Mirrors /root/reference/src/utils_mtf.py:84-96 (including the optional
    size override used by group_linear's widened key dim).
    """
    name = dim.name if isinstance(dim, Dim) else dim
    if not name.startswith("_"):
        name = "_" + name
    if size is None:
        if not isinstance(dim, Dim):
            raise ValueError("size required when anonymizing a bare name")
        size = dim.size
    return Dim(name, size)


def unanonymize_dim(dim: Dim, size: typing.Optional[int] = None) -> Dim:
    name = dim.name.lstrip("_")
    return Dim(name, dim.size if size is None else size)


def dim_name(dim: typing.Union[Dim, str]) -> str:
    return dim.name if isinstance(dim, Dim) else dim


def deduplicate(dims: SHAPE) -> DIM_LIST:
    """Stable-order dedup (reference: src/utils_mtf.py deduplicate)."""
    out: DIM_LIST = []
    for d in dims:
        if d not in out:
            out.append(d)
    return out


def shape_size(dims: SHAPE) -> int:
    return int(np.prod([d.size for d in dims], dtype=np.int64)) if dims else 1


def shape_sub(shape: SHAPE, other: typing.Union[SHAPE, Dim]) -> DIM_LIST:
    """Shape difference by dim equality, preserving order (mtf.Shape.__sub__)."""
    if isinstance(other, Dim):
        other = [other]
    other = list(other)
    return [d for d in shape if d not in other]


def shape_addition(*shapes: SHAPE) -> DIM_LIST:
    dims: DIM_LIST = []
    for s in shapes:
        dims.extend(s)
    return deduplicate(dims)


def shape_crossection(*shapes: SHAPE) -> DIM_LIST:
    """Ordered intersection of shapes (reference: src/utils_mtf.py:394-397)."""
    return [d for d in shape_addition(*shapes) if all(d in list(s) for s in shapes)]


def missing_dims(self_shape: SHAPE, other: SHAPE) -> DIM_LIST:
    return shape_sub(other, self_shape)


def index_of(shape: SHAPE, dim: typing.Union[Dim, str]) -> int:
    name = dim_name(dim)
    for i, d in enumerate(shape):
        if d.name == name and (not isinstance(dim, Dim) or d.size == dim.size):
            return i
    raise KeyError(f"dim {dim!r} not in shape {list(shape)!r}")


def has_dim(shape: SHAPE, dim: typing.Union[Dim, str]) -> bool:
    try:
        index_of(shape, dim)
        return True
    except KeyError:
        return False
