"""Mesh construction + named-dim -> PartitionSpec layout rules.

The TPU-native replacement for the reference's auto-derived mtf mesh
(`mesh_shape = "b:<tpu_size/heads>,h:<heads>"`, `layout = "batch:b,heads:h"`,
/root/reference/src/dataclass.py:247-252) and SimdMeshImpl lowering: dim
*names* map to mesh axes; anonymized (``_``-prefixed) dims never match a rule
and are therefore replicated, exactly like the reference's anonymize trick —
but here XLA GSPMD materialises the collectives.

Axes: 'data' (batch), 'model' (heads), optional 'sequence' (long-context
sequence sharding — new capability, reference has none, SURVEY.md §5.7).
"""
from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import ModelParameter
from .dims import Dim
from .tensor import NamedTensor, nt

#: canonical mesh-axis names.  Code OUTSIDE this module / ``parallel/`` /
#: ``config.py`` must reference axes through these constants — the
#: ``mesh-axis-literal`` AST rule (analysis/ast_lint.py) flags hardcoded
#: axis-name strings so an axis rename cannot silently strand a
#: PartitionSpec or a ``mesh.shape.get("...")`` probe.
DATA_AXIS = "data"
PIPE_AXIS = "pipe"
MODEL_AXIS = "model"
SEQUENCE_AXIS = "sequence"
#: mesh construction order (build_mesh below)
MESH_AXES = (DATA_AXIS, PIPE_AXIS, MODEL_AXIS, SEQUENCE_AXIS)


def build_mesh(params: ModelParameter,
               devices: typing.Optional[typing.Sequence[jax.Device]] = None) -> Mesh:
    """Mesh from the config's derived mesh_shape, adapted to the devices
    actually present (the config targets a pod; tests run on 8 virtual CPU
    devices; bench runs on 1 chip)."""
    if devices is None:
        devices = jax.devices()
    ndev = len(devices)
    shape = dict(params.mesh_shape)
    model = shape.get("model", 1)
    seq = shape.get("sequence", 1)
    pipe = shape.get("pipe", 1)
    while model * seq * pipe > ndev and model > 1:
        model //= 2
    while model * seq * pipe > ndev and seq > 1:
        seq //= 2
    while model * seq * pipe > ndev and pipe > 1:
        pipe //= 2
    data = max(1, ndev // (model * seq * pipe))
    axes, sizes = [], []
    for name, size in (("data", data), ("pipe", pipe), ("model", model),
                       ("sequence", seq)):
        if name in shape or name == "data":
            axes.append(name)
            sizes.append(size)
    dev_array = np.asarray(devices[: int(np.prod(sizes))]).reshape(sizes)
    return Mesh(dev_array, tuple(axes))


def inference_mesh(params: ModelParameter,
                   devices: typing.Optional[typing.Sequence[jax.Device]] = None
                   ) -> Mesh:
    """Serving mesh: the config's device layout with the 'pipe' and
    'sequence' axes folded into 'data'.

    Incremental decode has no pipeline schedule and no ring-attention
    schedule (KV caches hold the full anonymized sequence), so those axes
    would idle; folding them into 'data' keeps every device of the training
    topology participating — parameters and KV caches shard over 'model'
    (tensor parallelism), batches over 'data'.  The reference served
    inference through the same SimdMeshImpl mesh as training
    (/root/reference/src/run/run.py:200-308)."""
    mesh = build_mesh(params, devices)
    fold = mesh.shape.get("pipe", 1) * mesh.shape.get("sequence", 1)
    if fold == 1:
        return mesh
    sizes = dict(mesh.shape)
    data = sizes.get("data", 1) * fold
    # build_mesh orders axes (data, pipe, model, sequence); a plain reshape
    # would interleave 'model' between the folded axes, so transpose the
    # device array to (data, pipe, sequence, model) first
    order = [mesh.axis_names.index(a)
             for a in ("data", "pipe", "sequence", "model")
             if a in mesh.axis_names]
    dev = np.transpose(mesh.devices, order)
    model = sizes.get("model", 1)
    if "model" in mesh.axis_names:
        return Mesh(dev.reshape(data, model), ("data", "model"))
    return Mesh(dev.reshape(data), ("data",))


def spec_for_dims(params: ModelParameter, dims: typing.Sequence[Dim],
                  mesh: Mesh) -> PartitionSpec:
    """PartitionSpec from layout rules; each mesh axis used at most once."""
    used: set = set()
    entries = []
    for d in dims:
        axis = params.layout.get(d.name)
        if axis is not None and axis in mesh.axis_names and axis not in used \
                and d.size % mesh.shape[axis] == 0:
            entries.append(axis)
            used.add(axis)
        else:
            entries.append(None)
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def named_sharding(params: ModelParameter, dims: typing.Sequence[Dim],
                   mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, spec_for_dims(params, dims, mesh))


def shard_params(params: ModelParameter, variables: typing.Dict[str, jax.Array],
                 param_dims: typing.Dict[str, tuple], mesh: Mesh
                 ) -> typing.Dict[str, jax.Array]:
    """device_put every variable with its layout-derived NamedSharding
    (weights carrying a 'heads' dim shard over 'model', like mtf layout
    rules sharded every heads-bearing weight)."""
    out = {}
    for name, value in variables.items():
        dims = param_dims.get(name, ())
        sharding = named_sharding(params, dims, mesh)
        out[name] = jax.device_put(value, sharding)
    return out


@functools.lru_cache(maxsize=8)
def process_data_slice(mesh: Mesh) -> typing.Tuple[int, int]:
    """(slice_index, slice_count) of the global batch this process must feed.

    The 'data' mesh axis may span fewer process groups than there are
    processes (e.g. full model parallelism: data=1, model across hosts —
    every process must then feed IDENTICAL full batches), or more than one
    row-block per process.  Derived from which data-axis coordinates this
    process's devices actually occupy; cached per mesh (called every step
    from shard_batch — the device scan is O(all devices))."""
    if "data" not in mesh.axis_names:
        return 0, 1
    axis = mesh.axis_names.index("data")
    pid = jax.process_index()
    coords = sorted({idx[axis] for idx, dev in np.ndenumerate(mesh.devices)
                     if dev.process_index == pid})
    if not coords:
        return 0, 1
    data_size = mesh.shape["data"]
    span = len(coords)
    if coords != list(range(coords[0], coords[0] + span)):
        raise ValueError(
            f"non-contiguous data coords for process {pid}: {coords}")
    # unaligned layouts would let two processes claim the same slice while
    # another goes unfed — refuse instead of silently training on wrong data
    if coords[0] % span or data_size % span:
        raise ValueError(f"process {pid} data coords {coords} not "
                         f"block-aligned in data axis of size {data_size}")
    slice_count = max(1, data_size // span)
    return coords[0] // span, slice_count


def place_tree(template_tree, host_tree):
    """Lay host (numpy) arrays out with the shardings of a template tree of
    live jax Arrays.  Works in multi-controller runs where a plain
    ``device_put`` cannot target non-addressable devices: every process holds
    the full host value and contributes the shards it owns
    (``make_array_from_callback``)."""
    def place(template, host):
        host = np.asarray(host)
        if not isinstance(template, jax.Array):
            return jnp.asarray(host)
        assert template.shape == host.shape, (template.shape, host.shape)
        return jax.make_array_from_callback(
            host.shape, template.sharding, lambda idx: host[idx])
    return jax.tree_util.tree_map(place, template_tree, host_tree)


def shard_batch(params: ModelParameter, batch: typing.Dict[str, jax.Array],
                mesh: Mesh, batch_axis: typing.Optional[int] = None
                ) -> typing.Dict[str, jax.Array]:
    """Batch arrays shard along their leading (batch) axis over 'data'.

    Single-process: a plain ``device_put`` with the NamedSharding.  Multi-host
    (``jax.process_count() > 1``): every process holds only its per-process
    slice of the global batch (the train loop feeds
    ``slice_index=process_index``), so the slices are assembled into one
    global array via ``jax.make_array_from_process_local_data`` — the named
    equivalent of the reference's per-host infeed placement
    (/root/reference/src/run/dataloader_placement.py:153-227).  A bare
    ``device_put`` here would treat each process's slice as the full global
    batch: wrong data on every host but host 0.
    """
    out = {}
    nproc = jax.process_count()
    # the number of distinct batch slices across processes follows the
    # data-axis process layout, NOT the process count: with full model
    # parallelism (data axis inside each host group) every process feeds
    # identical full batches
    _, slice_count = process_data_slice(mesh) if nproc > 1 else (0, 1)
    # under macro-batching the leading axis is the macro index; the batch
    # axis (the one sharded over 'data' and split across processes) is 1.
    # Callers feeding micro-shaped batches under a macro config (the eval
    # pass) say so via ``batch_axis=0``
    if batch_axis is None:
        batch_axis = 1 if params.macro_batching > 1 else 0
    for key, value in batch.items():
        entries: typing.List[typing.Optional[str]] = [None] * value.ndim
        global_shape = list(value.shape)
        if "data" in mesh.axis_names and value.ndim > batch_axis:
            if nproc > 1:
                global_shape[batch_axis] *= slice_count
            if global_shape[batch_axis] % mesh.shape["data"] == 0:
                entries[batch_axis] = "data"
            elif nproc > 1:
                # a replicated multi-host assembly is unservable: each process
                # holds a distinct slice, so fail here with a clear message
                # rather than deep inside make_array_from_process_local_data
                raise ValueError(
                    f"global batch {global_shape[batch_axis]} for {key!r} is "
                    f"not divisible by the 'data' mesh axis "
                    f"({mesh.shape['data']}) across {nproc} processes")
        sharding = NamedSharding(mesh, PartitionSpec(*entries))
        if nproc > 1:
            out[key] = jax.make_array_from_process_local_data(
                sharding, np.asarray(value), tuple(global_shape))
        else:
            out[key] = jax.device_put(value, sharding)
    return out


def with_constraint(t: NamedTensor, params: ModelParameter,
                    mesh: typing.Optional[Mesh]) -> NamedTensor:
    """Annotate a named tensor's sharding inside jit (activation layouts)."""
    if mesh is None:
        return t
    spec = spec_for_dims(params, t.dims, mesh)
    return nt(jax.lax.with_sharding_constraint(t.data, NamedSharding(mesh, spec)),
              t.dims)
