"""Persistent XLA compilation cache wiring (ROADMAP item 5, first sliver).

Every BENCH round and every serving relaunch pays ~90s setup + ~100s
compile+warmup before the first useful step.  jax ships a persistent
compilation cache (``jax_compilation_cache_dir``) that serves an unchanged
program's compile from disk; this module turns the config knob
``compile_cache_dir`` into that configuration, applied once per process
BEFORE the first jit compile (main.py does it for every run mode, the
serving bench for its spawned servers).

The two threshold knobs are forced permissive: jax's defaults only persist
compiles slower than ~1s / larger than a floor, which silently skips
exactly the many-small-programs profile of the stepped decode path (dozens
of chunk-step variants, each fast to compile but slow in aggregate).

tests/continuous_batching_test.py asserts a second in-process build of the
same program HITS the cache (entries appear on the first compile, none are
added by the second after ``jax.clear_caches()``).
"""
from __future__ import annotations

import os
import typing


def install_compile_cache(params_or_dir) -> typing.Optional[str]:
    """Point jax's persistent compilation cache at the configured directory.

    Accepts a ``ModelParameter`` (reads ``compile_cache_dir``) or a path
    string; returns the installed path, or None when the knob is off.
    Idempotent — safe to call from every entry point that might run first.
    """
    path = getattr(params_or_dir, "compile_cache_dir", params_or_dir)
    if not path:
        return None
    path = os.path.abspath(os.path.expanduser(str(path)))
    os.makedirs(path, exist_ok=True)
    import jax
    # persist EVERYTHING: the default min-compile-time (~1s) skips the
    # decode chunk steps this exists for
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:  # knob renamed across jax versions — best effort
        pass
    jax.config.update("jax_compilation_cache_dir", path)
    # ALSO reset the cache object: jax initialises it lazily on the first
    # compile and never re-reads the config after — without the reset, any
    # earlier jit in the process (warmup, another mode) would leave the
    # knob silently dead for the rest of the process
    _reset_cache_object()
    return path


def _reset_cache_object() -> None:
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except (ImportError, AttributeError):
        pass


def uninstall_compile_cache() -> None:
    """Turn the persistent cache back off (test isolation)."""
    import jax
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_cache_object()
