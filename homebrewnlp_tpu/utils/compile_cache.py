"""Persistent XLA compilation cache wiring (ROADMAP item 5, first sliver).

Every BENCH round and every serving relaunch pays ~90s setup + ~100s
compile+warmup before the first useful step.  jax ships a persistent
compilation cache (``jax_compilation_cache_dir``) that serves an unchanged
program's compile from disk; this module turns the config knob
``compile_cache_dir`` into that configuration, applied once per process
BEFORE the first jit compile (main.py does it for every run mode, the
serving bench for its spawned servers).

The two threshold knobs are forced permissive: jax's defaults only persist
compiles slower than ~1s / larger than a floor, which silently skips
exactly the many-small-programs profile of the stepped decode path (dozens
of chunk-step variants, each fast to compile but slow in aggregate).

tests/continuous_batching_test.py asserts a second in-process build of the
same program HITS the cache (entries appear on the first compile, none are
added by the second after ``jax.clear_caches()``).

**Reload-broken environments** (docs/PERFORMANCE.md 'Round 11'): on
jax-0.4.37's CPU backend, DESERIALIZING a cached train-step executable on a
warm relaunch corrupts the heap (SIGSEGV/SIGABRT) — the cold run that
POPULATES the cache works, so the knob looks fine until the restart it
exists to speed up dies.  ``bench.py --compile-probe`` classifies this
structurally and, when probing a persistent cache dir, records the verdict
into ``<cache_dir>/compile_probe_verdict.json``
(:func:`record_reload_verdict`).  ``install_compile_cache`` reads that
verdict: a matching backend + jax version marked broken REFUSES to enable
the cache with a loud structured warning instead of letting the warm
relaunch crash — graceful degradation to cold compiles, not a mystery
segfault (tests/spec_decode_test.py pins the refusal).
"""
from __future__ import annotations

import json
import os
import typing
import warnings

#: the probe's verdict marker inside a persistent cache dir
VERDICT_FILE = "compile_probe_verdict.json"


def _env_fingerprint() -> typing.Tuple[str, str]:
    """(backend, jax_version) WITHOUT initialising jax's backends — the
    install runs before ``jax.distributed`` bootstrap on multi-host, where
    touching ``jax.default_backend()`` would bind the wrong topology."""
    import jax
    backend = (os.environ.get("JAX_PLATFORMS") or "default").split(",")[0]
    return backend or "default", jax.__version__


def record_reload_verdict(cache_dir: str, broken: bool,
                          evidence: str = "") -> str:
    """Write the compile-probe's warm-reload verdict into ``cache_dir``.

    ``bench.py --compile-probe`` calls this after classifying the warm
    relaunch; operators arm the guard by probing the deployment's actual
    ``compile_cache_dir`` once.  Returns the verdict path."""
    backend, jax_version = _env_fingerprint()
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, VERDICT_FILE)
    with open(path, "w") as f:
        json.dump({"backend": backend, "jax_version": jax_version,
                   "reload_broken": bool(broken), "evidence": evidence}, f,
                  indent=1)
    return path


def read_reload_verdict(cache_dir: str) -> typing.Optional[dict]:
    """The recorded verdict, or None (no probe ran / unreadable file —
    unreadable is treated as no evidence, never as broken)."""
    try:
        with open(os.path.join(cache_dir, VERDICT_FILE)) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def _reload_refusal(path: str) -> typing.Optional[dict]:
    """The verdict blocking installation for THIS environment, if any: the
    probe must have marked reload broken for the same jax version (an
    upgrade invalidates the classification — re-probe) and a COMPATIBLE
    backend.  "default" (JAX_PLATFORMS unset) matches any recorded
    backend and vice versa: the fingerprint is read without initialising
    jax's backends, so an unset variable is "unknown", and refusing on
    unknown is the safe direction — the cost of a false refusal is cold
    compiles, the cost of a false install is the warm-relaunch segfault
    this guard exists for."""
    verdict = read_reload_verdict(path)
    if not verdict or not verdict.get("reload_broken"):
        return None
    backend, jax_version = _env_fingerprint()
    if verdict.get("jax_version") != jax_version:
        return None
    recorded = verdict.get("backend") or "default"
    if recorded != backend and "default" not in (recorded, backend):
        return None
    return verdict


def install_compile_cache(params_or_dir) -> typing.Optional[str]:
    """Point jax's persistent compilation cache at the configured directory.

    Accepts a ``ModelParameter`` (reads ``compile_cache_dir``) or a path
    string; returns the installed path, or None when the knob is off — or
    when ``bench.py --compile-probe`` has classified this backend + jax
    version as RELOAD-BROKEN for this cache dir (loud structured warning;
    the warm relaunch would segfault deserializing the cache, so cold
    compiles are the fast path that actually finishes).  Idempotent — safe
    to call from every entry point that might run first.
    """
    path = getattr(params_or_dir, "compile_cache_dir", params_or_dir)
    if not path:
        return None
    path = os.path.abspath(os.path.expanduser(str(path)))
    os.makedirs(path, exist_ok=True)
    # the probe's own subprocesses must BYPASS the refusal: re-probing an
    # armed dir has to actually exercise the cache to find out whether a
    # jax upgrade fixed the reload — refusing inside the probe would
    # measure two uncached runs and record a vacuous "healthy"
    ignore = os.environ.get("HBNLP_COMPILE_CACHE_IGNORE_VERDICT") == "1"
    refusal = None if ignore else _reload_refusal(path)
    if refusal is not None:
        msg = ("compile_cache_dir REFUSED: bench.py --compile-probe "
               f"classified backend={refusal.get('backend')!r} "
               f"jax={refusal.get('jax_version')!r} as reload-broken for "
               f"{path!r} ({refusal.get('evidence') or 'no evidence text'}); "
               "serving cold compiles instead of crashing the warm "
               "relaunch.  Re-probe after a jax upgrade to re-enable.")
        print("WARNING: " + msg, flush=True)
        warnings.warn(msg)
        return None
    import jax
    # persist EVERYTHING: the default min-compile-time (~1s) skips the
    # decode chunk steps this exists for
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:  # knob renamed across jax versions — best effort
        pass
    jax.config.update("jax_compilation_cache_dir", path)
    # ALSO reset the cache object: jax initialises it lazily on the first
    # compile and never re-reads the config after — without the reset, any
    # earlier jit in the process (warmup, another mode) would leave the
    # knob silently dead for the rest of the process
    _reset_cache_object()
    return path


def _reset_cache_object() -> None:
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except (ImportError, AttributeError):
        pass


def uninstall_compile_cache() -> None:
    """Turn the persistent cache back off (test isolation)."""
    import jax
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_cache_object()
