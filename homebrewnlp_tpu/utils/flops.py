"""Matmul FLOP counting + MFU.

Model FLOPs are counted exactly by walking a jaxpr and summing
``2 * M * N * K * batch`` over every ``dot_general`` (descending into scans
with their trip counts, pjit/custom-vjp calls, etc.).  MFU follows the
standard convention: useful model FLOPs = 3x the forward pass (forward +
2x backward), NOT the executed FLOPs — rematerialization (revnet/checkpoint
recompute) does not get credit.  The reference had no FLOP accounting at all
(SURVEY.md §5.1: wall-clock phase prints only).
"""
from __future__ import annotations

import typing

import jax
import numpy as np

# bf16 peak TFLOP/s per chip by device kind (MXU); int8 peaks are 2x
PEAK_TFLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v4 lite": 138e12,   # v4i inference
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
    "cpu": 1e12,             # nominal, so CPU runs still print a number
}


# sustained HBM bandwidth per chip by device kind (bytes/s): the other half
# of the roofline — arithmetic intensity above PEAK_TFLOPS/bandwidth is
# compute-bound, below it HBM-bound.  Published chip figures; the cpu row is
# a nominal planning figure so CPU-rig ledgers still classify
HBM_BANDWIDTH = {
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,        # v5p
    "TPU v5p": 2765e9,
    "TPU v4": 1228e9,
    "TPU v4 lite": 614e9,
    "TPU v6 lite": 1640e9,   # v6e / Trillium
    "TPU v6e": 1640e9,
    "cpu": 50e9,             # nominal planning figure
}


# HBM bytes per chip by device kind; the axon tunnel returns no
# memory_stats, so capacity planning (stash auto-enable, fused-backward
# dq-partial cap) keys on the kind string
HBM_BYTES = {
    "TPU v5 lite": int(15.75 * 1024 ** 3),   # v5e
    "TPU v5e": int(15.75 * 1024 ** 3),
    "TPU v5": 95 * 1024 ** 3,                # v5p
    "TPU v5p": 95 * 1024 ** 3,
    "TPU v4": 32 * 1024 ** 3,
    "TPU v4 lite": 8 * 1024 ** 3,
    "TPU v6 lite": 32 * 1024 ** 3,           # v6e / Trillium
    "TPU v6e": 32 * 1024 ** 3,
    "cpu": 16 * 1024 ** 3,                   # nominal planning figure
}


def device_hbm_bytes(device: typing.Optional[jax.Device] = None) -> int:
    """Per-chip HBM for capacity planning (device kind table; the runtime's
    memory_stats is unavailable through the tunnel)."""
    if device is None:
        device = jax.devices()[0]
    stats = None
    try:
        stats = device.memory_stats()
    except Exception:
        pass
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    kind = getattr(device, "device_kind", "cpu")
    if kind in HBM_BYTES:
        return HBM_BYTES[kind]
    for name, cap in HBM_BYTES.items():
        if name.lower() in str(kind).lower():
            return cap
    return HBM_BYTES["cpu"]


def _kind_lookup(table: typing.Mapping[str, float], kind: str) -> float:
    if kind in table:
        return table[kind]
    for name, val in table.items():
        if name.lower() in str(kind).lower():
            return val
    return table["cpu"]


def peak_flops(device: typing.Optional[jax.Device] = None) -> float:
    if device is None:
        device = jax.devices()[0]
    return _kind_lookup(PEAK_TFLOPS, getattr(device, "device_kind", "cpu"))


def peak_hbm_bandwidth(device: typing.Optional[jax.Device] = None) -> float:
    """Sustained HBM bytes/s for the device kind (table above) — the decode
    cache-read roofline PR 2 proved governs big-cache serving."""
    if device is None:
        device = jax.devices()[0]
    return _kind_lookup(HBM_BANDWIDTH, getattr(device, "device_kind", "cpu"))


def roofline_bound(flops: float, bytes_: float,
                   peak: float, bandwidth: float) -> str:
    """``"compute"`` when the arithmetic intensity (flops/byte) clears the
    ridge point ``peak/bandwidth``, else ``"hbm"`` — the classification the
    cost ledger records per scope (analysis/cost_ledger.py)."""
    if bytes_ <= 0:
        return "compute" if flops > 0 else "hbm"
    return "compute" if flops / bytes_ >= peak / bandwidth else "hbm"


def _dot_flops(eqn) -> int:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = int(np.prod([lhs.shape[i] for i in lb], dtype=np.int64)) if lb else 1
    k = int(np.prod([lhs.shape[i] for i in lc], dtype=np.int64)) if lc else 1
    m = int(np.prod([d for i, d in enumerate(lhs.shape)
                     if i not in set(lc) | set(lb)], dtype=np.int64))
    n = int(np.prod([d for i, d in enumerate(rhs.shape)
                     if i not in set(rc) | set(rb)], dtype=np.int64))
    return 2 * batch * m * n * k


def _conv_flops(eqn) -> int:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    out = eqn.outvars[0].aval
    # 2 * output elements * kernel-window size * input feature depth
    dn = eqn.params["dimension_numbers"]
    kshape = rhs.shape
    spatial_k = int(np.prod([kshape[i] for i in dn.rhs_spec[2:]], dtype=np.int64))
    cin = kshape[dn.rhs_spec[1]]
    return 2 * int(np.prod(out.shape, dtype=np.int64)) * spatial_k * cin


def count_matmul_flops(jaxpr) -> int:
    """Total dot/conv FLOPs in a (closed) jaxpr, scans scaled by length.

    Full-square convention: every pallas grid cell is counted as if live,
    including the causally-dead cells the flash kernels skip.  Kept stable
    round-over-round; use :func:`count_matmul_flops_split` for the
    executed-FLOP (causal) count alongside it."""
    return count_matmul_flops_split(jaxpr)[0]


def count_matmul_flops_split(jaxpr) -> typing.Tuple[int, int]:
    """(full, executed) dot/conv FLOPs of a (closed) jaxpr.

    ``full`` is the stable full-square convention (see
    :func:`count_matmul_flops`).  ``executed`` subtracts the causally-dead
    grid cells of causal pallas kernels (the cells ``pl.when`` skips —
    flash_attention.py names those calls ``*_causal``), i.e. the FLOPs the
    hardware actually performs.  Dense masked attention (the XLA fallback)
    executes the full square, so there ``executed == full``."""
    total, dead = _count_split(jaxpr)
    return total, total - dead


def _descend(eqn):
    """``(inner_jaxpr, trip_multiplier)`` of a higher-order equation, or
    None for leaves.  The ONE primitive/param-key table both jaxpr walkers
    (:func:`_count_split` and :func:`_scope_walk`) descend through — a jax
    upgrade renaming a param key gets fixed here once, instead of letting
    the MFU count and the cost ledger silently disagree.  ``cond`` and
    ``pallas_call`` are excluded: their conventions differ per walker
    (max-branch vs dead-cell accounting) but share :func:`_pallas_grid`."""
    prim = eqn.primitive.name
    if prim == "scan":
        return eqn.params["jaxpr"].jaxpr, int(eqn.params["length"])
    if prim == "while":
        # trip count unknown; count one body iteration
        return eqn.params["body_jaxpr"].jaxpr, 1
    if prim in ("custom_vjp_call", "custom_jvp_call",
                "custom_vjp_call_jaxpr", "remat", "checkpoint"):
        inner = eqn.params.get("call_jaxpr") or eqn.params.get("fun_jaxpr")
    elif prim in ("pjit", "jit", "xla_call", "closed_call", "core_call",
                  "shard_map"):
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
    else:
        return None
    if inner is None:
        return None
    return getattr(inner, "jaxpr", inner), 1


def _pallas_grid(eqn):
    """``(inner_jaxpr_or_None, grid, cells)`` of a ``pallas_call`` — the
    kernel body runs once per grid cell, so FLOPs are grid product × body
    FLOPs."""
    inner = eqn.params.get("jaxpr")
    gm = eqn.params.get("grid_mapping")
    grid = getattr(gm, "grid", ()) if gm is not None else ()
    cells = int(np.prod([g for g in grid if isinstance(g, int)],
                        dtype=np.int64)) if grid else 1
    return (getattr(inner, "jaxpr", inner) if inner is not None else None,
            grid, cells)


def _count_split(jaxpr) -> typing.Tuple[int, int]:
    """Recursive core: (full-square total, causally-dead) FLOPs."""
    total = 0
    dead = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        inner = _descend(eqn)
        if inner is not None:
            t, d = _count_split(inner[0])
            total += inner[1] * t
            dead += inner[1] * d
        elif prim == "dot_general":
            total += _dot_flops(eqn)
        elif prim == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif prim == "cond":
            branches = eqn.params.get("branches", ())
            if branches:
                t, d = max((_count_split(b.jaxpr)
                            for b in branches), key=lambda td: td[0])
                total += t
                dead += d
        elif prim == "pallas_call":
            # every grid cell counted as if live in ``total`` — the
            # full-square convention for causal flash kernels, kept stable
            # round-over-round.  Causal kernels (name carries "causal";
            # grid (batch·heads, a, b) with {a, b} = {q blocks, k blocks}
            # in either order) additionally report their skipped cells in
            # ``dead``: live block pairs are the ones overlapping the lower
            # triangle, sum_j min(b, ceil(j·b/a)) — transpose-symmetric, so
            # the (i, q, k) and (i, k, q) grids count identically
            body_jaxpr, grid, cells = _pallas_grid(eqn)
            if body_jaxpr is not None:
                body = _pallas_body_flops(body_jaxpr)
                total += cells * body
                # jax 0.4.37 moved the kernel name param to
                # ``name_and_src_info`` (str() = "<name> for kernel ...");
                # without the fallback the causal-dead subtraction silently
                # never fired and ``executed`` == ``full`` everywhere
                name = str(eqn.params.get("name", "")
                           or eqn.params.get("name_and_src_info", "") or "")
                if "causal" in name and len(grid) == 3 \
                        and all(isinstance(g, int) for g in grid):
                    a, b = grid[1], grid[2]
                    live = sum(min(b, (j * b + a - 1) // a)
                               for j in range(1, a + 1))
                    dead += grid[0] * (a * b - live) * body
    return total, dead


def _pallas_body_flops(jaxpr) -> int:
    """Per-cell FLOPs of a pallas kernel body.

    ``pl.when`` branches lower to ``cond`` eqns; kernels that split the
    causal mask into interior/diagonal variants (parallel/flash_attention.py
    ``_masked_step``) emit MUTUALLY EXCLUSIVE conds containing the SAME
    dots, so summing every cond (as the generic walker does) double-counts.
    Exclusivity is not visible in the jaxpr, but the exclusive mask pair
    always has IDENTICAL per-branch dot counts (same shapes, masked vs
    not) — so equal nonzero cond counts are deduplicated to one, while
    conds with DIFFERING dot counts (two genuinely sequential gated
    stages) are summed; a future two-stage kernel is over- rather than
    silently under-counted."""
    uncond = count_matmul_flops(
        _StrippedJaxpr([e for e in jaxpr.eqns if e.primitive.name != "cond"]))
    conds = [count_matmul_flops(b.jaxpr)
             for e in jaxpr.eqns if e.primitive.name == "cond"
             for b in e.params.get("branches", ())]
    return uncond + sum(set(c for c in conds if c))


class _StrippedJaxpr:
    def __init__(self, eqns):
        self.eqns = eqns


def forward_flops(fn, *args) -> int:
    """Matmul FLOPs of one forward call (traced abstractly, no execution)."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    return count_matmul_flops(jaxpr.jaxpr)


def forward_flops_split(fn, *args) -> typing.Tuple[int, int]:
    """(full-square, executed) matmul FLOPs of one forward call — the
    executed count excludes the causally-dead cells the flash kernels skip
    (:func:`count_matmul_flops_split`)."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    return count_matmul_flops_split(jaxpr.jaxpr)


def mfu(fwd_flops_per_step: float, step_time_s: float, n_chips: int = 1,
        device: typing.Optional[jax.Device] = None) -> float:
    """Model FLOPs utilization: 3x forward FLOPs over peak (no remat credit)."""
    return 3.0 * fwd_flops_per_step / step_time_s / (peak_flops(device) * n_chips)


# ---- per-scope cost attribution (docs/OBSERVABILITY.md) ---------------------
#
# The model graph carries jax.named_scope regions (core/scope.py name_scope
# mirrors every scope frame), so each jaxpr equation's
# ``source_info.name_stack`` names the block/layer that produced it.  The
# walker below attributes {matmul flops, unfused bytes} to those stacks —
# the analytical half of the cost ledger (analysis/cost_ledger.py), which
# folds stacks into coarse scope keys and joins them with XLA's
# cost_analysis and profiler time shares.


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    try:
        return int(np.prod(aval.shape, dtype=np.int64)
                   ) * np.dtype(aval.dtype).itemsize
    except TypeError:
        return 0


def _eqn_bytes(eqn) -> int:
    """Operand + result bytes of one equation — the UNFUSED memory-traffic
    convention (fusion elides intermediates on real hardware, so per-scope
    byte totals are an upper bound; shares between scopes stay meaningful
    because the convention is uniform)."""
    return (sum(_aval_bytes(v) for v in eqn.invars)
            + sum(_aval_bytes(v) for v in eqn.outvars))


def scope_costs(jaxpr, prefix: str = ""
                ) -> typing.Dict[str, typing.Tuple[int, int]]:
    """``{name_stack: (flops, bytes)}`` over a (closed) jaxpr.

    Scan bodies multiply by trip count (the full-square convention of
    :func:`count_matmul_flops`); inner jaxprs' stacks are prefixed with the
    enclosing equation's stack, since a sub-trace's name_stack restarts at
    its own trace boundary."""
    out: typing.Dict[str, typing.List[int]] = {}
    _scope_walk(getattr(jaxpr, "jaxpr", jaxpr), prefix, 1, out)
    return {k: (v[0], v[1]) for k, v in out.items()}


def _join_stack(prefix: str, stack: str) -> str:
    if prefix and stack:
        return f"{prefix}/{stack}"
    return prefix or stack


def _scope_walk(jaxpr, prefix: str, mult: int, out) -> None:
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        path = _join_stack(prefix, str(eqn.source_info.name_stack))
        inner = _descend(eqn)
        if inner is not None:
            _scope_walk(inner[0], path, mult * inner[1], out)
            continue
        if prim == "cond":
            branches = eqn.params.get("branches", ())
            if branches:
                # the max-flops branch, matching count_matmul_flops
                best = max(branches,
                           key=lambda b: count_matmul_flops(b.jaxpr))
                _scope_walk(best.jaxpr, path, mult, out)
                continue
        flops = 0
        if prim == "dot_general":
            flops = _dot_flops(eqn)
        elif prim == "conv_general_dilated":
            flops = _conv_flops(eqn)
        elif prim == "pallas_call":
            body_jaxpr, _grid, cells = _pallas_grid(eqn)
            if body_jaxpr is not None:
                flops = cells * _pallas_body_flops(body_jaxpr)
        ent = out.setdefault(path, [0, 0])
        ent[0] += mult * flops
        ent[1] += mult * _eqn_bytes(eqn)
