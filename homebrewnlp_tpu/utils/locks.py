"""Named locks + opt-in runtime lock tracing (graft-lint ``--conc``).

``named_lock``/``named_rlock`` are drop-in ``threading.Lock``/``RLock``
factories the audited control-plane classes use so every lock carries
the SAME name the ``GUARDED_BY`` registry declares
(``analysis/conc_lint.py``).  Default mode returns the plain primitive
— zero overhead, ``Condition``-compatible, nothing changes.

With ``HBNLP_LOCK_TRACE=<dir>`` set at import time, the factories
return :class:`TracedLock` instead: every acquisition appends one JSONL
row to ``<dir>/lock_trace_<pid>.jsonl`` recording the lock name, the
locks this thread already held (the acquisition-order edge), the wait
time, and — at release — the hold time.  ``conc_lint.load_trace_edges``
folds these observed edges into the same ordering cycle checker as the
static ``with``-nesting graph and the interleaving explorer, so the
declared discipline and observed reality cross-validate from real
marker-suite runs.  Hold/wait times also feed the ``hbnlp_lock_*``
telemetry series (docs/OBSERVABILITY.md) — registered lazily so the
un-traced path never touches the registry.

Tracing is per-process and write-only append; rows may tear at the tail
of a live run, and the trace reader skips unparseable lines.
"""
from __future__ import annotations

import json
import os
import threading
import typing

__all__ = ["named_lock", "named_rlock", "TracedLock", "trace_dir"]

#: thread-local stack of TracedLock names currently held (acquisition
#: order) — the source of the observed lock-ordering edges
_held = threading.local()


def trace_dir() -> typing.Optional[str]:
    """The active trace directory, or None when tracing is off."""
    d = os.environ.get("HBNLP_LOCK_TRACE", "").strip()
    return d or None


def _held_stack() -> typing.List[str]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


class _TraceSink:
    """One append-only JSONL file per traced process; lazily opened,
    shared by every TracedLock in the process."""

    def __init__(self, directory: str):
        self.directory = directory
        self._file = None
        self._flock = threading.Lock()
        self._metrics = None

    def _ensure(self):
        if self._file is None:
            os.makedirs(self.directory, exist_ok=True)
            path = os.path.join(self.directory,
                                f"lock_trace_{os.getpid()}.jsonl")
            self._file = open(path, "a", encoding="utf-8")
        return self._file

    def metrics(self):
        """hbnlp_lock_* series, registered on first traced acquisition
        (lazy: an un-traced process never creates them)."""
        if self._metrics is None:
            # the telemetry package __init__ rebinds `registry` to the
            # accessor FUNCTION, shadowing the submodule
            from ..telemetry import registry as _registry_fn
            r = _registry_fn()
            self._metrics = (
                r.counter("hbnlp_lock_acquire_total",
                          "Traced lock acquisitions", ("lock",)),
                r.histogram("hbnlp_lock_wait_seconds",
                            "Time spent waiting to acquire a traced "
                            "lock", ("lock",),
                            buckets=(1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0)),
                r.histogram("hbnlp_lock_hold_seconds",
                            "Time a traced lock was held", ("lock",),
                            buckets=(1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0)),
            )
        return self._metrics

    def write(self, row: dict) -> None:
        try:
            with self._flock:
                f = self._ensure()
                f.write(json.dumps(row) + "\n")
                f.flush()
        except OSError:
            pass  # tracing must never take down the traced run


_sink: typing.Optional[_TraceSink] = None
_sink_lock = threading.Lock()


def _get_sink(directory: str) -> _TraceSink:
    global _sink
    with _sink_lock:
        if _sink is None or _sink.directory != directory:
            _sink = _TraceSink(directory)
        return _sink


class TracedLock:
    """Lock/RLock wrapper recording acquisition order + wait/hold times.

    Not Condition-compatible (no ``_is_owned``): sites that build a
    ``threading.Condition`` over their lock (``AsyncCheckpointer``) keep
    the raw primitive even under tracing."""

    def __init__(self, name: str, reentrant: bool, directory: str,
                 meter: bool = True):
        self.name = str(name)
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._reentrant = reentrant
        self._sink = _get_sink(directory)
        self._meter = meter
        self._acquired_at = 0.0

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        import time
        t0 = time.monotonic()
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            return False
        waited = time.monotonic() - t0
        stack = _held_stack()
        # epoch stamp: trace rows are correlated with forensics blackbox
        # wall stamps  # graft-lint: allow[wallclock]
        row = {"t": round(time.time(), 6), "lock": self.name,
               "held": list(stack), "wait_s": round(waited, 6)}
        stack.append(self.name)
        self._acquired_at = time.monotonic()
        self._sink.write(row)
        if self._meter:
            try:
                acq, wait_h, _ = self._sink.metrics()
                acq.labels(lock=self.name).inc()
                wait_h.labels(lock=self.name).observe(waited)
            except Exception:
                pass  # telemetry is best-effort under tracing
        return True

    def release(self) -> None:
        import time
        held_s = time.monotonic() - self._acquired_at
        stack = _held_stack()
        # innermost-first removal: re-entrant acquires push duplicates
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._lock.release()
        if self._meter:
            try:
                _, _, hold_h = self._sink.metrics()
                hold_h.labels(lock=self.name).observe(held_s)
            except Exception:
                pass
        # graft-lint: allow[wallclock] — epoch stamp (see acquire)
        self._sink.write({"t": round(time.time(), 6), "lock": self.name,
                          "released": True, "hold_s": round(held_s, 6)})

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        if self._reentrant:
            # RLock has no locked(); a non-blocking probe is close enough
            if self._lock.acquire(blocking=False):
                self._lock.release()
                return False
            return True
        return self._lock.locked()


def named_lock(name: str, meter: bool = True):
    """A ``threading.Lock`` — or, under ``HBNLP_LOCK_TRACE``, a traced
    wrapper reporting as ``name`` (use the ``Class.attr`` the GUARDED_BY
    registry declares).  ``meter=False`` skips the hbnlp_lock_* series
    (required for the telemetry registry\'s OWN locks, which cannot meter
    themselves without recursing); the JSONL rows still record."""
    d = trace_dir()
    if d is None:
        return threading.Lock()
    return TracedLock(name, reentrant=False, directory=d, meter=meter)


def named_rlock(name: str, meter: bool = True):
    """``named_lock`` for re-entrant sites (signal handlers that re-enter
    the flight recorder)."""
    d = trace_dir()
    if d is None:
        return threading.RLock()
    return TracedLock(name, reentrant=True, directory=d, meter=meter)
