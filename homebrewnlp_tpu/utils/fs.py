"""Pluggable filesystem seam: one surface for every path the framework
touches (checkpoints, DataLog, metrics, config dumps, dataset records).

The reference ran everything against GCS through TF's GFile
(/root/reference/src/inputs.py:524-559, scripts/run_manager.py:26-56); here
the same role is a small registry keyed on URL scheme:

    fs.open_(path, mode) / exists / isdir / listdir / makedirs / glob /
    replace / rmtree / remove

* ``LocalFS`` (default, no scheme or ``file://``) — os/shutil/glob.
* ``GCSFS`` (``gs://``) — behind the optional ``google-cloud-storage``
  dependency; constructed lazily on first use so local-only installs never
  import it.
* ``MemFS`` (``mem://``) — in-process object store with OBJECT-STORE
  semantics (prefix listing, non-atomic directory replace implemented as
  ordered copy+delete, no true append) used by tests to prove consumers
  survive remote-storage behaviour.
* ``FaultInjectionFS`` (utils/fault_injection.py) — wraps any object-store
  backend and injects crashes / transient errors / torn writes from a
  deterministic schedule; registered the same way (docs/RELIABILITY.md).

Object-store note: ``replace`` of a directory is NOT atomic on object
stores.  Consumers that need crash-safety order their writes so a
completeness marker lands last (checkpoint.py writes ``index.json`` after
the shard files and ``latest_step`` ignores directories without it).
"""
from __future__ import annotations

import glob as globlib
import io
import os
import posixpath
import shutil
import typing


class FileSystem:
    def open_(self, path: str, mode: str = "r"): raise NotImplementedError

    def exists(self, path: str) -> bool: raise NotImplementedError

    def isdir(self, path: str) -> bool: raise NotImplementedError

    def listdir(self, path: str) -> typing.List[str]: raise NotImplementedError

    def makedirs(self, path: str): raise NotImplementedError

    def glob(self, pattern: str) -> typing.List[str]: raise NotImplementedError

    def replace(self, src: str, dst: str): raise NotImplementedError

    def rmtree(self, path: str): raise NotImplementedError

    def remove(self, path: str): raise NotImplementedError

    #: True when paths are plain local paths C extensions can open directly
    is_local = False

    #: True when the backend already retries transient failures inside its
    #: own primitives (GCSFS): higher layers skip their retry wrapper so
    #: attempt budgets never nest multiplicatively
    retries_internally = False


class LocalFS(FileSystem):
    is_local = True

    def open_(self, path, mode="r"):
        if any(m in mode for m in ("w", "a", "x")):
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        return open(path, mode)

    def exists(self, path):
        return os.path.exists(path)

    def isdir(self, path):
        return os.path.isdir(path)

    def listdir(self, path):
        return os.listdir(path)

    def makedirs(self, path):
        os.makedirs(path, exist_ok=True)

    def glob(self, pattern):
        return sorted(globlib.glob(pattern))

    def replace(self, src, dst):
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        os.replace(src, dst)

    def rmtree(self, path):
        shutil.rmtree(path, ignore_errors=True)

    def remove(self, path):
        os.remove(path)


class _ObjectStoreFS(FileSystem):
    """Shared directory-emulation logic for flat object stores: directories
    exist implicitly as key prefixes; replace = ordered copy+delete."""

    def _keys(self, prefix: str) -> typing.List[str]:
        raise NotImplementedError

    def _read(self, key: str) -> bytes:
        raise NotImplementedError

    def _write(self, key: str, data: bytes):
        raise NotImplementedError

    def _delete(self, key: str):
        raise NotImplementedError

    # -- FileSystem surface over those four primitives ---------------------
    def open_(self, path, mode="r"):
        binary = "b" in mode
        if "r" in mode:
            data = self._read(path)
            return io.BytesIO(data) if binary else \
                io.StringIO(data.decode("utf-8"))
        fs = self

        class _Writer(io.BytesIO if binary else io.StringIO):
            def __init__(self, initial=""):
                super().__init__()
                if initial:
                    self.write(initial)

            def flush(self):
                if self.closed:
                    return
                data = self.getvalue()
                fs._write(path, data if binary else data.encode("utf-8"))

            def close(self):
                # commit exactly once: io.IOBase.__del__ calls close(), so
                # without the closed guard an abandoned writer (e.g. a
                # failed attempt inside a retry loop) would re-upload its
                # stale buffer at GC time — possibly over a newer
                # successful write.  super().close() runs even when the
                # commit raises, so the destructor never replays it.
                if self.closed:
                    return
                try:
                    self.flush()
                finally:
                    super().close()

            def __exit__(self, *exc):
                self.close()

        if "a" in mode and self.exists(path):
            # no true append on object stores: read-modify-write on close
            prev = self._read(path)
            return _Writer(prev if binary else prev.decode("utf-8"))
        return _Writer()

    def exists(self, path):
        return bool(self._keys(path))

    def isdir(self, path):
        keys = self._keys(path.rstrip("/") + "/")
        return bool(keys)

    def listdir(self, path):
        prefix = path.rstrip("/") + "/"
        names = set()
        for key in self._keys(prefix):
            rest = key[len(prefix):]
            if rest:
                names.add(rest.split("/")[0])
        return sorted(names)

    def makedirs(self, path):
        pass  # directories are implicit

    def glob(self, pattern):
        if not any(c in pattern for c in "*?["):
            return [pattern] if self._keys(pattern) else []
        import fnmatch
        base = pattern.split("*")[0].split("?")[0].split("[")[0].rsplit("/", 1)[0]
        pat_parts = pattern.split("/")
        out = []
        for key in self._keys(base):
            # segment-wise match so '*' does NOT cross '/' — identical
            # semantics to LocalFS/glob (a recursive remote '*' would feed
            # nested stale objects into the record reader)
            parts = key.split("/")
            if len(parts) == len(pat_parts) and all(
                    fnmatch.fnmatch(p, q) for p, q in zip(parts, pat_parts)):
                out.append(key)
        return sorted(out)

    def replace(self, src, dst):
        src_prefix = src.rstrip("/")
        dst_prefix = dst.rstrip("/")
        exact = self._keys(src_prefix)
        if exact == [src_prefix]:  # single object
            self._write(dst_prefix, self._read(src_prefix))
            self._delete(src_prefix)
            return
        self.rmtree(dst_prefix)
        # copy completeness markers (index.json) LAST: replace is not atomic
        # on object stores, and readers treat a directory without its marker
        # as incomplete — a crash mid-copy must never leave a marker without
        # the data files it indexes
        keys = list(self._keys(src_prefix + "/"))
        keys.sort(key=lambda k: (k.split("/")[-1] == "index.json", k))
        for key in keys:
            self._write(dst_prefix + key[len(src_prefix):], self._read(key))
        for key in keys:
            self._delete(key)

    def rmtree(self, path):
        prefix = path.rstrip("/")
        for key in list(self._keys(prefix + "/")) + list(
                k for k in self._keys(prefix) if k == prefix):
            self._delete(key)

    def remove(self, path):
        self._delete(path)


class MemFS(_ObjectStoreFS):
    """In-process object store for tests (``mem://``)."""

    def __init__(self):
        self.objects: typing.Dict[str, bytes] = {}

    def _keys(self, prefix):
        return sorted(k for k in self.objects
                      if k == prefix or k.startswith(prefix.rstrip("/") + "/")
                      or (prefix.endswith("/") and k.startswith(prefix)))

    def _read(self, key):
        if key not in self.objects:
            raise FileNotFoundError(key)
        return self.objects[key]

    def _write(self, key, data):
        self.objects[key] = bytes(data)

    def _delete(self, key):
        self.objects.pop(key, None)


class GCSFS(_ObjectStoreFS):
    """gs:// via the optional google-cloud-storage package.

    Every primitive (the network boundary) runs under the process-wide
    ``utils.retry`` policy: transient GCS failures (503/429/connection
    resets) back off and retry; permanent ones (NotFound -> translated
    FileNotFoundError, permissions) surface immediately.

    ``retries_internally`` tells higher layers (the checkpoint fs call
    sites) not to stack a second retry loop on top — nesting would square
    the attempt budget into minutes-long hangs per op during an outage."""

    retries_internally = True

    def __init__(self):
        try:
            from google.cloud import storage  # noqa
        except ImportError as e:
            raise ImportError(
                "gs:// paths need the optional google-cloud-storage "
                "dependency (pip install google-cloud-storage)") from e
        self._client = storage.Client()

    @staticmethod
    def _retry(fn, *args):
        from . import retry
        return retry.default_policy().call(fn, *args, site="gcs")

    def _split(self, key):
        rest = key[len("gs://"):]
        bucket, _, name = rest.partition("/")
        return self._client.bucket(bucket), name

    def _keys(self, prefix):
        return self._retry(self._keys_once, prefix)

    def _keys_once(self, prefix):
        bucket, name = self._split(prefix)
        out = [f"gs://{bucket.name}/{b.name}"
               for b in bucket.list_blobs(prefix=name)]
        return [k for k in out
                if k == prefix or k.startswith(prefix.rstrip("/") + "/")
                or (prefix.endswith("/") and k.startswith(prefix))]

    def _read(self, key):
        return self._retry(self._read_once, key)

    def _read_once(self, key):
        bucket, name = self._split(key)
        try:
            return bucket.blob(name).download_as_bytes()
        except Exception as e:
            # the cloud client surfaces a missing blob as
            # google.api_core.exceptions.NotFound, not FileNotFoundError —
            # translate so gs:// behaves like every other backend of the
            # seam (consumers catch FileNotFoundError), and so the retry
            # policy classifies it permanent instead of burning its budget
            if type(e).__name__ == "NotFound":
                raise FileNotFoundError(key) from e
            raise

    def _write(self, key, data):
        self._retry(self._write_once, key, bytes(data))

    def _write_once(self, key, data):
        bucket, name = self._split(key)
        bucket.blob(name).upload_from_string(data)

    def _delete(self, key):
        self._retry(self._delete_once, key)

    def _delete_once(self, key):
        bucket, name = self._split(key)
        try:
            bucket.blob(name).delete()
        except Exception as e:
            # delete is idempotent: a retry after a committed-but-lost
            # response (connection reset after the server applied it) sees
            # NotFound — that is success, not an error
            if type(e).__name__ == "NotFound":
                return
            raise


_local = LocalFS()
_registry: typing.Dict[str, typing.Union[FileSystem, typing.Callable[[], FileSystem]]] = {
    "gs": GCSFS,   # instantiated lazily: may raise ImportError with guidance
    "mem": MemFS,
}


def register(scheme: str, fs: FileSystem):
    """Install (or replace) the filesystem serving ``scheme://`` paths."""
    _registry[scheme] = fs


def for_path(path: str) -> FileSystem:
    path = str(path)
    if "://" not in path:
        return _local
    scheme = path.split("://", 1)[0]
    fs = _registry.get(scheme)
    if fs is None:
        raise ValueError(f"no filesystem registered for {scheme}:// paths")
    if isinstance(fs, type):
        fs = fs()
        _registry[scheme] = fs
    return fs


def join(*parts: str) -> str:
    """Path join that keeps URL schemes intact."""
    if "://" in str(parts[0]):
        return posixpath.join(*[str(p) for p in parts])
    return os.path.join(*parts)


# module-level convenience wrappers -----------------------------------------

def open_(path, mode="r"):
    return for_path(path).open_(str(path), mode)


def exists(path):
    return for_path(path).exists(str(path))


def isdir(path):
    return for_path(path).isdir(str(path))


def listdir(path):
    return for_path(path).listdir(str(path))


def makedirs(path):
    return for_path(path).makedirs(str(path))


def glob(pattern):
    return for_path(pattern).glob(str(pattern))


def replace(src, dst):
    return for_path(src).replace(str(src), str(dst))


def rmtree(path):
    return for_path(path).rmtree(str(path))


def remove(path):
    return for_path(path).remove(str(path))


def is_local(path) -> bool:
    return for_path(path).is_local
