"""Deterministic storage fault injection over the fs seam.

``FaultInjectionFS`` wraps an object-store backend (normally ``MemFS``) at
the four primitives every other operation is built from — ``_keys`` /
``_read`` / ``_write`` / ``_delete`` — and injects faults from a
deterministic schedule keyed on a monotonically increasing OP INDEX:

* ``crash_at = K``: the K-th primitive raises ``InjectedFault`` BEFORE
  executing — the moment the process dies.  Because ``fs.replace`` on an
  object store expands into many ``_write``/``_delete`` primitives, a sweep
  over every index also crashes MID-replace and MID-prune, the exact windows
  the checkpoint completeness-marker ordering exists for.
* ``transient = {K: M}``: the K-th primitive raises ``InjectedTransient``
  (classified retryable by ``utils.retry``) M times, then succeeds — proving
  the retry seam absorbs GCS-style 503 bursts.  Failed attempts do NOT
  consume the op index, so schedules stay stable under retries.
* ``truncate = {K: N}``: if the K-th primitive is a write, only the first N
  bytes land — a silently torn write, the case checkpoint crc verification
  exists for.

Register it like any backend and every consumer of the seam runs against it
unchanged::

    fi = FaultInjectionFS(crash_at=7)
    fs.register("fault", fi)
    checkpoint.save("fault://bucket/run", ...)   # dies at primitive #7

``ops`` records every successfully-issued primitive, so a clean dry run
measures how many crash points an operation sequence has
(tests/fault_injection_test.py sweeps all of them).
"""
from __future__ import annotations

import typing

from . import fs as fslib
from .retry import TransientError


class InjectedFault(RuntimeError):
    """Permanent injected failure: simulates the process dying at (or the
    storage service hard-failing) a specific operation index."""


class InjectedTransient(TransientError, ConnectionError):
    """Retryable injected failure (a GCS 503 / connection reset stand-in)."""


class FaultInjectionFS(fslib._ObjectStoreFS):
    def __init__(self, inner: typing.Optional[fslib._ObjectStoreFS] = None,
                 crash_at: typing.Optional[int] = None,
                 transient: typing.Optional[typing.Dict[int, int]] = None,
                 truncate: typing.Optional[typing.Dict[int, int]] = None):
        inner = inner if inner is not None else fslib.MemFS()
        assert isinstance(inner, fslib._ObjectStoreFS), \
            "FaultInjectionFS schedules faults at object-store primitives"
        self.inner = inner
        self.crash_at = crash_at
        self.transient = dict(transient or {})
        self.truncate = dict(truncate or {})
        self.op_index = 0
        self.ops: typing.List[typing.Tuple[str, str]] = []

    def _before(self, op: str, key: str) -> int:
        i = self.op_index
        remaining = self.transient.get(i, 0)
        if remaining > 0:
            self.transient[i] = remaining - 1
            raise InjectedTransient(
                f"injected transient failure at op {i} ({op} {key})")
        if self.crash_at is not None and i == self.crash_at:
            raise InjectedFault(f"injected crash at op {i} ({op} {key})")
        self.op_index += 1
        self.ops.append((op, key))
        return i

    # -- the four object-store primitives, fault-gated -----------------------
    def _keys(self, prefix):
        self._before("keys", prefix)
        return self.inner._keys(prefix)

    def _read(self, key):
        self._before("read", key)
        return self.inner._read(key)

    def _write(self, key, data):
        i = self._before("write", key)
        keep = self.truncate.get(i)
        if keep is not None:
            data = bytes(data)[:keep]
        self.inner._write(key, data)

    def _delete(self, key):
        self._before("delete", key)
        self.inner._delete(key)
