"""Deterministic storage fault injection over the fs seam.

``FaultInjectionFS`` wraps an object-store backend (normally ``MemFS``) at
the four primitives every other operation is built from — ``_keys`` /
``_read`` / ``_write`` / ``_delete`` — and injects faults from a
deterministic schedule keyed on a monotonically increasing OP INDEX:

* ``crash_at = K``: the K-th primitive raises ``InjectedFault`` BEFORE
  executing — the moment the process dies.  Because ``fs.replace`` on an
  object store expands into many ``_write``/``_delete`` primitives, a sweep
  over every index also crashes MID-replace and MID-prune, the exact windows
  the checkpoint completeness-marker ordering exists for.
* ``transient = {K: M}``: the K-th primitive raises ``InjectedTransient``
  (classified retryable by ``utils.retry``) M times, then succeeds — proving
  the retry seam absorbs GCS-style 503 bursts.  Failed attempts do NOT
  consume the op index, so schedules stay stable under retries.
* ``truncate = {K: N}``: if the K-th primitive is a write, only the first N
  bytes land — a silently torn write, the case checkpoint crc verification
  exists for.

Register it like any backend and every consumer of the seam runs against it
unchanged::

    fi = FaultInjectionFS(crash_at=7)
    fs.register("fault", fi)
    checkpoint.save("fault://bucket/run", ...)   # dies at primitive #7

``ops`` records every successfully-issued primitive, so a clean dry run
measures how many crash points an operation sequence has
(tests/fault_injection_test.py sweeps all of them).
"""
from __future__ import annotations

import typing

from . import fs as fslib
from .retry import TransientError


class InjectedFault(RuntimeError):
    """Permanent injected failure: simulates the process dying at (or the
    storage service hard-failing) a specific operation index."""


class InjectedTransient(TransientError, ConnectionError):
    """Retryable injected failure (a GCS 503 / connection reset stand-in)."""


class FaultInjectionFS(fslib._ObjectStoreFS):
    def __init__(self, inner: typing.Optional[fslib._ObjectStoreFS] = None,
                 crash_at: typing.Optional[int] = None,
                 transient: typing.Optional[typing.Dict[int, int]] = None,
                 truncate: typing.Optional[typing.Dict[int, int]] = None):
        inner = inner if inner is not None else fslib.MemFS()
        assert isinstance(inner, fslib._ObjectStoreFS), \
            "FaultInjectionFS schedules faults at object-store primitives"
        self.inner = inner
        self.crash_at = crash_at
        self.transient = dict(transient or {})
        self.truncate = dict(truncate or {})
        self.op_index = 0
        self.ops: typing.List[typing.Tuple[str, str]] = []

    def _before(self, op: str, key: str) -> int:
        i = self.op_index
        remaining = self.transient.get(i, 0)
        if remaining > 0:
            self.transient[i] = remaining - 1
            raise InjectedTransient(
                f"injected transient failure at op {i} ({op} {key})")
        if self.crash_at is not None and i == self.crash_at:
            raise InjectedFault(f"injected crash at op {i} ({op} {key})")
        self.op_index += 1
        self.ops.append((op, key))
        return i

    # -- the four object-store primitives, fault-gated -----------------------
    def _keys(self, prefix):
        self._before("keys", prefix)
        return self.inner._keys(prefix)

    def _read(self, key):
        self._before("read", key)
        return self.inner._read(key)

    def _write(self, key, data):
        i = self._before("write", key)
        keep = self.truncate.get(i)
        if keep is not None:
            data = bytes(data)[:keep]
        self.inner._write(key, data)

    def _delete(self, key):
        self._before("delete", key)
        self.inner._delete(key)


class FaultyInterface:
    """Deterministic SERVING fault injection: wraps an
    ``infer.interface.InterfaceWrapper`` (or any interface-alike) and
    injects faults at decode-call granularity, keyed on a monotonically
    increasing CALL INDEX shared across ``complete`` / ``complete_tokens`` /
    ``complete_tokens_batch`` — the serving analogue of
    ``FaultInjectionFS``'s op-index schedules:

    * ``fail_at = {K, ...}`` (or ``{K: "msg"}``): the K-th decode call
      raises ``InjectedFault`` — a crashing/poisoned decode.
    * ``latency = {K: seconds}``: the K-th decode call sleeps first — a
      slow decode that expires the deadlines of everything queued behind it.
    * ``block_on = threading.Event()`` (optionally ``block_at = {K, ...}``;
      default ALL calls): the matching decode calls wait until the event is
      SET — a wedged device loop, released by the test.  ``block_timeout_s``
      bounds the wait so a broken test cannot hang the suite.

    Attribute access proxies to the wrapped interface (``tokenizer``,
    ``params``, ``decode_calls``, ...), so the REST stack runs against it
    unchanged (tests/serving_robustness_test.py, marker: ``serving``).
    ``calls`` records how many decode calls were issued."""

    def __init__(self, inner,
                 fail_at: typing.Union[typing.Dict[int, str],
                                       typing.Iterable[int]] = (),
                 latency: typing.Optional[typing.Dict[int, float]] = None,
                 block_on=None,
                 block_at: typing.Optional[typing.Iterable[int]] = None,
                 block_timeout_s: float = 60.0):
        self._inner = inner
        self.fail_at = (dict(fail_at) if isinstance(fail_at, dict)
                        else {k: None for k in fail_at})
        self.latency = dict(latency or {})
        self.block_on = block_on
        self.block_at = None if block_at is None else set(block_at)
        self.block_timeout_s = block_timeout_s
        self.calls = 0

    def _gate(self):
        import time
        i = self.calls
        self.calls += 1
        if self.block_on is not None and (self.block_at is None
                                          or i in self.block_at):
            self.block_on.wait(timeout=self.block_timeout_s)
        if i in self.latency:
            time.sleep(self.latency[i])
        if i in self.fail_at:
            raise InjectedFault(self.fail_at[i]
                                or f"injected decode failure at call {i}")

    def complete_tokens(self, *args, **kwargs):
        self._gate()
        return self._inner.complete_tokens(*args, **kwargs)

    def complete_tokens_batch(self, *args, **kwargs):
        self._gate()
        return self._inner.complete_tokens_batch(*args, **kwargs)

    def complete(self, *args, **kwargs):
        self._gate()
        return self._inner.complete(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)
