"""Retry policy for flaky remote storage (the fault model of docs/RELIABILITY.md).

The reference stack leaned on TF1's GFile to absorb transient GCS errors; the
fs seam (utils/fs.py) has no such cushion, so one 503 mid-checkpoint killed a
pod-scale run.  This module is the cushion: exponential backoff + jitter with
a per-operation attempt budget, applied

* inside every ``GCSFS`` primitive (the network boundary), and
* around every fs call site in ``train/checkpoint.py`` (so non-GCS remote
  backends registered through ``fs.register`` get the same protection).

Only TRANSIENT errors are retried.  Classification is structural (exception
type / errno / HTTP status attribute) rather than import-based so the google
client libraries stay optional.  Permanent errors — missing objects, bad
permissions, corrupt data — surface immediately; retrying those only delays
the real diagnostic.

The clock (``sleep``) and jitter source (``rng``) are injectable so tests run
the full retry schedule deterministically with zero wall-clock sleeps
(tests/retry_test.py, tests/fault_injection_test.py).
"""
from __future__ import annotations

import errno
import random
import time
import typing


class TransientError(Exception):
    """Explicitly-retryable failure.  Raised by backends that already know an
    error is transient (and by the fault-injection harness's
    ``InjectedTransient``)."""


#: google-cloud / requests / urllib3 transient exception TYPE NAMES — matched
#: by name so the optional dependencies never need importing here.
_TRANSIENT_TYPE_NAMES = frozenset({
    "ServiceUnavailable", "TooManyRequests", "InternalServerError",
    "BadGateway", "GatewayTimeout", "DeadlineExceeded", "RetryError",
    "TransportError", "ChunkedEncodingError", "ProtocolError",
    "IncompleteRead", "RemoteDisconnected",
})

_TRANSIENT_HTTP_CODES = frozenset({408, 429, 500, 502, 503, 504})

_TRANSIENT_ERRNOS = frozenset({
    errno.EAGAIN, errno.ETIMEDOUT, errno.ECONNRESET, errno.ECONNABORTED,
    errno.ECONNREFUSED, errno.EPIPE, errno.EIO, errno.ENETUNREACH,
    errno.ENETRESET, errno.EHOSTUNREACH,
})


def is_transient(exc: BaseException) -> bool:
    """Transient (retry) vs permanent (raise immediately) classification."""
    if isinstance(exc, TransientError):
        return True
    # precise permanent subclasses of OSError first: a missing checkpoint
    # shard must not burn the whole backoff budget before surfacing
    if isinstance(exc, (FileNotFoundError, FileExistsError, IsADirectoryError,
                        NotADirectoryError, PermissionError)):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS:
        return True
    if type(exc).__name__ in _TRANSIENT_TYPE_NAMES:
        return True
    code = getattr(exc, "code", None)
    if not isinstance(code, int):
        code = getattr(exc, "status_code", None)
    return isinstance(code, int) and code in _TRANSIENT_HTTP_CODES


class RetryPolicy:
    """Exponential backoff + jitter with a hard attempt budget.

    ``delay(n) = min(max_delay, base_delay * multiplier**n) * (1 + jitter*u)``
    with ``u ~ rng.random()`` — jitter de-synchronises a pod's worth of hosts
    all retrying the same flaky bucket at once.  ``sleep`` and ``rng`` are
    injectable for deterministic tests."""

    def __init__(self, max_attempts: int = 5, base_delay: float = 0.5,
                 max_delay: float = 30.0, multiplier: float = 2.0,
                 jitter: float = 0.25,
                 sleep: typing.Callable[[float], None] = time.sleep,
                 rng: typing.Optional[random.Random] = None,
                 classify: typing.Callable[[BaseException], bool] = is_transient):
        assert max_attempts >= 1
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.sleep = sleep
        self.rng = rng if rng is not None else random.Random()
        self.classify = classify

    def backoff(self, attempt: int) -> float:
        base = min(self.max_delay, self.base_delay * self.multiplier ** attempt)
        return base * (1.0 + self.jitter * self.rng.random())

    def call(self, fn: typing.Callable, *args, site: str = "storage",
             **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying transient failures up to the
        attempt budget.  The last error (or any permanent error) re-raises.

        ``site`` (keyword-only, reserved — never forwarded to ``fn``) labels
        the failure-event counters this seam records into the telemetry
        registry: ``hbnlp_storage_retries_total`` per transient retry,
        ``hbnlp_storage_failures_total{kind=permanent|exhausted}`` when an
        error surfaces.  The happy path records nothing — one failure-free
        call costs zero registry calls."""
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                transient = self.classify(e)
                if attempt >= self.max_attempts - 1 or not transient:
                    _record_failure(site,
                                    "exhausted" if transient else "permanent")
                    raise
                _record_retry(site)
                self.sleep(self.backoff(attempt))
                attempt += 1

def _record_retry(site: str) -> None:
    # failure-path only (guarded above): a metric bug must never turn a
    # recoverable storage blip into a crash
    try:
        from ..telemetry import registry as _reg
        _reg().counter("hbnlp_storage_retries_total",
                       "transient storage errors that were retried",
                       ("site",)).labels(site=site).inc()
    except Exception:
        pass


def _record_failure(site: str, kind: str) -> None:
    try:
        from ..telemetry import registry as _reg
        _reg().counter("hbnlp_storage_failures_total",
                       "storage errors that surfaced to the caller "
                       "(permanent, or transient with the budget exhausted)",
                       ("site", "kind")).labels(site=site, kind=kind).inc()
    except Exception:
        pass


_default: typing.Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
    """The process-wide policy used by GCSFS and the checkpoint fs call
    sites.  Looked up at CALL time (never cached by consumers) so
    ``set_default_policy`` swaps take effect everywhere at once."""
    global _default
    if _default is None:
        _default = RetryPolicy()
    return _default


def set_default_policy(policy: typing.Optional[RetryPolicy]) -> None:
    """Install the process-wide policy (``train()`` derives one from the
    ``storage_retry_attempts`` / ``storage_retry_base_delay`` config knobs;
    tests install a no-sleep policy).  ``None`` resets to defaults."""
    global _default
    _default = policy
