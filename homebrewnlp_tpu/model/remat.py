"""Measured remat policy for the revnet/momentum backward (PR 11).

Replaces the boolean/``auto`` ``stash_attention_outputs`` tri-state with a
POLICY layer: what the memory-strategy backward does about
re-materializing block interiors is now one resolved decision
(:func:`resolve_remat`) consumed by ``model/blocks.py``:

==============  =============================================================
policy          behavior
==============  =============================================================
``recompute``   the strategy ``custom_vjp`` re-runs each block's forward
                inside ``jax.vjp`` — O(1) activation memory in depth, one
                extra forward of compute (the historical default)
``stash``       recompute, but every flash/ring attention layer's
                ``(out, lse)`` rides the strategy residuals so the backward
                replay runs no forward attention kernels (and no ring hops)
                — the old ``stash_attention_outputs: true``
``save``        NO ``custom_vjp``: the identical primal recurrence under
                native scan AD; every linearization residual is saved —
                zero recompute, O(depth) residual memory
``save_dots``   ``save`` with each block wrapped in ``jax.checkpoint``
                (policy ``dots_saveable``): GEMM outputs saved, elementwise
                recomputed — the middle ground for compute-bound chips
``auto``        resolved below
==============  =============================================================

All four execute the SAME primal recurrence — losses are bit-identical
and gradients agree to reconstruction ulps (tests/remat_policy_test.py).

**What auto does, and why (measured — docs/PERFORMANCE.md 'Round 11').**
The profile-guided A/B on the flagship step measured ``recompute`` 204
ms/step vs ``save`` 280 vs ``save_dots`` 249 on the CPU rig: the rig is
memory-bound, so writing + re-reading the stacked per-depth residuals
costs MORE than re-running the forward — and the committed cost ledger
classifies every body scope hbm-bound there, which is exactly the
classification this resolver keys on.  ``auto`` therefore picks:

1. the explicit ``remat_policy`` value when set;
2. the legacy ``stash_attention_outputs`` boolean when the user set one
   (``true`` → ``stash``, ``false`` → ``recompute``);
3. ``stash`` when the long-context stash rule pays and fits (seq >= 2048,
   % 128 == 0, per-device stash <= 15% of HBM — the measured +23% at 16k);
4. else ``recompute``.  The save modes stay measured OPT-INS: the A/B
   lost on the rig, the committed ledger classifies every body scope
   hbm-bound (residual round-trips are the expensive direction there),
   and a nominal roofline constant is not evidence enough to flip a
   default against a measurement.

:func:`remat_report` returns the analytic numbers behind the decision
(stash bytes, residual estimate, HBM budget, per-block recompute vs
residual-traffic seconds on the mesh's device roofline) for docs/ops.
"""
from __future__ import annotations

import typing

import numpy as np

from ..config import ModelParameter

#: fraction of per-chip HBM the attention stash may claim (the historical
#: resolve_stash gate)
STASH_HBM_FRACTION = 0.15
#: fraction of per-chip HBM the save-mode residual estimate may claim —
#: residuals coexist with params, optimizer state and the batch
SAVE_HBM_FRACTION = 0.35
#: f32 activation-sized intermediates a mixer block's linearization keeps
#: under native AD (norm stats/xhat, glu branches, relu masks, dot
#: operands) — calibrated against the measured flagship step
SAVE_RESIDUALS_PER_BLOCK = 16

POLICIES = ("recompute", "stash", "save", "save_dots")


def _mesh_geometry(params: ModelParameter, mesh):
    """(per-device shard divisor, device) for capacity estimates — the
    stash/residual arrays shard over every data/model/sequence axis."""
    shards = 1
    device = None
    if mesh is not None and getattr(mesh, "devices", None) is not None:
        for axis in ("data", "model", "sequence"):
            shards *= mesh.shape.get(axis, 1)
        device = np.asarray(mesh.devices).flat[0]
    return shards, device


def _stash_bytes(params: ModelParameter) -> int:
    """Global attention-stash estimate: one (out [b,s,h,d], lse [b,h,s])
    pair per block, sized as if every block held one attention layer."""
    seq = params.sequence_length // max(1, params.token_patch_size)
    calc_bytes = np.dtype(params.calculation_dtype).itemsize
    per_layer = (params.train_batch_size * seq * params.heads
                 * params.features_per_head * calc_bytes
                 + params.train_batch_size * params.heads * seq * 4)
    return per_layer * params.depth * max(1, params.macro_batching)


def _save_residual_bytes(params: ModelParameter) -> int:
    """Global estimate of the native-AD linearization residuals the save
    policy keeps: f32 activation-sized intermediates per block part,
    stacked over depth by scan AD."""
    seq = params.sequence_length // max(1, params.token_patch_size)
    act = params.train_batch_size * seq * params.heads \
        * params.features_per_head * 4
    blocks = params.depth * max(1, len(params.block_config))
    return act * SAVE_RESIDUALS_PER_BLOCK * blocks \
        * max(1, params.macro_batching)


def remat_report(params: ModelParameter, mesh=None) -> typing.Dict[str, typing.Any]:
    """The analytic inputs to :func:`resolve_remat`, for docs and ops
    surfaces: per-device byte estimates, the HBM budget they gate on, and
    the roofline comparison between one block's recompute and its
    residual round-trip on the mesh's device."""
    from ..utils.flops import (device_hbm_bytes, peak_flops,
                               peak_hbm_bandwidth)
    shards, device = _mesh_geometry(params, mesh)
    hbm = device_hbm_bytes(device)
    seq = params.sequence_length // max(1, params.token_patch_size)
    tokens = params.train_batch_size * seq
    d_model = params.heads * params.features_per_head
    # one depth-unit's forward: ~4 d_model^2 GEMMs (the mixer shape) plus
    # ~12 activation-sized passes of elementwise/norm traffic
    calc_bytes = np.dtype(params.calculation_dtype).itemsize
    flops_block = 2 * tokens * d_model * d_model * 4
    bytes_block = tokens * d_model * calc_bytes * 12
    resid_block = tokens * d_model * 4 * SAVE_RESIDUALS_PER_BLOCK
    peak, bw = peak_flops(device), peak_hbm_bandwidth(device)
    return {
        "stash_bytes_per_device": -(-_stash_bytes(params) // shards),
        "save_residual_bytes_per_device":
            -(-_save_residual_bytes(params) // shards),
        "hbm_bytes": hbm,
        "stash_budget_bytes": int(STASH_HBM_FRACTION * hbm),
        "save_budget_bytes": int(SAVE_HBM_FRACTION * hbm),
        "recompute_block_s": flops_block / peak + bytes_block / bw,
        "save_block_s": 2.0 * resid_block / bw,
        "seq": seq,
    }


def resolve_remat(params: ModelParameter, mesh=None) -> str:
    """The resolved remat policy for this (config, mesh) — see the module
    docstring for the decision order."""
    v = getattr(params, "remat_policy", "auto")
    if v != "auto":
        return v
    legacy = getattr(params, "stash_attention_outputs", "auto")
    if legacy is True:
        return "stash"
    if legacy is False:
        return "recompute"
    rep = remat_report(params, mesh)
    if rep["seq"] >= 2048 and rep["seq"] % 128 == 0 \
            and rep["stash_bytes_per_device"] <= rep["stash_budget_bytes"]:
        return "stash"
    # the save modes stay MEASURED opt-ins: the round-11 A/B on the
    # flagship step measured recompute 204 / save 280 / save_dots 249
    # ms/step (the residual round-trip loses on an hbm-bound rig, which is
    # what the committed cost ledger classifies every body scope as), and
    # the nominal roofline constants are not trustworthy enough to flip a
    # default against a measurement — remat_report carries the analytic
    # comparison for whoever measures a compute-bound chip with spare HBM
    return "recompute"


def block_caller(policy: str):
    """How the save-mode recurrences invoke a block: plain for ``save``,
    ``jax.checkpoint(policy=dots_saveable)`` for ``save_dots`` — GEMM
    outputs saved, elementwise recomputed."""
    import jax

    if policy == "save_dots":
        def call(f, subset, x, it=None):
            return jax.checkpoint(
                lambda s_, x_, it_: f(s_, x_, it=it_) if it_ is not None
                else f(s_, x_),
                policy=jax.checkpoint_policies.dots_saveable)(subset, x, it)
        return call

    def call(f, subset, x, it=None):
        return f(subset, x, it=it) if it is not None else f(subset, x)
    return call
