"""Layer registry + block string DSL (reference: /root/reference/src/model/frontend.py).

Block config strings like
``"attention-biased_attention_map-absolute-input_as_value-shared"`` are split
on '-' into the layer name + name_extras flags; ``split_path`` implements the
';'/',' add/multiply multi-branch DSL (frontend.py:39-55).
"""
from __future__ import annotations

from ..config import BlockArgs, BlockConfig, ModelParameter
from ..core import scope
from ..core.tensor import NamedTensor, add, multiply
from .activation import activate
from .basic import (bottleneck_group_linear, dropout, feed_forward,
                    feed_forward_product_key_memory, group_linear,
                    product_key_memory, reduced_half_linear, rezero, sum_heads,
                    transpose_sequence_features)
from .normalization import norm
from .spatial import attention, cummean, cumsum


def convolution(args: BlockArgs) -> NamedTensor:
    """Causal conv over the current attention dim.

    The reference ships this layer disabled — its hand-written mtf
    Operation raises ``ValueError("Convolution is currently broken")``
    (/root/reference/src/model/convolution.py:129).  Here it works: a dense
    features→features convolution with kernel ``convolution_size`` over the
    round-robin attention axis, causal when that axis is in
    ``masked_attention_dimensions``, via lax.conv_general_dilated (MXU path).
    """
    import jax.lax
    import jax.numpy as jnp
    from ..core.dims import Dim, shape_size
    from ..core.tensor import nt, transpose_to
    from .backend import orthogonal_var
    from .utils import get_attention_dim, is_masked

    from . import decode as decode_mod

    params = args.params
    dim = get_attention_dim(args).dim
    masked = is_masked(args)
    state = decode_mod.active()
    decoding = decode_mod.is_decode_dim(state, dim)
    full_len = state.seq_len if decoding else dim.size
    kernel = min(params.convolution_size, full_len)
    feature_dims = list(params.feature_dims)
    kernel_dim_in = [Dim("_conv_in", shape_size(feature_dims))]
    canonical = [d for d in args.tensor.dims if d not in feature_dims and d != dim] \
        + [dim] + feature_dims
    x = transpose_to(args.tensor, canonical)
    lead = shape_size(canonical[:-1 - len(feature_dims)])
    features = shape_size(feature_dims)
    data = x.data.reshape(lead, dim.size, features)
    w = orthogonal_var(args, [Dim("_conv_k", kernel)] + kernel_dim_in
                       + feature_dims, kernel_dim_in)
    wdata = w.data.reshape(kernel, features, features)
    if decoding:
        if not masked:
            raise NotImplementedError("incremental decode needs causal conv")
        xw = decode_mod.rolling_window(
            nt(data, [Dim("_lead", lead), dim, Dim("_feat", features)]),
            dim, kernel)
        out = jnp.einsum("lkf,kfo->lo", xw.data, wdata)[:, None]
    else:
        pstate = decode_mod.prefill_active()
        if masked and decode_mod.is_prefill_dim(pstate, dim):
            decode_mod.prefill_store_convwin(
                nt(data, [Dim("_lead", lead), dim, Dim("_feat", features)]),
                dim, kernel)
        if masked:
            data = jnp.pad(data, ((0, 0), (kernel - 1, 0), (0, 0)))
            padding = "VALID"
        else:
            padding = "SAME"
        out = jax.lax.conv_general_dilated(
            data, wdata, window_strides=(1,), padding=padding,
            dimension_numbers=("NWC", "WIO", "NWC"))
    out = nt(out.reshape([d.size for d in canonical]).astype(args.tensor.dtype),
             canonical)
    return transpose_to(out, args.tensor.dims)


def _get_block_part(block_part_config: BlockConfig, params: ModelParameter,
                    block_input: NamedTensor) -> NamedTensor:
    out = block_input
    for idx, layer in enumerate(block_part_config.layer, 1):
        name, *extras = layer.split('-')
        args = BlockArgs(params, out, extras, idx == len(block_part_config.layer))
        out = scope.scoped(name + '_', LAYER_FUNCTIONS[name], args)
    if block_part_config.skip and block_part_config.memory_reduction_strategy in ("none", "checkpoint"):
        out = out + block_input
    return out


def block_part_fn(params: ModelParameter, block_part_config: BlockConfig,
                  block_input: NamedTensor, name_prefix: str = 'block') -> NamedTensor:
    return scope.scoped(f"{name_prefix}_", _get_block_part, block_part_config,
                        params, block_input)


def split_path(args: BlockArgs) -> NamedTensor:
    """';'-separated parallel branches combined by add/multiply."""
    base, *name_extras = '-'.join(args.name_extras).split(';')
    base = base.split('-')
    if 'add' in base:
        out, fn = 0, add
    elif 'multiply' in base:
        out, fn = 1, multiply
    else:
        raise ValueError(f"split_path needs add/multiply base, got {base}")
    for conf in name_extras:
        out = fn(out, _get_block_part(BlockConfig({'skip': False, 'layer': conf.split(',')}, ''),
                                      args.params, args.tensor))
    return out


LAYER_FUNCTIONS = {'feed_forward': feed_forward,
                   'attention': attention,
                   'cummean': cummean,
                   'cumsum': cumsum,
                   'norm': norm,
                   'rezero': rezero,
                   'activation': activate,
                   'convolution': convolution,
                   'dropout': dropout,
                   'group_linear': group_linear,
                   'split_path': split_path,
                   'feed_forward_product_key_memory': feed_forward_product_key_memory,
                   'product_key_memory': product_key_memory,
                   'reduced_half_linear': reduced_half_linear,
                   'transpose_sequence_features': transpose_sequence_features,
                   'bottleneck_group_linear': bottleneck_group_linear,
                   'sum_heads': sum_heads,
                   }
