"""Basic layers (reference: /root/reference/src/model/basic.py).

rezero, dropout, wrapped_linear, soft mixture-of-experts, activated_linear
(glu / glu_add / norm flags with in:/mid:/out: prefix scoping), feed_forward,
group_linear (per-head grouped linear via the anonymized key dim),
sum_heads, transpose_sequence_features, reduced_half_linear, product-key
memory, bottleneck_group_linear.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..config import BlockArgs
from ..core import scope
from ..core.dims import shape_sub
from ..core.tensor import (NamedTensor, cast, dropout as tensor_dropout,
                           einsum, exp, multiply, reduce_max, reduce_sum,
                           reciprocal, rename_dim, reshape, sigmoid,
                           stop_gradient, top_1, transpose_to, unbind)
from .activation import activate
from .backend import ConstantInit, get_var, linear, orthogonal_var
from .embedding import gather_embed
from .normalization import norm
from .utils import anonymize_dim, anonymize_shape, linear_shapes


def rezero(args: BlockArgs) -> NamedTensor:
    return args.tensor * get_var(args, [], ConstantInit(0.))


def dropout(args: BlockArgs) -> NamedTensor:
    keep = 1.
    for extra in args.name_extras:
        if extra.startswith("dropout_rate"):
            keep = 1 - float(extra[len("dropout_rate"):])
    return tensor_dropout(args.tensor, args.params.train, keep,
                          scope.current().next_rng())


def wrapped_linear(args: BlockArgs) -> NamedTensor:
    return linear(args, *linear_shapes(args))


def mixture_of_experts(args: BlockArgs) -> NamedTensor:
    """Dense softmax-gated expert einsum (basic.py:37-44) — no routing, no
    all-to-all; the experts dim can be placed on the mesh for true EP."""
    params = args.params
    old, new = linear_shapes(args)
    gate = linear(args, old, [params.expert_dim])
    gate = gate - stop_gradient(reduce_max(gate, reduced_dim=params.expert_dim))
    gate = exp(gate)
    out_shape = shape_sub(args.tensor.dims, old) + list(new)
    return einsum([reciprocal(reduce_sum(gate, reduced_dim=params.expert_dim)),
                   args.tensor, gate,
                   orthogonal_var(args, list(old) + list(new) + [params.expert_dim])],
                  output_shape=out_shape)


def activated_linear(args: BlockArgs, prefix: str) -> NamedTensor:
    args = args([a[len(prefix):] for a in args if a.startswith(prefix)])
    feed_forward_fn = mixture_of_experts if "mixture_of_experts" in args.name_extras \
        else wrapped_linear
    out = dropout(args(activate(args(feed_forward_fn(args)))))
    if "glu" in args.name_extras or "glu_add" in args.name_extras:
        out = multiply(out, sigmoid(feed_forward_fn(args)))
    if "glu_add" in args.name_extras:
        out = out + activate(args(feed_forward_fn(args)))
    if "norm" in args.name_extras:
        out = norm(args(out))
    return out


def activated_linear_in(args: BlockArgs) -> NamedTensor:
    return activated_linear(args, "in:")


def activated_linear_out(args: BlockArgs) -> NamedTensor:
    return activated_linear(args, "out:")


def feed_forward(args: BlockArgs) -> NamedTensor:
    return activated_linear_out(args(activated_linear_in(args)))


def group_linear(args: BlockArgs) -> NamedTensor:
    """Per-head grouped linear: project features -> anonymized key dim and
    rename back (basic.py:72-74).  The reference's reshape round-trip is a
    pure rename here."""
    params = args.params
    anonymous_key = anonymize_shape(params.feature_dims, params.key_dim)
    out = linear(args("group"), list(params.feature_dims), anonymous_key)
    return rename_dim(out, anonymize_dim(params.key_dim), params.key_dim.name)


def sum_heads(args: BlockArgs) -> NamedTensor:
    return reduce_sum(args.tensor, reduced_dim=args.params.head_dim)


def transpose_sequence_features(args: BlockArgs) -> NamedTensor:
    """Swap sequence and feature axes (basic.py:81-86)."""
    from . import decode as decode_mod
    params = args.params
    if decode_mod.active() is not None:
        raise NotImplementedError(
            "transpose_sequence_features mixes sequence into features; "
            "incremental decode falls back to the full-forward sampler")
    assert params.features_per_head == params.sequence_length, \
        "transpose_sequence_features requires features_per_head == sequence_length"
    tensor = rename_dim(args.tensor, params.sequence_dim.name, "intermediate")
    tensor = rename_dim(tensor, params.key_dim.name, params.sequence_dim.name)
    tensor = rename_dim(tensor, "intermediate", params.key_dim.name)
    return transpose_to(tensor, args.tensor.dims)


def reduced_half_linear(args: BlockArgs) -> NamedTensor:
    return group_linear(args(reduce_sum(args.tensor, reduced_dim=args.params.head_dim)))


def product_key_memory(args: BlockArgs) -> NamedTensor:
    """Two/three-axis product-key memory with top-1 per axis + batched gather
    (basic.py:93-115)."""
    params = args.params
    anonymous_key = anonymize_dim(params.key_dim)
    features = [params.pkm_dim, anonymous_key]
    assignment = linear(args, linear_shapes(args).old, [params.head_dim] + features)
    assignment = norm(args(assignment), features)
    assignment = cast(assignment, jnp.float32)  # f64 in reference; f32 on TPU
    normalizer = reduce_max(assignment, reduced_dim=anonymous_key)
    normalizer = reduce_sum(normalizer, reduced_dim=params.pkm_dim)
    assignment = assignment - stop_gradient(normalizer)
    assignment = exp(assignment)
    normalizer = reduce_sum(assignment, output_shape=shape_sub(assignment.dims, [anonymous_key]))
    normalizer = einsum(unbind(normalizer, params.pkm_dim),
                        output_shape=shape_sub(normalizer.dims, [params.pkm_dim]))

    val, idx = top_1(assignment, anonymous_key)
    powers = jnp.asarray([params.features_per_head ** i for i in range(params.pkm_axes)],
                         dtype=jnp.int32)
    from ..core.tensor import nt
    powers_nt = nt(powers, [params.pkm_dim])
    idx = einsum([powers_nt, idx], output_shape=shape_sub(idx.dims, [params.pkm_dim]))
    val = einsum(unbind(val, params.pkm_dim),
                 output_shape=shape_sub(val.dims, [params.pkm_dim])) / normalizer
    val = cast(val, params.calculation_dtype)
    out = gather_embed(args(idx), [params.product_key_value_dim] + list(params.feature_dims),
                       [params.head_dim])
    return out * val


def feed_forward_product_key_memory(args: BlockArgs) -> NamedTensor:
    return product_key_memory(args(activated_linear_in(args)))


def bottleneck_group_linear(args: BlockArgs) -> NamedTensor:
    """features -> bottleneck(intermediate) -> widened grouped mid -> grouped
    out (basic.py:122-126); the workhorse of the flagship mixer configs."""
    args = args(activated_linear_in(args))
    args.name_extras.extend(["group", "mid:group", "out:group"])
    args = args(activated_linear(args, "mid:"))
    return activated_linear_out(args)
