"""Basic layers (reference: /root/reference/src/model/basic.py).

rezero, dropout, wrapped_linear, soft mixture-of-experts, activated_linear
(glu / glu_add / norm flags with in:/mid:/out: prefix scoping), feed_forward,
group_linear (per-head grouped linear via the anonymized key dim),
sum_heads, transpose_sequence_features, reduced_half_linear, product-key
memory, bottleneck_group_linear.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..config import BlockArgs
from ..core import scope
from ..core.dims import Dim, shape_sub
from ..core.tensor import (NamedTensor, cast, dropout as tensor_dropout, nt,
                           einsum, exp, multiply, reduce_max, reduce_sum,
                           reciprocal, rename_dim, reshape, sigmoid,
                           stop_gradient, top_1, transpose_to, unbind)
from .activation import activate
from .backend import ConstantInit, get_var, linear, orthogonal_var
from .embedding import gather_embed
from .normalization import norm
from .utils import anonymize_dim, anonymize_shape, linear_shapes


def rezero(args: BlockArgs) -> NamedTensor:
    return args.tensor * get_var(args, [], ConstantInit(0.))


def dropout(args: BlockArgs) -> NamedTensor:
    keep = 1.
    for extra in args.name_extras:
        if extra.startswith("dropout_rate"):
            keep = 1 - float(extra[len("dropout_rate"):])
    return tensor_dropout(args.tensor, args.params.train, keep,
                          scope.current().next_rng())


def wrapped_linear(args: BlockArgs) -> NamedTensor:
    return linear(args, *linear_shapes(args))


def mixture_of_experts(args: BlockArgs) -> NamedTensor:
    """Dense softmax-gated expert einsum (basic.py:37-44) — no routing, no
    all-to-all; the experts dim can be placed on the mesh for true EP."""
    params = args.params
    old, new = linear_shapes(args)
    gate = linear(args, old, [params.expert_dim])
    gate = gate - stop_gradient(reduce_max(gate, reduced_dim=params.expert_dim))
    gate = exp(gate)
    out_shape = shape_sub(args.tensor.dims, old) + list(new)
    return einsum([reciprocal(reduce_sum(gate, reduced_dim=params.expert_dim)),
                   args.tensor, gate,
                   orthogonal_var(args, list(old) + list(new) + [params.expert_dim])],
                  output_shape=out_shape)


def _topk_dispatch(probs, top_k: int, capacity: int):
    """Vectorized GShard-style greedy top-k dispatch.

    Equivalent to the sequential loop (iteration j: mask previous choices,
    argmax, assign buffer positions): the k-major cumsum gives every token's
    j-th choice a position behind ALL tokens' earlier choices, which is
    exactly the order the loop fills expert buffers in.  Returns
    (combine [g,t,E,C], idx [g,t,k], keep [g,k,t])."""
    g, t, e = probs.shape
    vals, idx = jax.lax.top_k(probs, top_k)            # [g, t, k]
    oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)     # [g, t, k, E]
    oh_k = jnp.transpose(oh, (0, 2, 1, 3))             # [g, k, t, E]
    oh_km = oh_k.reshape(g, top_k * t, e)              # k-major flatten
    pos = jnp.cumsum(oh_km, axis=1) - oh_km            # earlier fills per E
    pos_tok = jnp.sum(pos * oh_km, axis=-1).reshape(g, top_k, t)
    keep = (pos_tok < capacity).astype(jnp.float32)    # [g, k, t]
    gate_w = jnp.transpose(vals, (0, 2, 1))            # [g, k, t]
    slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                          dtype=jnp.float32)           # [g, k, t, C]
    combine = jnp.einsum("gkt,gkte,gktc->gtec", gate_w * keep, oh_k, slot,
                         precision=jax.lax.Precision.HIGHEST)
    return combine, idx, keep


def _router_aux(wb: float, wz: float, top_k: int, logits):
    """Switch/GShard auxiliary losses as a function of the router logits
    alone: ``wb * E * mean_g sum_e f_e P_e`` (f_e = fraction of (token,
    choice) pairs routed to expert e — constant w.r.t. logits, gradient
    flows through the mean-probability term, as in Switch) plus
    ``wz * mean logsumexp(logits)^2`` (router z-loss)."""
    e = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    total = jnp.float32(0)
    if wb:
        _, idx = jax.lax.top_k(logits, top_k)
        oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)     # [g, t, k, E]
        frac = jnp.mean(jnp.sum(oh, axis=2), axis=1)       # [g, E], sums to k
        mean_p = jnp.mean(probs, axis=1)                   # [g, E]
        total = total + wb * e * jnp.mean(
            jnp.sum(jax.lax.stop_gradient(frac) * mean_p, axis=-1)) / top_k
    if wz:
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        total = total + wz * jnp.mean(lse ** 2)
    return total


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _router_aux_inject(wb: float, wz: float, top_k: int, logits):
    """Identity on the forward; the backward ADDS the auxiliary-loss gradient
    to the logits cotangent.  Because the aux losses depend only on the
    logits, this injects their exact gradient without the loss value ever
    having to escape the block stack — which makes it correct under every
    memory strategy (revnet/momentum custom_vjp replays, lax.scan over
    depth, jax.checkpoint, 1F1B per-stage vjp) with zero changes to that
    machinery.  The reported total loss stays the task loss; the aux VALUES
    are observable through the routing-stats probe (Trainer.moe_stats)."""
    return logits


def _router_aux_fwd(wb, wz, top_k, logits):
    return logits, logits


def _router_aux_bwd(wb, wz, top_k, logits, ct):
    aux_grad = jax.grad(lambda l: _router_aux(wb, wz, top_k, l))(logits)
    return (ct + aux_grad.astype(ct.dtype),)


_router_aux_inject.defvjp(_router_aux_fwd, _router_aux_bwd)


def routed_mixture_of_experts(args: BlockArgs) -> NamedTensor:
    """Top-k routed MoE with capacity-bounded dense dispatch (GShard/Switch
    style) — NEW capability: the reference only has the dense soft-MoE above
    (/root/reference/src/model/basic.py:37-44, every expert computes every
    token).  Routing flags: ``routed`` engages it inside activated_linear;
    ``top_k<k>`` and ``capacity_factor<f>`` override config
    ``moe_top_k``/``moe_capacity_factor``.

    Formulation is einsum dispatch/combine (one-hot capacity slots), the
    standard TPU-native shape: with the ``experts`` dim on a mesh axis
    (``layout_override {"experts": "model"}``) GSPMD turns the dispatch and
    combine contractions into all-to-alls over that axis, and expert weights
    shard 1/E per device.  With k = E and unbounded capacity it reproduces
    the dense soft-MoE exactly (parity-tested).
    """
    from ..core.sharding import with_constraint

    params = args.params
    old, new = linear_shapes(args)
    top_k = params.moe_top_k
    capacity_factor = params.moe_capacity_factor
    for extra in args.name_extras:
        if extra.startswith("top_k"):
            top_k = int(extra[len("top_k"):])
        elif extra.startswith("capacity_factor"):
            capacity_factor = float(extra[len("capacity_factor"):])
    n_exp = params.expert_dim.size
    top_k = min(top_k, n_exp)

    # gate: same projection shape + scope order as the dense soft-MoE gate
    gate = linear(args, old, [params.expert_dim])
    weights = orthogonal_var(args, list(old) + list(new) + [params.expert_dim])

    x = args.tensor
    token_dims = [d for d in x.dims if d not in old]   # [batch, seq, ...]
    feat_dims = list(old)
    # flatten: g = batch (routing group), t = positions per group, f = features
    g_sz = token_dims[0].size
    t_sz = math.prod([d.size for d in token_dims[1:]]) if len(token_dims) > 1 else 1
    f_sz = math.prod([d.size for d in feat_dims])
    n_sz = math.prod([d.size for d in new])
    xt = transpose_to(x, token_dims + feat_dims)
    xf = xt.data.reshape(g_sz, t_sz, f_sz)              # [g, t, f]
    gate_t = transpose_to(gate, token_dims + [params.expert_dim])
    logits = gate_t.data.reshape(g_sz, t_sz, n_exp).astype(jnp.float32)

    wb, wz = float(params.moe_balance_loss), float(params.moe_router_z_loss)
    if params.train and (wb or wz):
        logits = _router_aux_inject(wb, wz, top_k, logits)
    probs = jax.nn.softmax(logits, axis=-1)             # [g, t, E]
    capacity = max(1, int(math.ceil(top_k * t_sz / n_exp * capacity_factor)))
    capacity = min(capacity, t_sz)

    combine, idx, keep = _topk_dispatch(probs, top_k, capacity)

    sink = scope.current().stats_sink
    if sink is not None:
        oh = jax.nn.one_hot(idx, n_exp, dtype=jnp.float32)
        frac = jnp.mean(jnp.sum(oh, axis=2), axis=(0, 1))    # [E], sums to k
        util = frac * n_exp / top_k                # 1.0 = perfectly balanced
        sink.append((scope.current().path(), {
            "balance_loss": _router_aux(1.0, 0.0, top_k, logits),
            "router_z_loss": _router_aux(0.0, 1.0, top_k, logits),
            "dropped_fraction": 1.0 - jnp.mean(keep),
            "utilization_min": jnp.min(util),
            "utilization_max": jnp.max(util),
            "utilization": util,
        }))

    # renormalize the kept top-k gate mass (standard top-k softmax renorm)
    denom = jnp.sum(combine, axis=(2, 3), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    dispatch = (combine > 0).astype(xf.dtype)

    # dispatch -> expert compute -> combine (all-to-alls materialize here
    # when 'experts' is a mesh axis)
    cap_dim = Dim("_capacity", capacity)
    grp_dim = token_dims[0]
    mesh = scope.current().mesh if scope.in_context() else None

    def constrain(arr, last_dim):
        t = nt(arr, [params.expert_dim, grp_dim, cap_dim, last_dim])
        return with_constraint(t, params, mesh).data

    exp_in = jnp.einsum("gtec,gtf->egcf", dispatch, xf)
    exp_in = constrain(exp_in, Dim("_moe_features", f_sz))

    w_t = transpose_to(weights, [params.expert_dim] + list(old) + list(new))
    wf = w_t.data.reshape(n_exp, f_sz, n_sz).astype(xf.dtype)
    exp_out = jnp.einsum("egcf,efn->egcn", exp_in, wf)
    exp_out = constrain(exp_out, Dim("_moe_out", n_sz))

    out = jnp.einsum("gtec,egcn->gtn", combine.astype(exp_out.dtype), exp_out)
    out_dims = token_dims + list(new)
    out = out.reshape([d.size for d in out_dims]).astype(x.dtype)
    return transpose_to(nt(out, out_dims),
                        shape_sub(x.dims, old) + list(new))


def activated_linear(args: BlockArgs, prefix: str) -> NamedTensor:
    args = args([a[len(prefix):] for a in args if a.startswith(prefix)])
    if "mixture_of_experts" in args.name_extras:
        feed_forward_fn = routed_mixture_of_experts \
            if "routed" in args.name_extras else mixture_of_experts
    else:
        feed_forward_fn = wrapped_linear
    out = dropout(args(activate(args(feed_forward_fn(args)))))
    if "glu" in args.name_extras or "glu_add" in args.name_extras:
        out = multiply(out, sigmoid(feed_forward_fn(args)))
    if "glu_add" in args.name_extras:
        out = out + activate(args(feed_forward_fn(args)))
    if "norm" in args.name_extras:
        out = norm(args(out))
    return out


def activated_linear_in(args: BlockArgs) -> NamedTensor:
    return activated_linear(args, "in:")


def activated_linear_out(args: BlockArgs) -> NamedTensor:
    return activated_linear(args, "out:")


def feed_forward(args: BlockArgs) -> NamedTensor:
    return activated_linear_out(args(activated_linear_in(args)))


def group_linear(args: BlockArgs) -> NamedTensor:
    """Per-head grouped linear: project features -> anonymized key dim and
    rename back (basic.py:72-74).  The reference's reshape round-trip is a
    pure rename here."""
    params = args.params
    anonymous_key = anonymize_shape(params.feature_dims, params.key_dim)
    out = linear(args("group"), list(params.feature_dims), anonymous_key)
    return rename_dim(out, anonymize_dim(params.key_dim), params.key_dim.name)


def sum_heads(args: BlockArgs) -> NamedTensor:
    return reduce_sum(args.tensor, reduced_dim=args.params.head_dim)


def transpose_sequence_features(args: BlockArgs) -> NamedTensor:
    """Swap sequence and feature axes (basic.py:81-86)."""
    from . import decode as decode_mod
    params = args.params
    if decode_mod.active() is not None:
        raise NotImplementedError(
            "transpose_sequence_features mixes sequence into features; "
            "incremental decode falls back to the full-forward sampler")
    assert params.features_per_head == params.sequence_length, \
        "transpose_sequence_features requires features_per_head == sequence_length"
    tensor = rename_dim(args.tensor, params.sequence_dim.name, "intermediate")
    tensor = rename_dim(tensor, params.key_dim.name, params.sequence_dim.name)
    tensor = rename_dim(tensor, "intermediate", params.key_dim.name)
    return transpose_to(tensor, args.tensor.dims)


def reduced_half_linear(args: BlockArgs) -> NamedTensor:
    return group_linear(args(reduce_sum(args.tensor, reduced_dim=args.params.head_dim)))


def product_key_memory(args: BlockArgs) -> NamedTensor:
    """Two/three-axis product-key memory with top-1 per axis + batched gather
    (basic.py:93-115)."""
    params = args.params
    anonymous_key = anonymize_dim(params.key_dim)
    features = [params.pkm_dim, anonymous_key]
    assignment = linear(args, linear_shapes(args).old, [params.head_dim] + features)
    assignment = norm(args(assignment), features)
    assignment = cast(assignment, jnp.float32)  # f64 in reference; f32 on TPU
    normalizer = reduce_max(assignment, reduced_dim=anonymous_key)
    normalizer = reduce_sum(normalizer, reduced_dim=params.pkm_dim)
    assignment = assignment - stop_gradient(normalizer)
    assignment = exp(assignment)
    normalizer = reduce_sum(assignment, output_shape=shape_sub(assignment.dims, [anonymous_key]))
    normalizer = einsum(unbind(normalizer, params.pkm_dim),
                        output_shape=shape_sub(normalizer.dims, [params.pkm_dim]))

    val, idx = top_1(assignment, anonymous_key)
    powers = jnp.asarray([params.features_per_head ** i for i in range(params.pkm_axes)],
                         dtype=jnp.int32)
    from ..core.tensor import nt
    powers_nt = nt(powers, [params.pkm_dim])
    idx = einsum([powers_nt, idx], output_shape=shape_sub(idx.dims, [params.pkm_dim]))
    val = einsum(unbind(val, params.pkm_dim),
                 output_shape=shape_sub(val.dims, [params.pkm_dim])) / normalizer
    val = cast(val, params.calculation_dtype)
    out = gather_embed(args(idx), [params.product_key_value_dim] + list(params.feature_dims),
                       [params.head_dim])
    return out * val


def feed_forward_product_key_memory(args: BlockArgs) -> NamedTensor:
    return product_key_memory(args(activated_linear_in(args)))


def bottleneck_group_linear(args: BlockArgs) -> NamedTensor:
    """features -> bottleneck(intermediate) -> widened grouped mid -> grouped
    out (basic.py:122-126); the workhorse of the flagship mixer configs."""
    args = args(activated_linear_in(args))
    args.name_extras.extend(["group", "mid:group", "out:group"])
    args = args(activated_linear(args, "mid:"))
    return activated_linear_out(args)
