"""Activation registry (reference: /root/reference/src/model/activation.py).

The reference hand-writes forward AND backward slicewise kernels for
mish/silu/lecun_tanh/softsign because mtf can't differentiate through
``cwise``; under jax every one of these is a plain jnp expression with native
AD, and XLA fuses them into the surrounding matmuls.
"""
from __future__ import annotations

import numpy as np

from ..config import BlockArgs
from ..core import scope
from ..core.tensor import (NamedTensor, einsum, multiply, sigmoid as _sigmoid,
                           softplus, tanh as _tanh, unary)
import jax
import jax.numpy as jnp


def _gelu(args: BlockArgs) -> NamedTensor:
    """tanh-approx gelu, exactly the reference's einsum formulation
    (activation.py:158-161)."""
    x = args.tensor
    inner = einsum([x, x, x, __const(x, 0.044715)], x.dims) + x * np.sqrt(2 / np.pi)
    return einsum([x, _tanh(inner) + 1.0, __const(x, 0.5)], x.dims)


def __const(like: NamedTensor, value: float) -> NamedTensor:
    from ..core.tensor import constant
    return constant(value, like.dtype)


def _relu(args):
    return unary(jax.nn.relu, args.tensor)


def _sigmoid_fn(args):
    return _sigmoid(args.tensor)


def _tanh_fn(args):
    return _tanh(args.tensor)


def _lecun_tanh(args):
    # tanh(x) + 0.1 * x (activation.py:93-94)
    return unary(lambda x: jnp.tanh(x) + x * 0.1, args.tensor)


def _silu(args):
    return unary(lambda x: x * jax.nn.sigmoid(x), args.tensor)


def _mish(args):
    return multiply(_tanh(softplus(args.tensor)), args.tensor)


def _softsign(args):
    # x / (1 + |x|) (activation.py:126-127)
    return unary(lambda x: x / (1. + jnp.abs(x)), args.tensor)


def _exp(args):
    return unary(jnp.exp, args.tensor)


ACTIVATIONS = {'relu': _relu,
               'sigmoid': _sigmoid_fn,
               'tanh': _tanh_fn,
               'gelu': _gelu,
               'lecun_tanh': _lecun_tanh,
               'silu': _silu,
               'mish': _mish,
               'mtf_mish': _mish,
               'softsign': _softsign,
               'exp': _exp,
               }


def activate(args: BlockArgs) -> NamedTensor:
    """First recognised activation flag wins; identity otherwise
    (activation.py:200-211)."""
    for fn_name in args:
        if fn_name not in ACTIVATIONS:
            continue
        return scope.scoped(fn_name, ACTIVATIONS[fn_name], args)
    return args.tensor
