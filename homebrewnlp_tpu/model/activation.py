"""Activation registry (reference: /root/reference/src/model/activation.py).

The reference hand-writes forward AND backward slicewise kernels for
mish/silu/lecun_tanh/softsign because mtf can't differentiate through
``cwise``; under jax every one of these is a plain jnp expression with native
AD, and XLA fuses them into the surrounding matmuls.
"""
from __future__ import annotations

import numpy as np

from ..config import BlockArgs
from ..core import scope
from ..core.tensor import (NamedTensor, multiply, sigmoid as _sigmoid,
                           softplus, tanh as _tanh, unary)
import jax
import jax.numpy as jnp


def _gelu(args: BlockArgs) -> NamedTensor:
    """tanh-approx gelu — the reference's formula (activation.py:158-161),
    as ONE fused scalar expression.

    The historical spelling built the cubic and the final product through
    ``einsum([x, x, x, const])`` with NamedTensor scalar constants; on the
    profiled flagship step each constant materialised as a full
    activation-shaped broadcast instruction with multiple fusion users
    (~4% of step time pure broadcast traffic — docs/PERFORMANCE.md 'Round
    11').  The single jnp expression keeps every constant scalar inside
    one fusion.  Same formula and dtype; product association differs by
    <= 1 bf16 ulp (step-loss parity to 4 decimals verified in the round-11
    A/B; tests/basic_pointwise_test.py pins the closed form)."""
    x = args.tensor

    def f(v):
        c = np.float32(0.044715).astype(v.dtype)
        s = np.float32(np.sqrt(2 / np.pi)).astype(v.dtype)
        inner = v * v * v * c + v * s
        return v * (jnp.tanh(inner) + np.float32(1).astype(v.dtype)) \
            * np.float32(0.5).astype(v.dtype)
    return unary(f, x)


def _relu(args):
    return unary(jax.nn.relu, args.tensor)


def _sigmoid_fn(args):
    return _sigmoid(args.tensor)


def _tanh_fn(args):
    return _tanh(args.tensor)


def _lecun_tanh(args):
    # tanh(x) + 0.1 * x (activation.py:93-94)
    return unary(lambda x: jnp.tanh(x) + x * 0.1, args.tensor)


def _silu(args):
    return unary(lambda x: x * jax.nn.sigmoid(x), args.tensor)


def _mish(args):
    return multiply(_tanh(softplus(args.tensor)), args.tensor)


def _softsign(args):
    # x / (1 + |x|) (activation.py:126-127)
    return unary(lambda x: x / (1. + jnp.abs(x)), args.tensor)


def _exp(args):
    return unary(jnp.exp, args.tensor)


ACTIVATIONS = {'relu': _relu,
               'sigmoid': _sigmoid_fn,
               'tanh': _tanh_fn,
               'gelu': _gelu,
               'lecun_tanh': _lecun_tanh,
               'silu': _silu,
               'mish': _mish,
               'mtf_mish': _mish,
               'softsign': _softsign,
               'exp': _exp,
               }


def activate(args: BlockArgs) -> NamedTensor:
    """First recognised activation flag wins; identity otherwise
    (activation.py:200-211)."""
    for fn_name in args:
        if fn_name not in ACTIVATIONS:
            continue
        return scope.scoped(fn_name, ACTIVATIONS[fn_name], args)
    return args.tensor
