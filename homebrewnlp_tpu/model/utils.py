"""Shape-algebra helpers for the layer DSL.

jax-native analogues of /root/reference/src/utils_mtf.py.  The reference's
``anonymize`` physically reshaped tensors onto replicated dims so mtf could do
cross-shard ops; here an anonymized dim is only a *name* change (``seq`` ->
``_seq``) so that einsum treats query/key positions as distinct axes and the
sharding layer replicates it (layout rules never match ``_``-prefixed names).
No data movement — GSPMD inserts any needed collective.
"""
from __future__ import annotations

import typing

import jax.numpy as jnp

from ..config import BlockArgs, ModelParameter
from ..core.dims import (Dim, SHAPE, anonymize_dim, deduplicate, dim_name,
                         has_dim, shape_crossection, shape_sub)
from ..core.tensor import NamedTensor, cast, greater_equal, range_, rename_dim

ATTENTION_DIM = typing.NamedTuple("AttentionDim", (("index", int), ("dim", Dim)))
LINEAR_SHAPES = typing.NamedTuple("LinearShapes", (("old", list), ("new", list)))


def anonymize(tensor: NamedTensor, dim: typing.Union[Dim, str]) -> NamedTensor:
    """Rename dim -> _dim (replicated under layout rules).
    Reference: src/utils_mtf.py:207-232 — there a reshape, here a no-op rename."""
    name = dim_name(dim)
    if not has_dim(tensor.dims, name):
        return tensor
    return rename_dim(tensor, name, "_" + name.lstrip("_") if not name.startswith("_") else name)


def unanonymize(tensor: NamedTensor, dim: typing.Union[Dim, str]) -> NamedTensor:
    name = dim_name(dim)
    anon = "_" + name.lstrip("_")
    if not has_dim(tensor.dims, anon):
        return tensor
    return rename_dim(tensor, anon, name.lstrip("_"))


def anonymize_shape(dims: SHAPE, dim: Dim,
                    size: typing.Optional[int] = None) -> typing.List[Dim]:
    """Copy of dims with `dim` anonymized (src/utils_mtf.py anonymize_shape)."""
    return [anonymize_dim(d, size) if d == dim else d for d in dims]


def get_intermediate(args: BlockArgs) -> typing.List[Dim]:
    if "group" not in args.name_extras:
        return list(args.params.intermediate)
    return [args.params.head_dim,
            anonymize_dim(args.params.key_dim,
                          args.params.key_dim.size * args.params.group_linear_factor)]


def linear_shapes(args: BlockArgs) -> LINEAR_SHAPES:
    """Infer (old, new) einsum dims from tensor shape ∩ feature dims
    (reference: src/utils_mtf.py:383-391)."""
    params = args.params
    features = get_intermediate(args) + list(params.feature_dims)
    if "group" in args.name_extras and has_dim(args.tensor.dims, params.intermediate[-1]):
        features = [d for d in features if d != params.key_dim]
        features.extend(params.intermediate)
    features = deduplicate(features)
    old = shape_crossection(args.tensor.dims, features)
    drop = [params.head_dim] if ("group" in args.name_extras and params.head_dim in old) else []
    new = [d for d in features if d not in shape_sub(old, drop)]
    return LINEAR_SHAPES(list(old), list(new))


def feature_dims_used(params: ModelParameter, shape: SHAPE,
                      dims: typing.Optional[SHAPE] = None) -> bool:
    if isinstance(shape, NamedTensor):
        shape = shape.dims
    if dims is None:
        dims = list(params.feature_dims) + [anonymize_dim(d) for d in params.feature_dims]
        return bool(sum(f in list(shape) for f in dims) // 2)
    return all(f in list(shape) for f in dims)


def compare_range(params: ModelParameter, dim0: Dim, dim1: Dim,
                  comparison) -> NamedTensor:
    """comparison(range(dim0), range(dim1)) as activation dtype — causal masks
    (reference: src/utils_mtf.py:411-415).  Under incremental decoding the
    length-1 query dim evaluates as ``[pos]`` so masks select row pos."""
    from ..core.tensor import nt
    from . import decode

    state = decode.active()

    def _range(d: Dim) -> NamedTensor:
        if decode.is_decode_dim(state, d):
            if decode.is_vector_pos(state.pos):
                # continuous-batching engine: each slot sits at its own
                # position, so the query range is per-row — masks gain a
                # batch dim and broadcast by name downstream.  A width-m
                # verify slice (speculative decoding) evaluates the range
                # as pos + [0..m); width 1 keeps the original expression
                assert state.pos.shape[0] == params.batch_dim.size, \
                    (state.pos.shape, params.batch_dim)
                base = state.pos[:, None]
                if d.size != 1:
                    base = base + jnp.arange(d.size)
                return nt(base.astype(jnp.int32), [params.batch_dim, d])
            base = state.pos[None]
            if d.size != 1:
                base = base + jnp.arange(d.size)
            return nt(base.astype(jnp.int32), [d])
        return range_(d, jnp.int32)

    return cast(comparison(_range(dim0), _range(dim1)),
                params.calculation_dtype)


def attention_axis_candidates(dims, params) -> list:
    """Dims eligible for the attention round-robin: all non-feature dims
    after batch (src/utils_mtf.py:418-422).  Single source of truth for
    get_attention_dim, the scan-over-layers homogeneity gate, and the
    pipeline scheduler."""
    return [d for d in dims
            if d not in params.feature_dims and d not in params.intermediate][1:]


def get_attention_dim(args: BlockArgs) -> ATTENTION_DIM:
    """Round-robin choice of the mixing axis (src/utils_mtf.py:418-422):
    cycles over all non-feature dims after batch, enabling factorized
    multi-axis (time/height/width) attention for video."""
    params = args.params
    attention_dims = attention_axis_candidates(args.tensor.dims, params)
    idx = params.attention_idx % len(attention_dims)
    return ATTENTION_DIM(idx, attention_dims[idx])


def is_masked(args: BlockArgs) -> bool:
    return get_attention_dim(args).index in args.params.masked_attention_dimensions
