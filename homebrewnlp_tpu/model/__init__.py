"""Model graph assembly (reference: /root/reference/src/model/__init__.py).

``build`` mirrors the reference's scoped _input/_body/_output/_loss pipeline
(:203-228): video patch/bit-unfold + empty-frame embeds, factorized-vocab text
embedding, depth × block_config body under a memory-reduction strategy, tied
token head einsum + sigmoid video head, softmax-xent with z-loss,
contrastive variants, L1 video loss, optional accuracy.

``Model`` packages the two-phase init/apply around it: init materialises
parameters and records the per-block parameter plan used by the reversible /
checkpointed body (model/blocks.py).
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

from ..config import BlockArgs, ModelParameter
from ..core import scope
from ..core.dims import Dim, shape_sub
from ..core.tensor import (NamedTensor, add_n, argmax, cast, concat,
                           dropout as tensor_dropout, einsum, equal,
                           nt, ones, reciprocal, reduce_sum, sigmoid, sign,
                           slice_, sqrt, square, weighted_add)
from .backend import linear_from_features, linear_to_features
from .blocks import BlockSpec, run_body_blocks
from .embedding import batched_gather, embed, gather_embed
from .frontend import block_part_fn

LossInfo = typing.NamedTuple("LossInfo", [("total_loss", typing.Any),
                                          ("loss_list", list),
                                          ("video_loss", typing.Any),
                                          ("accuracy", typing.Any),
                                          ("token_loss", typing.Any),
                                          ("frame_out", typing.Any),
                                          ("token_out", typing.Any)])


def _default_ones(params: ModelParameter, inp) -> NamedTensor:
    if inp is None:
        return ones([], params.calculation_dtype)
    return cast(inp, params.calculation_dtype)


def _input(params: ModelParameter, vid, cat_msk_src, txt_src, vid_msk_src,
           spatial_ctx: Dim, storage: dict):
    tgt = None
    src = None
    if params.use_video:
        base_args = BlockArgs(params, vid, [''])
        vid = cast(vid, params.calculation_dtype)
        vid = tensor_dropout(vid, params.train, 1 - params.input_dropout,
                             scope.current().next_rng())

        if params.use_bit_fold_input_pipeline:
            folded = cast(vid, jnp.int64)
            concat_list = []
            for unfold_idx in range(params.fold_count):
                part = (folded.data // ((2 ** params.bit_fold_value) ** unfold_idx)
                        ) % (2 ** params.bit_fold_value)
                concat_list.append(nt(part.astype(jnp.uint8), folded.dims))
            vid = concat(concat_list, 'color_channels')

        vid = cast(vid, params.calculation_dtype) / 255
        context_dimension = vid.dims[1]
        input_features = [vid.dims[-1]]
        # the reference's utils_slice unanonymizes after slicing, which renames
        # the '_sequence' input dim to 'sequence' (src/utils_mtf.py:336-351)
        from .utils import unanonymize
        tgt = unanonymize(slice_(vid, 1, context_dimension.size, context_dimension),
                          'sequence')
        src = unanonymize(slice_(vid, 0, context_dimension.size - 1, context_dimension),
                          'sequence')

        if params.empty_frame_embedding is not None:
            embed_args = base_args(params.empty_frame_embedding)
            src = weighted_add(src, embed(embed_args, list(vid.dims[2:])), vid_msk_src)
            src = weighted_add(src, embed(embed_args, list(vid.dims[2:])), cat_msk_src)

        src = linear_to_features(base_args(src), input_features)

        for config_idx, config in enumerate(params.input_block_config):
            src = block_part_fn(params, config, src, f'vid_inp{config_idx}')

    if params.use_language:
        base_args = BlockArgs(params, txt_src, [''])
        intermediate = Dim(params.intermediate[0].name,
                           int(params.intermediate[0].size * params.vocab_weight_factorization))
        txt_args = base_args(txt_src, list(params.token_embedding))
        txt = gather_embed(txt_args, [params.vocab_dim, intermediate], storage=storage)
        txt = tensor_dropout(txt, params.train, 1 - params.input_dropout,
                             scope.current().next_rng())
        txt = linear_to_features(base_args(txt), [params.token_patch_dim, intermediate])

        for config_idx, config in enumerate(params.input_block_config):
            txt = block_part_fn(params, config, txt, f'lang_inp{config_idx}')

    if params.use_video and params.use_language:
        # src: [batch, sequence, height_v, width?, feat...] / txt joins on the
        # spatial_ctx axis exactly as the reference concat (model/__init__.py:88)
        return concat([src, txt], spatial_ctx.name), tgt
    if not params.use_video:
        return txt, tgt
    return src, tgt


def _body(params: ModelParameter, src: NamedTensor,
          plan) -> typing.Tuple[NamedTensor, tuple]:
    base_args = BlockArgs(params, src, [''])
    if params.use_initial_position_embedding:
        for dim in shape_sub(src.dims, params.feature_dims)[1:]:
            src = src + embed(base_args(list(params.position_embedding)),
                              [dim] + list(params.feature_dims))
    return run_body_blocks(params, src, plan)


def _output(params: ModelParameter, out: NamedTensor, spatial_ctx: Dim):
    base_args = BlockArgs(params, out, [''])
    token_out = frame_out = None

    contrastive = (params.contrastive_across_token_embeddings
                   or params.contrastive_across_samples)
    if params.use_language:
        token_out = slice_(out, 0, params.language_token_patch, spatial_ctx.name) \
            if params.use_video else out
        if not contrastive:
            for config_idx, config in enumerate(params.output_block_config):
                token_out = block_part_fn(params, config, token_out, f'lang_out{config_idx}')
            new = [params.token_patch_dim, params.vocab_dim]
            old = list(params.feature_dims)
            emb = embed(base_args(list(params.output_embedding)), old + new)
            token_out = einsum([token_out, emb],
                               output_shape=shape_sub(token_out.dims, old) + new)

    if params.use_video:
        frame_out = slice_(out, params.language_token_patch * params.use_language,
                           out.dim(spatial_ctx.name).size, spatial_ctx.name)
        for config_idx, config in enumerate(params.output_block_config):
            frame_out = block_part_fn(params, config, frame_out, f'vid_out{config_idx}')
        frame_out = sigmoid(linear_from_features(base_args(frame_out),
                                                 [params.color_channel_dim]))
    return frame_out, token_out


def softmax_cross_entropy_with_logits(params: ModelParameter, logits: NamedTensor,
                                      targets: NamedTensor) -> NamedTensor:
    """Max-subtracted xent + z-loss (reference: src/mtf_wrapper.py:64-71)."""
    from ..core.tensor import (exp, log, one_hot, reduce_max, stop_gradient,
                               reduce_sum as rsum, constant)
    max_logit = reduce_max(stop_gradient(logits), reduced_dim=params.vocab_dim)
    log_z = log(rsum(exp(logits - max_logit), reduced_dim=params.vocab_dim)) + max_logit
    tgt_size = targets.size
    oh = one_hot(targets, params.vocab_dim, dtype=logits.dtype)
    loss = einsum([logits - log_z, oh, constant(-1 / tgt_size, logits.dtype)], [])
    if params.z_loss:
        loss = loss + einsum([log_z, log_z,
                              constant(params.z_loss / tgt_size, logits.dtype)], [])
    return loss


def _loss(params: ModelParameter, frame_out, token_out, txt_tgt, loss_list,
          vid_msk_tgt, cat_msk_tgt, vid_tgt, storage: dict):
    token_loss = accuracy = video_loss = None
    if params.use_language:
        if params.contrastive_across_samples or params.contrastive_across_token_embeddings:
            token_out = token_out / sqrt(reduce_sum(square(token_out),
                                                    reduced_dim=params.feature_dims))
        if params.contrastive_across_samples:
            sum_across_samples = reduce_sum(token_out, reduced_dim=params.sequence_dim)
            sum_across_batch = reduce_sum(token_out, reduced_dim=params.batch_dim)
            token_loss = einsum([sum_across_batch, sum_across_batch], []) / params.train_batch_size
            token_loss = token_loss - einsum([sum_across_samples, sum_across_samples],
                                             []) / params.sequence_length
            token_loss = token_loss / (params.train_batch_size * params.sequence_length)
        elif params.contrastive_across_token_embeddings:
            emb = storage['text_input_embedding']
            token_loss = einsum([token_out, emb], [])
            gathered = batched_gather(emb, txt_tgt, [params.head_dim])
            token_loss = token_loss - einsum([token_out, gathered], []) * 2
            token_loss = token_loss / (token_out.size * params.vocab_size)
        else:
            token_loss = softmax_cross_entropy_with_logits(params, token_out, txt_tgt)
        loss_list.append(token_loss)
        if params.calc_accuracy:
            acc = cast(equal(argmax(token_out, params.vocab_dim), txt_tgt),
                       params.calculation_dtype)
            accuracy = reduce_sum(acc, output_shape=[]) / txt_tgt.size

    if params.use_video:
        out = frame_out - vid_tgt
        video_loss = einsum([out, vid_msk_tgt, cat_msk_tgt,
                             nt(jnp.asarray(1 / frame_out.size,
                                            params.calculation_dtype), ()),
                             sign(out)], [])
        loss_list.append(video_loss)
        if vid_msk_tgt is not None:
            video_loss = einsum([nt(jnp.asarray(float(vid_msk_tgt.size),
                                                params.calculation_dtype), ()),
                                 reciprocal(reduce_sum(vid_msk_tgt)),
                                 nt(jnp.asarray(float(cat_msk_tgt.size),
                                                params.calculation_dtype), ()),
                                 reciprocal(reduce_sum(cat_msk_tgt)),
                                 video_loss], [])
    return loss_list, token_loss, accuracy, video_loss


def _build(params: ModelParameter, vid, cat_msk_src, cat_msk_tgt, txt_src,
           txt_tgt, vid_msk_src, vid_msk_tgt, txt_msk, plan):
    cat_msk_src = _default_ones(params, cat_msk_src) if params.use_video else cat_msk_src
    cat_msk_tgt = _default_ones(params, cat_msk_tgt) if params.use_video else cat_msk_tgt
    vid_msk_src = _default_ones(params, vid_msk_src) if params.use_video else vid_msk_src
    vid_msk_tgt = _default_ones(params, vid_msk_tgt) if params.use_video else vid_msk_tgt

    loss_list: list = []
    spatial_ctx: Dim = txt_tgt.dims[-2] if params.use_language else vid.dims[2]
    storage: dict = {}

    src, vid_tgt = scope.scoped("input", _input, params, vid, cat_msk_src,
                                txt_src, vid_msk_src, spatial_ctx, storage)
    out, plan = scope.scoped("body", _body, params, src, plan)
    frame_out, token_out = scope.scoped("output", _output, params, out, spatial_ctx)
    loss_list, token_loss, accuracy, video_loss = scope.scoped(
        "loss", _loss, params, frame_out, token_out, txt_tgt, loss_list,
        vid_msk_tgt, cat_msk_tgt, vid_tgt, storage)

    params.attention_idx = 0
    return LossInfo(add_n(loss_list), loss_list, video_loss, accuracy,
                    token_loss, frame_out, token_out), plan


def build(params: ModelParameter, vid, cat_msk_src, cat_msk_tgt, txt_src,
          txt_tgt, vid_msk_src, vid_msk_tgt, txt_msk, plan=None):
    return scope.scoped(params.model_mode, _build, params, vid, cat_msk_src,
                        cat_msk_tgt, txt_src, txt_tgt, vid_msk_src,
                        vid_msk_tgt, txt_msk, plan)


class Model:
    """Two-phase wrapper: ``init`` materialises params + block plan,
    ``apply`` is a pure function of (params, inputs) suitable for jit/grad."""

    def __init__(self, params: ModelParameter):
        self.params = params
        self.plan: typing.Optional[typing.Tuple[BlockSpec, ...]] = None
        self.param_dims: typing.Dict[str, tuple] = {}
        # contracted-dim names per parameter (core/scope.py param_fan_in);
        # serving quantization's safe scale axes
        self.param_fan_in: typing.Dict[str, tuple] = {}

    def _named_inputs(self, batch: typing.Dict[str, jax.Array]):
        p = self.params
        def get(key, dims):
            if key not in batch or batch[key] is None:
                return None
            return nt(batch[key], dims)
        vid = get('frame', p.frame_input_shape) if p.use_video else None
        token_x = get('token_x', p.token_dim_shape) if p.use_language else None
        token_y = get('token_y', p.token_dim_shape) if p.use_language else None
        cat_msk_x = get('cat_mask_x', p.frame_mask_shape) if p.use_video else None
        cat_msk_y = get('cat_mask_y', p.frame_mask_shape) if p.use_video else None
        vid_msk_src = get('vid_msk_src', p.frame_mask_shape) if p.use_video else None
        vid_msk_tgt = get('vid_msk_tgt', p.frame_mask_shape) if p.use_video else None
        txt_msk = get('txt_msk', p.token_dim_shape) if p.use_language else None
        return vid, cat_msk_x, cat_msk_y, token_x, token_y, vid_msk_src, vid_msk_tgt, txt_msk

    def init(self, batch: typing.Dict[str, jax.Array], seed: typing.Optional[int] = None
             ) -> typing.Dict[str, jax.Array]:
        """Materialise parameters (host numpy) and the block plan.

        The forward pass is traced abstractly (eval_shape) so init performs
        no device computation at all — parameters are numpy master copies;
        the trainer device_puts them with their NamedShardings.
        """
        ctx = scope.Context("init", seed=self.params.data_seed if seed is None else seed,
                            record_touched=True)

        def _run(abstract_batch):
            with scope.context(ctx):
                args = self._named_inputs(abstract_batch)
                self.params.attention_idx = 0
                info, self.plan = build(self.params, *args, plan=None)
            return info.total_loss

        jax.eval_shape(_run, {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                              for k, v in batch.items() if v is not None})
        self.param_dims = dict(ctx.param_dims)
        self.param_fan_in = dict(ctx.param_fan_in)
        return ctx.params

    def apply(self, variables: typing.Dict[str, jax.Array],
              batch: typing.Dict[str, jax.Array],
              rng: typing.Optional[jax.Array] = None,
              mesh: typing.Any = None,
              stats_sink: typing.Optional[list] = None) -> LossInfo:
        assert self.plan is not None, "call init() first (or assign .plan)"
        ctx = scope.Context("apply", params=variables, rng_key=rng, mesh=mesh)
        ctx.quant_scales = getattr(self, "quant_scales", None)
        ctx.matmul_accumulation = self.params.matmul_accumulation
        ctx.stats_sink = stats_sink
        with scope.context(ctx):
            args = self._named_inputs(batch)
            self.params.attention_idx = 0
            info, _ = build(self.params, *args, plan=self.plan)
        return info

    def train_grads_1f1b(self, variables: typing.Dict[str, jax.Array],
                         batch: typing.Dict[str, jax.Array],
                         rng: typing.Optional[jax.Array],
                         mesh) -> typing.Tuple[typing.Dict[str, jax.Array],
                                               LossInfo]:
        """Loss + gradients via the fused 1F1B pipeline schedule
        (parallel/pipeline_1f1b.py): the body runs the per-tick
        forward/backward table with the output head + loss inside the last
        stage; the input embedding and its gradients run outside through an
        ordinary ``jax.vjp``.  Text models with the linear loss only."""
        from ..parallel.pipeline_1f1b import pipeline_train_1f1b

        p = self.params
        assert self.plan is not None, "call init() first (or assign .plan)"
        assert p.use_language and not p.use_video, \
            "1f1b pipeline supports text (gpt) mode only"
        assert not (p.contrastive_across_samples
                    or p.contrastive_across_token_embeddings), \
            "1f1b pipeline supports the plain xent loss only"
        from ..core import sharding as shardlib
        n_micro = max(1, int(p.pipeline_microbatches
                             or mesh.shape[shardlib.PIPE_AXIS]))
        if p.train_batch_size % n_micro:
            raise ValueError(f"batch {p.train_batch_size} not divisible by "
                             f"pipeline_microbatches={n_micro}")

        ctx = scope.Context("apply", params=variables, rng_key=rng, mesh=mesh)
        with scope.context(ctx):
            (_, _, _, txt_src, txt_tgt, _, _, _) = self._named_inputs(batch)
            p.attention_idx = 0
            mode_frame = ctx.enter(p.model_mode)          # e.g. "gpt0"
            spatial_ctx: Dim = txt_tgt.dims[-2]
            input_names = [n for n in variables
                           if n.startswith(f"{mode_frame}/input")]
            head_names = [n for n in variables
                          if n.startswith((f"{mode_frame}/output",
                                           f"{mode_frame}/loss"))]

            src_dims_box = []

            def input_f(sub):
                c = scope.Context("apply", params={**variables, **sub},
                                  rng_key=rng, mesh=mesh)
                c.stack.append(scope._Frame(mode_frame))
                with scope.context(c):
                    src, _ = scope.scoped("input", _input, p, None, None,
                                          txt_src, None, spatial_ctx, {})
                src_dims_box.append(src.dims)
                return src.data

            src_data, input_vjp = jax.vjp(
                input_f, {n: variables[n] for n in input_names})
            src_nt = nt(src_data, src_dims_box[0])

            # body blocks exactly as run_body_blocks builds them
            ctx.enter("body")
            prefix = tuple(f.name for f in ctx.stack[1:])
            from .blocks import ReplayBlock
            blocks = [(i, c, bc) for i in range(p.depth)
                      for c, bc in enumerate(p.block_config)]
            fns, subsets = [], []
            attn_idx = 0
            for (i, c, bc), (_, _, names) in zip(blocks, self.plan):
                fns.append(ReplayBlock(p, bc, i, c, prefix, attn_idx))
                attn_idx += sum(layer.split('-')[0] == "attention"
                                for layer in bc.layer)
                subsets.append({n: variables[n] for n in names})
            ctx.exit()
            ctx.exit()  # mode frame

            mb = p.train_batch_size // n_micro
            src_dims_mb = (Dim(src_nt.dims[0].name, mb),) + tuple(src_nt.dims[1:])
            tgt_dims_mb = (Dim(txt_tgt.dims[0].name, mb),) + tuple(txt_tgt.dims[1:])

            def head_fn(head_sub, y_comb, tgt_data):
                c = scope.Context("apply", params={**variables, **head_sub},
                                  rng_key=rng, mesh=None)
                c.stack.append(scope._Frame(mode_frame))
                with scope.context(c):
                    out_nt = nt(y_comb, src_dims_mb)
                    tgt_nt = nt(tgt_data, tgt_dims_mb)
                    frame_out, token_out = scope.scoped("output", _output, p,
                                                        out_nt, spatial_ctx)
                    loss_list, token_loss, accuracy, _ = scope.scoped(
                        "loss", _loss, p, frame_out, token_out, tgt_nt, [],
                        None, None, None, {})
                total = add_n(loss_list).data
                acc = accuracy.data if accuracy is not None else jnp.zeros(())
                aux = jnp.stack([token_loss.data.astype(jnp.float32),
                                 acc.astype(jnp.float32)])
                return total, aux

            tgt_mb = txt_tgt.data.reshape((n_micro, mb)
                                          + txt_tgt.data.shape[1:])
            loss, aux, body_grads, head_grads, d_src = pipeline_train_1f1b(
                p, mesh, fns, subsets, self.plan, src_nt, tgt_mb, head_fn,
                {n: variables[n] for n in head_names}, 2,
                p.memory_reduction_strategy)
            (d_input,) = input_vjp(d_src.data)
            p.attention_idx = 0

        grads = dict(body_grads)
        for n, g in head_grads.items():
            grads[n] = g.astype(variables[n].dtype)
        for n, g in d_input.items():
            grads[n] = g
        for n in variables:
            grads.setdefault(n, jnp.zeros_like(variables[n]))
        loss_nt = nt(loss, ())
        info = LossInfo(loss_nt, [loss_nt], None, nt(aux[1], ()),
                        nt(aux[0], ()), None, None)
        return grads, info

    def apply_decode(self, variables: typing.Dict[str, jax.Array],
                     token_slice: jax.Array, pos: jax.Array,
                     caches: typing.Dict[str, jax.Array],
                     mesh: typing.Any = None
                     ) -> typing.Tuple[jax.Array, typing.Dict[str, jax.Array]]:
        """One incremental-decode step (model/decode.py).

        ``token_slice``: the input tokens at ``pos``, shaped like token_x
        with the sequence axis of length ``width`` (1 for every classic
        sampler; the speculative VERIFY step passes ``k + 1`` consecutive
        tokens per row and scores all of them in this one call — the width
        is inferred from the slice shape).  Returns (next-token logits at
        ``pos .. pos + width - 1`` as [batch, width, token_patch, vocab],
        updated caches).  Replaces the reference sampler's full forward per
        token (/root/reference/src/run/inference.py:76-97) with
        O(width)-per-step compute; only valid for causal text models
        (use_video off).
        """
        from .decode import DecodeState
        assert self.plan is not None, "call init() first (or assign .plan)"
        p = self.params
        assert not p.use_video and p.use_language, \
            "incremental decode supports text (gpt) mode only"
        width = int(token_slice.shape[1])
        assert width < p.sequence_dim.size, \
            "decode slice must be narrower than the sequence (use apply)"
        state = DecodeState(jnp.asarray(pos, jnp.int32), p.sequence_dim.size,
                            p.sequence_dim.name, caches,
                            cache_dtype=p.decode_cache_dtype, model_params=p,
                            width=width)
        ctx = scope.Context("apply", params=variables, mesh=mesh, decode=state)
        ctx.quant_scales = getattr(self, "quant_scales", None)
        ctx.matmul_accumulation = p.matmul_accumulation
        decode_dims = [Dim(d.name, width)
                       if d.name == p.sequence_dim.name else d
                       for d in p.token_dim_shape]
        with scope.context(ctx):
            tok = nt(token_slice, decode_dims)
            tgt = nt(jnp.zeros_like(token_slice), decode_dims)
            self.params.attention_idx = 0
            info, _ = build(p, None, None, None, tok, tgt, None, None, None,
                            plan=self.plan)
        return info.token_out.data, state.out

    def apply_prefill(self, variables: typing.Dict[str, jax.Array],
                      token_x: jax.Array, n: jax.Array,
                      mesh: typing.Any = None) -> typing.Dict[str, jax.Array]:
        """Capture the decode caches for prompt positions in ONE forward.

        Returns the cache pytree equivalent to having run decode steps
        ``0..n-1`` of ``apply_decode`` (model/decode.py ``PrefillState``
        documents the per-cache argument), so the sampler can start its
        while_loop at ``q = n`` instead of walking the prompt one model call
        per token.  The full forward runs the normal (fastest) code paths —
        flash kernels, depth scan — with the capture hooks riding along.
        """
        from .decode import PrefillState
        assert self.plan is not None, "call init() first (or assign .plan)"
        p = self.params
        assert not p.use_video and p.use_language, \
            "prefill supports text (gpt) mode only"
        from ..core import sharding as shardlib
        if mesh is not None \
                and getattr(mesh, "shape", {}).get(shardlib.SEQUENCE_AXIS, 1) > 1:
            raise ValueError("prefill needs the serving mesh (sequence axis "
                             "folded into data); got a sequence-sharded mesh")
        state = PrefillState(jnp.asarray(n, jnp.int32), p.sequence_dim.size,
                             p.sequence_dim.name,
                             cache_dtype=p.decode_cache_dtype, model_params=p)
        ctx = scope.Context("apply", params=variables, mesh=mesh)
        ctx.quant_scales = getattr(self, "quant_scales", None)
        ctx.matmul_accumulation = p.matmul_accumulation
        ctx.prefill = state

        def _output_blocks(params, out):
            # output_block_config layers may create caches too (e.g. a
            # cumsum head block) — run them under the same "output" frame
            # _build opens so their cache names match the decode build;
            # contrastive configs skip them there as well
            if (params.contrastive_across_token_embeddings
                    or params.contrastive_across_samples):
                return
            token_out = out
            for config_idx, config in enumerate(params.output_block_config):
                token_out = block_part_fn(params, config, token_out,
                                          f'lang_out{config_idx}')

        def _prefill_forward(params, tok):
            # same scope frames _build opens, minus the vocab projection and
            # loss: the [b, s, patch, vocab] logits would be computed only
            # to be discarded — at BPE vocab sizes a significant share of
            # prefill FLOPs and HBM — and neither creates caches
            spatial_ctx: Dim = tok.dims[-2]
            src, _ = scope.scoped("input", _input, params, None, None, tok,
                                  None, spatial_ctx, {})
            out, _ = scope.scoped("body", _body, params, src, self.plan)
            scope.scoped("output", _output_blocks, params, out)
            params.attention_idx = 0

        with scope.context(ctx):
            tok = nt(token_x, p.token_dim_shape)
            self.params.attention_idx = 0
            scope.scoped(p.model_mode, _prefill_forward, p, tok)
        return state.out
